// Native placement engine: ICI-torus sub-block search over bitmasks.
//
// C++ twin of yoda_scheduler_tpu/topology/torus.py's placement search —
// the scheduler's per-cycle hot spot. Python memoises repeated queries; this
// library makes the cache-miss path ~100x cheaper by representing chip sets
// as 64-bit word bitmasks (subset test = AND+compare per word) instead of
// Python frozensets. Exposed through a C ABI for ctypes
// (yoda_scheduler_tpu/topology/native.py); results are bit-identical to the
// Python implementation (same tie-break keys: fragmentation, compactness,
// low-corner origin), which the parity tests in tests/test_native.py verify.
//
// Build: make native   (g++ -O2 -shared -fPIC)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int kMaxWords = 64;  // up to 4096 chips per slice

struct Mask {
  uint64_t w[kMaxWords];
  int words;
  void clear(int n_words) {
    words = n_words;
    std::memset(w, 0, sizeof(uint64_t) * words);
  }
  void set(int bit) { w[bit >> 6] |= (uint64_t{1} << (bit & 63)); }
  bool subset_of(const Mask& o) const {
    for (int i = 0; i < words; ++i)
      if (w[i] & ~o.w[i]) return false;
    return true;
  }
  int count() const {
    int c = 0;
    for (int i = 0; i < words; ++i) c += __builtin_popcountll(w[i]);
    return c;
  }
};

struct Shape {
  int x, y, z;
  int volume() const { return x * y * z; }
};

inline int bit_index(const Shape& grid, int x, int y, int z) {
  return x + grid.x * (y + grid.y * z);
}

void block_mask(const Shape& grid, int ox, int oy, int oz, const Shape& b,
                Mask* out) {
  out->clear((grid.volume() + 63) / 64);
  for (int dz = 0; dz < b.z; ++dz)
    for (int dy = 0; dy < b.y; ++dy)
      for (int dx = 0; dx < b.x; ++dx)
        out->set(bit_index(grid, ox + dx, oy + dy, oz + dz));
}

// all (x,y,z) with x*y*z == n, x ascending then y (torus._factor_shapes order)
void factor_shapes(int n, std::vector<Shape>* out) {
  out->clear();
  for (int x = 1; x <= n; ++x) {
    if (n % x) continue;
    int rem = n / x;
    for (int y = 1; y <= rem; ++y) {
      if (rem % y) continue;
      out->push_back({x, y, rem / y});
    }
  }
}

int largest_free_block(const Shape& grid, const Mask& free) {
  int max_n = free.count();
  if (max_n == 0) return 0;
  // all in-grid block shapes with volume <= |free|, sorted by volume
  // descending — first placeable shape IS the largest block (equivalent to
  // the per-n factor-shape scan, without re-deriving factors per n)
  std::vector<Shape> shapes;
  for (int bx = 1; bx <= grid.x; ++bx)
    for (int by = 1; by <= grid.y; ++by)
      for (int bz = 1; bz <= grid.z; ++bz)
        if (bx * by * bz <= max_n) shapes.push_back({bx, by, bz});
  std::sort(shapes.begin(), shapes.end(), [](const Shape& a, const Shape& b) {
    return a.volume() > b.volume();
  });
  Mask bm;
  for (const Shape& b : shapes) {
    for (int ox = 0; ox + b.x <= grid.x; ++ox)
      for (int oy = 0; oy + b.y <= grid.y; ++oy)
        for (int oz = 0; oz + b.z <= grid.z; ++oz) {
          block_mask(grid, ox, oy, oz, b, &bm);
          if (bm.subset_of(free)) return b.volume();
        }
  }
  return 1;
}

double fragmentation_after(const Shape& grid, const Mask& remaining) {
  int n = remaining.count();
  if (n == 0) return 0.0;
  return 1.0 - double(largest_free_block(grid, remaining)) / double(n);
}

struct Key {
  double frag;
  int compactness;
  int oz, oy, ox;
  bool operator<(const Key& o) const {
    if (frag != o.frag) return frag < o.frag;
    if (compactness != o.compactness) return compactness < o.compactness;
    if (oz != o.oz) return oz < o.oz;
    if (oy != o.oy) return oy < o.oy;
    return ox < o.ox;
  }
};

// shared search core; candidates supplied by caller (factor shapes or
// explicit permutations)
bool best_placement(const Shape& grid, const Mask& free,
                    const std::vector<Shape>& candidates, int32_t* out_origin,
                    int32_t* out_shape, double* out_frag) {
  bool found = false;
  Key best{};
  Shape best_b{};
  int best_o[3] = {0, 0, 0};
  Mask bm, rem;
  for (const Shape& b : candidates) {
    if (b.x > grid.x || b.y > grid.y || b.z > grid.z) continue;
    for (int ox = 0; ox + b.x <= grid.x; ++ox)
      for (int oy = 0; oy + b.y <= grid.y; ++oy)
        for (int oz = 0; oz + b.z <= grid.z; ++oz) {
          block_mask(grid, ox, oy, oz, b, &bm);
          if (!bm.subset_of(free)) continue;
          rem.words = free.words;
          for (int i = 0; i < free.words; ++i) rem.w[i] = free.w[i] & ~bm.w[i];
          Key k{fragmentation_after(grid, rem), b.x + b.y + b.z, oz, oy, ox};
          if (!found || k < best) {
            found = true;
            best = k;
            best_b = b;
            best_o[0] = ox;
            best_o[1] = oy;
            best_o[2] = oz;
          }
        }
  }
  if (!found) return false;
  out_origin[0] = best_o[0];
  out_origin[1] = best_o[1];
  out_origin[2] = best_o[2];
  out_shape[0] = best_b.x;
  out_shape[1] = best_b.y;
  out_shape[2] = best_b.z;
  if (out_frag) *out_frag = best.frag;
  return true;
}

bool load_free(const Shape& grid, const int32_t* coords, int n_free,
               Mask* out) {
  if (grid.volume() > kMaxWords * 64) return false;
  out->clear((grid.volume() + 63) / 64);
  for (int i = 0; i < n_free; ++i) {
    int x = coords[i * 3], y = coords[i * 3 + 1], z = coords[i * 3 + 2];
    if (x < 0 || y < 0 || z < 0 || x >= grid.x || y >= grid.y || z >= grid.z)
      return false;
    out->set(bit_index(grid, x, y, z));
  }
  return true;
}

}  // namespace

extern "C" {

// returns 1 on fit, 0 no fit, -1 bad input
int yoda_best_fit(const int32_t grid_shape[3], const int32_t* free_coords,
                  int32_t n_free, int32_t n_chips, int32_t out_origin[3],
                  int32_t out_shape[3]) {
  Shape grid{grid_shape[0], grid_shape[1], grid_shape[2]};
  Mask free;
  if (!load_free(grid, free_coords, n_free, &free)) return -1;
  std::vector<Shape> candidates;
  factor_shapes(n_chips, &candidates);
  return best_placement(grid, free, candidates, out_origin, out_shape, nullptr)
             ? 1
             : 0;
}

int yoda_fits_shape(const int32_t grid_shape[3], const int32_t* free_coords,
                    int32_t n_free, const int32_t req_shape[3],
                    int32_t out_origin[3], int32_t out_shape[3]) {
  Shape grid{grid_shape[0], grid_shape[1], grid_shape[2]};
  Mask free;
  if (!load_free(grid, free_coords, n_free, &free)) return -1;
  // unique permutations in sorted order (matches torus.fits_shape)
  int d[3] = {req_shape[0], req_shape[1], req_shape[2]};
  std::vector<Shape> perms;
  int idx[6][3] = {{0, 1, 2}, {0, 2, 1}, {1, 0, 2},
                   {1, 2, 0}, {2, 0, 1}, {2, 1, 0}};
  for (auto& p : idx) {
    Shape s{d[p[0]], d[p[1]], d[p[2]]};
    bool dup = false;
    for (const Shape& q : perms)
      if (q.x == s.x && q.y == s.y && q.z == s.z) dup = true;
    if (!dup) perms.push_back(s);
  }
  // sorted order
  for (size_t i = 0; i < perms.size(); ++i)
    for (size_t j = i + 1; j < perms.size(); ++j) {
      auto less = [](const Shape& a, const Shape& b) {
        if (a.x != b.x) return a.x < b.x;
        if (a.y != b.y) return a.y < b.y;
        return a.z < b.z;
      };
      if (less(perms[j], perms[i])) std::swap(perms[i], perms[j]);
    }
  return best_placement(grid, free, perms, out_origin, out_shape, nullptr) ? 1
                                                                           : 0;
}

int yoda_largest_free_block(const int32_t grid_shape[3],
                            const int32_t* free_coords, int32_t n_free) {
  Shape grid{grid_shape[0], grid_shape[1], grid_shape[2]};
  Mask free;
  if (!load_free(grid, free_coords, n_free, &free)) return -1;
  return largest_free_block(grid, free);
}

// contiguity score 0..100 (torus.contiguity_score); -1 on bad input
double yoda_contiguity(const int32_t grid_shape[3], const int32_t* free_coords,
                       int32_t n_free, int32_t n_chips) {
  Shape grid{grid_shape[0], grid_shape[1], grid_shape[2]};
  Mask free;
  if (!load_free(grid, free_coords, n_free, &free)) return -1.0;
  std::vector<Shape> candidates;
  factor_shapes(n_chips, &candidates);
  int32_t origin[3], shape_out[3];
  double frag;
  if (!best_placement(grid, free, candidates, origin, shape_out, &frag))
    return 0.0;
  return 100.0 * (1.0 - frag);
}

}  // extern "C"
