"""The incremental-scheduling machinery: bounded ChangeLog semantics,
dirty-node snapshot reuse, and the unschedulable-class memo's O(1) fast
path with event-driven invalidation. These are the structures behind the
sub-linear 1000-node bench — regressions here are silent (everything
still schedules, just slower or staler), so the contracts get pinned.
"""

from __future__ import annotations

import time

from yoda_scheduler_tpu.scheduler import FakeCluster, Scheduler, SchedulerConfig
from yoda_scheduler_tpu.scheduler.core import FakeClock
from yoda_scheduler_tpu.telemetry import TelemetryStore, make_tpu_node
from yoda_scheduler_tpu.utils import Pod, PodPhase
from yoda_scheduler_tpu.utils.changelog import ChangeLog


class TestChangeLog:
    def test_basic_semantics(self):
        cl = ChangeLog()
        v0 = cl.version
        cl.record("a")
        cl.record("b")
        cur, dirty = cl.changes_since(v0)
        assert cur == v0 + 2 and dirty == {"a", "b"}
        # caller already current: empty set, not None
        cur2, dirty2 = cl.changes_since(cur)
        assert cur2 == cur and dirty2 == set()

    def test_trimmed_past_caller_returns_none(self):
        cl = ChangeLog(cap=4)
        v0 = cl.version
        for i in range(10):
            cl.record(f"n{i}")
        cur, dirty = cl.changes_since(v0)
        assert dirty is None  # log no longer reaches back: full rebuild
        # but a recent-enough caller still gets the incremental answer
        cur2, dirty2 = cl.changes_since(cur - 2)
        assert dirty2 == {"n8", "n9"}

    def test_trim_boundary_exact(self):
        """The `log[0] version > V+1` edge: V+1 being the oldest retained
        entry is still answerable; one older is not."""
        cl = ChangeLog(cap=3)
        for i in range(5):
            cl.record(f"n{i}")  # retained versions: 3,4,5
        assert cl.changes_since(2)[1] == {"n2", "n3", "n4"}
        assert cl.changes_since(1)[1] is None


def mk_sched(chips=4, nodes=("n1", "n2"), **cfg):
    store = TelemetryStore()
    now = time.time()
    for n in nodes:
        m = make_tpu_node(n, chips=chips)
        m.heartbeat = now + 1e8
        store.put(m)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    sched = Scheduler(cluster, SchedulerConfig(telemetry_max_age_s=1e9, **cfg),
                      clock=FakeClock(start=time.time()))
    return cluster, store, sched


class TestIncrementalSnapshot:
    def test_unchanged_cluster_reuses_the_snapshot_object(self):
        cluster, store, sched = mk_sched()
        s1 = sched.snapshot()
        s2 = sched.snapshot()
        assert s2 is s1  # zero dirty nodes: same object, zero walk

    def test_bind_dirties_exactly_its_node(self):
        cluster, store, sched = mk_sched()
        s1 = sched.snapshot()
        n1_before = s1.get("n1")
        n2_before = s1.get("n2")
        cluster.bind(Pod("p", labels={"tpu/assigned-chips": "0,0,0"}), "n1",
                     [(0, 0, 0)])
        s2 = sched.snapshot()
        assert s2 is not s1
        assert s2.get("n2") is n2_before      # untouched node carried over
        assert s2.get("n1") is not n1_before  # dirty node rebuilt
        assert len(s2.get("n1").pods) == 1

    def test_telemetry_put_dirties_its_node(self):
        cluster, store, sched = mk_sched()
        s1 = sched.snapshot()
        m = make_tpu_node("n2", chips=4, hbm_free_mb=123)
        m.heartbeat = time.time() + 1e8
        store.put(m)
        s2 = sched.snapshot()
        assert s2.get("n1") is s1.get("n1")
        assert s2.get("n2").metrics.chips[0].hbm_free_mb == 123

    def test_membership_change_forces_full_rebuild(self):
        cluster, store, sched = mk_sched()
        sched.snapshot()
        m = make_tpu_node("n3", chips=4)
        m.heartbeat = time.time() + 1e8
        store.put(m)
        cluster.add_node("n3")
        s2 = sched.snapshot()
        assert {ni.name for ni in s2.list()} == {"n1", "n2", "n3"}
        cluster.remove_node("n3")
        s3 = sched.snapshot()
        assert {ni.name for ni in s3.list()} == {"n1", "n2"}


class TestUnschedulableClassMemo:
    def _trace_of_last(self, sched):
        return sched.traces.recent(1)[0]

    def test_classmate_fails_without_a_node_scan(self):
        cluster, store, sched = mk_sched(chips=2, nodes=("n1",),
                                         preemption=False)
        big = {"scv/number": "4", "tpu/accelerator": "tpu"}
        sched.submit(Pod("a", labels=dict(big)))
        sched.run_one()
        t1 = self._trace_of_last(sched)
        assert t1.outcome == "unschedulable"
        assert t1.filter_verdicts  # the first classmate did the real scan
        sched.submit(Pod("b", labels=dict(big)))
        sched.run_one()
        t2 = self._trace_of_last(sched)
        assert t2.outcome == "unschedulable"
        assert t2.reason == t1.reason
        assert not t2.filter_verdicts  # memo fast path: no per-node work
        assert sched.metrics.counters.get("unsched_memo_hits_total") == 1

    def test_any_cluster_event_invalidates(self):
        cluster, store, sched = mk_sched(chips=2, nodes=("n1",),
                                         preemption=False,
                                         pod_initial_backoff_s=0.01,
                                         pod_max_backoff_s=0.01)
        big = {"scv/number": "4", "tpu/accelerator": "tpu"}
        a = Pod("a", labels=dict(big))
        sched.submit(a)
        sched.run_one()
        assert a.phase != PodPhase.BOUND
        # telemetry event: the node grows to 4 chips -> next attempt SCANS
        # and binds
        m = make_tpu_node("n1", chips=4)
        m.heartbeat = time.time() + 1e8
        store.put(m)
        sched.clock.advance(1.0)
        assert sched.run_one() == "bound"

    def test_bind_event_invalidates(self):
        cluster, store, sched = mk_sched(chips=4, nodes=("n1",),
                                         preemption=False,
                                         pod_initial_backoff_s=0.01,
                                         pod_max_backoff_s=0.01)
        blocker = Pod("blocker", labels={"scv/number": "4",
                                         "tpu/accelerator": "tpu"})
        sched.submit(blocker)
        assert sched.run_one() == "bound"
        b = Pod("b", labels={"scv/number": "2", "tpu/accelerator": "tpu"})
        sched.submit(b)
        sched.run_one()
        assert b.phase != PodPhase.BOUND
        cluster.evict(blocker)  # evict bumps the cluster change log
        sched.clock.advance(1.0)
        assert sched.run_one() == "bound"

    def test_gangs_never_take_the_memo_path(self):
        """Gang verdicts depend on coordinator state outside the version
        vector: every gang cycle must evaluate live state, never the
        unschedulable-class memo. On a cluster with NO slice nodes the
        gang pre-filter's sound narrowing now fails the cycle itself
        with an explicit reason (instead of the scan producing per-node
        'needs a pod-slice node' verdicts)."""
        cluster, store, sched = mk_sched(chips=2, nodes=("n1",),
                                         preemption=False)
        g = {"tpu/gang-name": "g", "tpu/gang-size": "2", "scv/number": "4",
             "tpu/accelerator": "tpu"}
        sched.submit(Pod("g-0", labels=dict(g)))
        sched.run_one()
        sched.submit(Pod("g-1", labels=dict(g)))
        sched.run_one()
        t = self._trace_of_last(sched)
        # a real evaluation happened: the narrowing's reason is recorded
        # (not a memoised verdict, which the memo counter would show)
        assert "slice narrowing" in (t.reason or "")
        assert sched.metrics.counters.get("unsched_memo_hits_total", 0) == 0


class TestFeasibleClassMemo:
    def test_classmates_hit_the_memo_and_still_place_correctly(self):
        """A burst of identical pods: the first pays the full scan, later
        classmates repair the cached feasible list (feas_memo_hits_total
        counts them) and every pod still binds with correct capacity
        accounting — n2 fills exactly after its chips run out."""
        cluster, store, sched = mk_sched(chips=2, nodes=("n1", "n2"))
        pods = [Pod(f"p{i}", labels={"scv/number": "1",
                                     "tpu/accelerator": "tpu"})
                for i in range(4)]
        for p in pods:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in pods)
        # 4 chips total, 4 single-chip pods: both nodes exactly full
        per_node = {"n1": 0, "n2": 0}
        for p in pods:
            per_node[p.node] += 1
        assert per_node == {"n1": 2, "n2": 2}
        assert sched.metrics.counters.get("feas_memo_hits_total", 0) >= 2

    def test_repair_drops_a_filled_node(self):
        """After n1 fills, a repaired feasible list must re-filter the
        dirty node and stop offering it — the 3rd classmate lands on n2,
        never 'successfully' on a full n1."""
        cluster, store, sched = mk_sched(chips=2, nodes=("n1", "n2"))
        # bias scoring off: fill n1 first via pre-bound pods
        for i in range(2):
            cluster.bind(Pod(f"pre{i}", labels={"scv/number": "1"}),
                         "n1", [(i, 0, 0)])
        pods = [Pod(f"p{i}", labels={"scv/number": "1",
                                     "tpu/accelerator": "tpu"})
                for i in range(2)]
        for p in pods:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND and p.node == "n2"
                   for p in pods)

    def test_stale_node_leaves_a_repaired_list(self):
        """Staleness moves with TIME, not with any change log: a node
        whose sniffer stops publishing must fall out of the cached
        feasible list even though no version changed."""
        from yoda_scheduler_tpu.scheduler.core import FakeClock

        store = TelemetryStore()
        t0 = 1000.0
        for n in ("n1", "n2"):
            m = make_tpu_node(n, chips=4)
            m.heartbeat = t0
            store.put(m)
        cluster = FakeCluster(store)
        cluster.add_nodes_from_telemetry()
        clock = FakeClock(start=t0)
        sched = Scheduler(cluster, SchedulerConfig(telemetry_max_age_s=60.0),
                          clock=clock)
        p1 = Pod("p1", labels={"scv/number": "1", "tpu/accelerator": "tpu"})
        sched.submit(p1)
        sched.run_until_idle()
        assert p1.phase == PodPhase.BOUND
        # keep n2 fresh, let n1's sniffer die; advance past max_age
        clock.advance(120.0)
        m = store.get("n2")
        m.heartbeat = t0 + 120.0
        store.put(m)
        p2 = Pod("p2", labels={"scv/number": "1", "tpu/accelerator": "tpu"})
        sched.submit(p2)
        sched.run_until_idle()
        assert p2.phase == PodPhase.BOUND
        assert p2.node == "n2", "stale n1 must not be served from the memo"


def count_scores(sched):
    """Wrap every score plugin to count per-node score() calls (memo
    tests assert how much scoring a cycle actually did)."""
    counts = {"n": 0, "nodes": []}
    for p in sched.profile.score:
        orig = p.score

        def counted(state, pod, node, _orig=orig):
            counts["n"] += 1
            counts["nodes"].append(node.name)
            return _orig(state, pod, node)

        p.score = counted
    return counts


class TestScoreClassMemo:
    """Round-5 score-repair memo: classmate cycles rescore ONLY dirty
    nodes; slice-usage coupling and maxima changes force rescoring."""

    def test_classmate_rescores_only_the_dirty_node(self):
        cluster, store, sched = mk_sched(chips=8, nodes=tuple(
            f"n{i}" for i in range(20)))
        pods = [Pod(f"p{i}", labels={"scv/number": "1",
                                     "tpu/accelerator": "tpu"})
                for i in range(4)]
        for p in pods:
            sched.submit(p)
        sched.run_one()  # first of class: full score, memo seeded
        counts = count_scores(sched)
        sched.run_one()  # classmate: only the bound node is dirty
        assert pods[1].phase == PodPhase.BOUND
        # 2 score plugins x 1 dirty node (p0's bind target) = 2 calls,
        # versus 2 x 20 for a full scoring pass
        assert counts["n"] <= 4, (counts["n"], counts["nodes"])

    def test_slice_usage_coupling_rescores_slice_mates(self):
        """A bind on one slice host dents the SLICE: clean slice-mates'
        packing term moved, so they must be rescored, while standalone
        nodes replay."""
        from yoda_scheduler_tpu.telemetry import make_v4_slice

        store = TelemetryStore()
        now = time.time()
        nodes = make_v4_slice("s", "2x2x4") + [
            make_tpu_node(f"lone{i}", chips=4) for i in range(6)]
        for m in nodes:
            m.heartbeat = now + 1e8
            store.put(m)
        cluster = FakeCluster(store)
        cluster.add_nodes_from_telemetry()
        # batch off: this test pins the PER-POD score-memo replay (which
        # nodes rescore on the classmate's own cycle); a batch would
        # place all three pods in run_one #1 (parity pinned elsewhere)
        sched = Scheduler(cluster, SchedulerConfig(telemetry_max_age_s=1e9,
                                                   batch_max_pods=1),
                          clock=FakeClock(start=now))
        pods = [Pod(f"p{i}", labels={"scv/number": "1",
                                     "tpu/accelerator": "tpu"})
                for i in range(3)]
        for p in pods:
            sched.submit(p)
        sched.run_one()
        if pods[0].node and pods[0].node.startswith("s-"):
            counts = count_scores(sched)
            sched.run_one()
            rescored = set(counts["nodes"])
            # every host of slice s rescored (usage entry moved)
            assert {n for n in rescored if n.startswith("s-")} == {
                m.node for m in nodes if m.node.startswith("s-")}, rescored
        else:
            # packing sent p0 to a standalone node: that node alone is
            # dirty; no slice entry moved
            counts = count_scores(sched)
            sched.run_one()
            assert set(counts["nodes"]) == {pods[0].node}, counts["nodes"]

    def test_scores_still_rank_correctly_under_memo(self):
        """End state sanity: a burst over heterogeneous nodes lands the
        same way with the memo as a fresh engine computes it."""
        cluster, store, sched = mk_sched(chips=2, nodes=("a", "b", "c"))
        pods = [Pod(f"p{i}", labels={"scv/number": "2",
                                     "tpu/accelerator": "tpu"})
                for i in range(3)]
        for p in pods:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in pods)
        assert {p.node for p in pods} == {"a", "b", "c"}  # one each


class TestScoreMemoMaximaGuard:
    def test_maxima_change_forces_full_rescore(self):
        """When the cycle's MaxValue moved (a node carrying a cluster
        maximum left the feasible set), clean nodes' cached raw scores
        are scaled against the WRONG maxima — the memo must miss and
        rescore everything."""
        store = TelemetryStore()
        now = time.time()
        # A carries the max clock; filling A removes it from feasibility
        # and drops the maxima for the B/C rescore
        for name, clock in (("a", 2000), ("b", 1000), ("c", 1500)):
            m = make_tpu_node(name, chips=4)
            for c in m.chips:
                c.clock_mhz = clock
            m.heartbeat = now + 1e8
            store.put(m)
        cluster = FakeCluster(store)
        cluster.add_nodes_from_telemetry()
        # columnar off (this test pins the SCALAR score-memo mechanics —
        # per-node score() call counts; the batch path recomputes all
        # candidates each cycle, so no replay can go stale there) and
        # fragmentation off (a third scorer would shift the call counts)
        sched = Scheduler(cluster, SchedulerConfig(telemetry_max_age_s=1e9,
                                                   columnar=False,
                                                   fragmentation_weight=0,
                                                   batch_max_pods=1),
                          clock=FakeClock(start=now))
        pods = [Pod(f"p{i}", labels={"scv/number": "4",
                                     "tpu/accelerator": "tpu"})
                for i in range(2)]
        for p in pods:
            sched.submit(p)
        sched.run_one()
        assert pods[0].node == "a"  # highest clock wins the basic term
        counts = count_scores(sched)
        sched.run_one()
        assert pods[1].phase == PodPhase.BOUND
        # a (full) left feasibility -> maxima moved -> BOTH remaining
        # nodes rescored by BOTH plugins (no replay): 2 x 2 = 4 calls
        assert counts["n"] == 4, (counts["n"], counts["nodes"])


class TestMaximaMemoFastPath:
    """MaxCollection's incremental walk: a classmate cycle reuses every
    CLEAN node's cached per-node maxima tuple and pays class_stats only
    for dirty or newly-surfaced nodes — never a full re-fold (the old
    carried-maxima design degraded to one on homogeneous clusters where
    every node ties the max). Pinned from the public scheduler surface
    via the plugin's own counters (stats_calls / fast_hits) and memo."""

    def _mk(self, max_age=1e9):
        from yoda_scheduler_tpu.telemetry import make_gpu_node

        store = TelemetryStore()
        t0 = 1000.0
        for n in ("n1", "n2"):
            m = make_tpu_node(n, chips=4)
            m.heartbeat = t0 + 1e9  # never stale unless a test says so
            store.put(m)
        g = make_gpu_node("g1", cards=4)
        g.heartbeat = t0 + 1e9
        store.put(g)
        cluster = FakeCluster(store)
        cluster.add_nodes_from_telemetry()
        # columnar off: these tests pin the SCALAR contributor-memo fold
        # (class_stats call counts); the columnar path computes the same
        # maxima as masked array folds without touching class_stats
        sched = Scheduler(cluster,
                          SchedulerConfig(telemetry_max_age_s=max_age,
                                          columnar=False),
                          clock=FakeClock(start=t0))
        maxc = next(p for p in sched.profile.pre_score
                    if getattr(p, "name", "") == "max-collection")
        return store, sched, maxc

    def _tpu_pod(self, name):
        return Pod(name, labels={"scv/number": "1",
                                 "tpu/accelerator": "tpu"})

    def test_classmate_pays_only_for_the_dirty_node(self):
        """After p1 binds (dirtying exactly one TPU node), p2's cycle
        must re-fold ONLY that node — one class_stats call, not a full
        re-fold of the feasible list. A GPU bind in between (dirtying a
        node outside the TPU class) must not add to the bill."""
        store, sched, maxc = self._mk()
        sched.submit(self._tpu_pod("p1"))  # primes the memo, binds
        sched.run_until_idle()
        gp = Pod("gp", labels={"scv/number": "1", "tpu/accelerator": "gpu"})
        sched.submit(gp)
        sched.run_until_idle()
        assert gp.phase == PodPhase.BOUND and gp.node == "g1"
        before = maxc.stats_calls
        p2 = self._tpu_pod("p2")
        sched.submit(p2)
        sched.run_until_idle()
        assert p2.phase == PodPhase.BOUND
        assert maxc.stats_calls - before == 1, \
            "classmate must pay exactly one class_stats (p1's bind node)"

    def test_quiet_classmate_reuses_everything(self):
        """A cycle with no TPU-side events since the memo stamp makes
        zero class_stats calls. A bound classmate always dirties its own
        node, so the quiet case needs a cycle that binds nothing: an
        unschedulable pod (8 chips never fit a 4-chip node) followed by
        a classmate — the first cycle dirtied nothing."""
        store, sched, maxc = self._mk()
        big = Pod("big", labels={"scv/number": "8",
                                 "tpu/accelerator": "tpu",
                                 "scv/priority": "5"})
        sched.submit(big)  # 8 chips never fit a 4-chip node: unschedulable
        sched.run_until_idle()
        assert big.phase != PodPhase.BOUND
        big2 = Pod("big2", labels={"scv/number": "8",
                                   "tpu/accelerator": "tpu",
                                   "scv/priority": "5"})
        before_stats = maxc.stats_calls
        sched.submit(big2)
        sched.run_until_idle()
        # the unschedulable-class memo may short-circuit before
        # pre_score; either way the quiet classmate must trigger NO
        # class_stats re-fold
        assert maxc.stats_calls == before_stats
        assert big2.phase != PodPhase.BOUND

    def test_stale_departure_drops_the_contributor(self):
        """A contributor aging out of feasibility produces NO change-log
        event; the next classmate's walk simply never visits it, so its
        tuple must leave the memo (its stale contribution must not keep
        inflating the cluster maxima)."""
        store, sched, maxc = self._mk(max_age=60.0)
        t0 = 1000.0
        for n in ("n1", "n2"):  # both initially fresh at t0
            m = store.get(n)
            m.heartbeat = t0
            store.put(m)
        sched.submit(self._tpu_pod("p1"))
        sched.run_until_idle()
        # keep n1 publishing via direct mutation (no store.put = no
        # change-log event), let n2 age out
        store.get("n1").heartbeat = t0 + 120.0
        sched.clock.advance(120.0)
        p2 = self._tpu_pod("p2")
        sched.submit(p2)
        sched.run_until_idle()
        assert p2.phase == PodPhase.BOUND and p2.node == "n1"
        spec_keys = list(maxc._memo)
        assert spec_keys, "memo must be stamped"
        _, contribs, *_ = maxc._memo[spec_keys[-1]]
        assert "n2" not in contribs, \
            "a staleness-departed node must leave the contributor memo"
