"""Scheduler fleet: optimistic shared-state concurrency units.

The multi-replica chaos matrix lives in tests/test_chaos.py (fleet fuzz);
this file pins the building blocks: server-side bind-conflict semantics
(FakeCluster as the authority), the engine's 409 resolution paths
(foreign-bind drop vs local retry, never the circuit breaker), shard
leases + fencing (LocalLeaseStore and the wire ShardLeaseManager), the
clean lease-loss abort, and the contract that a fleet of ONE is the
classic engine bit-for-bit."""

import random
import threading
import time

import pytest

from yoda_scheduler_tpu.scheduler import (
    BindConflictError,
    FakeCluster,
    FleetCoordinator,
    LocalLeaseStore,
    Scheduler,
    SchedulerConfig,
)
from yoda_scheduler_tpu.scheduler.core import FakeClock
from yoda_scheduler_tpu.scheduler.fleet import SHARD_LEASE_PREFIX, shard_of
from yoda_scheduler_tpu.telemetry import (
    TelemetryStore, make_gpu_node, make_tpu_node, make_v4_slice)
from yoda_scheduler_tpu.utils import Pod, PodPhase


# ------------------------------------------------------------------ fixtures
def _rig(n_standalone=3):
    store = TelemetryStore()
    metrics = list(make_v4_slice("s0", "2x2x4"))
    for i in range(n_standalone):
        metrics.append(make_tpu_node(f"t{i}", chips=4))
    metrics.append(make_gpu_node("g0", cards=8))
    for m in metrics:
        m.heartbeat = 0.0
        store.put(m)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    return store, cluster


def _workload(seed, n_tpu=18, n_gpu=5):
    rng = random.Random(seed)
    pods = [Pod(f"c{i}", labels={"tpu/accelerator": "tpu",
                                 "scv/number": "1"}) for i in range(n_tpu)]
    pods += [Pod(f"g{i}", labels={"tpu/accelerator": "gpu",
                                  "scv/number": "1"}) for i in range(n_gpu)]
    rng.shuffle(pods)
    return pods


def _placements(pods):
    return {p.key: (p.node, tuple(sorted(p.assigned_chips())))
            for p in pods}


# --------------------------------------------- authority: conflict semantics
def test_already_bound_pod_rejected_409_without_mutation():
    _store, cluster = _rig()
    p = Pod("a", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    cluster.bind(p, "t0", [(0, 0, 0)])
    clone = Pod("a", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    with pytest.raises(BindConflictError) as ei:
        cluster.bind(clone, "t1", [(0, 0, 0)])
    assert getattr(ei.value, "status", None) == 409
    # nothing mutated: the loser's pod object untouched, the winner intact
    assert clone.phase == PodPhase.PENDING and clone.node is None
    assert cluster.bound_node_of("default/a") == "t0"
    assert cluster.bind_conflicts.get("pod_bound") == 1


def test_chip_claim_conflict_rejected_409():
    _store, cluster = _rig()
    a = Pod("a", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    b = Pod("b", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    cluster.bind(a, "t0", [(0, 0, 0)])
    with pytest.raises(BindConflictError):
        cluster.bind(b, "t0", [(0, 0, 0)])  # same chip, different pod
    assert b.phase == PodPhase.PENDING
    assert cluster.bind_conflicts.get("chip_claim") == 1
    cluster.bind(b, "t0", [(1, 0, 0)])  # disjoint claim proceeds
    assert b.phase == PodPhase.BOUND


def test_hbm_oversubscription_rejected_409():
    store, cluster = _rig()
    free = store.get("t0").chips[0].hbm_free_mb
    big = Pod("big", labels={"tpu/accelerator": "tpu", "scv/number": "1",
                             "scv/memory": str(free + 1)})
    with pytest.raises(BindConflictError):
        cluster.bind(big, "t0", [(0, 0, 0)])
    assert cluster.bind_conflicts.get("hbm") == 1
    ok = Pod("ok", labels={"tpu/accelerator": "tpu", "scv/number": "1",
                           "scv/memory": str(free)})
    cluster.bind(ok, "t0", [(0, 0, 0)])
    assert ok.phase == PodPhase.BOUND


def test_stale_fence_rejected_409():
    clock = FakeClock()
    _store, cluster = _rig()
    leases = LocalLeaseStore(clock)
    cluster.lease_authority = leases
    epoch = leases.try_acquire("yoda-shard-0", "rep-a", 30.0)
    p = Pod("p", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    # live token: accepted
    cluster.bind(p, "t0", [(0, 0, 0)], fence=("yoda-shard-0", "rep-a", epoch))
    # stolen lease: the old epoch is history, commits carrying it bounce
    leases.steal("yoda-shard-0", "rep-b")
    q = Pod("q", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    with pytest.raises(BindConflictError):
        cluster.bind(q, "t1", [(0, 0, 0)],
                     fence=("yoda-shard-0", "rep-a", epoch))
    assert cluster.bind_conflicts.get("stale_fence") == 1


# ------------------------------------------------- engine: 409 resolution
def test_foreign_bind_at_commit_adopted_not_requeued():
    """The pod was bound by a FOREIGN replica between our snapshot and
    commit (the engine's local copy still reads Pending): the 409 is
    resolved by dropping the entry and adopting cluster truth — no
    requeue loop, no breaker, no failed pod."""
    clock = FakeClock()
    _store, cluster = _rig()
    sched = Scheduler(cluster, SchedulerConfig(telemetry_max_age_s=1e9),
                      clock=clock)
    ours = Pod("x", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    sched.submit(ours)
    # a foreign replica's incarnation of the same pod key commits first —
    # onto a node it FILLED, so our engine provably chooses elsewhere and
    # the 409 resolves as a foreign bind, not same-node adoption
    theirs = Pod("x", labels={"tpu/accelerator": "tpu", "scv/number": "4"})
    cluster.bind(theirs, "t2",
                 [(0, 0, 0), (1, 0, 0), (2, 0, 0), (3, 0, 0)])
    outcome = sched.run_one()
    assert outcome in ("bind-error", "foreign-bound")  # via _bind_conflict
    c = sched.metrics.counters
    assert c["bind_conflicts_total"] == 1
    assert c["foreign_bind_conflicts_total"] == 1
    assert c.get("bind_errors_total", 0) == 0
    assert c.get("breaker_opens_total", 0) == 0
    # our copy adopted cluster truth and the queue is empty
    assert ours.phase == PodPhase.BOUND and ours.node == "t2"
    assert not sched.tracks(ours.key)


def test_foreign_bound_pod_skipped_before_cycle():
    """Shared-object fleets see the winner's phase directly: the queue
    entry is dropped pre-cycle, counted as a skip, no 409 burned."""
    clock = FakeClock()
    _store, cluster = _rig()
    sched = Scheduler(cluster, SchedulerConfig(telemetry_max_age_s=1e9),
                      clock=clock)
    pod = Pod("x", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    sched.submit(pod)
    cluster.bind(pod, "t1", [(0, 0, 0)])  # foreign replica wins, same object
    assert sched.run_one() == "foreign-bound"
    assert sched.metrics.counters["foreign_bind_skips_total"] == 1
    assert sched.metrics.counters.get("bind_conflicts_total", 0) == 0
    assert not sched.tracks(pod.key)


class _ScriptedConflictCluster(FakeCluster):
    """Rejects the first `times` binds with a claim conflict — the
    deterministic stand-in for losing an optimistic race."""

    def __init__(self, telemetry, times=1):
        super().__init__(telemetry)
        self.times = times

    def bind(self, pod, node, assigned_chips=None, fence=None):
        if self.times > 0:
            self.times -= 1
            self.bind_conflicts["chip_claim"] = \
                self.bind_conflicts.get("chip_claim", 0) + 1
            raise BindConflictError(
                f"chip claim conflict on {node} (scripted)")
        super().bind(pod, node, assigned_chips, fence=fence)


def test_claim_conflict_retries_locally_without_backoff():
    clock = FakeClock()
    store = TelemetryStore()
    for i in range(2):
        m = make_tpu_node(f"n{i}", chips=4)
        m.heartbeat = 0.0
        store.put(m)
    cluster = _ScriptedConflictCluster(store, times=2)
    cluster.add_nodes_from_telemetry()
    sched = Scheduler(cluster, SchedulerConfig(telemetry_max_age_s=1e9),
                      clock=clock)
    pod = Pod("p", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    sched.submit(pod)
    outcomes = [sched.run_one(), sched.run_one(), sched.run_one()]
    # two conflict retries (attempt-free, no clock advance needed: the
    # requeue is immediate), then the bind lands
    assert outcomes[-1] == "bound" and pod.phase == PodPhase.BOUND
    c = sched.metrics.counters
    assert c["bind_conflicts_total"] == 2
    assert c["bind_conflict_retries_total"] == 2
    assert c.get("pods_unschedulable_total", 0) == 0  # no backoff burned
    assert c.get("breaker_opens_total", 0) == 0
    # the losing cycles leaked no reservation
    for n in cluster.node_names():
        assert not sched.allocator.pending_on(n)


def test_conflict_streak_falls_back_to_backoff():
    clock = FakeClock()
    store = TelemetryStore()
    m = make_tpu_node("n0", chips=4)
    m.heartbeat = 0.0
    store.put(m)
    cluster = _ScriptedConflictCluster(store, times=8)
    cluster.add_nodes_from_telemetry()
    sched = Scheduler(cluster, SchedulerConfig(telemetry_max_age_s=1e9),
                      clock=clock)
    pod = Pod("p", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    sched.submit(pod)
    spins = 0
    while pod.phase != PodPhase.BOUND:
        spins += 1
        assert spins < 50
        if sched.run_one() is None:
            w = sched.next_wake_at()
            assert w is not None
            clock.advance(max(w - clock.time(), 0.01))
    # the 8th straight conflict took the ordinary backoff path
    assert sched.metrics.counters["pods_unschedulable_total"] == 1
    assert sched.metrics.counters["bind_conflict_retries_total"] == 7


# ------------------------------------------------------ leases and fencing
def test_local_lease_store_epochs_and_expiry():
    clock = FakeClock()
    store = LocalLeaseStore(clock)
    e1 = store.try_acquire("L", "a", 10.0)
    assert e1 == 1
    assert store.try_acquire("L", "b", 10.0) is None  # live holder
    assert store.renew("L", "a", e1)
    clock.advance(11.0)
    assert not store.renew("L", "a", e1)  # expired: renew refused
    e2 = store.try_acquire("L", "b", 10.0)  # takeover bumps the epoch
    assert e2 == 2
    assert store.validate_fence(("L", "b", e2))
    assert not store.validate_fence(("L", "a", e1))  # history
    store.revoke("L")
    assert not store.validate_fence(("L", "b", e2))
    e3 = store.try_acquire("L", "a", 10.0)
    assert e3 > e2


def test_lease_loss_mid_cycle_aborts_commit_cleanly():
    """Fencing's engine half: the replica owned the shard at cycle start,
    the lease is revoked before commit — the bind aborts through the
    unwind path (no RPC, no reservation leak, attempt-free retry) and the
    pod still places on the next cycle, unfenced."""
    clock = FakeClock()
    _store, cluster = _rig()
    fleet = FleetCoordinator(cluster,
                             SchedulerConfig(telemetry_max_age_s=1e9),
                             replicas=2, clock=clock, seed=3)
    rng = random.Random(0)
    assert fleet.step(rng) is None  # acquires leases; queues are empty
    assert all(r.owned for r in fleet.replicas)
    for idx in range(2):
        fleet.revoke_replica_leases(idx)
    pods = _workload(1, n_tpu=6, n_gpu=0)
    for p in pods:
        fleet.submit(p)
    # next_renew is 0.5s out: cycles run BEFORE upkeep notices, so the
    # first fenced commit per replica hits FENCE_LOST
    fleet.run_until_idle(rng=rng)
    assert all(p.phase == PodPhase.BOUND for p in pods)
    stats = fleet.fleet_stats()
    assert stats["lease_lost_aborts_total"] >= 1
    assert stats["pods_scheduled_total"] == len(pods)
    for rep in fleet.replicas:
        for n in cluster.node_names():
            assert not rep.engine.allocator.pending_on(n)


def test_trust_owned_posture_stale_token_bounces_at_authority():
    """validate_fence_locally=False (the wire posture): a stolen lease
    leaves the replica's belief stale, its token travels to the
    AUTHORITY, bounces as a stale_fence 409, and the pod still converges
    through the ordinary conflict/backoff recovery."""
    clock = FakeClock()
    _store, cluster = _rig()
    fleet = FleetCoordinator(cluster,
                             SchedulerConfig(telemetry_max_age_s=1e9),
                             replicas=2, clock=clock, seed=4,
                             validate_fence_locally=False)
    rng = random.Random(0)
    assert fleet.step(rng) is None  # leases acquired, queues empty
    # split brain: every shard stolen out from under both replicas
    for rep in fleet.replicas:
        for s in list(rep.owned):
            fleet.lease_store.steal(f"{SHARD_LEASE_PREFIX}{s}", "phantom")
    pods = _workload(2, n_tpu=6, n_gpu=0)
    for p in pods:
        fleet.submit(p)
    fleet.run_until_idle(rng=rng)
    assert all(p.phase == PodPhase.BOUND for p in pods)
    assert cluster.bind_conflicts.get("stale_fence", 0) >= 1
    stats = fleet.fleet_stats()
    assert stats["lease_lost_aborts_total"] == 0  # never caught locally
    _seen = set()
    for node in cluster.node_names():
        for p in cluster.pods_on(node):
            assert p.key not in _seen
            _seen.add(p.key)


def test_shard_lease_manager_over_the_wire():
    """ShardLeaseManager against the real localhost fake apiserver:
    disjoint preferred sets yield disjoint ownership, fencing tokens
    validate, a dead manager's shards are taken over after expiry with a
    bumped epoch, and the old epoch's token goes stale."""
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fake_apiserver import FakeApiServer
    from yoda_scheduler_tpu.k8s.client import KubeClient
    from yoda_scheduler_tpu.k8s.leaderelect import ShardLeaseManager

    with FakeApiServer() as api:
        ca = KubeClient(api.url, max_retries=1, retry_backoff_s=0.05)
        cb = KubeClient(api.url, max_retries=1, retry_backoff_s=0.05)
        a = ShardLeaseManager(ca, 4, identity="a", preferred={0, 1},
                              lease_duration_s=1.0)
        b = ShardLeaseManager(cb, 4, identity="b", preferred={2, 3},
                              lease_duration_s=1.0)
        a.step()
        b.step()
        assert sorted(a.owned) == [0, 1]
        assert sorted(b.owned) == [2, 3]
        fence = a.fence(0)
        assert fence == (f"{SHARD_LEASE_PREFIX}0", "a", 1)
        assert b.validate_fence(fence)  # authority view is shared
        # a dies (stops renewing); past the 1s duration b takes over with
        # a bumped fencing epoch, and a's token is history
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and 0 not in b.owned:
            b.step()
            time.sleep(0.2)
        assert 0 in b.owned and 1 in b.owned
        assert b.owned[0] == 2  # transitions bumped on holder change
        assert not b.validate_fence(fence)
        assert b.fence(0) == (f"{SHARD_LEASE_PREFIX}0", "b", 2)


def test_fake_apiserver_rejects_stale_fence_on_binding():
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fake_apiserver import FakeApiServer
    from yoda_scheduler_tpu.k8s.client import ApiError, KubeClient
    from yoda_scheduler_tpu.k8s.leaderelect import ShardLeaseManager

    with FakeApiServer() as api:
        api.state.add_node("n0")
        client = KubeClient(api.url, max_retries=1, retry_backoff_s=0.05)
        mgr = ShardLeaseManager(client, 1, identity="a", preferred={0},
                                lease_duration_s=30.0)
        mgr.step()
        api.state.add_pod({"metadata": {"name": "p1",
                                        "namespace": "default"},
                           "spec": {}})
        api.state.add_pod({"metadata": {"name": "p2",
                                        "namespace": "default"},
                           "spec": {}})
        pod1 = Pod("p1")
        client.bind(pod1, "n0", [(0, 0, 0)], fence=mgr.fence(0))
        assert api.state.pod("p1")["spec"]["nodeName"] == "n0"
        # another manager steals the shard; the old epoch must bounce
        thief = ShardLeaseManager(KubeClient(api.url, max_retries=1),
                                  1, identity="b", preferred={0},
                                  lease_duration_s=30.0)
        lease = api.state.leases[f"{SHARD_LEASE_PREFIX}0"]
        lease["spec"]["renewTime"] = "2000-01-01T00:00:00.000000Z"
        thief.step()
        assert 0 in thief.owned
        pod2 = Pod("p2")
        with pytest.raises(ApiError) as ei:
            client.bind(pod2, "n0", [(1, 0, 0)], fence=("yoda-shard-0",
                                                        "a", 1))
        assert ei.value.status == 409
        assert api.state.pod("p2")["spec"].get("nodeName") is None


def test_fake_apiserver_rejects_foreign_chip_claim():
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fake_apiserver import FakeApiServer
    from yoda_scheduler_tpu.k8s.client import ApiError, KubeClient

    with FakeApiServer() as api:
        api.state.add_node("n0")
        for name in ("p1", "p2"):
            api.state.add_pod({"metadata": {"name": name,
                                            "namespace": "default"},
                               "spec": {}})
        client = KubeClient(api.url, max_retries=1, retry_backoff_s=0.05)
        client.bind(Pod("p1"), "n0", [(0, 0, 0), (1, 0, 0)])
        with pytest.raises(ApiError) as ei:
            client.bind(Pod("p2"), "n0", [(1, 0, 0)])
        assert ei.value.status == 409
        # disjoint claim on the same node is fine
        client.bind(Pod("p2"), "n0", [(2, 0, 0)])
        assert api.state.pod("p2")["spec"]["nodeName"] == "n0"


# ------------------------------------------------------------- fleet shape
def test_fleet_of_one_is_bit_identical_to_classic_engine():
    base_store, base_cluster = _rig()
    clock = FakeClock()
    sched = Scheduler(base_cluster, SchedulerConfig(telemetry_max_age_s=1e9),
                      clock=clock)
    base_pods = _workload(7)
    for p in base_pods:
        sched.submit(p)
    sched.run_until_idle()

    _store, cluster = _rig()
    fleet = FleetCoordinator(cluster,
                             SchedulerConfig(telemetry_max_age_s=1e9),
                             replicas=1, clock=FakeClock())
    pods = _workload(7)
    for p in pods:
        fleet.submit(p)
    fleet.run_until_idle()
    assert _placements(pods) == _placements(base_pods)
    assert fleet.fleet_stats()["bind_conflicts_total"] == 0


@pytest.mark.parametrize("mode", ["sharded", "free-for-all"])
def test_fleet_drains_and_partitions(mode):
    _store, cluster = _rig()
    fleet = FleetCoordinator(cluster,
                             SchedulerConfig(telemetry_max_age_s=1e9),
                             replicas=3, clock=FakeClock(), mode=mode,
                             seed=11)
    pods = _workload(3)
    for p in pods:
        fleet.submit(p)
    fleet.run_until_idle()
    assert all(p.phase == PodPhase.BOUND for p in pods)
    stats = fleet.fleet_stats()
    assert stats["pods_scheduled_total"] == len(pods)
    # work actually spread: no replica scheduled everything
    assert max(stats["per_replica_binds"]) < len(pods)
    if mode == "sharded":
        owned = [set(s) for s in stats["shards_owned"]]
        assert all(owned)  # every replica holds leases
        for i in range(3):
            for j in range(i + 1, 3):
                assert not (owned[i] & owned[j])  # disjoint ownership
    # no pod appears twice in the cluster book
    seen = {}
    for node in cluster.node_names():
        for p in cluster.pods_on(node):
            assert p.key not in seen
            seen[p.key] = node


def test_sharded_placement_prefers_owned_shards():
    _store, cluster = _rig(n_standalone=6)
    fleet = FleetCoordinator(cluster,
                             SchedulerConfig(telemetry_max_age_s=1e9),
                             replicas=2, clock=FakeClock(), seed=2)
    pods = _workload(5, n_tpu=10, n_gpu=0)
    for p in pods:
        fleet.submit(p)
    fleet.run_until_idle()
    assert all(p.phase == PodPhase.BOUND for p in pods)
    # every bind a replica committed landed on a node of a shard THAT
    # replica owned (capacity permitting — the workload is far under
    # capacity): the shard-affinity score actually partitions placement,
    # not just "someone owns every shard"
    checked = 0
    for rep in fleet.replicas:
        for t in rep.engine.traces.recent(100):
            if t.outcome == "bound" and t.node:
                checked += 1
                assert shard_of(t.node, fleet.shard_count) in rep.owned, (
                    rep.idx, t.node, sorted(rep.owned))
    assert checked == len(pods)


def test_free_for_all_routes_gangs_whole():
    """Round-robin intake must never shred a gang across replicas: each
    engine's GangPermit would park forever waiting for peers the other
    engine holds. Gangs ride their gang name in every mode."""
    store = TelemetryStore()
    for m in make_v4_slice("s0", "2x2x4"):
        m.heartbeat = 0.0
        store.put(m)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    fleet = FleetCoordinator(cluster,
                             SchedulerConfig(telemetry_max_age_s=1e9,
                                             gang_timeout_s=5.0),
                             replicas=2, clock=FakeClock(),
                             mode="free-for-all", seed=6)
    pods = [Pod(f"g{i}", labels={
        "tpu/accelerator": "tpu", "scv/number": "4",
        "tpu/gang-name": "gg", "tpu/gang-size": "2"}) for i in range(2)]
    for p in pods:
        fleet.submit(p)
    fleet.run_until_idle()
    assert all(p.phase == PodPhase.BOUND for p in pods)
    # both members were handled by ONE engine
    binds = fleet.fleet_stats()["per_replica_binds"]
    assert sorted(binds) == [0, 2]


def test_wire_same_node_foreign_win_not_adopted_as_ours():
    """KubeClient.bind's 409 recovery must not mistake a FOREIGN
    replica's same-node win for its own replay: the chip annotation
    discriminates, and the loser gets a 409 instead of overwriting the
    winner's assignment in its cache."""
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fake_apiserver import FakeApiServer
    from yoda_scheduler_tpu.k8s.client import ApiError, KubeClient

    with FakeApiServer() as api:
        api.state.add_node("n0")
        api.state.add_pod({"metadata": {"name": "p", "namespace":
                                        "default"}, "spec": {}})
        winner = KubeClient(api.url, max_retries=1, retry_backoff_s=0.05)
        loser = KubeClient(api.url, max_retries=1, retry_backoff_s=0.05)
        winner.bind(Pod("p"), "n0", [(0, 0, 0)])
        with pytest.raises(ApiError) as ei:
            loser.bind(Pod("p"), "n0", [(1, 0, 0)])
        assert ei.value.status == 409
        # the winner's assignment survives on the server
        ann = api.state.pod("p")["metadata"]["annotations"]
        assert ann["tpu/assigned-chips"] == "0,0,0"
        # a genuine same-payload replay (lost response) still adopts
        winner.bind(Pod("p"), "n0", [(0, 0, 0)])


def test_split_brain_duplicate_submission_single_bind():
    """The same pods queued on TWO replicas at once (duplicate-replica
    injection): exactly one bind lands per pod; the loser drops its entry
    through the foreign-bind path."""
    _store, cluster = _rig()
    fleet = FleetCoordinator(cluster,
                             SchedulerConfig(telemetry_max_age_s=1e9),
                             replicas=2, clock=FakeClock(), seed=9)
    pods = _workload(13, n_tpu=8, n_gpu=0)
    for p in pods:
        fleet.submit_to(0, p)
        fleet.submit_to(1, p)  # split brain: both replicas think they own it
    fleet.run_until_idle()
    assert all(p.phase == PodPhase.BOUND for p in pods)
    stats = fleet.fleet_stats()
    resolved = (stats["foreign_bind_skips_total"]
                + stats["foreign_bind_conflicts_total"])
    assert resolved == len(pods)  # every duplicate resolved exactly once
    seen = set()
    for node in cluster.node_names():
        for p in cluster.pods_on(node):
            assert p.key not in seen
            seen.add(p.key)
    assert len(seen) == len(pods)


# ------------------------------------------------- dynamic shard rebalancing
def test_local_lease_store_release_and_live():
    clock = FakeClock()
    store = LocalLeaseStore(clock)
    e1 = store.try_acquire("L", "a", 10.0)
    assert e1 == 1 and store.live("L")
    # wrong identity/epoch cannot release
    assert not store.release("L", "b", e1)
    assert not store.release("L", "a", e1 + 1)
    assert store.live("L")
    # a real release clears the holder, retires the epoch, and the lease
    # is immediately acquirable by anyone
    assert store.release("L", "a", e1)
    assert not store.live("L")
    assert not store.validate_fence(("L", "a", e1))
    e2 = store.try_acquire("L", "b", 10.0)
    assert e2 == e1 + 2  # release bumped once, takeover bumped again
    # expiry also reads not-live
    clock.advance(11.0)
    assert not store.live("L")


def test_shard_rebalance_releases_takeover_back_to_preferred():
    """PR 6's takeover was sticky: a dead replica's shards stayed with
    whoever took them over. With rebalancing, the survivor hands them
    back the moment the replacement's heartbeat is live again."""
    _store, cluster = _rig()
    clock = FakeClock()
    fleet = FleetCoordinator(
        cluster, SchedulerConfig(telemetry_max_age_s=1e9),
        replicas=2, clock=clock, mode="sharded", seed=3,
        lease_duration_s=2.0, renew_period_s=0.25, rebalance_s=1.0)
    rng = random.Random(3)
    for _ in range(4):
        fleet.step(rng)
        clock.advance(0.3)
    assert sorted(fleet.replicas[0].owned) == [0]
    assert sorted(fleet.replicas[1].owned) == [1]
    # replica 1 dies; its replacement exists but must wait out the old
    # heartbeat+leases, during which replica 0 takes the orphan over
    fleet.crash_replica(1)
    clock.advance(2.5)  # past the lease duration
    fleet.step(rng)
    assert 1 in fleet.replicas[0].owned, "survivor never took over"
    c0 = fleet.replicas[0].engine.metrics.counters
    assert c0.get("shard_takeovers_total", 0) >= 1
    # ... and hands it back once the replacement heartbeats
    deadline = clock.time() + 30.0
    while clock.time() < deadline:
        if sorted(fleet.replicas[1].owned) == [1] \
                and sorted(fleet.replicas[0].owned) == [0]:
            break
        fleet.step(rng)
        clock.advance(0.25)
    assert sorted(fleet.replicas[0].owned) == [0]
    assert sorted(fleet.replicas[1].owned) == [1]
    assert c0.get("shard_rebalance_releases_total", 0) >= 1
    flight_kinds = [e["kind"] for e in
                    fleet.replicas[0].engine.flight.snapshot()]
    assert "shard_takeover" in flight_kinds
    assert "shard_rebalance" in flight_kinds


def test_orphaned_absent_shard_claimed_after_grace():
    """A preferrer that dies before EVER creating its shard lease must
    not leave the shard permanently unowned: after one lease duration of
    observed absence, a survivor claims it."""
    _store, cluster = _rig()
    clock = FakeClock()
    fleet = FleetCoordinator(
        cluster, SchedulerConfig(telemetry_max_age_s=1e9),
        replicas=2, clock=clock, mode="sharded", seed=3,
        lease_duration_s=2.0, renew_period_s=0.25, rebalance_s=1.0)
    rep0 = fleet.replicas[0]
    # replica 1 NEVER steps (died pre-acquisition): drive only replica 0
    for _ in range(30):
        fleet._lease_step(rep0, clock.time())
        clock.advance(0.3)
        if sorted(rep0.owned) == [0, 1]:
            break
    assert sorted(rep0.owned) == [0, 1], (
        "orphaned absent shard was never claimed", sorted(rep0.owned))


def test_sticky_takeover_without_rebalance_knob():
    """rebalance_s=0 restores the PR 6 posture exactly: takeover
    ownership stays where it landed."""
    _store, cluster = _rig()
    clock = FakeClock()
    fleet = FleetCoordinator(
        cluster, SchedulerConfig(telemetry_max_age_s=1e9),
        replicas=2, clock=clock, mode="sharded", seed=3,
        lease_duration_s=2.0, renew_period_s=0.25, rebalance_s=0.0)
    rng = random.Random(3)
    for _ in range(4):
        fleet.step(rng)
        clock.advance(0.3)
    fleet.crash_replica(1)
    clock.advance(2.5)
    fleet.step(rng)
    assert 1 in fleet.replicas[0].owned
    for _ in range(40):
        fleet.step(rng)
        clock.advance(0.25)
    assert 1 in fleet.replicas[0].owned  # sticky, by explicit choice


def test_wire_shard_lease_manager_rebalances_over_real_http():
    """The wire twin: ShardLeaseManager heartbeats + releases through
    the real Lease API — a returning replica gets its shards back."""
    from fake_apiserver import FakeApiServer
    from yoda_scheduler_tpu.k8s.client import KubeClient
    from yoda_scheduler_tpu.k8s.leaderelect import ShardLeaseManager

    with FakeApiServer() as api:
        client = KubeClient(api.url, max_retries=1, retry_backoff_s=0.05)
        # preferred sets follow the s %% replica_count convention the
        # rebalancer keys handoffs on (same mapping FleetCoordinator uses)
        a = ShardLeaseManager(client, 4, identity="a",
                              preferred={0, 2}, lease_duration_s=1.0,
                              replica_count=2, replica_idx=0,
                              rebalance=True)
        b = ShardLeaseManager(client, 4, identity="b",
                              preferred={1, 3}, lease_duration_s=1.0,
                              replica_count=2, replica_idx=1,
                              rebalance=True)
        a.step()
        b.step()
        assert sorted(a.owned) == [0, 2]
        assert sorted(b.owned) == [1, 3]
        # a dies: b takes its expired shards over (a's heartbeat expires
        # on the same horizon, so the handoff gate opens)
        time.sleep(1.2)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and sorted(b.owned) != [0, 1, 2, 3]:
            b.step()
            time.sleep(0.1)
        assert sorted(b.owned) == [0, 1, 2, 3]
        assert b.takeovers >= 2
        # a returns: its heartbeat revives, b releases, a re-acquires
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not (
                sorted(a.owned) == [0, 2]
                and sorted(b.owned) == [1, 3]):
            a.step()
            b.step()
            time.sleep(0.1)
        assert sorted(a.owned) == [0, 2]
        assert sorted(b.owned) == [1, 3]
        assert b.rebalance_releases >= 2
