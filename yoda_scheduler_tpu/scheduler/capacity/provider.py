"""Capacity provider contract: how nodes enter and leave the fleet.

The provisioner control loop (provisioner.py) never touches the cluster
backend directly — every node add/remove goes through a *provider* (the
cloud API analogue) speaking three verbs:

- ``request(pool, template, now) -> ProvisionRequest``: ask for one
  node of a pool's shape (the PROVIDER assigns the request id, so two
  fleet replicas sharing a provider can never collide). Asynchronous by
  nature: providers take seconds-to-minutes, answer out of order, deny
  (stockout / quota), or lose the response entirely.
- ``poll(now)``: completed results since the last poll, on the engine's
  injectable clock. A result references its request id; an arriving node
  that matches NO live request (the request was written off, or another
  fleet replica issued it before crashing) is the ADOPTION case the
  provisioner reconciles by membership, never by response.
- ``release(node, pool)``: return an (empty) node to the provider.

The only production-shaped implementation today is chaos.py's
``SimulatedProvider`` (seeded latency + the four provider fault kinds);
it composes with the two *backend adapters* here, which own the
mechanics of making a node real:

- ``FakeBackend``: in-memory FakeCluster — telemetry put + node-meta
  set, publishing NODE_ADDED through the ordinary subscribe surface.
- ``WireBackend``: a real/fake apiserver via KubeClient — the node
  object and its TpuNodeMetrics CR are POSTed and the REFLECTOR brings
  them back through the same watch intake every other node uses, so
  columnar shard rebuilds and NODE_ADDED queue hints fire for free.

Either way, a provisioned node is indistinguishable from a hand-added
one by the time the scheduler sees it — the provisioner's whole state
about it is the two node labels below.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# node labels stamped on every provisioned node: the pool it belongs to
# and the managed marker membership reconciliation keys on. Pod-side
# scv/* labels are workload contract; these two live on NODE objects.
POOL_LABEL = "scv/pool"
MANAGED_LABEL = "scv/provisioned"


@dataclass(frozen=True)
class NodeTemplate:
    """The shape one pool provisions: every node the provider creates
    for the pool is a clone of this. ``pool`` doubles as the node-name
    prefix (names are ``<pool>-<seq>``, so columnar.pool_of groups them
    with hand-built members of the same pool).

    ``hosts`` > 1 makes this a SLICE pool: one capacity request
    provisions a whole multi-host ICI slice (``slice_topology``
    required, validated against the generation catalog) — the unit TPU
    clouds actually sell, and the only thing that can satisfy a parked
    gang (gangs pin to one slice). Slice pools serve gang demand;
    single-host pools serve everything else."""

    pool: str
    generation: str = "v4"
    chips: int = 4
    accelerator: str = "tpu"
    hbm_mb: int | None = None      # per-chip override; None = catalog
    clock_mhz: int | None = None
    min_nodes: int = 0
    max_nodes: int = 64
    hosts: int = 1
    slice_topology: str | None = None

    def satisfies(self, spec) -> bool:
        """Can ONE provisioning unit of this shape host a pod of
        `spec`? The demand router uses this to map an unschedulable
        shape onto a pool. Gang members route to slice pools whose
        host count covers the gang; everything else routes to
        single-host pools."""
        if spec.is_gang:
            if self.hosts < max(spec.gang_size, 2):
                return False
        elif self.hosts > 1:
            return False  # whole slices are never provisioned for singles
        if spec.accelerator is not None \
                and spec.accelerator != self.accelerator:
            return False
        if spec.tpu_generation is not None \
                and spec.tpu_generation != self.generation:
            return False
        if spec.chips > self.chips:
            return False
        if spec.topology is not None:
            from ...topology.torus import parse_topology

            dims = parse_topology(spec.topology)
            vol = 1
            for d in dims:
                vol *= d
            if vol > self.chips:
                return False
        if spec.min_free_mb or spec.min_clock_mhz:
            from ...topology.generations import generation as gen_of

            cat = gen_of(self.generation)
            hbm = self.hbm_mb if self.hbm_mb is not None else cat.hbm_mb
            clock = (self.clock_mhz if self.clock_mhz is not None
                     else cat.clock_mhz)
            if spec.min_free_mb > hbm or spec.min_clock_mhz > clock:
                return False
        return True


@dataclass
class ProvisionRequest:
    id: int
    pool: str
    template: NodeTemplate
    requested_at: float


@dataclass
class ProvisionResult:
    request_id: int
    pool: str
    outcome: str                  # "ready" | "stockout" | "quota-denied"
    node: str | None = None       # primary name when outcome == "ready"
    nodes: tuple = ()             # every host (== (node,) for hosts=1)
    detail: str = ""


def build_metrics(template: NodeTemplate, name: str, now: float) -> list:
    """TpuNodeMetrics for one freshly provisioned unit of this shape —
    a single standalone host, or every host of one slice (hosts > 1)."""
    from ...telemetry.fake import make_gpu_node, make_slice, make_tpu_node

    if template.hosts > 1:
        if not template.slice_topology:
            raise ValueError(
                f"pool {template.pool}: hosts={template.hosts} needs a "
                "slice_topology")
        out = make_slice(name, template.slice_topology,
                         generation=template.generation,
                         hbm_free_mb=template.hbm_mb)
        for m in out:
            m.heartbeat = now
        return out
    if template.accelerator == "gpu":
        m = make_gpu_node(name, cards=template.chips)
    else:
        m = make_tpu_node(
            name, chips=template.chips,
            generation=template.generation,
            hbm_total_mb=template.hbm_mb,
            clock_mhz=template.clock_mhz)
    m.heartbeat = now
    return [m]


class FakeBackend:
    """Node add/remove against the in-memory FakeCluster family:
    telemetry first (so the node is schedulable the instant NODE_ADDED
    fires), then node meta carrying the pool/managed labels. Removal
    routes any orphaned pods back through ``orphan_router`` (the engine
    or fleet submit) so a yanked node never loses a pod."""

    def __init__(self, cluster, orphan_router=None) -> None:
        self.cluster = cluster
        self.orphan_router = orphan_router

    def create(self, name: str, template: NodeTemplate,
               now: float) -> list[str]:
        names = []
        for m in build_metrics(template, name, now):
            self.cluster.telemetry.put(m)
            self.cluster.set_node_meta(
                m.node,
                labels={POOL_LABEL: template.pool, MANAGED_LABEL: "1"})
            names.append(m.node)
        return names

    def destroy(self, name: str) -> list:
        orphans = self.cluster.remove_node(name)
        self.cluster.telemetry.delete(name)
        if self.orphan_router is not None:
            for p in orphans:
                p.labels.pop("tpu/assigned-chips", None)
                self.orphan_router(p)
        return orphans

    def heartbeat(self, name: str, now: float) -> None:
        """Refresh a provisioned node's telemetry heartbeat (the fake
        backend has no sniffer DaemonSet to do it)."""
        m = self.cluster.telemetry.get(name)
        if m is not None:
            m.heartbeat = now
            self.cluster.telemetry.put(m)


class WireBackend:
    """Node add/remove over the apiserver wire (KubeClient): POST the
    node object + its TpuNodeMetrics CR, DELETE both on destroy. The
    scheduler never sees these writes directly — its reflector watch
    delivers them through the ordinary intake (the whole point of the
    wire path: provisioned nodes exercise the same change-log/columnar/
    queue-hint machinery as any other membership change)."""

    def __init__(self, client) -> None:
        from ...telemetry.publisher import CrPublisher

        self.client = client
        self._publisher = CrPublisher(client)

    def create(self, name: str, template: NodeTemplate,
               now: float) -> list[str]:
        names = []
        for m in build_metrics(template, name, now):
            self.client.create_node(
                m.node,
                labels={POOL_LABEL: template.pool, MANAGED_LABEL: "1"})
            self._publisher.publish(m)
            names.append(m.node)
        return names

    def destroy(self, name: str) -> list:
        # apiserver semantics: pods on a deleted node are the node
        # controller's problem (they go Pending and re-enter through
        # the pod watch) — no local orphan routing
        self.client.delete_node(name)
        try:
            from ...k8s.client import METRICS_PATH

            self.client.request("DELETE", f"{METRICS_PATH}/{name}")
        except Exception:
            pass  # CR cleanup is best-effort; a stale CR ages out
        return []

    def heartbeat(self, name: str, now: float) -> None:
        return None  # a real fleet's sniffer owns wire heartbeats
