"""Concurrency stress (SURVEY §5 race-detection row): the scheduler loop
runs in one thread while submitters and the telemetry publisher hammer it
from others — the in-Python equivalent of `go test -race` over the
fake-store cycle tests. Invariants: every pod resolves, no chip is ever
double-booked, caches stay coherent under concurrent mutation."""

import threading
import time

from yoda_scheduler_tpu.scheduler import (
    FakeCluster, MultiProfileScheduler, Scheduler, SchedulerConfig)
from yoda_scheduler_tpu.telemetry import TelemetryStore, make_tpu_node
from yoda_scheduler_tpu.utils import Pod, PodPhase

NODES = 8
CHIPS = 4
PODS = 60


def _mk_cluster():
    store = TelemetryStore()
    now = time.time()
    for i in range(NODES):
        n = make_tpu_node(f"n{i}", chips=CHIPS)
        n.heartbeat = now
        store.put(n)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    return cluster, store


def _drive(run_one, stop):
    while not stop.is_set():
        if run_one() is None:
            time.sleep(0.0005)


def _heartbeat(store, stop):
    while not stop.is_set():
        for m in store.list():
            m.heartbeat = time.time()
            store.put(m)
        time.sleep(0.002)


def _assert_no_double_booking(pods):
    claims = []
    for p in pods:
        if p.phase == PodPhase.BOUND and "tpu/assigned-chips" in p.labels:
            for c in p.labels["tpu/assigned-chips"].split(";"):
                claims.append((p.node, c))
    assert len(claims) == len(set(claims)), "chip double-booked under races"


def test_concurrent_submit_telemetry_and_scheduling():
    cluster, store = _mk_cluster()
    sched = Scheduler(cluster, SchedulerConfig(max_attempts=4,
                                               telemetry_max_age_s=3600))
    stop = threading.Event()
    threads = [
        threading.Thread(target=_drive, args=(sched.run_one, stop)),
        threading.Thread(target=_heartbeat, args=(store, stop)),
    ]
    pods = [Pod(f"p{i}", labels={"scv/number": "1", "scv/memory": "100"})
            for i in range(PODS)]

    def submit(chunk):
        for p in chunk:
            sched.submit(p)
            time.sleep(0.0002)

    for i in range(4):
        threads.append(threading.Thread(target=submit,
                                        args=(pods[i::4],)))
    for t in threads:
        t.start()
    deadline = time.time() + 30
    try:
        while time.time() < deadline:
            if all(p.phase in (PodPhase.BOUND, PodPhase.FAILED)
                   for p in pods):
                break
            time.sleep(0.01)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
    resolved = sum(p.phase in (PodPhase.BOUND, PodPhase.FAILED)
                   for p in pods)
    assert resolved == PODS, f"only {resolved}/{PODS} pods resolved"
    # 32 chips, 60 one-chip pods: exactly 32 bind, the rest exhaust retries
    assert sum(p.phase == PodPhase.BOUND for p in pods) == NODES * CHIPS
    _assert_no_double_booking(pods)


def test_concurrent_multi_profile_engines():
    cluster, store = _mk_cluster()
    sched = MultiProfileScheduler(cluster, [
        (SchedulerConfig(max_attempts=4, telemetry_max_age_s=3600), None),
        (SchedulerConfig(scheduler_name="yoda-scheduler2", max_attempts=4,
                         telemetry_max_age_s=3600), None),
    ])
    stop = threading.Event()
    # each engine driven by its OWN thread: the shared allocator/gang state
    # is what the races exercise
    threads = [threading.Thread(target=_drive, args=(e.run_one, stop))
               for e in sched.engines.values()]
    threads.append(threading.Thread(target=_heartbeat, args=(store, stop)))
    names = ["yoda-scheduler", "yoda-scheduler2"]
    pods = [Pod(f"p{i}", labels={"scv/number": "1"},
                scheduler_name=names[i % 2]) for i in range(PODS)]

    def submit(chunk):
        for p in chunk:
            sched.submit(p)

    threads.append(threading.Thread(target=submit, args=(pods,)))
    for t in threads:
        t.start()
    deadline = time.time() + 30
    try:
        while time.time() < deadline:
            if all(p.phase in (PodPhase.BOUND, PodPhase.FAILED)
                   for p in pods):
                break
            time.sleep(0.01)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
    assert all(p.phase in (PodPhase.BOUND, PodPhase.FAILED) for p in pods)
    assert sum(p.phase == PodPhase.BOUND for p in pods) == NODES * CHIPS
    _assert_no_double_booking(pods)


def test_anti_affinity_invariant_under_concurrent_submit():
    """Anti-affinity replicas submitted from several threads while the
    scheduler loop runs and node meta mutates: at most one replica per
    host, every pod resolves, no stale cached verdict slips a second
    replica onto a host (the memo self-disables while anti-affinity pods
    are bound)."""
    cluster, store = _mk_cluster()
    for i in range(NODES):
        cluster.set_node_meta(f"n{i}",
                              labels={"kubernetes.io/hostname": f"n{i}"})
    sched = Scheduler(cluster, SchedulerConfig(telemetry_max_age_s=1e9,
                                               max_attempts=3,
                                               preemption=False))
    stop = threading.Event()
    driver = threading.Thread(target=_drive, args=(sched.run_one, stop))
    hb = threading.Thread(target=_heartbeat, args=(store, stop))

    def churn_meta():
        # concurrent label edits on an IRRELEVANT key: each one bumps the
        # node's change counter, hammering the NodeInfo cache + memo
        # invalidation paths the anti-affinity verdicts depend on
        i = 0
        while not stop.is_set():
            cluster.set_node_meta(
                f"n{i % NODES}",
                labels={"kubernetes.io/hostname": f"n{i % NODES}",
                        "churn": str(i)})
            i += 1
            time.sleep(0.002)

    meta = threading.Thread(target=churn_meta)
    driver.start()
    hb.start()
    meta.start()

    ANTI = {"podAntiAffinity": {
        "requiredDuringSchedulingIgnoredDuringExecution": [
            {"labelSelector": {"matchLabels": {"app": "spread"}},
             "topologyKey": "kubernetes.io/hostname"}]}}
    pods = []

    def submit(start):
        for i in range(start, start + 6):
            p = Pod.from_manifest({
                "metadata": {"name": f"r{i}",
                             "labels": {"scv/number": "1",
                                        "app": "spread"}},
                "spec": {"schedulerName": "yoda-scheduler",
                         "affinity": ANTI}})
            pods.append(p)
            sched.submit(p)
            time.sleep(0.001)

    try:
        subs = [threading.Thread(target=submit, args=(s,)) for s in (0, 6)]
        for t in subs:
            t.start()
        for t in subs:
            t.join()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(p.phase != PodPhase.PENDING for p in pods):
                break
            time.sleep(0.02)
    finally:
        stop.set()
        driver.join(timeout=5)
        hb.join(timeout=5)
        meta.join(timeout=5)
    # every pod RESOLVES: 8 bind (one per host), the 4 excess fail after
    # max_attempts — a pod stuck PENDING means the invariant broke
    assert all(p.phase != PodPhase.PENDING for p in pods), \
        f"unresolved: {[(p.name, p.phase) for p in pods]}"
    bound = [p for p in pods if p.phase == PodPhase.BOUND]
    assert len(bound) == NODES, \
        f"{len(bound)} bound of {len(pods)} ({[p.phase for p in pods]})"
    assert sum(p.phase == PodPhase.FAILED for p in pods) == len(pods) - NODES
    hosts = [p.node for p in bound]
    assert len(set(hosts)) == len(hosts), f"double-placed: {hosts}"
