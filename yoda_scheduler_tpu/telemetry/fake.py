"""Fake telemetry publisher — synthetic TpuNodeMetrics for tests and benches.

The reference has no test fixtures of any kind (zero *_test.go files); its
telemetry comes only from a live NVML sniffer DaemonSet. This module is the
well-specified fake that SURVEY.md §5 calls for: it can build single-host TPU
nodes, multi-host v4-style pod slices with real ICI coordinates, GPU nodes for
the mixed-cluster scenario, and inject faults (stale heartbeats, unhealthy
chips, missing telemetry) to test the failure-detection path.
"""

from __future__ import annotations

import copy
import random
import threading
import time

from .schema import Chip, TpuNodeMetrics, GPU, TPU, HEALTHY
from .store import TelemetryStore
from ..topology.generations import generation as tpu_generation
from ..topology.torus import host_blocks

# v4 chip defaults, kept as module constants for existing callers
# (canonical per-generation numbers live in topology/generations.py).
V4_HBM_MB = 32_768
V4_CLOCK_MHZ = 940
V4_ICI_GBPS = 100
V4_MXUS = 4
V4_POWER_W = 170


def make_tpu_node(
    name: str,
    chips: int = 4,
    hbm_free_mb: int | None = None,
    hbm_total_mb: int | None = None,
    clock_mhz: int | None = None,
    unhealthy: int = 0,
    generation: str = "v4",
    **kw,
) -> TpuNodeMetrics:
    """A standalone single-host TPU node (e.g. one v4-8 host: 4 chips).
    Chip attributes default to the generation's catalog entry; explicit
    keyword values override per-field."""
    gen = tpu_generation(generation)
    total = hbm_total_mb if hbm_total_mb is not None else gen.hbm_mb
    free = hbm_free_mb if hbm_free_mb is not None else total
    chip_list = [
        Chip(
            index=i,
            hbm_free_mb=free,
            hbm_total_mb=total,
            clock_mhz=clock_mhz if clock_mhz is not None else gen.clock_mhz,
            ici_bandwidth_gbps=gen.ici_gbps,
            core_count=gen.mxus,
            power_w=gen.power_w,
            coords=(i % 2, i // 2, 0),
            health=("Unhealthy" if i < unhealthy else HEALTHY),
        )
        for i in range(chips)
    ]
    return TpuNodeMetrics(node=name, chips=chip_list, accelerator=TPU,
                          tpu_generation=gen.name, **kw)


def make_gpu_node(
    name: str,
    cards: int = 8,
    mem_free_mb: int = 40_000,
    mem_total_mb: int = 40_000,
    clock_mhz: int = 1410,
    **kw,
) -> TpuNodeMetrics:
    """A GPU node for the mixed-cluster scenario (BASELINE config #5); the
    schema is accelerator-agnostic, only `accelerator` differs."""
    chip_list = [
        Chip(
            index=i,
            hbm_free_mb=mem_free_mb,
            hbm_total_mb=mem_total_mb,
            clock_mhz=clock_mhz,
            ici_bandwidth_gbps=64,  # NVLink-ish
            core_count=108,
            power_w=400,
            coords=(i, 0, 0),
        )
        for i in range(cards)
    ]
    return TpuNodeMetrics(node=name, chips=chip_list, accelerator=GPU, **kw)


def make_slice(
    slice_id: str,
    slice_topology: str,
    generation: str = "v4",
    node_prefix: str | None = None,
    hbm_free_mb: int | None = None,
) -> list[TpuNodeMetrics]:
    """A multi-host pod slice of any generation: one node per host, chips
    carrying real ICI coordinates that tile the slice torus.

    Packaging follows the generation catalog: v4/v5p hosts contribute a
    2x2x1 block of 4 chips to a 3-D torus (a v4-32 slice is 2x2x4 = 16 chips
    over 4 hosts); v5e/v6e hosts contribute a 2x4 block of 8 chips to a 2-D
    torus (a v5e-256 slice is 16x16 over 32 hosts). The topology string is
    validated against what the generation can form.
    """
    gen = tpu_generation(generation)
    shape = gen.validate_slice_topology(slice_topology)
    prefix = node_prefix or slice_id
    nodes: list[TpuNodeMetrics] = []
    blocks = host_blocks(shape, gen.host_block)
    for host_index, coords_block in enumerate(blocks):
        chips = [
            Chip(
                index=i,
                hbm_free_mb=hbm_free_mb if hbm_free_mb is not None else gen.hbm_mb,
                hbm_total_mb=gen.hbm_mb,
                clock_mhz=gen.clock_mhz,
                ici_bandwidth_gbps=gen.ici_gbps,
                core_count=gen.mxus,
                power_w=gen.power_w,
                coords=coords,
            )
            for i, coords in enumerate(coords_block)
        ]
        nodes.append(
            TpuNodeMetrics(
                node=f"{prefix}-host-{host_index}",
                chips=chips,
                accelerator=TPU,
                tpu_generation=gen.name,
                slice_id=slice_id,
                topology="x".join(str(d) for d in gen.host_block),
                slice_topology=slice_topology,
                host_index=host_index,
                num_hosts=len(blocks),
            )
        )
    return nodes


def make_v4_slice(
    slice_id: str,
    slice_topology: str = "2x2x4",
    node_prefix: str | None = None,
    hbm_free_mb: int = V4_HBM_MB,
) -> list[TpuNodeMetrics]:
    """A multi-host v4 pod slice (kept for existing callers; see make_slice)."""
    return make_slice(slice_id, slice_topology, generation="v4",
                      node_prefix=node_prefix, hbm_free_mb=hbm_free_mb)


class FakePublisher:
    """Continuously (or on demand) publishes synthetic telemetry to a store,
    with fault-injection hooks. Stands in for the per-node sniffer DaemonSet."""

    def __init__(self, store: TelemetryStore, seed: int = 0) -> None:
        self.store = store
        self.rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._frozen: set[str] = set()  # nodes whose heartbeat we stop (stale)

    # ----------------------------------------------------------- one-shot API
    def publish(self, *nodes: TpuNodeMetrics) -> None:
        for n in nodes:
            n.heartbeat = time.time()
            self.store.put(n)

    # -------------------------------------------------------- fault injection
    def freeze(self, node: str) -> None:
        """Stop heartbeating a node — its telemetry goes stale."""
        self._frozen.add(node)

    def unfreeze(self, node: str) -> None:
        self._frozen.discard(node)

    def set_duty(self, node: str, pct: float) -> None:
        """Report a measured MXU duty cycle on every chip of a node (a
        noisy-neighbour / busy-chip scenario for utilisation-aware scoring)."""
        m = self.store.get(node)
        if m is None:
            raise KeyError(node)
        m = copy.deepcopy(m)
        for c in m.chips:
            c.duty_cycle_pct = pct
        self.publish(m)

    def fail_chip(self, node: str, chip_index: int, health: str = "Unhealthy") -> None:
        m = self.store.get(node)
        if m is None:
            raise KeyError(node)
        # publish a mutated COPY: the store-held object may be mid-read by the
        # scheduler thread, and its aggregate memos key on generation — an
        # in-place edit would be a torn read pinned until the next publish
        m = copy.deepcopy(m)
        m.chips[chip_index].health = health
        self.publish(m)

    def drop(self, node: str) -> None:
        """Remove a node's telemetry entirely (sniffer crash)."""
        self.store.delete(node)

    # ------------------------------------------------------------- background
    def start(self, interval_s: float = 1.0, jitter_hbm_mb: int = 0) -> None:
        def loop() -> None:
            while not self._stop.wait(interval_s):
                for m in self.store.list():
                    if m.node in self._frozen:
                        continue
                    # snapshot semantics (a real sniffer builds a fresh reading
                    # each poll): never mutate the store-held object in place
                    m = copy.deepcopy(m)
                    if jitter_hbm_mb:
                        for c in m.chips:
                            delta = self.rng.randint(-jitter_hbm_mb, jitter_hbm_mb)
                            c.hbm_free_mb = max(0, min(c.hbm_total_mb, c.hbm_free_mb + delta))
                    self.publish(m)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
