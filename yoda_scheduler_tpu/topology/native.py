"""ctypes bridge to the native placement engine (native/placement.cc).

Loads ``libyodaplace.so`` through the shared hardened loader
(utils/nativeloader.py — one dlopen serves this kernel and the fused
scheduling kernel, each resolving its OWN symbol set so a stale library
degrades per kernel, never process-wide) and exposes drop-in twins of
the torus placement functions. torus.py routes through here
automatically when the library is available; the pure-Python
implementation remains the reference and the fallback.
"""

from __future__ import annotations

import ctypes
import os
from functools import lru_cache

from ..utils import nativeloader


@lru_cache(maxsize=1)
def _lib():
    return nativeloader.bind_symbols({
        "yoda_best_fit": (ctypes.c_int, None),
        "yoda_fits_shape": (ctypes.c_int, None),
        "yoda_largest_free_block": (ctypes.c_int, None),
        "yoda_contiguity": (ctypes.c_double, None),
    })


def available() -> bool:
    return _lib() is not None and os.environ.get("YODA_NO_NATIVE") != "1"


def _pack(shape, free):
    grid = (ctypes.c_int32 * 3)(*shape)
    flat = (ctypes.c_int32 * (3 * len(free)))()
    for i, (x, y, z) in enumerate(free):
        flat[3 * i], flat[3 * i + 1], flat[3 * i + 2] = x, y, z
    return grid, flat, len(free)


def _coords_of(origin, block):
    ox, oy, oz = origin
    bx, by, bz = block
    return frozenset(
        (ox + dx, oy + dy, oz + dz)
        for dx in range(bx) for dy in range(by) for dz in range(bz)
    )


def best_fit_block(shape, free, n_chips):
    grid, flat, n = _pack(shape, free)
    origin = (ctypes.c_int32 * 3)()
    block = (ctypes.c_int32 * 3)()
    rc = _lib().yoda_best_fit(grid, flat, n, n_chips, origin, block)
    if rc <= 0:
        return None if rc == 0 else NotImplemented
    o, b = tuple(origin), tuple(block)
    return o, b, _coords_of(o, b)


def fits_shape(shape, free, req_shape):
    grid, flat, n = _pack(shape, free)
    req = (ctypes.c_int32 * 3)(*req_shape)
    origin = (ctypes.c_int32 * 3)()
    block = (ctypes.c_int32 * 3)()
    rc = _lib().yoda_fits_shape(grid, flat, n, req, origin, block)
    if rc <= 0:
        return None if rc == 0 else NotImplemented
    o, b = tuple(origin), tuple(block)
    return o, b, _coords_of(o, b)


def largest_free_block(shape, free):
    grid, flat, n = _pack(shape, free)
    rc = _lib().yoda_largest_free_block(grid, flat, n)
    return NotImplemented if rc < 0 else rc


def contiguity_score(shape, free, n_chips):
    grid, flat, n = _pack(shape, free)
    v = _lib().yoda_contiguity(grid, flat, n, n_chips)
    return NotImplemented if v < 0 else v
