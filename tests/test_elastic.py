"""Elastic gangs + active defragmentation (scheduler/elastic/, ISSUE 10).

Covers the tentpole surfaces — tpu/gang-min admission-at-min, event-woken
growth, scv/deadline-seconds SLO pressure, shrink-to-min preemption, the
defrag controller's closed loop with its safety rails and fleet
ownership — plus the satellites: off-by-default parity, the gang-fail
quota-claim retirement regression, and the new metrics' exposition
round-trip through prometheus_client's reference parser.
"""

import random

import pytest

from yoda_scheduler_tpu.scheduler import (
    FakeCluster, FleetCoordinator, Scheduler, SchedulerConfig)
from yoda_scheduler_tpu.scheduler.core import FakeClock, HybridClock, default_profile
from yoda_scheduler_tpu.telemetry import (
    TelemetryStore, make_tpu_node, make_v4_slice)
from yoda_scheduler_tpu.utils import Pod, PodPhase
from yoda_scheduler_tpu.utils.labels import LabelError, WorkloadSpec, spec_for


def mk_sched(nodes, config=None, start=1000.0):
    store = TelemetryStore()
    clock = FakeClock(start=start)
    for n in nodes:
        n.heartbeat = clock.time()
        store.put(n)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    return Scheduler(cluster, config or SchedulerConfig(), clock=clock), clock


def refresh(sched):
    for m in sched.cluster.telemetry.list():
        m.heartbeat = sched.clock.time()


def elastic_gang(name, size, gmin, chips=4, prio=0, deadline=None,
                 extra=None):
    pods = []
    for i in range(size):
        labels = {
            "tpu/gang-name": name,
            "tpu/gang-size": str(size),
            "tpu/gang-min": str(gmin),
            "scv/number": str(chips),
            "scv/priority": str(prio),
        }
        if deadline is not None:
            labels["scv/deadline-seconds"] = str(deadline)
        if extra:
            labels.update(extra)
        pods.append(Pod(f"{name}-w{i}", labels=labels))
    return pods


def blocker(name, chips=4, prio=0):
    return Pod(name, labels={"scv/number": str(chips),
                             "tpu/accelerator": "tpu",
                             "scv/priority": str(prio)})


def drive(sched, clock, n=40, tick=0.5):
    for _ in range(n):
        refresh(sched)
        while sched.run_one() is not None:
            pass
        clock.advance(tick)


ELASTIC = SchedulerConfig(elastic_gangs=True)


# ---------------------------------------------------------------- labels
class TestGangMinLabels:
    def test_parses_min_and_deadline(self):
        spec = spec_for(Pod("p", labels={
            "tpu/gang-name": "g", "tpu/gang-size": "4",
            "tpu/gang-min": "2", "scv/deadline-seconds": "120"}))
        assert spec.gang_min == 2 and spec.deadline_s == 120

    def test_min_requires_gang(self):
        with pytest.raises(LabelError):
            spec_for(Pod("p", labels={"tpu/gang-min": "2"}))

    def test_min_must_not_exceed_size(self):
        with pytest.raises(LabelError):
            spec_for(Pod("p", labels={
                "tpu/gang-name": "g", "tpu/gang-size": "2",
                "tpu/gang-min": "3"}))

    def test_defaults_are_zero(self):
        spec = spec_for(Pod("p", labels={"scv/number": "1"}))
        assert spec.gang_min == 0 and spec.deadline_s == 0

    def test_min_rides_the_spec_class(self):
        """Two gangs differing only in tpu/gang-min must not share a
        WorkloadSpec (the memo/batch-key soundness audit)."""
        a = spec_for(Pod("a", labels={"tpu/gang-name": "g",
                                      "tpu/gang-size": "4",
                                      "tpu/gang-min": "2"}))
        b = spec_for(Pod("b", labels={"tpu/gang-name": "g",
                                      "tpu/gang-size": "4"}))
        assert a != b and hash(a) != hash(b)


# ------------------------------------------------------ admission at min
class TestAdmitAtMin:
    def _fragmented(self, config=None):
        """4-host slice with 2 hosts fully occupied by equal-priority
        singles (preemption cannot cure) + a spare standalone node."""
        nodes = make_v4_slice("s", "2x2x4") + [make_tpu_node("lone", chips=4)]
        sched, clock = mk_sched(nodes, config or ELASTIC.with_(
            gang_timeout_s=30.0))
        blockers = [blocker(f"b{i}") for i in range(2)]
        for b in blockers:
            sched.submit(b)
        drive(sched, clock, n=6)
        occupied = {b.node for b in blockers if b.node}
        assert len([n for n in occupied if n.startswith("s-host-")]) >= 1
        return sched, clock, blockers

    def test_gang_binds_at_min_when_full_does_not_fit(self):
        nodes = make_v4_slice("s", "2x2x4")
        sched, clock = mk_sched(nodes, ELASTIC)
        # occupy 2 of the 4 hosts with equal-priority singles
        occupants = [blocker("b0"), blocker("b1")]
        for b in occupants:
            sched.submit(b)
        drive(sched, clock, n=4)
        assert sum(1 for b in occupants
                   if b.node and b.node.startswith("s-host-")) == 2
        workers = elastic_gang("job", 4, 2)
        for w in workers:
            sched.submit(w)
        drive(sched, clock, n=8)
        bound = [w for w in workers if w.phase == PodPhase.BOUND]
        assert len(bound) == 2, [w.phase for w in workers]
        assert sched.metrics.labeled_counter(
            "gang_elastic_admissions_total", {"reason": "no-fit"}) == 1
        # the unplaced members are parked, not failed, and not waiting
        # at Permit (they are growth members in the queue)
        assert not sched.waiting
        assert all(w.phase == PodPhase.PENDING
                   for w in workers if w not in bound)

    def test_gang_grows_as_chips_free(self):
        nodes = make_v4_slice("s", "2x2x4")
        sched, clock = mk_sched(nodes, ELASTIC)
        occupants = [blocker("b0"), blocker("b1")]
        for b in occupants:
            sched.submit(b)
        drive(sched, clock, n=4)
        workers = elastic_gang("job", 4, 2)
        for w in workers:
            sched.submit(w)
        drive(sched, clock, n=8)
        assert sum(w.phase == PodPhase.BOUND for w in workers) == 2
        # capacity frees: each departure wakes a growth member
        sched.cluster.evict(occupants[0])
        drive(sched, clock, n=8)
        assert sum(w.phase == PodPhase.BOUND for w in workers) == 3
        sched.cluster.evict(occupants[1])
        drive(sched, clock, n=8)
        assert all(w.phase == PodPhase.BOUND for w in workers)
        assert sched.metrics.counters.get("gang_grow_total", 0) == 2
        assert sched.metrics.counters.get(
            "gang_elastic_completions_total", 0) == 1
        # all four members share the slice
        assert len({w.node for w in workers}) == 4
        assert all(w.node.startswith("s-host-") for w in workers)

    def test_grow_hint_wakes_on_telemetry_recovery_too(self):
        """Chips also free by RECOVERING: the growth hint must register
        NODE_TELEMETRY_UPDATED (like classic gang-permit and the
        telemetry filter) or a member parked behind unhealthy chips
        waits out its full hinted backoff after the slice heals."""
        from yoda_scheduler_tpu.scheduler.elastic import ELASTIC_GROW_HINT
        from yoda_scheduler_tpu.scheduler.framework import (
            NODE_ADDED, NODE_TELEMETRY_UPDATED, POD_DELETED)

        sched, clock = mk_sched(make_v4_slice("s", "2x2x4"), ELASTIC)
        kinds, _ = sched.queue._hints[ELASTIC_GROW_HINT]
        assert {POD_DELETED, NODE_ADDED, NODE_TELEMETRY_UPDATED} <= kinds

    def test_classic_gang_still_all_or_nothing(self):
        """No tpu/gang-min label: the elastic knob must change nothing —
        a gang the cluster cannot host whole binds nobody."""
        nodes = make_v4_slice("s", "2x2x4")
        sched, clock = mk_sched(nodes, ELASTIC.with_(max_attempts=3))
        occupants = [blocker("b0"), blocker("b1")]
        for b in occupants:
            sched.submit(b)
        drive(sched, clock, n=4)
        workers = [Pod(f"c-w{i}", labels={
            "tpu/gang-name": "c", "tpu/gang-size": "4",
            "scv/number": "4"}) for i in range(4)]
        for w in workers:
            sched.submit(w)
        drive(sched, clock, n=80, tick=2.0)
        assert not any(w.phase == PodPhase.BOUND for w in workers)

    def test_growth_member_exhausting_attempts_spares_the_gang(self):
        """A growth member hitting max_attempts fails ALONE — the
        reduced-size gang keeps running (gang_doom disarmed)."""
        nodes = make_v4_slice("s", "2x2x4")
        sched, clock = mk_sched(nodes, ELASTIC.with_(max_attempts=3))
        occupants = [blocker("b0"), blocker("b1")]
        for b in occupants:
            sched.submit(b)
        drive(sched, clock, n=4)
        workers = elastic_gang("job", 4, 2)
        for w in workers:
            sched.submit(w)
        drive(sched, clock, n=120, tick=2.0)
        bound = [w for w in workers if w.phase == PodPhase.BOUND]
        failed = [w for w in workers if w.phase == PodPhase.FAILED]
        assert len(bound) == 2 and len(failed) == 2
        # the bound half is still bound and the gang is not doomed
        assert "job" not in sched.doomed_gangs


# ------------------------------------------------------ deadline pressure
class TestDeadlinePressure:
    def _sched(self):
        nodes = make_v4_slice("s", "2x2x4")
        return mk_sched(nodes, ELASTIC.with_(gang_timeout_s=30.0))

    def test_tight_deadline_starts_at_min_without_waiting(self):
        sched, clock = self._sched()
        workers = elastic_gang("slo", 4, 2, deadline=10)  # < timeout scaled
        for w in workers[:2]:  # the rest never arrive
            sched.submit(w)
        drive(sched, clock, n=4)
        assert all(w.phase == PodPhase.BOUND for w in workers[:2])
        assert sched.metrics.labeled_counter(
            "gang_elastic_admissions_total", {"reason": "deadline"}) == 1

    def test_loose_deadline_waits_for_full_assembly(self):
        sched, clock = self._sched()
        # budget comfortably covers another assembly round: wait
        workers = elastic_gang("slo", 4, 2, deadline=100000)
        for w in workers[:2]:
            sched.submit(w)
        drive(sched, clock, n=4)
        assert all(w.phase == PodPhase.PENDING for w in workers[:2])
        assert len(sched.waiting) == 2
        # the stragglers arrive: classic full assembly completes
        for w in workers[2:]:
            sched.submit(w)
        drive(sched, clock, n=6)
        assert all(w.phase == PodPhase.BOUND for w in workers)

    def test_deadline_threshold_scales_with_sacrifice_not_inverse(self):
        """The pressure threshold is gang_timeout_s * r * (min/size):
        it shrinks as the min-size throughput sacrifice grows, so a
        bigger sacrifice holds out for full assembly LONGER. (The
        inverted size/min scaling would make every mid-range deadline
        pressed immediately — threshold >= the whole budget.)"""
        from yoda_scheduler_tpu.scheduler.elastic import ElasticGangs

        eg = ElasticGangs(SchedulerConfig(gang_timeout_s=30.0))

        def spec(gmin):
            return spec_for(Pod("p", labels={
                "tpu/gang-name": "slo", "tpu/gang-size": "4",
                "tpu/gang-min": str(gmin), "scv/number": "4",
                "scv/deadline-seconds": "20"}))

        eg.note_member_seen("slo", 0.0)
        # min 2/4 (2x sacrifice): threshold 15s — budget 18s holds out,
        # budget 14s is pressed
        assert not eg.deadline_pressed(spec(2), 2.0)
        assert eg.deadline_pressed(spec(2), 6.0)
        # min 1/4 (4x sacrifice): threshold 7.5s — still holding out at
        # a remaining budget that already pressed the cheaper sacrifice
        assert not eg.deadline_pressed(spec(1), 6.0)
        assert eg.deadline_pressed(spec(1), 13.0)

    def test_no_deadline_waits(self):
        sched, clock = self._sched()
        workers = elastic_gang("nod", 4, 2)
        for w in workers[:2]:
            sched.submit(w)
        drive(sched, clock, n=4)
        assert len(sched.waiting) == 2

    def test_name_reuse_after_completion_starts_deadline_fresh(self):
        """A gang that assembles FULLY (classic path) must retire its
        _first_seen deadline anchor at completion: a later gang reusing
        the name would otherwise inherit a stale anchor, read a huge
        'waited', and be deadline-pressed into admitting at min on its
        first eligible cycle even though full assembly fits."""
        sched, clock = self._sched()
        first = elastic_gang("reuse", 4, 2, deadline=100000)
        for w in first:
            sched.submit(w)
        drive(sched, clock, n=6)
        assert all(w.phase == PodPhase.BOUND for w in first)
        # the job finishes; its pods leave the cluster
        for w in first:
            sched.cluster.evict(w)
        # burn almost the whole reused deadline budget: a stale anchor
        # would read waited≈99990, remaining≈10 <= the 15s threshold
        clock.advance(99990.0)
        # new incarnation, same gang name, same loose deadline: only 2
        # of 4 submitted — with a fresh anchor it must WAIT for full
        # assembly, not start at min off the dead gang's clock
        second = elastic_gang("reuse", 4, 2, deadline=100000)
        for w in second[:2]:
            sched.submit(w)
        drive(sched, clock, n=4)
        assert all(w.phase == PodPhase.PENDING for w in second[:2])
        assert sched.metrics.labeled_counter(
            "gang_elastic_admissions_total", {"reason": "deadline"}) == 0


# -------------------------------------------------------- shrink to min
class TestShrinkToMin:
    def _running_gang(self, max_attempts=4):
        nodes = make_v4_slice("s", "2x2x4")
        sched, clock = mk_sched(nodes,
                                ELASTIC.with_(max_attempts=max_attempts))
        workers = elastic_gang("donor", 4, 2)
        for w in workers:
            sched.submit(w)
        drive(sched, clock, n=6)
        assert all(w.phase == PodPhase.BOUND for w in workers)
        return sched, clock, workers

    def _bound_members(self, sched, gang):
        return sum(1 for n in sched.cluster.node_names()
                   for p in sched.cluster.pods_on(n)
                   if p.labels.get("tpu/gang-name") == gang)

    def test_preemption_shrinks_gang_to_min_never_below(self):
        sched, clock, workers = self._running_gang()
        preemptors = [blocker(f"hi{i}", prio=9) for i in range(3)]
        for p in preemptors:
            sched.submit(p)
        drive(sched, clock, n=120, tick=2.0)
        # surplus was 2: exactly two preemptors got a host, the third
        # found no plan (shrinking below min is never offered)
        assert sum(p.phase == PodPhase.BOUND for p in preemptors) == 2
        assert self._bound_members(sched, "donor") == 2
        assert sched.metrics.labeled_counter(
            "gang_shrink_total", {"reason": "preemption"}) == 2

    def test_obstacle_eviction_never_drops_gang_below_min(self):
        """Regression: a hostPort-conflict OBSTACLE folded into a plan
        consumes gang surplus like any capacity pick — a plan whose
        capacity victim already exhausted the surplus must be refused
        WHOLE, never allowed to evict the obstacle past tpu/gang-min."""
        port = ((8080, "TCP", ""),)

        def member(name, prio, ports=()):
            return Pod(name, labels={
                "tpu/gang-name": "donor", "tpu/gang-size": "3",
                "tpu/gang-min": "2", "scv/number": "2",
                "scv/priority": str(prio)}, host_ports=ports)

        nodes = make_v4_slice("s", "2x2x2")
        sched, clock = mk_sched(nodes, ELASTIC.with_(max_attempts=3))
        # host-0: w0 holds the port (prio 5), w1 is the cheap capacity
        # victim (prio 1) — surplus is 1, so evicting BOTH breaks min
        w0 = member("donor-w0", 5, port)
        w1 = member("donor-w1", 1)
        # host-1: third member + an equal-priority port holder, so no
        # alternative plan exists there (obstacle not evictable)
        w2 = member("donor-w2", 5)
        wall = Pod("wall", labels={"scv/number": "2", "scv/priority": "9",
                                   "tpu/accelerator": "tpu"},
                   host_ports=port)
        sched.cluster.bind(w0, "s-host-0", [(0, 0, 0), (1, 0, 0)])
        sched.cluster.bind(w1, "s-host-0", [(0, 1, 0), (1, 1, 0)])
        sched.cluster.bind(w2, "s-host-1", [(0, 0, 1), (1, 0, 1)])
        sched.cluster.bind(wall, "s-host-1", [(0, 1, 1), (1, 1, 1)])
        hi = Pod("hi", labels={"scv/number": "2", "scv/priority": "9",
                               "tpu/accelerator": "tpu"},
                 host_ports=port)
        sched.submit(hi)
        drive(sched, clock, n=60, tick=2.0)
        # no admissible plan anywhere: host-0's obstacle fold would
        # overdraw the surplus, host-1's port holder outranks eviction
        assert hi.phase == PodPhase.FAILED
        assert self._bound_members(sched, "donor") == 3

    def test_shrunk_gang_regrows_when_capacity_returns(self):
        # max_attempts=0: the shrunk member keeps retrying as a growth
        # member until capacity returns (the serve posture)
        sched, clock, workers = self._running_gang(max_attempts=0)
        hi = blocker("hi", prio=9)
        sched.submit(hi)
        drive(sched, clock, n=30, tick=2.0)
        assert hi.phase == PodPhase.BOUND
        assert self._bound_members(sched, "donor") == 3
        sched.cluster.evict(hi)
        drive(sched, clock, n=30, tick=2.0)
        assert self._bound_members(sched, "donor") == 4
        assert sched.metrics.counters.get("gang_grow_total", 0) >= 1

    def test_elastic_off_gangs_stay_untouchable(self):
        nodes = make_v4_slice("s", "2x2x4")
        sched, clock = mk_sched(nodes, SchedulerConfig(max_attempts=3))
        workers = elastic_gang("donor", 4, 2)  # label present, knob off
        for w in workers:
            sched.submit(w)
        drive(sched, clock, n=6)
        assert all(w.phase == PodPhase.BOUND for w in workers)
        hi = blocker("hi", prio=9)
        sched.submit(hi)
        drive(sched, clock, n=60, tick=2.0)
        assert hi.phase == PodPhase.FAILED
        assert self._bound_members(sched, "donor") == 4


# ------------------------------------------------------ defrag controller
class TestDefragController:
    def _fragmented_slice(self, config=None):
        """2-host slice with one stray single on host-0 (blocking a
        2-host gang) + an empty standalone destination."""
        nodes = make_v4_slice("s", "2x2x2") + [make_tpu_node("lone",
                                                             chips=4)]
        cfg = config or SchedulerConfig(defrag_interval_s=5.0,
                                        defrag_cooldown_s=60.0)
        sched, clock = mk_sched(nodes, cfg)
        stray = Pod("stray", labels={"scv/number": "1",
                                     "tpu/accelerator": "tpu"})
        # pin the stray onto a slice host so the slice is dented
        sched.cluster.bind(stray, "s-host-0", [(0, 0, 0)])
        return sched, clock, stray

    def test_pass_reassembles_the_slice(self):
        sched, clock, stray = self._fragmented_slice()
        gang = [Pod(f"g-w{i}", labels={
            "tpu/gang-name": "g", "tpu/gang-size": "2",
            "scv/number": "4"}) for i in range(2)]
        for w in gang:
            sched.submit(w)
        drive(sched, clock, n=40, tick=1.0)
        # the stray migrated to the standalone node and the gang took
        # the whole slice
        assert stray.node == "lone"
        assert all(w.phase == PodPhase.BOUND for w in gang)
        assert all(w.node.startswith("s-host-") for w in gang)
        assert sched.metrics.labeled_counter(
            "defrag_evictions_total",
            {"strategy": "slice-conservation"}) == 1
        assert sched.metrics.counters.get("defrag_passes_total", 0) >= 1
        kinds = [e["kind"] for e in sched.flight.snapshot()]
        assert "defrag_pass" in kinds

    def test_no_demand_no_pass(self):
        sched, clock, stray = self._fragmented_slice()
        drive(sched, clock, n=20, tick=5.0)
        assert sched.metrics.counters.get("defrag_passes_total", 0) == 0
        assert stray.node == "s-host-0"

    def test_cooldown_prevents_rethrash(self):
        """A pod the loop migrated is immune for the cooldown window —
        no pod migrates more than once per window."""
        sched, clock, stray = self._fragmented_slice(
            SchedulerConfig(defrag_interval_s=5.0,
                            defrag_cooldown_s=1e6))
        # an unsatisfiable pending pod keeps the demand gate open
        sched.submit(Pod("want", labels={"scv/number": "4",
                                         "scv/memory": "999999999"}))
        drive(sched, clock, n=40, tick=5.0)
        assert sched.metrics.counters.get(
            "pods_descheduled_total", 0) <= 1

    def test_breaker_interlock(self):
        sched, clock, stray = self._fragmented_slice()
        sched.submit(Pod("want", labels={"scv/number": "4"}))
        sched._breaker_until = clock.time() + 1e9  # breaker open
        sched.defrag.run_pass(clock.time())
        assert sched.metrics.labeled_counter(
            "defrag_skips_total", {"reason": "breaker-open"}) == 1
        assert sched.metrics.counters.get("defrag_passes_total", 0) == 0

    def test_degraded_interlock(self):
        sched, clock, stray = self._fragmented_slice(
            SchedulerConfig(defrag_interval_s=5.0,
                            telemetry_max_age_s=10.0))
        sched.submit(Pod("want", labels={"scv/number": "4"}))
        clock.advance(1e5)  # every heartbeat ancient: blackout
        sched.defrag.run_pass(clock.time())
        assert sched.metrics.labeled_counter(
            "defrag_skips_total", {"reason": "degraded"}) == 1

    def test_pin_never_poisons_class_memos(self):
        """Real-apiserver shape: the migration pin arrives WITHOUT an
        allocator nomination (eviction destroyed the old incarnation,
        so Descheduler.run_once nominated nothing). The pinned one-node
        scan must not land in the class memos — a classmate must still
        see the open node, and the pin stays one-shot."""
        nodes = [make_tpu_node("full", chips=1),
                 make_tpu_node("open", chips=4)]
        sched, clock = mk_sched(nodes, SchedulerConfig(
            defrag_interval_s=1e9, telemetry_max_age_s=1e9))
        filler = Pod("filler", labels={"scv/number": "1"})
        sched.cluster.bind(filler, "full", [(0, 0, 0)])
        v = Pod("v", labels={"scv/number": "1"})
        sched.defrag._pins[v.key] = "full"  # destination taken meanwhile
        sched.submit(v)
        assert sched.run_one() is not None  # the pinned cycle fails
        assert v.phase != PodPhase.BOUND
        assert not sched.defrag._pins  # consumed one-shot
        # the narrowed "no feasible node" verdict must NOT be a class
        # verdict: pre-fix it sat in _unsched_memo and classmates
        # fast-failed in O(1) while `open` had capacity
        assert not sched._unsched_memo
        c = Pod("c", labels={"scv/number": "1"})
        sched.submit(c)
        sched.run_one()
        assert c.phase == PodPhase.BOUND
        # the victim's own retry is unrestricted after the failed pin
        drive(sched, clock, n=10)
        assert v.phase == PodPhase.BOUND and v.node == "open"

    def test_dest_cache_skips_topology_constrained_victims(self):
        """Affinity/spread verdicts are location-relative: two same-class
        victims bound in different domains satisfy their terms near
        DIFFERENT nodes, so their dry-run destination orders must never
        be shared through dest_cache (the same pods the engine's
        feas_ok excludes from the feasible-class memo)."""
        nodes = [make_tpu_node("a", chips=4), make_tpu_node("b", chips=4)]
        sched, clock = mk_sched(nodes, SchedulerConfig(
            defrag_interval_s=1e9, telemetry_max_age_s=1e9))
        desched = sched.defrag.desched
        snapshot = sched.snapshot()
        dest_free = {"a": 4, "b": 4}
        plain = Pod("plain", labels={"scv/number": "1"})
        cache = {}
        desched._fits_elsewhere(plain, "a", snapshot, {}, dest_free, cache)
        assert cache  # unconstrained classes ARE memoised
        sticky = Pod.from_manifest({
            "metadata": {"name": "sticky",
                         "labels": {"scv/number": "1"}},
            "spec": {"affinity": {"podAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"labelSelector": {"matchLabels": {"app": "x"}},
                     "topologyKey": "zone"}]}}},
        })
        assert sticky.pod_affinity
        cache = {}
        desched._fits_elsewhere(sticky, "a", snapshot, {}, dest_free,
                                cache)
        assert not cache
        spread = Pod.from_manifest({
            "metadata": {"name": "sp", "labels": {"scv/number": "1"}},
            "spec": {"topologySpreadConstraints": [
                {"maxSkew": 1, "topologyKey": "zone",
                 "whenUnsatisfiable": "DoNotSchedule",
                 "labelSelector": {"matchLabels": {"app": "sp"}}}]},
        })
        assert spread.topology_spread
        cache = {}
        desched._fits_elsewhere(spread, "a", snapshot, {}, dest_free,
                                cache)
        assert not cache

    def test_free_for_all_drops_controller_on_pinned_non_owners(self):
        """Free-for-all ownership is pinned to replica 0: the other
        replicas must not keep a permanently-refused loop that wakes
        every interval and grows the not-owner skip counter forever."""
        store = TelemetryStore()
        clock = FakeClock(start=1000.0)
        for m in [make_tpu_node(f"t{i}", chips=4) for i in range(2)]:
            m.heartbeat = clock.time()
            store.put(m)
        cluster = FakeCluster(store)
        cluster.add_nodes_from_telemetry()
        fleet = FleetCoordinator(
            cluster, SchedulerConfig(telemetry_max_age_s=1e9,
                                     defrag_interval_s=5.0),
            replicas=3, clock=clock, mode="free-for-all")
        assert fleet.replicas[0].engine.defrag is not None
        assert all(r.engine.defrag is None for r in fleet.replicas[1:])

    def test_fleet_runs_defrag_on_shard0_owner_only(self):
        store = TelemetryStore()
        clock = FakeClock(start=1000.0)
        for m in [make_tpu_node(f"t{i}", chips=4) for i in range(4)]:
            m.heartbeat = clock.time()
            store.put(m)
        cluster = FakeCluster(store)
        cluster.add_nodes_from_telemetry()
        fleet = FleetCoordinator(
            cluster, SchedulerConfig(telemetry_max_age_s=1e9,
                                     defrag_interval_s=5.0),
            replicas=2, clock=clock, mode="sharded")
        rng = random.Random(0)
        fleet.step(rng)  # lease upkeep assigns shards
        owners = [bool(r.engine.defrag.owner_check())
                  for r in fleet.replicas]
        assert owners.count(True) == 1
        # ownership follows the shard-0 lease
        owner = owners.index(True)
        assert 0 in fleet.replicas[owner].owned


# ---------------------------------------- satellite: gang-fail quota claim
class TestGangFailRetiresQuotaClaim:
    def _quota_sched(self):
        nodes = make_v4_slice("s", "2x2x4")
        cfg = SchedulerConfig(
            gang_timeout_s=10.0, drf_fairness=True,
            tenant_quotas=(("acme", 1.0, -1),))
        return mk_sched(nodes, cfg)

    def test_permit_timeout_frees_the_whole_claim(self):
        """A gang the quota gate ADMITTED holds an engine-local in-flight
        claim; assembly timing out must retire it for EVERY parked
        member immediately — not at the 2x-timeout TTL (ISSUE 10
        satellite regression)."""
        sched, clock = self._quota_sched()
        workers = elastic_gang("q", 4, 0, extra={"scv/tenant": "acme"})
        for w in workers[:2]:  # the rest never arrive
            sched.submit(w)
        drive(sched, clock, n=4)
        assert len(sched.waiting) == 2
        assert sched.policy.gang_inflight("acme", None,
                                          clock.time()) != (0, 0)
        clock.advance(15.0)  # past the permit deadline, well short of TTL
        sched.check_waiting()
        assert not sched.waiting
        assert sched.policy.gang_inflight("acme", None,
                                          clock.time()) == (0, 0)

    def test_doomed_gang_frees_the_claim(self):
        sched, clock = self._quota_sched()
        sched.config = sched.config.with_(max_attempts=2)
        workers = elastic_gang("d", 4, 0, extra={"scv/tenant": "acme"})
        workers[3].labels["scv/memory"] = "999999999"  # can never fit
        for w in workers:
            sched.submit(w)
        drive(sched, clock, n=60, tick=2.0)
        assert all(w.phase == PodPhase.FAILED for w in workers)
        assert sched.policy.gang_inflight("acme", None,
                                          clock.time()) == (0, 0)


# ------------------------------------------------------------ off parity
class TestElasticOffParity:
    def _trace(self, cfg):
        nodes = (make_v4_slice("s", "2x2x4")
                 + [make_tpu_node(f"t{i}", chips=4) for i in range(3)])
        sched, clock = mk_sched(nodes, cfg)
        rng = random.Random(11)
        pods = []
        for i in range(24):
            if rng.random() < 0.7:
                pods.append(Pod(f"p{i}", labels={
                    "scv/number": str(rng.choice((1, 2))),
                    "tpu/accelerator": "tpu"}))
            else:
                pods.append(Pod(f"p{i}", labels={
                    "scv/memory": str(rng.choice((1000, 4000)))}))
        gang = [Pod(f"g-w{i}", labels={
            "tpu/gang-name": "g", "tpu/gang-size": "2",
            "scv/number": "4"}) for i in range(2)]
        for p in pods + gang:
            sched.submit(p)
        sched.run_until_idle(max_cycles=2000)
        return [(p.name, p.node, p.labels.get("tpu/assigned-chips"))
                for p in pods + gang]

    def test_knob_off_and_knob_on_without_labels_are_bit_identical(self):
        """elasticGangs on with NO tpu/gang-min labels in the workload
        must place bit-identically to the knob being off entirely (and
        to the from_profile round-trip) — the acceptance criterion the
        CI elastic-disabled tier-1 leg re-proves."""
        base = self._trace(SchedulerConfig(
            telemetry_max_age_s=1e9, max_attempts=3))
        knob_on = self._trace(SchedulerConfig(
            telemetry_max_age_s=1e9, max_attempts=3, elastic_gangs=True))
        roundtrip = self._trace(SchedulerConfig.from_profile({
            "schedulerName": "yoda-scheduler",
            "pluginConfig": [{"name": "yoda-tpu", "args": {
                "telemetryMaxAgeSeconds": 1e9,
                "elasticGangs": False,
                "defragIntervalSeconds": 0}}],
        }).with_(max_attempts=3))
        assert base == knob_on == roundtrip

    def test_off_profile_carries_no_elastic_state(self):
        profile, _, gang_permit = default_profile(SchedulerConfig())
        assert profile.elastic is None
        assert gang_permit.elastic is None
        sched, _ = mk_sched([make_tpu_node("t", chips=4)],
                            SchedulerConfig())
        assert sched.elastic is None and sched.defrag is None

    def test_config_roundtrip_parses_elastic_block(self):
        cfg = SchedulerConfig.from_profile({
            "pluginConfig": [{"name": "yoda-tpu", "args": {
                "elasticGangs": True,
                "defragIntervalSeconds": 30,
                "maxMigrationsPerPass": 2,
                "defragCooldownSeconds": 120,
            }}]})
        assert cfg.elastic_gangs
        assert cfg.defrag_interval_s == 30
        assert cfg.max_migrations_per_pass == 2
        assert cfg.defrag_cooldown_s == 120


# -------------------------------------------------------- observability
class TestElasticObservability:
    def test_new_families_round_trip_with_help(self):
        prometheus_client = pytest.importorskip(
            "prometheus_client",
            reason="exposition golden tests need the reference parser")
        from prometheus_client.parser import text_string_to_metric_families
        from yoda_scheduler_tpu.utils.obs import Metrics

        m = Metrics()
        m.inc("defrag_evictions_total",
              labels={"strategy": "slice-conservation"})
        m.inc("defrag_evictions_total", labels={"strategy": "compaction"})
        m.inc("gang_grow_total")
        m.inc("gang_shrink_total", labels={"reason": "preemption"})
        m.inc("defrag_passes_total")
        m.inc("defrag_skips_total", labels={"reason": "breaker-open"})
        m.inc("defrag_errors_total")
        text = m.render_prometheus()
        fams = {}
        for fam in text_string_to_metric_families(text):
            for s in fam.samples:
                fams.setdefault(s.name, {})[
                    frozenset(s.labels.items())] = s.value
        assert fams["yoda_tpu_defrag_evictions_total"][
            frozenset({("strategy", "slice-conservation")})] == 1
        assert fams["yoda_tpu_gang_shrink_total"][
            frozenset({("reason", "preemption")})] == 1
        assert fams["yoda_tpu_gang_grow_total"][frozenset()] == 1
        for name in ("defrag_evictions_total", "gang_grow_total",
                     "gang_shrink_total", "defrag_passes_total",
                     "defrag_skips_total", "defrag_errors_total"):
            assert f"# HELP yoda_tpu_{name}" in text
            # registered HELP, not the generated fallback one-liner
            assert Metrics.HELP.get(name), name

    def test_defrag_pass_is_a_trip_kind(self):
        from yoda_scheduler_tpu.utils.obs import RING_ONLY_TRIPS, TRIP_KINDS

        assert "defrag_pass" in TRIP_KINDS
        # ...but ring-only: passes are planned recurring behavior, and a
        # steady cadence must not grow a dump file per rate-limit window
        assert "defrag_pass" in RING_ONLY_TRIPS

    def test_defrag_pass_never_auto_dumps(self, tmp_path):
        from yoda_scheduler_tpu.utils.obs import FlightRecorder

        fr = FlightRecorder(dump_dir=str(tmp_path),
                            min_dump_interval_s=0.0)
        for i in range(5):
            fr.record("defrag_pass", evictions=1, pods=[f"p{i}"])
        assert fr.dumps == [] and list(tmp_path.iterdir()) == []
        fr.record("breaker_open")  # real faults still land on disk
        assert len(fr.dumps) == 1
