"""Cluster backends: where pods live and binds land.

The reference's cluster backend is the Kubernetes API server reached through
client-go/controller-runtime (reference pkg/yoda/scheduler.go:53-68,111).
Here the backend is an interface with two implementations:

- FakeCluster (this module): in-memory API-server stand-in. Primary target
  for tests and the benchmark harness — the fake control plane SURVEY.md §4
  says the reference lacks entirely.
- KubeCluster (k8s/client.py): the same interface over a real API server,
  gated on network availability.
"""

from __future__ import annotations

import threading
from typing import Protocol

from .framework import (
    ClusterEvent,
    NODE_ADDED,
    NODE_SPEC_CHANGED,
    POD_BOUND,
    POD_DELETED,
)
from ..telemetry.store import TelemetryStore
from ..utils.changelog import ChangeLog
from ..utils.pod import ASSIGNED_CHIPS_LABEL, Pod, PodPhase, format_assigned_chips


class Cluster(Protocol):
    def node_names(self) -> list[str]: ...
    def pods_on(self, node: str) -> list[Pod]: ...
    # `fence` is only passed when the engine's fence_provider is set
    # (sharded fleet replicas); fence-unaware backends are safe anywhere
    # else, but a backend used under a sharded fleet must accept it
    def bind(self, pod: Pod, node: str,
             assigned_chips: list[tuple[int, int, int]] | None,
             fence: tuple | None = None) -> None: ...
    def evict(self, pod: Pod) -> None: ...


class BindConflictError(RuntimeError):
    """The authority rejected a bind at commit time: the target pod is
    already bound, the chip/HBM claim would oversubscribe the node, or the
    caller's fencing token is stale. The HTTP analogue is a server-returned
    409 — `status` carries that so the engine's breaker logic (which only
    counts WIRE failures, status 0) never trips on a healthy-but-contended
    cluster, and the conflict path can route on it."""

    status = 409


class FakeCluster:
    """In-memory nodes + bound-pod book-keeping, with a telemetry store
    playing the role of the SCV CRD cache."""

    # evict() here merely unbinds — the same Pod object can be resubmitted
    # (descheduler local requeue). A real API server's evict is a DELETE,
    # where the controller recreates a new incarnation instead.
    supports_local_requeue = True

    def __init__(self, telemetry: TelemetryStore | None = None) -> None:
        self.telemetry = telemetry or TelemetryStore()
        self._lock = threading.RLock()
        self._nodes: set[str] = set()
        self._bound: dict[str, list[Pod]] = {}  # node -> pods
        # pod.key -> node, maintained alongside _bound: O(1) already-bound
        # conflict checks at bind time and O(1) bound_node_of (the fleet's
        # foreign-bind guard reads it on the scheduling path)
        self._bound_keys: dict[str, str] = {}
        # optional shard-lease authority (scheduler/fleet.py
        # LocalLeaseStore): when set, a bind carrying a fencing token is
        # validated against it — a replica whose lease epoch went stale
        # (split-brain, expiry mid-bind) gets a 409, never a silent write
        self.lease_authority = None
        # server-side rejections, by reason — the fleet bench and chaos
        # fuzz read these to prove the authority (not engine bookkeeping)
        # is what held the invariants
        self.bind_conflicts: dict[str, int] = {}
        self._meta: dict[str, tuple[dict, tuple]] = {}  # node -> (labels, taints)
        self._pdbs: tuple = ()
        self._namespaces: dict[str, dict] = {}  # ns -> metadata.labels
        # monotonic per-node change counter (bind/evict/removal): lets the
        # scheduler reuse per-node snapshot state across cycles — a bind
        # invalidates one node, not the whole cluster
        self._pods_ver: dict[str, int] = {}
        # global change log + node-membership version for incremental
        # snapshots and the unschedulable-class memo
        self._changes = ChangeLog()
        self._nodes_ver = 0
        # event subscribers (scheduler engines): called OUTSIDE the lock
        # with a framework.ClusterEvent per mutation, feeding the queues'
        # event-driven requeue (list append/iteration are GIL-atomic)
        self._subscribers: list = []

    def subscribe(self, cb) -> None:
        """Register a cluster-event callback (cb(ClusterEvent)). Callbacks
        must be cheap and thread-safe — they run on whichever thread
        mutated the cluster."""
        self._subscribers.append(cb)

    def _publish(self, event: ClusterEvent) -> None:
        for cb in list(self._subscribers):
            cb(event)

    def _bump(self, node: str, grew: bool = True) -> None:
        # callers hold self._lock; every mutation of a node's bound-pod set
        # MUST bump, or cross-cycle snapshot reuse serves stale NodeInfos.
        # grew=False marks capacity-consuming changes (a bind): repair
        # paths then skip hunting that node for NEW feasibility.
        self._pods_ver[node] = self._pods_ver.get(node, 0) + 1
        self._changes.record(node, grew=grew)

    @property
    def nodes_version(self) -> int:
        """Bumped whenever node MEMBERSHIP changes (add/remove)."""
        return self._nodes_ver

    @property
    def pods_global_version(self) -> int:
        """Bumped on any bound-pod mutation anywhere (cheap read)."""
        return self._changes.version

    def changes_since(self, version: int) -> tuple[int, set[str] | None]:
        """(current version, nodes whose bound-pod set changed after
        `version`); None when the log was trimmed past it (full rebuild).
        Mirrors TelemetryStore.changes_since."""
        with self._lock:
            return self._changes.changes_since(version)

    def changes_since_directed(self, version: int):
        """changes_since plus the grew subset (changelog docstring): a
        node only in dirty (all its changes were binds/claims) cannot
        have gained capacity since `version`."""
        with self._lock:
            return self._changes.changes_since_directed(version)

    # ------------------------------------------------------------- node admin
    def add_node(self, name: str) -> None:
        with self._lock:
            fresh = name not in self._nodes
            if fresh:
                self._nodes_ver += 1
            self._nodes.add(name)
            self._bound.setdefault(name, [])
        if fresh:
            self._publish(ClusterEvent(NODE_ADDED, node=name))

    def add_nodes_from_telemetry(self) -> None:
        for m in self.telemetry.list():
            self.add_node(m.node)

    def pods_version(self, node: str) -> int:
        with self._lock:
            return self._pods_ver.get(node, 0)

    def remove_node(self, name: str) -> list[Pod]:
        """Node goes away; its pods return to the caller for requeueing."""
        with self._lock:
            if name in self._nodes:
                self._nodes_ver += 1
            self._nodes.discard(name)
            self._meta.pop(name, None)
            orphans = self._bound.pop(name, [])
            for p in orphans:
                if self._bound_keys.get(p.key) == name:
                    del self._bound_keys[p.key]
            self._bump(name)
        for p in orphans:
            p.node = None
            p.phase = PodPhase.PENDING
        return orphans

    def set_pdbs(self, budgets) -> None:
        """Install the cluster's PodDisruptionBudgets (utils/pdb.py model).
        Bumps the global change log: allowance changes can unblock pods
        whose preemption previously had no non-violating plan."""
        with self._lock:
            self._pdbs = tuple(budgets)
            self._nodes_ver += 1

    def disruption_budgets(self) -> tuple:
        with self._lock:
            return self._pdbs

    def set_namespace_labels(self, ns: str, labels: dict[str, str]) -> None:
        """Install a namespace object's metadata.labels (podAffinityTerm
        namespaceSelector input). Bumps the membership version like a PDB
        change: verdicts anywhere can depend on namespace labels."""
        with self._lock:
            self._namespaces[ns] = dict(labels)
            self._nodes_ver += 1

    def namespace_labels_map(self) -> dict[str, dict]:
        with self._lock:
            return dict(self._namespaces)

    def set_node_meta(self, name: str, labels: dict[str, str] | None = None,
                      taints: list[dict] | tuple = (),
                      allocatable: tuple | None = None,
                      unschedulable: bool = False) -> None:
        """Node-object metadata.labels / spec.taints / status.allocatable
        as (cpu millicores, memory bytes) / spec.unschedulable (cordon)
        (admission plugin inputs). Bumps the node's change counter: an
        edit must invalidate cached NodeInfos and filter verdicts — and
        an uncordon must wake pending classmates event-driven."""
        with self._lock:
            self.add_node(name)
            self._meta[name] = (dict(labels or {}), tuple(taints),
                                allocatable, bool(unschedulable))
            self._bump(name)
        self._publish(ClusterEvent(NODE_SPEC_CHANGED, node=name))

    def node_meta(self, name: str) -> tuple[dict[str, str], tuple]:
        with self._lock:
            return self._meta.get(name, ({}, (), None, False))[:2]

    def node_allocatable(self, name: str) -> tuple | None:
        with self._lock:
            meta = self._meta.get(name)
            return meta[2] if meta is not None else None

    def node_unschedulable(self, name: str) -> bool:
        with self._lock:
            meta = self._meta.get(name)
            return bool(meta[3]) if meta is not None else False

    # ---------------------------------------------------------------- reading
    def node_names(self) -> list[str]:
        with self._lock:
            return sorted(self._nodes)

    def pods_on(self, node: str) -> list[Pod]:
        with self._lock:
            return list(self._bound.get(node, []))

    def all_pods(self) -> list[Pod]:
        with self._lock:
            return [p for pods in self._bound.values() for p in pods]

    def bound_node_of(self, key: str) -> str | None:
        """Node holding pod `key`, or None — the cluster-truth read the
        engine's ambiguous-bind adoption, restart reconciliation, and
        foreign-bind conflict handling use (binding present in the
        cluster => adopt/drop; absent => requeue). O(1) off the bound-key
        index."""
        with self._lock:
            return self._bound_keys.get(key)

    # ---------------------------------------------------------------- binding
    def _reject(self, reason: str, message: str) -> None:
        # callers hold self._lock
        self.bind_conflicts[reason] = self.bind_conflicts.get(reason, 0) + 1
        raise BindConflictError(message)

    def _check_bind(self, pod: Pod, node: str, assigned_chips,
                    fence) -> None:
        """Bind-time conflict enforcement, the authority's half of the
        optimistic-concurrency contract (callers hold self._lock; raises
        BEFORE any mutation). A fleet replica commits from its own
        snapshot — the one place its stale view is actually checked is
        here: already-bound pod, overlapping chip claim, per-chip HBM
        oversubscription, or a stale fencing token all 409."""
        cur = self._bound_keys.get(pod.key)
        if cur is not None:
            self._reject("pod_bound",
                         f"pod {pod.key} is already bound to {cur}")
        if fence is not None and self.lease_authority is not None \
                and not self.lease_authority.validate_fence(fence):
            self._reject("stale_fence",
                         f"fencing token {fence} is stale (lease "
                         "expired or reassigned)")
        claimed = set(assigned_chips or ())
        if not claimed:
            return
        taken: set = set()
        for q in self._bound.get(node, ()):
            taken |= q.assigned_chips()
        overlap = claimed & taken
        if overlap:
            self._reject("chip_claim",
                         f"chip claim conflict on {node}: "
                         f"{sorted(overlap)} already owned")
        need_mb = int(pod.labels.get("scv/memory", "0") or 0)
        if need_mb:
            m = self.telemetry.get(node)
            if m is not None:
                # coords index memoised per metrics incarnation (put()
                # installs a fresh object): rebuilt dicts per bind were
                # a measurable slice of authority cost at drain scale
                by_coord = m.__dict__.get("_by_coord")
                if by_coord is None:
                    by_coord = {c.coords: c for c in m.chips}
                    m.__dict__["_by_coord"] = by_coord
                for c in claimed:
                    chip = by_coord.get(c)
                    if chip is not None and need_mb > chip.hbm_free_mb:
                        self._reject(
                            "hbm",
                            f"HBM oversubscription on {node}/{c}: "
                            f"need {need_mb}MB > free {chip.hbm_free_mb}MB")

    def bind(self, pod: Pod, node: str,
             assigned_chips: list[tuple[int, int, int]] | None = None,
             fence=None) -> None:
        with self._lock:
            if node not in self._nodes:
                raise KeyError(f"bind target {node!r} is not a node")
            self._check_bind(pod, node, assigned_chips, fence)
            pod.node = node
            pod.phase = PodPhase.BOUND
            if assigned_chips is not None:
                pod.labels[ASSIGNED_CHIPS_LABEL] = format_assigned_chips(assigned_chips)
            self._bound[node].append(pod)
            self._bound_keys[pod.key] = node
            self._bump(node, grew=False)  # a bind only consumes capacity
        self._publish(ClusterEvent(POD_BOUND, node=node))

    def evict(self, pod: Pod) -> None:
        node = pod.node
        removed = False
        with self._lock:
            if pod.node and pod.node in self._bound:
                before = self._bound[pod.node]
                after = [p for p in before if p.uid != pod.uid]
                removed = len(after) != len(before)
                self._bound[pod.node] = after
                if removed and self._bound_keys.get(pod.key) == pod.node:
                    del self._bound_keys[pod.key]
                self._bump(pod.node)
        pod.node = None
        pod.phase = PodPhase.PENDING
        pod.labels.pop(ASSIGNED_CHIPS_LABEL, None)
        if removed:
            # only a REAL departure frees capacity; evicting a pod that
            # was never bound (or already gone) must not wake every
            # capacity-parked pod for a doomed retry (mirrors
            # KubeCluster._pod_event, which emits POD_DELETED only for
            # cached pods with a node). The gang label rides along so
            # the elastic controller can retire a growing record whose
            # gang was deleted externally (core._drain_elastic_retires).
            self._publish(ClusterEvent(
                POD_DELETED, node=node,
                gang=pod.labels.get("tpu/gang-name")))
