"""Gang preemption (VERDICT r2 item 4b): a high-priority multi-host gang
evicts lower-priority pods across the hosts of ONE slice, all-or-nothing,
holds the slice while assembling (gang-level nomination), and gang members
themselves stay protected from eviction.

Before this feature the engine bailed out ("gangs don't preempt in v1"):
under contention a v4-32 Llama gang — the workload the blueprint cares
most about — could neither evict the singles denting its slice nor go
anywhere else.
"""

from __future__ import annotations

import time

from yoda_scheduler_tpu.scheduler import FakeCluster, Scheduler, SchedulerConfig
from yoda_scheduler_tpu.scheduler.core import FakeClock
from yoda_scheduler_tpu.telemetry import TelemetryStore, make_tpu_node, make_v4_slice
from yoda_scheduler_tpu.utils import Pod, PodPhase


def mk_cluster(*, slices=1, standalone=0):
    store = TelemetryStore()
    now = time.time()
    for i in range(slices):
        for m in make_v4_slice(f"s{i}", "2x2x4"):
            m.heartbeat = now + 1e8
            store.put(m)
    for i in range(standalone):
        m = make_tpu_node(f"t{i}", chips=4)
        m.heartbeat = now + 1e8
        store.put(m)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    return cluster


def mk_sched(cluster, **cfg):
    clock = FakeClock(start=time.time())
    sched = Scheduler(
        cluster,
        SchedulerConfig(telemetry_max_age_s=1e9, gang_timeout_s=30.0, **cfg),
        clock=clock)
    return sched, clock


def gang_pods(name, size, chips="4", prio="8"):
    return [Pod(f"{name}-{i}", labels={
        "tpu/gang-name": name, "tpu/gang-size": str(size),
        "scv/number": chips, "scv/priority": prio,
        "tpu/accelerator": "tpu"}) for i in range(size)]


def dent_slice(sched, clock, n_hosts=4, chips="2", prio="0"):
    """Bind one low-priority single per slice host so no host has 4 free."""
    singles = [Pod(f"low-{i}", labels={
        "scv/number": chips, "scv/priority": prio, "tpu/accelerator": "tpu"})
        for i in range(n_hosts)]
    for p in singles:
        sched.submit(p)
    sched.run_until_idle()
    assert all(p.phase == PodPhase.BOUND for p in singles)
    # the topology scorer concentrates; force one per host if needed
    assert len({p.node for p in singles}) == n_hosts, \
        {p.node for p in singles}
    return singles


class TestGangPreemption:
    def test_gang_evicts_singles_across_slice_hosts(self):
        cluster = mk_cluster(slices=1)
        sched, clock = mk_sched(cluster)
        singles = dent_slice(sched, clock)

        gang = gang_pods("llama", 4)
        for p in gang:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in gang), \
            [(p.name, p.phase) for p in gang]
        # all four low-priority singles were evicted (each held 2 of the 4
        # chips its host needed to free)
        assert all(p.node is None for p in singles)
        assert sched.metrics.counters.get("preemptions_total", 0) >= 1
        assert sched.metrics.counters.get("pods_evicted_total", 0) == 4
        # entitlement consumed on completion
        assert sched.allocator.gang_nomination_of("llama") is None

    def test_gang_prefers_slice_with_fewest_victims(self):
        cluster = mk_cluster(slices=2)
        sched, clock = mk_sched(cluster)
        # dent slice s0 on all 4 hosts, s1 on only... occupy s1 fully with
        # a rival gang so only s0 is evictable: simpler — dent s0 with 4
        # singles and s1 with 8 (two per host): fewest-victims picks s0
        for i in range(4):
            p = Pod(f"a{i}", labels={"scv/number": "2", "scv/priority": "0",
                                     "tpu/accelerator": "tpu"})
            coords = sorted(cluster.telemetry.get(f"s0-host-{i}").healthy_coords())[:2]
            cluster.bind(p, f"s0-host-{i}", coords)
        for i in range(4):
            m = cluster.telemetry.get(f"s1-host-{i}")
            cs = sorted(m.healthy_coords())
            p1 = Pod(f"b{i}", labels={"scv/number": "2", "scv/priority": "0",
                                      "tpu/accelerator": "tpu"})
            p2 = Pod(f"c{i}", labels={"scv/number": "1", "scv/priority": "0",
                                      "tpu/accelerator": "tpu"})
            cluster.bind(p1, f"s1-host-{i}", cs[:2])
            cluster.bind(p2, f"s1-host-{i}", cs[2:3])
        gang = gang_pods("g", 4)
        for p in gang:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in gang)
        assert {p.node for p in gang} == {f"s0-host-{i}" for i in range(4)}
        assert sched.metrics.counters.get("pods_evicted_total", 0) == 4

    def test_slice_hold_blocks_lower_priority_thief(self):
        """Between the evictions and gang completion, a lower-priority pod
        must not bind into the freed slice capacity."""
        cluster = mk_cluster(slices=1)
        sched, clock = mk_sched(cluster, max_attempts=6)
        dent_slice(sched, clock)
        gang = gang_pods("g", 4)
        # submit only ONE member first: it preempts, takes the slice hold,
        # and parks; the thief then tries to slip in
        sched.submit(gang[0])
        out = sched.run_one()
        assert out == "preempting"
        assert sched.allocator.gang_nomination_of("g") is not None
        thief = Pod("thief", labels={"scv/number": "4", "scv/priority": "1",
                                     "tpu/accelerator": "tpu"})
        sched.submit(thief)
        # the thief outranks nothing: every slice host holds 4 chips for g
        for _ in range(4):
            sched.run_one()
            clock.advance(1.0)
        assert thief.phase != PodPhase.BOUND
        # remaining members arrive; gang completes on its entitlement
        for p in gang[1:]:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in gang)
        assert thief.phase != PodPhase.BOUND

    def test_gang_members_are_protected_victims(self):
        """A higher-priority gang must not evict a BOUND gang's members
        (partial-gang deadlock protection holds even against gangs)."""
        cluster = mk_cluster(slices=1)
        sched, clock = mk_sched(cluster, max_attempts=4)
        g1 = gang_pods("first", 4, prio="2")
        for p in g1:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in g1)
        g2 = gang_pods("second", 4, prio="9")
        for p in g2:
            sched.submit(p)
        sched.run_until_idle()
        # no capacity anywhere and g1 is untouchable: g2 fails, g1 intact
        assert all(p.phase == PodPhase.BOUND for p in g1)
        assert not any(p.phase == PodPhase.BOUND for p in g2)
        assert sched.metrics.counters.get("pods_evicted_total", 0) == 0

    def test_expired_gang_hold_frees_the_slice(self):
        """An abandoned gang's slice entitlement must not block the slice
        forever: gang_hold prunes expired entries."""
        cluster = mk_cluster(slices=1)
        sched, clock = mk_sched(cluster)
        dent_slice(sched, clock)
        gang = gang_pods("g", 4)
        sched.submit(gang[0])
        assert sched.run_one() == "preempting"
        alloc = sched.allocator
        assert alloc.gang_nomination_of("g") is not None
        t_exp = alloc.gang_nomination_of("g")[3]
        assert alloc.gang_hold("s0", priority=0, now=t_exp - 1.0) == 4
        # past the expiry the hold evaporates and the entry is pruned
        assert alloc.gang_hold("s0", priority=0, now=t_exp + 1.0) == 0
        assert alloc.gang_nomination_of("g") is None

    def test_planning_is_pinned_to_the_parked_members_slice(self):
        """Members already parked on slice A pin the gang there; a member
        that then needs preemption must plan evictions on A — never on
        another slice the gang's own filter would refuse to use."""
        cluster = mk_cluster(slices=2)
        sched, clock = mk_sched(cluster)
        # slice s1 fully free EXCEPT we want the gang pinned to s0 first:
        # park two members by keeping s1 out of reach (dent every s1 host
        # so a 4-chip member can't fit there, and keep 2 free hosts on s0)
        for i in range(4):
            m = cluster.telemetry.get(f"s1-host-{i}")
            cs = sorted(m.healthy_coords())
            cluster.bind(Pod(f"s1pod{i}", labels={
                "scv/number": "2", "scv/priority": "9",
                "tpu/accelerator": "tpu"}), f"s1-host-{i}", cs[:2])
        # dent two of s0's hosts with EVICTABLE low-prio singles
        for i in (2, 3):
            m = cluster.telemetry.get(f"s0-host-{i}")
            cs = sorted(m.healthy_coords())
            cluster.bind(Pod(f"low{i}", labels={
                "scv/number": "2", "scv/priority": "0",
                "tpu/accelerator": "tpu"}), f"s0-host-{i}", cs[:2])
        gang = gang_pods("g", 4)
        for p in gang:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in gang)
        assert {p.node for p in gang} == {f"s0-host-{i}" for i in range(4)}
        # only s0's two low-prio singles were evicted; s1's high-prio pods
        # (which outrank nothing here but live on the wrong slice) intact
        assert sched.metrics.counters.get("pods_evicted_total", 0) == 2
        assert len(cluster.pods_on("s1-host-0")) == 1

    def test_external_deletion_of_preempting_member_releases_hold(self):
        cluster = mk_cluster(slices=1)
        sched, clock = mk_sched(cluster)
        dent_slice(sched, clock)
        gang = gang_pods("g", 4)
        sched.submit(gang[0])
        assert sched.run_one() == "preempting"
        assert sched.allocator.gang_nomination_of("g") is not None
        sched.forget(gang[0].key)  # external DELETE observed by serve loop
        assert sched.allocator.gang_nomination_of("g") is None
