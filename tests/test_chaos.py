"""Chaos harness: seeded fault scenarios against the self-healing engine.

The paper's scheduler places pods off live telemetry through an apiserver
connection — its two single points of failure. This suite proves the
control loop keeps CONVERGING through any single-component outage:

- a seeded fuzz (200+ scenarios; the first 16 are the tier-1 smoke
  subset, the rest run in CI's chaos job) replays apiserver error storms,
  lost-response binds, telemetry blackouts, raising plugins, and
  mid-drain engine crashes on a virtual clock, then asserts the four
  global invariants: no pod lost, no double bind, no chip/HBM
  oversubscription, and convergence to the fault-free placement count
  (the workload is sized satisfiable, so convergence == everything
  bound) after the fault window closes;
- targeted tests pin each recovery path's counter: cycle-crash
  containment + poison-pod quarantine, the bind circuit breaker,
  blackout degraded mode (+ recovery without restart), ambiguous-bind
  adoption (sync, async, and batch), restart reconciliation, the
  event-storm inbox flush, and wire-level watch cuts / 410 storms /
  leader failover over the real localhost fake apiserver.
"""

import json
import os
import random
import tempfile
import threading
import time

import pytest

from yoda_scheduler_tpu import chaos
from yoda_scheduler_tpu.chaos import (
    APISERVER_STORM,
    AsyncChaosCluster,
    BIND_LOST,
    CLOCK_SKEW,
    ChaosCluster,
    CrashingFilter,
    CrashingReserve,
    CrashingScore,
    DEFRAG_RACE,
    ELASTIC_KINDS,
    ENGINE_CRASH,
    FLEET_KINDS,
    FaultPlan,
    FaultWindow,
    LEASE_EXPIRY,
    NETWORK_PARTITION,
    PartitionableView,
    PLUGIN_ERROR,
    REPLICA_CRASH,
    SLOW_APISERVER,
    SPLIT_BRAIN,
    TELEMETRY_BLACKOUT,
    VanillaAuthorityCluster,
    WEBHOOK_DOWN,
    WEBHOOK_KINDS,
)
from yoda_scheduler_tpu.scheduler import (
    FakeCluster, FleetCoordinator, Scheduler, SchedulerConfig)
from yoda_scheduler_tpu.scheduler.core import FakeClock, default_profile
from yoda_scheduler_tpu.scheduler.framework import ClusterEvent, POD_DELETED
from yoda_scheduler_tpu.telemetry import (
    TelemetryStore, make_gpu_node, make_tpu_node, make_v4_slice)
from yoda_scheduler_tpu.utils import Pod, PodPhase

MAX_AGE = 60.0
TICK = 0.05


# ------------------------------------------------------------------ fixtures
def _fleet(rng: random.Random) -> TelemetryStore:
    """One v4 slice (4 hosts x 4 chips) + 3 standalone hosts + a GPU node
    = 28 TPU chips / 8 GPU cards, heartbeats at the virtual-clock epoch."""
    store = TelemetryStore()
    metrics = list(make_v4_slice("s0", "2x2x4"))
    for i in range(3):
        metrics.append(make_tpu_node(f"t{i}", chips=4))
    metrics.append(make_gpu_node("g0", cards=8))
    for m in metrics:
        m.heartbeat = 0.0
        store.put(m)
    return store


def _workload(rng: random.Random) -> list[Pod]:
    """A SATISFIABLE burst (demand strictly under fleet capacity), so the
    convergence invariant is exact: with faults or without, every pod
    must end up bound. 1-chip / 2-chip TPU pods plus GPU pods."""
    pods: list[Pod] = []
    tpu_left, gpu_left = 20, 5
    i = 0
    while tpu_left > 0 or gpu_left > 0:
        i += 1
        roll = rng.random()
        if roll < 0.55 and tpu_left >= 1:
            pods.append(Pod(f"c{i}", labels={
                "tpu/accelerator": "tpu", "scv/number": "1"}))
            tpu_left -= 1
        elif roll < 0.80 and tpu_left >= 2:
            pods.append(Pod(f"c{i}", labels={
                "tpu/accelerator": "tpu", "scv/number": "2",
                "scv/memory": "1000"}))
            tpu_left -= 2
        elif gpu_left >= 1:
            pods.append(Pod(f"c{i}", labels={
                "tpu/accelerator": "gpu", "scv/number": "1"}))
            gpu_left -= 1
        else:  # gpu budget gone but the roll asked for gpu: burn tpu
            pods.append(Pod(f"c{i}", labels={
                "tpu/accelerator": "tpu", "scv/number": "1"}))
            tpu_left -= 1
    rng.shuffle(pods)
    return pods


def _build_engine(cluster, clock, plan=None, crash_hook=None,
                  **cfg_kw) -> Scheduler:
    config = SchedulerConfig(
        telemetry_max_age_s=cfg_kw.pop("telemetry_max_age_s", MAX_AGE),
        gang_timeout_s=cfg_kw.pop("gang_timeout_s", 1.0),
        quarantine_threshold=cfg_kw.pop("quarantine_threshold", 0),
        breaker_cooldown_s=cfg_kw.pop("breaker_cooldown_s", 1.0),
        **cfg_kw)
    profile, _allocator, _gang = default_profile(config)
    if crash_hook == "filter":
        profile.filter.append(CrashingFilter(plan, clock))
    elif crash_hook == "score":
        profile.score.append(CrashingScore(plan, clock))
    elif crash_hook == "reserve":
        profile.reserve.append(CrashingReserve(plan, clock))
    return Scheduler(cluster, config, profile=profile, clock=clock)


def _drive(sched, store, plan, pods, rebuild=None):
    """Run the engine to convergence on its virtual clock, applying the
    plan's clock-keyed transitions the call sites can't inject
    (telemetry blackout on/off, engine crash+reconcile). Returns the
    (possibly rebuilt) engine."""
    clock = sched.clock
    blackout_on = False
    crashed: set[float] = set()
    fault_end = plan.fault_end() if plan is not None else 0.0
    budget = 300.0 + fault_end  # virtual-seconds safety net
    cycles = 0
    while True:
        now = clock.time()
        assert now < budget, (
            f"chaos drive did not converge by t={now:.1f}: pending "
            f"{[p.name for p in pods if p.phase == PodPhase.PENDING]}")
        cycles += 1
        assert cycles < 200_000, "chaos drive cycle budget exhausted"
        if plan is not None:
            if plan.active(TELEMETRY_BLACKOUT, now):
                if not blackout_on:
                    blackout_on = True
                    chaos.blackout(store, now, MAX_AGE)
            elif blackout_on:
                blackout_on = False
                chaos.revive(store, now)
            if rebuild is not None:
                for w in plan.windows_of(ENGINE_CRASH):
                    if w.start <= now and w.start not in crashed:
                        crashed.add(w.start)
                        sched = rebuild(sched)
        if sched.run_one() is not None:
            clock.advance(TICK)
            continue
        wake = sched.next_wake_at()
        if wake is None:
            if now >= fault_end and all(
                    p.phase in (PodPhase.BOUND, PodPhase.FAILED)
                    for p in pods):
                return sched
            # idle but a plan transition is still due: step toward it
            clock.advance(0.5)
        else:
            clock.advance(max(wake - clock.time(), TICK))


def _dump_flight(sched, cluster, tag: str) -> str | None:
    """Black-box failure reporting: write the engine's (or fleet's)
    flight-recorder ring to disk so a failed chaos seed ships its
    interleaved fault/recovery timeline with the assertion. Directory:
    $YODA_FLIGHT_DIR (CI uploads it as an artifact on chaos-job failure)
    or a tempdir fallback."""
    if sched is None:
        return None
    d = os.environ.get("YODA_FLIGHT_DIR") or os.path.join(
        tempfile.gettempdir(), "yoda-flight")
    try:
        os.makedirs(d, exist_ok=True)
        events = sched.flight.snapshot()
        injected = dict(getattr(cluster, "injected", {}) or {})
        path = os.path.join(d, f"flight-{tag}.json")
        with open(path, "w") as f:
            json.dump({"reason": f"invariant violation ({tag})",
                       "injected_faults": injected,
                       "events": events}, f, indent=1)
        return path
    except OSError:
        return None


def _assert_invariants(pods, store, cluster, seed, sched=None):
    try:
        _assert_invariants_inner(pods, store, cluster, seed)
    except AssertionError as e:
        # stamp the violation into the ring first (single engines only —
        # the fleet's merged view is read-only), then dump the black box
        flight = getattr(sched, "flight", None)
        if hasattr(flight, "record"):
            flight.record("invariant_violation", seed=str(seed))
        path = _dump_flight(sched, cluster, str(seed))
        if path is not None and hasattr(e, "add_note"):
            e.add_note(f"flight recorder dumped to {path}")
        raise


def _assert_invariants_inner(pods, store, cluster, seed):
    by_metrics = {m.node: m for m in store.list()}

    # 1 + 4. no pod lost / convergence: the workload is satisfiable, so
    # after the fault window closes EVERY pod must be bound — exactly the
    # fault-free placement count
    unbound = [p.name for p in pods if p.phase != PodPhase.BOUND]
    assert not unbound, f"seed {seed}: not converged, unbound {unbound}"

    # 2. no double bind: each pod appears exactly once in the cluster's
    # bound book, on the node it believes it is on
    seen: dict[str, str] = {}
    for node in cluster.node_names():
        for p in cluster.pods_on(node):
            assert p.key not in seen, (
                f"seed {seed}: {p.key} double-bound on {seen[p.key]} "
                f"AND {node}")
            seen[p.key] = node
    for p in pods:
        assert seen.get(p.key) == p.node, (
            f"seed {seed}: {p.name} believes node={p.node}, cluster "
            f"says {seen.get(p.key)}")

    # 3. no chip/HBM oversubscription: exact counts, existing chips,
    # single owner per chip, per-chip claims within the chip's free HBM
    owners: dict[tuple, str] = {}
    for p in pods:
        chips_held = p.assigned_chips()
        m = by_metrics[p.node]
        want = int(p.labels.get("scv/number", "1"))
        assert len(chips_held) == want, (
            f"seed {seed}: {p.name} wanted {want} chips, "
            f"holds {len(chips_held)}")
        node_chips = {c.coords: c for c in m.chips}
        need_mb = int(p.labels.get("scv/memory", "0"))
        for c in chips_held:
            assert c in node_chips, (
                f"seed {seed}: {p.name} holds nonexistent chip "
                f"{p.node}/{c}")
            key = (p.node, c)
            assert key not in owners, (
                f"seed {seed}: chip {key} double-booked by "
                f"{owners[key]} and {p.name}")
            owners[key] = p.name
            assert need_mb <= node_chips[c].hbm_free_mb, (
                f"seed {seed}: {p.name} overcommits HBM on {key}")


# --------------------------------------------------------------- seeded fuzz
_SMOKE_SEEDS = 16
_FULL_SEEDS = 208  # >= 200 scenarios in CI's chaos job


def _seed_params():
    return [s if s < _SMOKE_SEEDS else pytest.param(s, marks=pytest.mark.slow)
            for s in range(_FULL_SEEDS)]


@pytest.mark.parametrize("seed", _seed_params())
def test_chaos_fuzz(seed):
    """One seeded outage scenario end to end: the plan scripts 1-3 fault
    windows (storms, lost binds, blackouts, raising plugins, engine
    crashes), the driver runs the engine through them on a virtual
    clock, and the four global invariants must hold at convergence."""
    rng = random.Random(seed)
    plan = FaultPlan(seed, horizon_s=20.0)
    clock = FakeClock()
    store = _fleet(rng)
    cluster = ChaosCluster(store, plan=plan, clock=clock)
    cluster.add_nodes_from_telemetry()
    crash_hook = (rng.choice(("filter", "score", "reserve"))
                  if PLUGIN_ERROR in plan.kinds() else None)
    pods = _workload(rng)

    def build():
        s = _build_engine(cluster, clock, plan=plan,
                          crash_hook=crash_hook)
        # injected faults land in the engine's black box, so a failing
        # seed's dump reads as one interleaved fault/recovery timeline
        cluster.flight = s.flight
        return s

    def rebuild(_old):
        # ENGINE_CRASH: the process died; all engine-local state
        # (queue, reservations, memos) is gone. Reconcile the workload
        # from cluster truth and keep going.
        fresh = build()
        fresh.reconcile(pods)
        return fresh

    sched = build()
    for p in pods:
        sched.submit(p)
    sched = _drive(sched, store, plan, pods, rebuild=rebuild)
    _assert_invariants(pods, store, cluster, seed, sched=sched)
    # engine thread survived by construction — a raise anywhere in the
    # drive would have failed the test. (Whether a PLUGIN_ERROR window
    # actually intersected live cycles is seed-dependent — pods may all
    # bind before the window opens — so crash counters are asserted in
    # the targeted containment tests, not per fuzz seed.)


# --------------------------------------------------------- fleet chaos fuzz
_FLEET_SMOKE = 16
_FLEET_FULL = 112  # >= 100 multi-replica scenarios in CI's chaos job


def _fleet_seed_params():
    return [s if s < _FLEET_SMOKE
            else pytest.param(s, marks=pytest.mark.slow)
            for s in range(_FLEET_FULL)]


def _drive_fleet(fleet, plan, pods, rng):
    """Run a replica fleet to convergence on its virtual clock, firing the
    plan's fleet transitions the call sites can't inject: REPLICA_CRASH
    (rebuild one replica + reconcile from cluster truth), LEASE_EXPIRY
    (revoke one replica's shard leases mid-drain), and SPLIT_BRAIN
    (duplicate-replica injection: every pod the chosen replica is
    working on gets queued on a second replica too). Storms / lost binds
    ride the ChaosCluster bind surface as in the single-engine fuzz."""
    clock = fleet.clock
    fired: set = set()
    fault_end = plan.fault_end()
    budget = 300.0 + fault_end
    cycles = 0
    while True:
        now = clock.time()
        assert now < budget, (
            f"fleet drive did not converge by t={now:.1f}: pending "
            f"{[p.name for p in pods if p.phase == PodPhase.PENDING]}")
        cycles += 1
        assert cycles < 300_000, "fleet drive cycle budget exhausted"
        for w in plan.windows:
            key = (w.kind, w.start)
            if w.start > now or key in fired:
                continue
            if w.kind == REPLICA_CRASH:
                fired.add(key)
                fleet.crash_replica(rng.randrange(fleet.n), pods)
            elif w.kind == LEASE_EXPIRY:
                fired.add(key)
                fleet.revoke_replica_leases(rng.randrange(fleet.n))
            elif w.kind == SPLIT_BRAIN:
                fired.add(key)
                src = rng.randrange(fleet.n)
                dst = (src + 1 + rng.randrange(fleet.n - 1)) % fleet.n
                for p in pods:
                    if (p.phase == PodPhase.PENDING
                            and fleet.replicas[src].engine.tracks(p.key)):
                        fleet.submit_to(dst, p)
                # the duplicate replica also STEALS one of the original
                # holder's shard leases: src's belief (and its fencing
                # epoch) goes stale without it noticing — in trust-owned
                # fleets the stale token travels all the way to the
                # authority and must bounce there (stale_fence 409)
                src_rep = fleet.replicas[src]
                if src_rep.owned:
                    s = sorted(src_rep.owned)[0]
                    fleet.lease_store.steal(
                        f"yoda-shard-{s}",
                        fleet.replicas[dst].identity)
        if fleet.step(rng) is not None:
            clock.advance(TICK)
            continue
        wake = fleet.next_wake_at()
        if wake is None:
            if now >= fault_end and all(
                    p.phase in (PodPhase.BOUND, PodPhase.FAILED)
                    for p in pods):
                return
            clock.advance(0.5)
        else:
            clock.advance(max(wake - clock.time(), TICK))


@pytest.mark.parametrize("seed", _fleet_seed_params())
def test_fleet_chaos_fuzz(seed):
    """One seeded multi-replica scenario end to end: 2-4 engine replicas
    (sharded or free-for-all) race optimistic commits against the same
    chaos cluster while the plan scripts storms, lost binds, replica
    crashes, lease expiry mid-bind, and split-brain windows — and the
    four invariants must hold FLEET-WIDE at convergence. The authority's
    conflict rejections (not engine bookkeeping) are what carries the
    no-double-bind / no-oversubscription half; post-fault convergence
    carries the rest."""
    rng = random.Random(10_000 + seed)
    plan = FaultPlan(seed, horizon_s=20.0, kinds=FLEET_KINDS)
    clock = FakeClock()
    store = _fleet(rng)
    cluster = ChaosCluster(store, plan=plan, clock=clock)
    cluster.add_nodes_from_telemetry()
    n_replicas = rng.choice((2, 3, 4))
    mode = rng.choice(("sharded", "free-for-all"))
    # both fencing postures: local re-validation (lease loss = clean
    # FENCE_LOST abort) and trust-owned (stale tokens travel to the
    # AUTHORITY and must bounce as stale_fence 409s — the wire posture)
    fleet = FleetCoordinator(
        cluster,
        SchedulerConfig(telemetry_max_age_s=MAX_AGE,
                        breaker_cooldown_s=1.0),
        replicas=n_replicas, clock=clock, mode=mode, seed=seed,
        validate_fence_locally=bool(rng.getrandbits(1)))
    pods = _workload(rng)
    for p in pods:
        fleet.submit(p)
    _drive_fleet(fleet, plan, pods, rng)
    _assert_invariants(pods, store, cluster, f"fleet-{seed}", sched=fleet)
    # the authority's conflict book is consistent with the outcome: any
    # server-side rejection was resolved (the invariants above prove no
    # rejected commit ever half-landed). pods_scheduled_total is NOT
    # asserted against the workload — a crashed replica's counters die
    # with it, and reconcile ADOPTS its binds without re-counting them.
    stats = fleet.fleet_stats()
    assert all(v >= 0 for v in stats["authority_rejections"].values())


# ----------------------------- parallel-heads chaos fuzz (ISSUE 16)
_HEADS_SMOKE = 8
_HEADS_FULL = 48


def _heads_seed_params():
    return [s if s < _HEADS_SMOKE
            else pytest.param(s, marks=pytest.mark.slow)
            for s in range(_HEADS_FULL)]


@pytest.mark.parametrize("seed", _heads_seed_params())
def test_heads_chaos_fuzz(seed):
    """One seeded scenario with INTRA-replica parallel heads crossed
    with the fleet fault grammar: 2-3 replicas, each running 2-4
    scheduling heads over one shared queue and allocator, race
    optimistic commits against storms, lost binds, replica crashes,
    lease expiry mid-bind, and split-brain windows. Heads multiply the
    commit-race surface INSIDE each replica (same-queue pops, shared
    reservations, per-head dispatch) on top of the inter-replica races
    the fleet fuzz covers — and the same four invariants must hold at
    convergence: no pod lost, no double bind, no chip/HBM
    oversubscription, full convergence. The deterministic step driver
    seeds the head interleave (HeadSet.step shuffles per fleet rng), so
    a failing seed replays bit-exact."""
    rng = random.Random(70_000 + seed)
    plan = FaultPlan(seed, horizon_s=20.0, kinds=FLEET_KINDS)
    clock = FakeClock()
    store = _fleet(rng)
    cluster = ChaosCluster(store, plan=plan, clock=clock)
    cluster.add_nodes_from_telemetry()
    n_replicas = rng.choice((2, 3))
    n_heads = rng.choice((2, 3, 4))
    mode = rng.choice(("sharded", "free-for-all"))
    fleet = FleetCoordinator(
        cluster,
        SchedulerConfig(telemetry_max_age_s=MAX_AGE,
                        breaker_cooldown_s=1.0,
                        schedule_heads=n_heads),
        replicas=n_replicas, clock=clock, mode=mode, seed=seed,
        validate_fence_locally=bool(rng.getrandbits(1)))
    assert all(r.headset is not None and r.headset.n == n_heads
               for r in fleet.replicas)
    pods = _workload(rng)
    for p in pods:
        fleet.submit(p)
    _drive_fleet(fleet, plan, pods, rng)
    _assert_invariants(pods, store, cluster, f"heads-{seed}", sched=fleet)
    # every replica's heads still share ONE allocator after any
    # crash-rebuilds (the rebuilt headset re-wires the sharing)
    for rep in fleet.replicas:
        assert all(h.allocator is rep.engine.allocator
                   for h in rep.headset.heads)
    stats = fleet.fleet_stats()
    assert all(v >= 0 for v in stats["authority_rejections"].values())
    assert "heads" in stats


# ----------------------- elastic/defrag chaos fuzz (ISSUE 10 satellite)
_EL_SMOKE = 8
_EL_FULL = 48


def _elastic_seed_params():
    return [s if s < _EL_SMOKE
            else pytest.param(s, marks=pytest.mark.slow)
            for s in range(_EL_FULL)]


def _elastic_workload(rng: random.Random) -> list[Pod]:
    """Satisfiable ONLY through defragmentation: one elastic gang wants
    the whole 4-host slice (4 x 4 chips, min 2) while singles — bounded
    by standalone capacity (12 chips) — may initially land ON the slice.
    Convergence therefore requires the defrag loop to migrate them off,
    and the gang to ride admission-at-min + growth through the faults."""
    pods = [Pod(f"eg-w{i}", labels={
        "tpu/gang-name": "eg", "tpu/gang-size": "4", "tpu/gang-min": "2",
        "scv/number": "4"}) for i in range(4)]
    for i in range(rng.randint(6, 10)):
        pods.append(Pod(f"s{i}", labels={
            "tpu/accelerator": "tpu", "scv/number": "1"}))
    for i in range(rng.randint(0, 4)):
        pods.append(Pod(f"gp{i}", labels={
            "tpu/accelerator": "gpu", "scv/number": "1"}))
    rng.shuffle(pods)
    return pods


def _gang_bound_now(cluster, gang: str) -> int:
    return sum(1 for n in cluster.node_names()
               for p in cluster.pods_on(n)
               if p.labels.get("tpu/gang-name") == gang
               and not p.terminating)


def _drive_elastic_fleet(fleet, plan, pods, rng, views, store):
    """_drive_fleet plus the elastic-era transitions: DEFRAG_RACE forces
    the owning replica's migration pass at the seeded instant (evictions
    interleaved with other replicas' binds on the same nodes) and
    NETWORK_PARTITION freezes one replica's view mid-growth. Checks the
    FIFTH invariant continuously: once the gang reached its min, our own
    migrations/evictions never take cluster truth below it."""
    clock = fleet.clock
    fired: set = set()
    active: dict = {}
    fault_end = plan.fault_end()
    budget = 300.0 + fault_end
    cycles = 0
    reached_min = False
    while True:
        now = clock.time()
        assert now < budget, (
            f"elastic drive did not converge by t={now:.1f}: pending "
            f"{[p.name for p in pods if p.phase == PodPhase.PENDING]}")
        cycles += 1
        assert cycles < 300_000, "elastic drive cycle budget exhausted"
        bound = _gang_bound_now(fleet.cluster, "eg")
        if bound >= 2:
            reached_min = True
        elif reached_min:
            raise AssertionError(
                f"gang dropped below min: {bound}/2 bound at t={now:.1f}")
        for w in plan.windows:
            key = (w.kind, w.start)
            if w.start > now or key in fired:
                continue
            if w.kind == REPLICA_CRASH:
                fired.add(key)
                fleet.crash_replica(rng.randrange(fleet.n), pods)
            elif w.kind == NETWORK_PARTITION:
                fired.add(key)
                idx = rng.randrange(fleet.n)
                views[idx].freeze()
                active[key] = (w.end, views[idx].thaw)
            elif w.kind == DEFRAG_RACE:
                fired.add(key)
                # force the migration pass NOW, on whichever replica
                # currently owns it — its evictions land between the
                # other replicas' optimistic binds on the same nodes
                for rep in fleet.replicas:
                    d = rep.engine.defrag
                    if d is not None and (d.owner_check is None
                                          or d.owner_check()):
                        d.run_pass(now)
                        break
        for key in list(active):
            end, undo = active[key]
            if now >= end:
                undo()
                del active[key]
        if fleet.step(rng) is not None:
            clock.advance(TICK)
            continue
        wake = fleet.next_wake_at()
        if wake is None:
            if now >= fault_end and not active and all(
                    p.phase in (PodPhase.BOUND, PodPhase.FAILED)
                    for p in pods):
                return
            clock.advance(0.5)
        else:
            clock.advance(max(wake - clock.time(), TICK))


@pytest.mark.parametrize("seed", _elastic_seed_params())
def test_elastic_defrag_chaos_fuzz(seed):
    """One seeded elastic/defrag scenario end to end: a 2-3 replica
    sharded fleet with the defrag loop live (shard-0 owner only) and an
    elastic gang growing from min toward full, while the plan scripts
    storms, lost binds, replica crashes, partitions, and DEFRAG_RACE
    windows (the descheduler evicting while another replica binds the
    same node). The four global invariants must hold fleet-wide at
    convergence, plus the fifth: no gang ever drops below its
    tpu/gang-min from our own migrations, and no pod migrates more than
    once per cooldown window."""
    rng = random.Random(50_000 + seed)
    plan = FaultPlan(seed, horizon_s=20.0, kinds=ELASTIC_KINDS)
    clock = FakeClock()
    store = _fleet(rng)
    # the feed stays LIVE through the whole run (no TELEMETRY_BLACKOUT
    # in ELASTIC_KINDS): this fuzz's convergence depends on the defrag
    # loop, and its degraded-mode interlock — correctly — refuses to
    # migrate off a dead feed (the interlock itself is pinned by
    # tests/test_elastic.py::TestDefragController). Re-put so the
    # store's heartbeat floor/ceiling follow.
    for m in store.list():
        m.heartbeat = 1e8
        store.put(m)
    cluster = ChaosCluster(store, plan=plan, clock=clock)
    cluster.add_nodes_from_telemetry()
    n_replicas = rng.choice((2, 3))
    views: dict = {}

    def wrap(c, idx):
        v = PartitionableView(c)
        views[idx] = v
        return v

    fleet = FleetCoordinator(
        cluster,
        SchedulerConfig(telemetry_max_age_s=MAX_AGE,
                        breaker_cooldown_s=1.0,
                        elastic_gangs=True,
                        gang_timeout_s=2.0,
                        defrag_interval_s=2.0,
                        defrag_cooldown_s=5.0),
        replicas=n_replicas, clock=clock, mode="sharded", seed=seed,
        validate_fence_locally=bool(rng.getrandbits(1)),
        cluster_wrapper=wrap)
    pods = _elastic_workload(rng)
    for p in pods:
        fleet.submit(p)
    _drive_elastic_fleet(fleet, plan, pods, rng, views, store)
    _assert_invariants(pods, store, cluster, f"elastic-{seed}",
                       sched=fleet)
    # the gang converged to FULL size (the workload is satisfiable once
    # defrag moves the singles off the slice)
    assert _gang_bound_now(cluster, "eg") == 4
    # migration churn bounded: no pod migrates more than once per
    # cooldown window. Checked per engine ring (the cooldown book is
    # engine-local; a crashed replica's replacement starts a fresh one)
    # from the defrag_pass flight events' pod lists + timestamps.
    for rep in fleet.replicas:
        per_pod: dict[str, float] = {}
        for ev in rep.engine.flight.snapshot():
            if ev["kind"] != "defrag_pass":
                continue
            for key in ev.get("pods", ()):
                last = per_pod.get(key)
                assert last is None or ev["ts"] - last >= 5.0 - 1e-6, (
                    f"seed {seed}: {key} migrated twice inside the "
                    f"cooldown window ({last} -> {ev['ts']})")
                per_pod[key] = ev["ts"]


# -------------------------------------- webhook-era chaos fuzz (vanilla
# authority + webhook gate + partition/skew/slow-apiserver windows)
_WH_SMOKE = 16
_WH_FULL = 96


def _wh_seed_params():
    return [s if s < _WH_SMOKE
            else pytest.param(s, marks=pytest.mark.slow)
            for s in range(_WH_FULL)]


def _ownership(fleet):
    """shard -> owning replica idx, or None while ownership is split,
    duplicated, or incomplete."""
    owned = {}
    for rep in fleet.replicas:
        for s in rep.owned:
            if s in owned:
                return None
            owned[s] = rep.idx
    if set(owned) != set(range(fleet.shard_count)):
        return None
    return owned


def _drive_webhook_fleet(fleet, plan, pods, rng, views):
    """Like _drive_fleet, plus the windowed faults the call sites can't
    inject: NETWORK_PARTITION freezes a seeded replica's cluster view for
    the window (binds still flow), CLOCK_SKEW drifts a replica's lease
    clock slow past the lease duration (renewals silently missed).
    SLOW_APISERVER / WEBHOOK_DOWN live inside the chaos cluster."""
    clock = fleet.clock
    fired: set = set()
    active: dict = {}  # (kind, start) -> (end, undo)
    fault_end = plan.fault_end()
    budget = 300.0 + fault_end
    cycles = 0
    while True:
        now = clock.time()
        assert now < budget, (
            f"webhook-fleet drive did not converge by t={now:.1f}: pending "
            f"{[p.name for p in pods if p.phase == PodPhase.PENDING]}")
        cycles += 1
        assert cycles < 300_000, "webhook-fleet drive budget exhausted"
        for w in plan.windows:
            key = (w.kind, w.start)
            if w.start > now or key in fired:
                continue
            if w.kind == REPLICA_CRASH:
                fired.add(key)
                # a crash during a partition implicitly heals it: the
                # replacement replica starts with a fresh (live) view
                fleet.crash_replica(rng.randrange(fleet.n), pods)
            elif w.kind == NETWORK_PARTITION:
                fired.add(key)
                idx = rng.randrange(fleet.n)
                views[idx].freeze()
                active[key] = (w.end, views[idx].thaw)
            elif w.kind == CLOCK_SKEW:
                fired.add(key)
                idx = rng.randrange(fleet.n)
                skew = -(fleet.lease_duration_s * 2
                         + rng.uniform(0.0, 3.0))
                fleet.skew_replica_clock(idx, skew)
                active[key] = (
                    w.end,
                    lambda i=idx: fleet.skew_replica_clock(i, 0.0))
        for key in list(active):
            end, undo = active[key]
            if now >= end:
                undo()
                del active[key]
        if fleet.step(rng) is not None:
            clock.advance(TICK)
            continue
        wake = fleet.next_wake_at()
        if wake is None:
            if now >= fault_end and not active and all(
                    p.phase in (PodPhase.BOUND, PodPhase.FAILED)
                    for p in pods):
                return
            clock.advance(0.5)
        else:
            clock.advance(max(wake - clock.time(), TICK))


@pytest.mark.parametrize("seed", _wh_seed_params())
def test_webhook_chaos_fuzz(seed):
    """One seeded scenario against the VANILLA-authority posture: the
    server itself enforces only the pod-level 409; the chip/HBM/fence
    battery lives in the webhook gate, which the plan can take DOWN while
    replicas are partitioned (watch frozen, binds flowing), clock-skewed
    (renewals silently missed), or behind a slow apiserver. The four
    invariants must hold fleet-wide, and shard ownership must re-converge
    to the preferred mapping afterwards (no permanently orphaned or
    sticky shard).

    Fail mode alternates by seed. failOpen's one documented blind spot —
    a partition CONCURRENT with webhook downtime — is excluded for
    fail-open seeds (the deployment guidance; the hazard itself is
    pinned by test_failopen_partition_hazard_is_real below)."""
    rng = random.Random(30_000 + seed)
    fail_open = bool(seed % 2)
    plan = FaultPlan(seed, horizon_s=20.0, kinds=WEBHOOK_KINDS)
    if fail_open:
        down = plan.windows_of(WEBHOOK_DOWN)
        plan.windows = [
            w for w in plan.windows
            if w.kind != NETWORK_PARTITION
            or not any(w.start < d.end and d.start < w.end for d in down)]
    clock = FakeClock()
    store = _fleet(rng)
    cluster = VanillaAuthorityCluster(store, plan=plan, clock=clock,
                                      fail_open=fail_open)
    cluster.add_nodes_from_telemetry()
    n_replicas = rng.choice((2, 3))
    views: dict = {}

    def wrap(c, idx):
        v = PartitionableView(c)
        views[idx] = v
        return v

    fleet = FleetCoordinator(
        cluster,
        SchedulerConfig(telemetry_max_age_s=MAX_AGE,
                        breaker_cooldown_s=1.0),
        replicas=n_replicas, clock=clock, mode="sharded", seed=seed,
        lease_duration_s=2.0, renew_period_s=0.25, rebalance_s=1.0,
        validate_fence_locally=bool(rng.getrandbits(1)),
        cluster_wrapper=wrap)
    pods = _workload(rng)
    for p in pods:
        fleet.submit(p)
    _drive_webhook_fleet(fleet, plan, pods, rng, views)
    _assert_invariants(pods, store, cluster, f"webhook-{seed}",
                       sched=fleet)
    # shard ownership re-convergence: every shard ends owned by exactly
    # its preferred replica (all replicas alive at the end), through the
    # heartbeat-keyed rebalance handoffs — no orphan, no sticky takeover
    deadline = clock.time() + 120.0
    while clock.time() < deadline:
        owned = _ownership(fleet)
        if owned is not None and all(i == s % fleet.n
                                     for s, i in owned.items()):
            break
        fleet.step(rng)
        clock.advance(0.25)
    owned = _ownership(fleet)
    assert owned is not None, (
        f"seed {seed}: shard ownership never re-converged: "
        f"{[sorted(r.owned) for r in fleet.replicas]}")
    assert all(i == s % fleet.n for s, i in owned.items()), (
        f"seed {seed}: takeover stayed sticky: {owned}")


# ----------------------- targeted: partition / skew / slow / webhook-down
def _two_chip_rig(plan=None, fail_open=False):
    """One node, two chips: pod A (1 chip) + pod B (2 chips) can never
    both fit — the staging for every partition-conflict test."""
    clock = FakeClock()
    store = TelemetryStore()
    m = make_tpu_node("n0", chips=2)
    m.heartbeat = 0.0
    store.put(m)
    cluster = VanillaAuthorityCluster(store, plan=plan, clock=clock,
                                      fail_open=fail_open)
    cluster.add_nodes_from_telemetry()
    views: dict = {}

    def wrap(c, idx):
        v = PartitionableView(c)
        views[idx] = v
        return v

    fleet = FleetCoordinator(
        cluster, SchedulerConfig(telemetry_max_age_s=MAX_AGE),
        replicas=2, clock=clock, mode="free-for-all", seed=1,
        cluster_wrapper=wrap)
    return clock, store, cluster, fleet, views


def test_partitioned_replica_stale_bind_caught_by_webhook():
    """A replica that can bind but not watch places off its frozen view;
    with the webhook UP, its chip-overlapping commit bounces at the API
    boundary (chip_claim 409) and nothing double-books — the exact
    safety claim the webhook port exists for."""
    clock, store, cluster, fleet, views = _two_chip_rig()
    a = Pod("a", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    b = Pod("b", labels={"tpu/accelerator": "tpu", "scv/number": "2"})
    views[1].freeze()  # replica 1 loses its watch BEFORE a binds
    fleet.submit_to(0, a)
    assert fleet.replicas[0].engine.run_one() == "bound"
    assert a.phase == PodPhase.BOUND
    # replica 1 schedules b off the frozen (both-chips-free) view
    fleet.submit_to(1, b)
    outcomes = []
    for _ in range(12):
        out = fleet.replicas[1].engine.run_one()
        if out is None:
            break
        outcomes.append(out)
        clock.advance(0.05)
    assert cluster.bind_conflicts.get("chip_claim", 0) >= 1, outcomes
    assert b.phase != PodPhase.BOUND
    # no double-booking: chip owners are disjoint
    owners = {}
    for p in cluster.all_pods():
        for c in p.assigned_chips():
            assert (p.node, c) not in owners
            owners[(p.node, c)] = p.name
    views[1].thaw()


def test_failopen_partition_hazard_is_real():
    """The contrast case, and the reason failOpen is NOT the default:
    with the webhook DOWN in fail-open AND the replica partitioned, the
    stale commit sails through the pod-level-only check and the chips
    double-book. This is the documented trade — the fuzz keeps these two
    windows disjoint for fail-open seeds, and deployments that cannot
    rule the overlap out must run failurePolicy=Fail."""
    plan = FaultPlan(0, horizon_s=100.0)
    plan.windows = [FaultWindow(WEBHOOK_DOWN, 0.0, 1e9)]
    clock, store, cluster, fleet, views = _two_chip_rig(
        plan=plan, fail_open=True)
    a = Pod("a", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    b = Pod("b", labels={"tpu/accelerator": "tpu", "scv/number": "2"})
    views[1].freeze()
    fleet.submit_to(0, a)
    assert fleet.replicas[0].engine.run_one() == "bound"
    fleet.submit_to(1, b)
    assert fleet.replicas[1].engine.run_one() == "bound"  # unchecked!
    assert b.phase == PodPhase.BOUND
    claimed = a.assigned_chips() & b.assigned_chips()
    assert claimed, "expected the fail-open double-booking to demonstrate"
    assert cluster.webhook_skipped >= 1


def test_slow_apiserver_is_latency_not_failure():
    """SLOW_APISERVER: binds complete after injected delay. The breaker
    must never count it, nothing backs off, every pod binds."""
    clock = FakeClock()
    plan = FaultPlan(0, horizon_s=10.0)
    plan.windows = [FaultWindow(SLOW_APISERVER, 0.0, 5.0)]
    store, cluster = _simple_rig(clock=clock, cluster_cls=ChaosCluster,
                                 plan=plan)
    cluster.clock = clock
    sched = _build_engine(cluster, clock, breaker_threshold=3,
                          telemetry_max_age_s=1e9)
    pods = [Pod(f"p{i}", labels={"tpu/accelerator": "tpu",
                                 "scv/number": "1"}) for i in range(5)]
    for p in pods:
        sched.submit(p)
    _drain(sched, pods)
    assert all(p.phase == PodPhase.BOUND for p in pods)
    c = sched.metrics.counters
    assert cluster.injected[SLOW_APISERVER] >= 1
    assert c.get("breaker_opens_total", 0) == 0
    assert c.get("bind_errors_total", 0) == 0


def test_clock_skew_stale_fence_bounces_at_authority():
    """A replica whose lease clock drifts slow silently misses renewals;
    its shards expire and change hands while it keeps committing on the
    old epochs (trust-owned posture) — the stale fences must bounce at
    the AUTHORITY, and the replica must recover once the drift heals."""
    clock = FakeClock()
    store = _fleet(random.Random(7))
    cluster = ChaosCluster(store, clock=clock)
    cluster.add_nodes_from_telemetry()
    fleet = FleetCoordinator(
        cluster, SchedulerConfig(telemetry_max_age_s=MAX_AGE),
        replicas=2, clock=clock, mode="sharded", seed=7,
        lease_duration_s=2.0, renew_period_s=0.25, rebalance_s=0.0,
        validate_fence_locally=False)
    rng = random.Random(7)
    # let both replicas acquire their preferred shards
    for _ in range(4):
        fleet.step(rng)
        clock.advance(0.3)
    assert all(rep.owned for rep in fleet.replicas)
    # the drifting replica must be the one whose shard holds the TPU
    # nodes, or its stale fence never rides a TPU bind (the tpu-shard is
    # deterministic for this seed's node names: crc32 puts them in 1)
    from yoda_scheduler_tpu.scheduler.fleet import shard_of
    tpu_shard = shard_of("t0", fleet.shard_count)
    victim = tpu_shard % fleet.n
    other = 1 - victim
    # the victim drifts 100s slow: its renewals stop dead
    fleet.skew_replica_clock(victim, -100.0)
    clock.advance(3.0)  # past the lease duration: its shards expire
    fleet.step(rng)     # the peer's upkeep takes the expired shards over
    stale = dict(fleet.replicas[victim].owned)
    assert tpu_shard in stale, \
        "victim should still BELIEVE it owns the tpu shard"
    assert tpu_shard in fleet.replicas[other].owned, \
        "peer never took over the expired shard"
    # the victim commits into its believed-owned shard with dead epochs
    pods = [Pod(f"p{i}", labels={"tpu/accelerator": "tpu",
                                 "scv/number": "1"}) for i in range(4)]
    for p in pods:
        fleet.submit_to(victim, p)
    for _ in range(30):
        if cluster.bind_conflicts.get("stale_fence", 0) >= 1:
            break
        if fleet.replicas[victim].engine.run_one() is None:
            clock.advance(0.1)
    assert cluster.bind_conflicts.get("stale_fence", 0) >= 1
    # heal the drift: the victim's next upkeep drops the lost leases and
    # everything converges unfenced/re-fenced
    fleet.skew_replica_clock(victim, 0.0)
    _drive_fleet(fleet, FaultPlan(0, horizon_s=0.1), pods, rng)
    assert all(p.phase == PodPhase.BOUND for p in pods)
    _assert_invariants(pods, store, cluster, "clock-skew", sched=fleet)


def test_partition_heals_and_view_rebuilds():
    """After thaw, the replica's memos must NOT serve frozen-era state:
    foreign binds that landed during the partition are visible and the
    replica places around them."""
    clock, store, cluster, fleet, views = _two_chip_rig()
    a = Pod("a", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    b = Pod("b", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    views[1].freeze()
    fleet.submit_to(0, a)
    assert fleet.replicas[0].engine.run_one() == "bound"
    views[1].thaw()
    fleet.submit_to(1, b)
    assert fleet.replicas[1].engine.run_one() == "bound"
    assert b.phase == PodPhase.BOUND
    assert not (a.assigned_chips() & b.assigned_chips())
    assert cluster.bind_conflicts.get("chip_claim", 0) == 0

# ------------------------------------------------- targeted: crash containment
def _simple_rig(n_nodes=4, clock=None, cluster_cls=FakeCluster, **ck):
    store = TelemetryStore()
    for i in range(n_nodes):
        m = make_tpu_node(f"n{i}", chips=4)
        m.heartbeat = 0.0
        store.put(m)
    cluster = cluster_cls(store, **ck)
    cluster.add_nodes_from_telemetry()
    return store, cluster


def _drain(sched, pods, budget=200.0):
    clock = sched.clock
    while not all(p.phase in (PodPhase.BOUND, PodPhase.FAILED)
                  for p in pods):
        assert clock.time() < budget, (
            "drain stalled: "
            f"{[(p.name, p.phase) for p in pods]}")
        if sched.run_one() is None:
            wake = sched.next_wake_at()
            assert wake is not None, "engine idle with unresolved pods"
            clock.advance(max(wake - clock.time(), 0.01))
        else:
            clock.advance(0.01)


@pytest.mark.parametrize("hook", ["filter", "score", "reserve"])
def test_plugin_crash_contained_and_quarantined(hook):
    """A plugin RAISING in filter/score/reserve never kills the engine
    thread: the poison pod crash-requeues, is quarantined at the
    threshold (counter asserted), and every healthy pod still binds."""
    clock = FakeClock()
    store, cluster = _simple_rig(clock=clock)
    config = SchedulerConfig(telemetry_max_age_s=1e9,
                             quarantine_threshold=3)
    profile, _a, _g = default_profile(config)
    poison = lambda p: p.name == "poison"  # noqa: E731
    if hook == "filter":
        profile.filter.append(CrashingFilter(match=poison))
    elif hook == "score":
        profile.score.append(CrashingScore(match=poison))
    else:
        profile.reserve.append(CrashingReserve(match=poison))
    sched = Scheduler(cluster, config, profile=profile, clock=clock)
    pods = [Pod("poison", labels={"tpu/accelerator": "tpu",
                                  "scv/number": "1"})]
    for i in range(4):
        pods.append(Pod(f"ok{i}", labels={"tpu/accelerator": "tpu",
                                          "scv/number": "1"}))
    for p in pods:
        sched.submit(p)
    _drain(sched, pods)
    for p in pods[1:]:
        assert p.phase == PodPhase.BOUND, (hook, p.name)
    assert pods[0].phase == PodPhase.FAILED
    assert "default/poison" in sched.quarantined
    assert sched.metrics.counters["cycle_crashes_total"] == 3
    assert sched.metrics.counters["pods_quarantined_total"] == 1
    # a crashed cycle must not leak its (partial) reservation
    if sched.allocator is not None:
        for n in cluster.node_names():
            assert not sched.allocator.pending_on(n)


def test_quarantine_disabled_keeps_requeueing():
    """quarantine_threshold=0: crashes requeue forever — and once the
    crash condition clears (here: a plan window ending), the pod binds."""
    clock = FakeClock()
    store, cluster = _simple_rig(clock=clock)
    plan = FaultPlan(0, horizon_s=10.0)
    plan.windows = [FaultWindow(PLUGIN_ERROR, 0.0, 5.0)]
    config = SchedulerConfig(telemetry_max_age_s=1e9,
                             quarantine_threshold=0)
    profile, _a, _g = default_profile(config)
    profile.filter.append(CrashingFilter(plan, clock))
    sched = Scheduler(cluster, config, profile=profile, clock=clock)
    pod = Pod("p", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    sched.submit(pod)
    _drain(sched, [pod])
    assert pod.phase == PodPhase.BOUND
    assert sched.metrics.counters["cycle_crashes_total"] >= 1
    assert sched.metrics.counters.get("pods_quarantined_total", 0) == 0


# ---------------------------------------------------- targeted: circuit breaker
def test_breaker_opens_parks_and_recovers():
    """An apiserver error storm trips the breaker after the threshold;
    scheduling parks (bounded bind attempts instead of a retry storm),
    the post-cooldown probe reopens on failure, and the first success
    after the storm closes the breaker — everything then binds."""
    clock = FakeClock()
    plan = FaultPlan(0, horizon_s=10.0)
    plan.windows = [FaultWindow(APISERVER_STORM, 0.0, 4.0)]
    store, cluster = _simple_rig(clock=clock, cluster_cls=ChaosCluster,
                                 plan=plan)
    cluster.clock = clock
    sched = _build_engine(cluster, clock, breaker_threshold=3,
                          telemetry_max_age_s=1e9)
    pods = [Pod(f"p{i}", labels={"tpu/accelerator": "tpu",
                                 "scv/number": "1"}) for i in range(6)]
    for p in pods:
        sched.submit(p)
    _drain(sched, pods)
    assert all(p.phase == PodPhase.BOUND for p in pods)
    c = sched.metrics.counters
    assert c["breaker_opens_total"] >= 1
    assert c["breaker_parked_cycles_total"] >= 1
    assert c["breaker_closes_total"] >= 1
    # the breaker's whole point: the 4s storm sees a handful of bind
    # attempts (threshold + one probe per reopen), not one per pod per
    # backoff tick
    assert cluster.injected[APISERVER_STORM] <= 8, cluster.injected


# ------------------------------------------- targeted: flight recorder
def test_flight_recorder_auto_dumps_on_breaker_open(tmp_path):
    """The black box: a storm that opens the breaker must leave a dump on
    disk (the trip kind auto-dump), and the ring must read as an
    interleaved timeline — injected faults (ChaosCluster.flight) next to
    the engine's breaker transitions."""
    clock = FakeClock()
    plan = FaultPlan(0, horizon_s=10.0)
    plan.windows = [FaultWindow(APISERVER_STORM, 0.0, 4.0)]
    store, cluster = _simple_rig(clock=clock, cluster_cls=ChaosCluster,
                                 plan=plan)
    cluster.clock = clock
    sched = _build_engine(cluster, clock, breaker_threshold=3,
                          telemetry_max_age_s=1e9,
                          flight_dump_dir=str(tmp_path))
    cluster.flight = sched.flight
    pods = [Pod(f"p{i}", labels={"tpu/accelerator": "tpu",
                                 "scv/number": "1"}) for i in range(4)]
    for p in pods:
        sched.submit(p)
    _drain(sched, pods)
    assert all(p.phase == PodPhase.BOUND for p in pods)
    kinds = [e["kind"] for e in sched.flight.snapshot()]
    assert "fault_injected" in kinds
    assert "breaker_open" in kinds
    assert "breaker_close" in kinds
    # chronology: the first injected fault precedes the breaker opening
    assert kinds.index("fault_injected") < kinds.index("breaker_open")
    # the trip kind auto-dumped to the configured directory
    assert sched.flight.dumps, "breaker_open did not dump the black box"
    with open(sched.flight.dumps[0]) as f:
        doc = json.load(f)
    assert doc["reason"] == "breaker_open"
    assert any(e["kind"] == "breaker_open" for e in doc["events"])


def test_invariant_violation_dump_path(tmp_path, monkeypatch):
    """_assert_invariants ships the black box with a failed seed: force a
    bogus invariant check and assert the dump lands in $YODA_FLIGHT_DIR."""
    monkeypatch.setenv("YODA_FLIGHT_DIR", str(tmp_path))
    clock = FakeClock()
    store, cluster = _simple_rig(clock=clock)
    sched = _build_engine(cluster, clock, telemetry_max_age_s=1e9)
    pod = Pod("p0", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    sched.submit(pod)
    _drain(sched, [pod])
    # lie about the pod's phase so invariant 1 trips
    pod.phase = PodPhase.PENDING
    with pytest.raises(AssertionError):
        _assert_invariants([pod], store, cluster, "forced", sched=sched)
    dumps = list(tmp_path.iterdir())
    assert dumps, "invariant violation did not dump the flight recorder"
    doc = json.loads(dumps[0].read_text())
    assert any(e["kind"] == "invariant_violation" for e in doc["events"])
    pod.phase = PodPhase.BOUND  # restore for any shared state


# ------------------------------------------------------ targeted: degraded mode
def test_blackout_degrades_then_recovers_without_restart():
    clock = FakeClock()
    store, cluster = _simple_rig(clock=clock)
    sched = _build_engine(cluster, clock, telemetry_max_age_s=30.0)
    # the whole feed is dark: heartbeats at 0, clock far past max_age
    clock.advance(100.0)
    first = [Pod(f"a{i}", labels={"tpu/accelerator": "tpu",
                                  "scv/number": "1"}) for i in range(3)]
    for p in first:
        sched.submit(p)
    _drain(sched, first)
    assert all(p.phase == PodPhase.BOUND for p in first)
    degraded_after_blackout = sched.metrics.counters["degraded_cycles_total"]
    assert degraded_after_blackout > 0
    assert sched.metrics.gauges["degraded"] == 1.0
    # recovery: fresh telemetry lands; NO restart — the same engine flips
    # back to telemetry-aware scheduling
    chaos.revive(store, clock.time())
    second = [Pod(f"b{i}", labels={"tpu/accelerator": "tpu",
                                   "scv/number": "1"}) for i in range(3)]
    for p in second:
        sched.submit(p)
    _drain(sched, second)
    assert all(p.phase == PodPhase.BOUND for p in second)
    assert sched.metrics.gauges["degraded"] == 0.0
    assert sched.metrics.counters["degraded_cycles_total"] == \
        degraded_after_blackout  # no degraded cycles after recovery


def test_blackout_without_degraded_mode_binds_nothing():
    """The contrast case: degraded_mode=False restores the old behaviour
    — a blackout marks every node stale-infeasible and nothing binds."""
    clock = FakeClock()
    store, cluster = _simple_rig(clock=clock)
    sched = _build_engine(cluster, clock, telemetry_max_age_s=30.0,
                          degraded_mode=False, max_attempts=2)
    clock.advance(100.0)
    pods = [Pod(f"p{i}", labels={"tpu/accelerator": "tpu",
                                 "scv/number": "1"}) for i in range(3)]
    for p in pods:
        sched.submit(p)
    _drain(sched, pods)
    assert all(p.phase == PodPhase.FAILED for p in pods)
    assert sched.metrics.counters.get("degraded_cycles_total", 0) == 0


def test_blackout_bench_leg_degrades_to_capacity_only():
    """Acceptance: the scale bench's blackout leg binds off last-known
    capacity (bound > 0, degraded_cycles > 0) instead of zero binds."""
    from bench import run_scale

    out = run_scale(2, pods_per_node=2, blackout=True)
    assert out["bound"] > 0, out
    assert out["degraded_cycles"] > 0, out


# -------------------------------------------- targeted: ambiguous-bind adoption
def test_sync_lost_response_bind_adopted_not_duplicated():
    clock = FakeClock()
    plan = FaultPlan(0, horizon_s=10.0)
    plan.windows = [FaultWindow(BIND_LOST, 0.0, 1e9)]
    store, cluster = _simple_rig(clock=clock, cluster_cls=ChaosCluster,
                                 plan=plan)
    cluster.clock = clock
    sched = _build_engine(cluster, clock, telemetry_max_age_s=1e9)
    pods = [Pod(f"p{i}", labels={"tpu/accelerator": "tpu",
                                 "scv/number": "1"}) for i in range(3)]
    for p in pods:
        sched.submit(p)
    _drain(sched, pods)
    assert all(p.phase == PodPhase.BOUND for p in pods)
    # every bind's response was lost; every one was adopted off cluster
    # truth — zero requeues, zero duplicate bind attempts
    assert sched.metrics.counters["ambiguous_bind_recoveries_total"] == 3
    assert sched.metrics.counters.get("bind_errors_total", 0) == 0
    assert cluster.bind_calls == 3
    _assert_invariants(pods, store, cluster, "sync-lost")


def test_async_storm_failure_reenters_via_drain():
    """Satellite: an async bind wire failure re-enters the engine through
    _drain_bind_failures and the pod binds on retry."""
    clock = FakeClock()
    store, cluster = _simple_rig(clock=clock, cluster_cls=AsyncChaosCluster,
                                 bind_script={0: APISERVER_STORM})
    cluster.clock = clock
    sched = _build_engine(cluster, clock, telemetry_max_age_s=1e9,
                          breaker_threshold=0)
    pod = Pod("p", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    sched.submit(pod)
    _drain(sched, [pod])
    assert pod.phase == PodPhase.BOUND
    assert sched.metrics.counters["bind_errors_total"] == 1
    assert cluster.bind_calls == 2  # failed dispatch + successful retry
    _assert_invariants([pod], store, cluster, "async-storm")


def test_async_lost_response_adopted_in_drain():
    clock = FakeClock()
    store, cluster = _simple_rig(clock=clock, cluster_cls=AsyncChaosCluster,
                                 bind_script={0: BIND_LOST})
    cluster.clock = clock
    sched = _build_engine(cluster, clock, telemetry_max_age_s=1e9)
    pod = Pod("p", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    sched.submit(pod)
    _drain(sched, [pod])
    sched.run_one()  # the dispatch reported success; drain the failure
    assert pod.phase == PodPhase.BOUND
    assert sched.metrics.counters["ambiguous_bind_recoveries_total"] == 1
    assert sched.metrics.counters.get("bind_errors_total", 0) == 0
    assert cluster.bind_calls == 1  # the lost-response POST, never replayed
    _assert_invariants([pod], store, cluster, "async-lost")


def test_gang_anchor_lost_response_adopted():
    """A gang's anchor bind losing its response must not tear the gang
    down: adoption sees the bind landed and the peers bind with it."""
    clock = FakeClock()
    store = TelemetryStore()
    for m in make_v4_slice("s0", "2x2x4"):
        m.heartbeat = 0.0
        store.put(m)
    cluster = ChaosCluster(store, clock=clock, bind_script={0: BIND_LOST})
    cluster.add_nodes_from_telemetry()
    sched = _build_engine(cluster, clock, telemetry_max_age_s=1e9)
    pods = [Pod(f"g{i}", labels={
        "tpu/accelerator": "tpu", "scv/number": "4",
        "tpu/gang-name": "gg", "tpu/gang-size": "2"}) for i in range(2)]
    for p in pods:
        sched.submit(p)
    _drain(sched, pods)
    assert all(p.phase == PodPhase.BOUND for p in pods)
    assert sched.metrics.counters["ambiguous_bind_recoveries_total"] == 1
    _assert_invariants(pods, store, cluster, "gang-lost")


# ------------------------------------------------ targeted: batch commit faults
def _batchable_pods(n):
    return [Pod(f"p{i}", labels={"tpu/accelerator": "tpu",
                                 "scv/number": "1"}) for i in range(n)]


def test_batch_commit_sync_bind_failure_falls_back_per_pod():
    """Satellite: a bind failure mid-batch sends the remaining members to
    per-pod cycles, and the failed pod re-enters and binds on retry."""
    clock = FakeClock()
    store, cluster = _simple_rig(clock=clock, cluster_cls=ChaosCluster,
                                 bind_script={2: APISERVER_STORM})
    cluster.clock = clock
    sched = _build_engine(cluster, clock, telemetry_max_age_s=1e9,
                          batch_max_pods=8, breaker_threshold=0)
    pods = _batchable_pods(8)
    for p in pods:
        sched.submit(p)
    _drain(sched, pods)
    assert all(p.phase == PodPhase.BOUND for p in pods)
    c = sched.metrics.counters
    assert c["bind_errors_total"] == 1
    assert c["batch_conflict_fallbacks_total"] >= 1
    assert c.get("batched_binds_total", 0) >= 1
    _assert_invariants(pods, store, cluster, "batch-sync")


def test_batch_commit_async_failure_reenters_via_drain():
    """The async flavour: the mid-batch wire failure lands in
    _drain_bind_failures after the batch, the pod requeues, everything
    still converges with no double bind."""
    clock = FakeClock()
    store, cluster = _simple_rig(clock=clock, cluster_cls=AsyncChaosCluster,
                                 bind_script={3: APISERVER_STORM})
    cluster.clock = clock
    sched = _build_engine(cluster, clock, telemetry_max_age_s=1e9,
                          batch_max_pods=8, breaker_threshold=0)
    pods = _batchable_pods(8)
    for p in pods:
        sched.submit(p)
    _drain(sched, pods)
    assert all(p.phase == PodPhase.BOUND for p in pods)
    assert sched.metrics.counters["bind_errors_total"] == 1
    _assert_invariants(pods, store, cluster, "batch-async")


# ------------------------------------------------ targeted: restart reconcile
def test_restart_reconciliation_adopts_bound_requeues_pending():
    """Crash mid-drain: the fresh engine rebuilds in-flight state from
    cluster truth — binding present => adopt (even when the old engine
    never saw the response), absent => scrub any stale annotation and
    requeue. No pod lost, none double-bound."""
    from yoda_scheduler_tpu.utils.pod import ASSIGNED_CHIPS_LABEL

    clock = FakeClock()
    store, cluster = _simple_rig(clock=clock)
    # batch off: the rig needs ONE bind per run_one to stage the crash
    old = _build_engine(cluster, clock, telemetry_max_age_s=1e9,
                        batch_max_pods=1)
    pods = [Pod(f"p{i}", labels={"tpu/accelerator": "tpu",
                                 "scv/number": "1"}) for i in range(5)]
    for p in pods:
        old.submit(p)
    # two orderly cycles bind p-two pods through the old engine
    assert old.run_one() == "bound"
    assert old.run_one() == "bound"
    bound_before = {p.name for p in pods if p.phase == PodPhase.BOUND}
    assert len(bound_before) == 2
    # a third pod's bind LANDED but the old engine died before learning
    # it (lost response + crash): bound in the cluster, phase stale
    lost = next(p for p in pods if p.phase != PodPhase.BOUND)
    cluster.bind(lost, cluster.node_names()[0], [(0, 0, 0)])
    lost.phase = PodPhase.PENDING  # the dead engine never updated it
    # a fourth carries a stale assignment annotation from a crash between
    # Reserve and Bind — never actually bound
    stale = next(p for p in pods
                 if p.phase != PodPhase.BOUND and p is not lost)
    stale.labels[ASSIGNED_CHIPS_LABEL] = "0.0.0"
    # the crash: everything engine-local is gone
    fresh = _build_engine(cluster, clock, telemetry_max_age_s=1e9)
    adopted, requeued = fresh.reconcile(pods)
    assert adopted == 3  # 2 orderly binds + the lost-response bind
    assert requeued == 2
    assert lost.phase == PodPhase.BOUND
    assert ASSIGNED_CHIPS_LABEL not in stale.labels or \
        stale.phase == PodPhase.BOUND
    _drain(fresh, pods)
    assert all(p.phase == PodPhase.BOUND for p in pods)
    c = fresh.metrics.counters
    assert c["reconcile_adopted_total"] == 3
    assert c["reconcile_requeued_total"] == 2
    _assert_invariants(pods, store, cluster, "reconcile")


# ------------------------------------------------- targeted: event storm drop
def test_event_storm_drops_past_cap_without_burning_attempts():
    """An event storm past the inbox cap must not grow memory, replay
    per-event hint work, or spuriously wake SKIP-parked pods (each wake
    burns an attempt under a max_attempts posture): excess events are
    dropped and counted, the parked pod keeps its backoff deadline, and
    its TIMER still retries it — events are a latency optimization, the
    timer is the correctness mechanism."""
    clock = FakeClock()
    store = TelemetryStore()
    m = make_tpu_node("n0", chips=1)
    m.heartbeat = 0.0
    store.put(m)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    sched = _build_engine(cluster, clock, telemetry_max_age_s=1e9)
    # unsatisfiable pod parks with backoff
    pod = Pod("big", labels={"tpu/accelerator": "tpu", "scv/number": "2"})
    sched.submit(pod)
    assert sched.run_one() == "unschedulable"
    cap = sched.queue._INBOX_CAP
    for _ in range(cap + 500):
        sched.notify_event(ClusterEvent(POD_DELETED, node="n0"))
    assert len(sched.queue._inbox) <= cap
    assert sched.metrics.counters["requeue_events_dropped_total"] == 500
    # draining the capped inbox processes the retained events through the
    # ordinary hint path and leaves memory bounded
    outcome = sched.run_one()
    assert len(sched.queue._inbox) == 0
    # the pod still resolves via its backoff timer after the storm
    deadline = 0
    while pod.phase == PodPhase.PENDING and deadline < 50:
        deadline += 1
        if sched.run_one() is None:
            w = sched.next_wake_at()
            if w is None:
                break
            clock.advance(max(w - clock.time(), 0.01))
    assert sched.queue.contains(pod.key) or pod.phase != PodPhase.PENDING


# ------------------------------------------------------- wire-level chaos
def _mk_client(url):
    from yoda_scheduler_tpu.k8s.client import KubeClient

    return KubeClient(url, max_retries=1, retry_backoff_s=0.05)


def test_watch_cut_and_410_storm_recovery_counted():
    """Wire-level: scripted watch-stream cuts and a 410 compaction storm
    against the real localhost fake apiserver. The reflector re-lists,
    the storm counters move, and the cache converges on the live state."""
    from fake_apiserver import FakeApiServer
    from yoda_scheduler_tpu.k8s.client import KubeCluster

    with FakeApiServer() as api:
        api.state.add_node("n0")
        client = _mk_client(api.url)
        cluster = KubeCluster(client, TelemetryStore())
        cluster.start()
        try:
            assert cluster.wait_synced(10.0)
            assert cluster.node_names() == ["n0"]
            relists0 = cluster.metrics.counters.get(
                "reflector_relists_total", 0)
            # mid-stream cut: clients must re-watch without losing events
            api.state.cut_watches("nodes")
            time.sleep(0.2)
            api.state.add_node("n1")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if "n1" in cluster.node_names():
                    break
                time.sleep(0.05)
            assert "n1" in cluster.node_names()
            # 410 compaction: advance the GLOBAL rv past the nodes
            # reflector's last-seen rv (a pod write), compact the nodes
            # history to that point, then cut its stream — the re-watch
            # comes from a compacted rv and must take the 410 re-list
            api.state.add_pod({"metadata": {"name": "rvbump"},
                               "spec": {}})
            api.state.compact("nodes")
            api.state.cut_watches("nodes")
            time.sleep(0.3)  # let the cut stream die before new events
            api.state.add_node("n2")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if "n2" in cluster.node_names() and \
                        cluster.metrics.counters.get(
                            "reflector_watch_expired_total", 0) >= 1:
                    break
                time.sleep(0.05)
            assert "n2" in cluster.node_names()
            assert cluster.metrics.counters.get(
                "reflector_watch_expired_total", 0) >= 1
            assert cluster.metrics.counters.get(
                "reflector_relists_total", 0) > relists0
        finally:
            cluster.stop()


def test_watch_bookmarks_avoid_410_relist():
    """The bookmark slice of the wire overhaul: with the server emitting
    BOOKMARKs (allowWatchBookmarks), a QUIET reflector's resourceVersion
    advances past other kinds' writes — so compaction + a stream cut
    resumes from the bookmark instead of taking the 410 full-relist path
    (contrast: test_watch_cut_and_410_storm_recovery_counted, which runs
    bookmarks-off and MUST keep seeing the 410)."""
    from fake_apiserver import FakeApiServer
    from yoda_scheduler_tpu.k8s.client import KubeCluster

    with FakeApiServer() as api:
        api.state.bookmarks_enabled = True
        api.state.add_node("n0")
        client = _mk_client(api.url)
        cluster = KubeCluster(client, TelemetryStore())
        cluster.start()
        try:
            assert cluster.wait_synced(10.0)
            relists0 = cluster.metrics.counters.get(
                "reflector_relists_total", 0)
            # rv churn on a DIFFERENT kind: the nodes stream stays quiet
            for i in range(3):
                api.state.add_pod({"metadata": {"name": f"rv{i}"},
                                   "spec": {}})
            # wait until the quiet nodes watcher has bookmarked past it
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if cluster.metrics.counters.get(
                        "reflector_bookmarks_total", 0) >= 1:
                    break
                time.sleep(0.05)
            assert cluster.metrics.counters.get(
                "reflector_bookmarks_total", 0) >= 1
            # compact nodes history, cut the stream: the re-watch comes
            # from the BOOKMARKED rv and must NOT 410
            api.state.compact("nodes")
            api.state.cut_watches("nodes")
            time.sleep(0.3)
            api.state.add_node("n1")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if "n1" in cluster.node_names():
                    break
                time.sleep(0.05)
            assert "n1" in cluster.node_names()
            assert cluster.metrics.counters.get(
                "reflector_watch_expired_total", 0) == 0
            assert cluster.metrics.counters.get(
                "reflector_relists_total", 0) == relists0
        finally:
            cluster.stop()


def test_leader_failover_stops_binding_before_new_leader():
    """Satellite: lease lost mid-serve stops the old leader (its stop
    event fires) BEFORE the standby's first acquisition — at no sampled
    instant do both hold leadership."""
    from fake_apiserver import FakeApiServer
    from yoda_scheduler_tpu.k8s.leaderelect import LeaderElector

    with FakeApiServer() as api:
        a_client = _mk_client(api.url)
        b_client = _mk_client(api.url)
        # lease_duration must be integer seconds (the Lease API field is
        # an int; sub-second values truncate to 0 = instantly expired)
        a = LeaderElector(a_client, lease_duration_s=2.0,
                          renew_deadline_s=0.6, retry_period_s=0.15,
                          identity="a")
        b = LeaderElector(b_client, lease_duration_s=2.0,
                          renew_deadline_s=0.6, retry_period_s=0.15,
                          identity="b")
        stop_a = threading.Event()
        stop_b = threading.Event()
        a.run_until_leader(stop_a)
        assert a.is_leader and not stop_a.is_set()

        overlap = []
        b_thread = threading.Thread(
            target=lambda: b.run_until_leader(stop_b), daemon=True)
        b_thread.start()

        # kill A's connectivity only (B stays healthy): its renews fail,
        # it steps down after the renew deadline, B takes the lease once
        # the old lease expires
        def dead_transport(method, path, body, timeout):
            raise ConnectionError("chaos: leader lost the apiserver")

        a_client._transport = dead_transport
        deadline = time.monotonic() + 15
        a_stopped_at = b_leader_at = None
        while time.monotonic() < deadline:
            if a.is_leader and b.is_leader:
                overlap.append(time.monotonic())
            if a_stopped_at is None and stop_a.is_set():
                a_stopped_at = time.monotonic()
            if b_leader_at is None and b.is_leader:
                b_leader_at = time.monotonic()
            if a_stopped_at is not None and b_leader_at is not None:
                break
            time.sleep(0.01)
        stop_b.set()
        b_thread.join(timeout=5)
        assert a_stopped_at is not None, "old leader never stepped down"
        assert b_leader_at is not None, "standby never took over"
        # binding stops (stop event set) before the new leader's first
        # bind could happen, and leadership never overlapped
        assert a_stopped_at <= b_leader_at
        assert not overlap, f"dual leadership sampled at {overlap}"
