"""Flash-attention block-size sweep on the real chip.

Produces the PERFORMANCE.md sweep table: wall time and useful-causal-FLOP
throughput per (block_q, block_k) at several sequence lengths, forward and
(with --bwd) forward+backward, against the plain-XLA baseline. Run on TPU
hardware (no JAX_PLATFORMS=cpu); the timing harness matches
bench_mfu._kernel_time_s (chained device-side loop, overhead cancelled by
loop-length differencing, median-of-3 per length).

    python tools/tune_attention.py [--bwd] [--seqs 2048,4096,8192]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/jax_comp_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from yoda_scheduler_tpu.ops.attention import (  # noqa: E402
    flash_attention, reference_attention)


def _sync(x) -> None:
    jax.device_get(jnp.sum(jax.tree.leaves(x)[0].astype(jnp.float32)))


def kernel_time(fn, q, k, v, n1=4, n2=24):
    @jax.jit
    def run(q, k, v, n):
        return jax.lax.fori_loop(
            0, n, lambda i, x: fn(x, k, v).astype(q.dtype), q)

    def measure(n, reps=3):
        na = jnp.int32(n)
        _sync(run(q, k, v, na))
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            _sync(run(q, k, v, na))
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    try:
        t1, t2 = measure(n1), measure(n2)
        return max(t2 - t1, 1e-9) / (n2 - n1)
    except Exception as e:
        print(f"  err {type(e).__name__}: {str(e)[:120]}", flush=True)
        return None


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--bwd", action="store_true",
                    help="sweep fwd+bwd (grad wrt q/k/v) instead of fwd")
    ap.add_argument("--seqs", default="2048,4096,8192")
    ap.add_argument("--blocks", default="128:128,256:256,512:256,512:512,1024:512")
    args = ap.parse_args()
    h, d = 16, 128
    for s in (int(x) for x in args.seqs.split(",")):
        b = max(1, 8192 // s)
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(s), 3)
        q = jax.random.normal(kq, (b, h, s, d), jnp.bfloat16)
        k = jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
        v = jax.random.normal(kv, (b, h, s, d), jnp.bfloat16)
        # useful causal FLOPs (x2.5 more compute in bwd, not counted: the
        # table compares configurations, not absolute MFU)
        fl = 4 * s * s * d * 0.5 * b * h
        for spec in args.blocks.split(","):
            bq, bk = (int(x) for x in spec.split(":"))
            if bq > s or bk > s or s % min(bq, s) or s % min(bk, s):
                continue
            if args.bwd:
                # grad wrt ALL of q,k,v — differentiating only q would let
                # XLA dead-code-eliminate the dk/dv kernel (the one whose
                # grid block_k_bwd tiles) and the sweep would time nothing
                # but dq. Summing the grads yields a q-shaped array that
                # chains through the timing loop's carry.
                def fn(q, k, v, bq=bq, bk=bk):
                    gq, gk, gv = jax.grad(
                        lambda q, k, v: jnp.sum(
                            flash_attention(q, k, v, causal=True,
                                            block_q=512, block_k=512,
                                            block_q_bwd=bq, block_k_bwd=bk
                                            ).astype(jnp.float32)),
                        argnums=(0, 1, 2))(q, k, v)
                    return gq + gk + gv
            else:
                fn = lambda q, k, v, bq=bq, bk=bk: flash_attention(
                    q, k, v, causal=True, block_q=bq, block_k=bk)
            t = kernel_time(fn, q, k, v)
            if t:
                print(f"S={s} bq={bq} bk={bk}{' bwd' if args.bwd else ''}: "
                      f"{t * 1e3:.3f} ms  {fl / t / 1e12:.1f} TF/s", flush=True)
        if args.bwd:
            def base(q, k, v):
                gq, gk, gv = jax.grad(
                    lambda q, k, v: jnp.sum(reference_attention(
                        q, k, v, True).astype(jnp.float32)),
                    argnums=(0, 1, 2))(q, k, v)
                return gq + gk + gv
        else:
            base = lambda q, k, v: reference_attention(q, k, v, True)
        t = kernel_time(base, q, k, v)
        if t:
            print(f"S={s} XLA{' bwd' if args.bwd else ''}: {t * 1e3:.3f} ms  "
                  f"{fl / t / 1e12:.1f} TF/s", flush=True)


if __name__ == "__main__":
    main()
