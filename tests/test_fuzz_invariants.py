"""Randomized invariant fuzz over the whole scheduling stack (SURVEY §4:
the reference ships zero tests; upstream kube-scheduler leans on
scheduler_perf + integration invariants — this is that idea at fake-store
speed). Each seed builds a random fleet and a random 90-pod burst — plain
TPU pods, memory-heavy pods, GPU pods, generation pins, topology blocks,
gangs — runs the engine to idle, and asserts the global invariants that
must hold for EVERY workload/fleet combination:

1. every pod resolves (bound or failed — nothing leaks in flight);
2. no chip is double-booked, and every assigned chip exists on its node;
3. a bound TPU pod holds exactly the chips it asked for;
4. failed pods hold nothing;
5. gang atomicity (all members bound, or none);
6. generation pins are honored;
7. topology-block pods get their chips on one node, contiguously
   (an axis-aligned sub-block of the node's torus, verified against the
   enumerated placements);
8. per-node HBM accounting never overcommits: bound claims fit the
   node's per-chip free HBM for each chip they landed on.
"""

import random
import time

import pytest

from yoda_scheduler_tpu.scheduler import FakeCluster, Scheduler, SchedulerConfig
from yoda_scheduler_tpu.scheduler.core import HybridClock
from yoda_scheduler_tpu.telemetry import (
    TelemetryStore, make_gpu_node, make_tpu_node, make_v4_slice)
from yoda_scheduler_tpu.topology.torus import enumerate_subblocks, parse_topology
from yoda_scheduler_tpu.utils import Pod, PodPhase
from yoda_scheduler_tpu.utils.pod import ASSIGNED_CHIPS_LABEL

N_PODS = 90


def _fleet(rng: random.Random) -> TelemetryStore:
    store = TelemetryStore()
    now = time.time()
    metrics = []
    for s in range(rng.randint(1, 2)):  # multi-host slices (4 hosts x 4)
        metrics.extend(make_v4_slice(f"s{s}", "2x2x4"))
    for i in range(rng.randint(3, 6)):  # standalone TPU hosts
        metrics.append(make_tpu_node(
            f"t{i}", chips=rng.choice((2, 4, 8)),
            generation=rng.choice(("v4", "v5e")),
            unhealthy=rng.choice((0, 0, 0, 1))))
    for i in range(rng.randint(1, 3)):
        metrics.append(make_gpu_node(f"g{i}", cards=rng.choice((4, 8))))
    for m in metrics:
        m.heartbeat = now
        store.put(m)
    return store


def _burst(rng: random.Random) -> list[Pod]:
    pods = []
    gang_id = 0
    i = 0
    while len(pods) < N_PODS:
        i += 1
        roll = rng.random()
        if roll < 0.40:  # plain TPU
            pods.append(Pod(f"p{i}", labels={
                "tpu/accelerator": "tpu",
                "scv/number": str(rng.choice((1, 1, 2, 4)))}))
        elif roll < 0.55:  # memory-constrained (sometimes unsatisfiable)
            pods.append(Pod(f"p{i}", labels={
                "tpu/accelerator": "tpu", "scv/number": "1",
                "scv/memory": str(rng.choice((4000, 16000, 40000)))}))
        elif roll < 0.70:  # GPU
            pods.append(Pod(f"p{i}", labels={
                "tpu/accelerator": "gpu",
                "scv/number": str(rng.choice((1, 2, 4)))}))
        elif roll < 0.80:  # generation pin
            pods.append(Pod(f"p{i}", labels={
                "tpu/accelerator": "tpu", "scv/number": "1",
                "tpu/generation": rng.choice(("v4", "v5e", "v5p"))}))
        elif roll < 0.90:  # topology block
            topo = rng.choice(("1x2", "2x2", "2x1x2"))
            pods.append(Pod(f"p{i}", labels={
                "tpu/accelerator": "tpu", "tpu/topology": topo,
                "scv/number": str(_block_size(topo))}))
        else:  # gang (one pod per host on a slice)
            size = rng.choice((2, 3, 4))
            gang_id += 1
            for m in range(size):
                pods.append(Pod(f"p{i}g{m}", labels={
                    "tpu/accelerator": "tpu", "scv/number": "4",
                    "tpu/gang-name": f"fz{gang_id}",
                    "tpu/gang-size": str(size)}))
    rng.shuffle(pods)
    return pods


def _block_size(topo: str) -> int:
    n = 1
    for d in parse_topology(topo):
        n *= d
    return n


def _chips_of(pod: Pod) -> set[tuple[int, int, int]]:
    return pod.assigned_chips()


def _make_sched(rng: random.Random):
    """Shared serial-fuzz rig: random fleet + scheduler on a HybridClock
    (virtualized backoff waits — bench.py's idiom; with the wall clock,
    the infeasible tail's 1-10s backoffs would make each seed take
    minutes). One copy so every serial regime runs the same config."""
    store = _fleet(rng)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    # degraded_mode off: this fuzz pins the per-node staleness fence and
    # placement invariants; heartbeats are published once at setup, so a
    # retry-heavy seed whose VIRTUAL clock outruns max_age would look
    # like a blackout and flip semantics mid-drain. Blackout behaviour
    # has its own seeded fuzz in tests/test_chaos.py.
    sched = Scheduler(cluster, SchedulerConfig(
        max_attempts=3, gang_timeout_s=0.5, telemetry_max_age_s=3600.0,
        degraded_mode=False),
        clock=HybridClock())
    return store, sched


@pytest.mark.parametrize("seed", range(12))
def test_random_burst_invariants(seed):
    rng = random.Random(seed)
    store, sched = _make_sched(rng)
    pods = _burst(rng)
    for p in pods:
        sched.submit(p)
    sched.run_until_idle(max_cycles=20000)
    _check_invariants(pods, store, seed)


@pytest.mark.parametrize("seed", range(4))
def test_random_burst_invariants_unbatched(seed):
    """The per-pod path stays wired in as the batch commit loop's
    fallback and ground truth: the same random interleaved bursts must
    hold every invariant with batching forced off (the default engine
    above runs batched — tier-1 covers that side on every test)."""
    rng = random.Random(seed)
    store, sched = _make_sched(rng)
    sched = Scheduler(sched.cluster, sched.config.with_(batch_max_pods=1),
                      clock=HybridClock())
    pods = _burst(rng)
    for p in pods:
        sched.submit(p)
    sched.run_until_idle(max_cycles=20000)
    _check_invariants(pods, store, seed)


@pytest.mark.parametrize("seed", range(3))
def test_random_burst_invariants_batched_interleaved(seed):
    """INTERLEAVED submission under a small batch cap: the gather may
    legally advance classmates past equal-priority pods (bounded
    fairness trade, queue.py), but every global invariant — nothing
    lost, nothing double-booked, gangs atomic — must hold exactly."""
    rng = random.Random(70_000 + seed)
    store, sched = _make_sched(rng)
    sched = Scheduler(sched.cluster, sched.config.with_(batch_max_pods=5),
                      clock=HybridClock())
    pods = _burst(rng)
    rng.shuffle(pods)  # maximally interleaved classes
    for p in pods:
        sched.submit(p)
    sched.run_until_idle(max_cycles=20000)
    _check_invariants(pods, store, seed)


def _check_invariants(pods, store, seed):
    """The global invariants every fleet/workload combination must satisfy
    after the engine drains — shared by the serial and concurrent fuzz so
    the racy regime is held to exactly the same bar."""
    by_metrics = {m.node: m for m in store.list()}

    # 1. everything resolves
    unresolved = [p.name for p in pods
                  if p.phase not in (PodPhase.BOUND, PodPhase.FAILED)]
    assert not unresolved, f"seed {seed}: unresolved {unresolved}"

    # 2+3+4. chip accounting: exact counts, existing chips, no double-booking
    claimed: dict[str, dict[tuple, str]] = {}
    for p in pods:
        chips = _chips_of(p)
        if p.phase == PodPhase.FAILED:
            assert not chips, f"seed {seed}: failed {p.name} holds chips"
            continue
        m = by_metrics[p.node]
        if p.labels.get("tpu/accelerator") != "gpu" \
                and m.accelerator != "gpu":
            want = int(p.labels.get("scv/number", "1"))
            assert len(chips) == want, \
                f"seed {seed}: {p.name} wanted {want} got {len(chips)}"
        node_coords = {c.coords for c in m.chips}
        owners = claimed.setdefault(p.node, {})
        for c in chips:
            assert c in node_coords, \
                f"seed {seed}: {p.name} assigned nonexistent chip {c}"
            assert c not in owners, (f"seed {seed}: chip {p.node}/{c} "
                                     f"double-booked by {owners[c]} "
                                     f"and {p.name}")
            owners[c] = p.name

    # 5. gang atomicity
    gangs: dict[str, list[Pod]] = {}
    for p in pods:
        g = p.labels.get("tpu/gang-name")
        if g:
            gangs.setdefault(g, []).append(p)
    for g, members in gangs.items():
        phases = {p.phase for p in members}
        assert len(phases) == 1, \
            f"seed {seed}: gang {g} split " \
            f"{[(p.name, p.phase) for p in members]}"

    # 6. generation pins
    for p in pods:
        gen = p.labels.get("tpu/generation")
        if gen and p.phase == PodPhase.BOUND:
            assert by_metrics[p.node].tpu_generation == gen, \
                f"seed {seed}: {p.name} pinned {gen} landed on " \
                f"{by_metrics[p.node].tpu_generation}"

    # 7. topology blocks are contiguous sub-blocks of the node torus
    for p in pods:
        topo = p.labels.get("tpu/topology")
        if not topo or p.phase != PodPhase.BOUND:
            continue
        chips = _chips_of(p)
        m = by_metrics[p.node]
        shape = _node_shape(m)
        ok = False
        for origin, bshape in enumerate_subblocks(shape, len(chips)):
            cells = {tuple((origin[d] + o[d]) % max(shape[d], 1)
                           for d in range(3))
                     for o in _offsets(bshape)}
            if cells == chips:
                ok = True
                break
        assert ok, f"seed {seed}: {p.name} chips {sorted(chips)} are not " \
                   f"a contiguous block on {p.node} {shape}"

    # 8. HBM: every chip a memory-demanding pod landed on satisfies it
    for p in pods:
        need = int(p.labels.get("scv/memory", "0"))
        if need and p.phase == PodPhase.BOUND \
                and p.labels.get("tpu/accelerator") != "gpu":
            m = by_metrics[p.node]
            free = {c.coords: c.hbm_free_mb for c in m.chips}
            for c in _chips_of(p):
                assert free[c] >= need, \
                    f"seed {seed}: {p.name} needs {need}MB, chip {c} " \
                    f"has {free[c]}"


def _node_shape(m):
    from yoda_scheduler_tpu.scheduler.plugins.allocator import _node_shape
    return _node_shape(m)


def _offsets(shape):
    return [(x, y, z)
            for x in range(shape[0])
            for y in range(shape[1])
            for z in range(shape[2])]


def _run_concurrent(rng, store, sched, pods, publish_fn,
                    drain_s: float = 45.0):
    """Shared racy rig: engine thread + custom publisher + three striped
    submitter threads, then a hardened two-stage drain. Stage 1 samples
    until every pod reads resolved; stage 2 stops the threads and drains
    single-threaded — an in-flight preempting cycle can revert a
    sampled-BOUND victim to PENDING right as the rig stops, so the rig
    itself reschedules any such victim (real-clock backoff included)
    before the invariants are checked."""
    import threading

    stop = threading.Event()

    def drive():
        while not stop.is_set():
            if sched.run_one() is None:
                time.sleep(0.0005)

    threads = [threading.Thread(target=drive, daemon=True),
               threading.Thread(target=lambda: publish_fn(stop),
                                daemon=True)]
    for i in range(3):
        chunk = pods[i::3]

        def submit(chunk=chunk):
            for p in chunk:
                sched.submit(p)
                time.sleep(0.0003)

        threads.append(threading.Thread(target=submit, daemon=True))
    for t in threads:
        t.start()
    deadline = time.time() + drain_s
    try:
        while time.time() < deadline:
            if all(p.phase in (PodPhase.BOUND, PodPhase.FAILED)
                   for p in pods):
                break
            time.sleep(0.02)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
    # stage 2: single-threaded post-drain for last-cycle evictions
    deadline = time.time() + 15
    while time.time() < deadline:
        if all(p.phase in (PodPhase.BOUND, PodPhase.FAILED)
               for p in pods):
            break
        if sched.run_one() is None:
            time.sleep(0.01)


@pytest.mark.parametrize("seed", range(3))
def test_random_burst_invariants_concurrent(seed):
    """The same random workloads under the racy regime: the engine loop in
    one thread, three submitter threads, and a telemetry publisher that
    heartbeats every node while periodically FREEZING one (its telemetry
    goes stale mid-scheduling, so the staleness gate must fence it off
    without tripping any of the global invariants)."""
    import threading

    rng = random.Random(1000 + seed)
    store = _fleet(rng)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    # degraded_mode off: the publisher thread stops before the rig's
    # final single-threaded drain, which reads as a cluster-wide
    # blackout and would waive the very staleness fence this regime
    # exists to race (blackout semantics: tests/test_chaos.py)
    sched = Scheduler(cluster, SchedulerConfig(
        max_attempts=3, gang_timeout_s=0.5, telemetry_max_age_s=0.4,
        degraded_mode=False))
    pods = _burst(rng)
    churn_done = threading.Event()

    def publish(stop):
        frozen: str | None = None
        flips = 0
        while not stop.is_set():
            now = time.time()
            for m in store.list():
                if m.node != frozen:
                    m.heartbeat = now
                    store.put(m)
            if not churn_done.is_set():
                flips += 1
                if flips % 10 == 0:  # roughly every 0.5s
                    frozen = (None if frozen is not None
                              else rng.choice(store.list()).node)
                    if frozen is None and flips >= 40:
                        churn_done.set()  # stop freezing; let it drain
            else:
                frozen = None
            time.sleep(0.05)

    _run_concurrent(rng, store, sched, pods, publish)
    _check_invariants(pods, store, seed)


# ---------------------------------------------------------------- maxima oracle
def _brute_maxima(allocator, spec, feasible):
    """Reference fold for MaxCollection: per-attribute maxima over every
    feasible node's qualifying chips, derived straight from telemetry +
    claims — bypassing free_coords/class_stats caches AND the prescore
    tuple memo, so a bug in any cache layer diverges from this."""
    mv = [1, 1, 1, 1, 1, 1]
    for ni in feasible:
        m = ni.metrics
        if m is None:
            continue
        free = (m.healthy_coords() - ni.assigned_coords()
                - allocator.pending_on(ni.name))
        for c in m.healthy_chips():
            if (c.coords in free and c.hbm_free_mb >= spec.min_free_mb
                    and c.clock_mhz >= spec.min_clock_mhz):
                for j, v in enumerate((c.ici_bandwidth_gbps, c.clock_mhz,
                                       c.core_count, c.hbm_free_mb,
                                       c.power_w, c.hbm_total_mb)):
                    if v > mv[j]:
                        mv[j] = v
    return tuple(mv)


@pytest.mark.parametrize("seed", range(8))
def test_incremental_maxima_match_brute_force(seed):
    """Property: the MaxValue the prescore memo writes every cycle equals
    the brute-force fold over the same feasible list. Pins the
    tuple-reuse design (clean nodes' cached tuples + dirty/new re-folds)
    against silent drift — a stale or leaked tuple shows up as the first
    mismatching cycle, with the pod and both folds in the failure."""
    rng = random.Random(10_000 + seed)
    store, sched = _make_sched(rng)
    maxc = next(p for p in sched.profile.pre_score
                if getattr(p, "name", "") == "max-collection")
    mismatches = []
    orig = maxc.pre_score

    def checked(state, pod, feasible):
        st = orig(state, pod, feasible)
        got = state.read("Max")
        got6 = (got.bandwidth, got.clock, got.core, got.free_memory,
                got.power, got.total_memory)
        want6 = _brute_maxima(maxc.allocator, state.read("workload_spec"),
                              feasible)
        if got6 != want6:
            mismatches.append((pod.name, got6, want6))
        return st

    maxc.pre_score = checked
    pods = _burst(rng)
    for p in pods:
        sched.submit(p)
    sched.run_until_idle(max_cycles=20000)
    assert not mismatches, f"seed {seed}: first={mismatches[0]} " \
                           f"({len(mismatches)} mismatching cycles)"
    # the REUSE path specifically must have fired (every seed does; the
    # class_stats fallback alone would make the oracle vacuous)
    assert maxc.fast_hits > 0


@pytest.mark.parametrize("seed", range(4))
def test_random_burst_invariants_with_preemption(seed):
    """The serial fuzz with priority labels sprinkled on ~40% of
    non-gang pods: priority inversions under capacity pressure drive the
    PostFilter preemption plugin (each of these seeds preempts at least
    once — asserted, so the regime can't silently go quiet), and every
    global invariant must survive the evict/requeue churn."""
    rng = random.Random(90_000 + seed)
    store, sched = _make_sched(rng)
    pods = _burst(rng)
    for p in pods:
        if rng.random() < 0.4 and "tpu/gang-name" not in p.labels:
            p.labels["scv/priority"] = str(rng.randint(1, 10))
    for p in pods:
        sched.submit(p)
    sched.run_until_idle(max_cycles=20000)
    assert sched.metrics.counters.get("preemptions_total", 0) > 0, \
        f"seed {seed}: the preemption regime went quiet"
    _check_invariants(pods, store, seed)


@pytest.mark.parametrize("seed", range(2))
def test_random_burst_invariants_concurrent_preemption(seed):
    """The racy regime with priorities: preemption's evict/requeue runs
    concurrently with submission and telemetry churn, and every global
    invariant must hold when it drains. (Whether preemption fires is
    timing-dependent here, so the fired assertion lives in the
    deterministic serial variant; a 20-seed offline sweep of this regime
    preempted 636 times with zero violations.)"""
    rng = random.Random(40_000 + seed)
    store = _fleet(rng)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    # degraded_mode off, same reason as the non-preempting racy regime
    sched = Scheduler(cluster, SchedulerConfig(
        max_attempts=3, gang_timeout_s=0.5, telemetry_max_age_s=0.4,
        degraded_mode=False))
    pods = _burst(rng)
    for p in pods:
        if rng.random() < 0.4 and "tpu/gang-name" not in p.labels:
            p.labels["scv/priority"] = str(rng.randint(1, 10))

    def publish(stop):
        while not stop.is_set():
            now = time.time()
            for m in store.list():
                m.heartbeat = now
                store.put(m)
            time.sleep(0.05)

    _run_concurrent(rng, store, sched, pods, publish)
    _check_invariants(pods, store, seed)


# ---------------------------------------------------------------- fairness
# Policy-engine fairness invariants (ISSUE 9), fuzzed chaos-style: each
# seed builds a random mixed-generation fleet, random tenant quotas +
# preemption budgets, and a random mixed-tenant burst, then asserts the
# three fairness invariants on the drained state:
#
#   F1 bounded wait / no starvation: every pod RESOLVES (bound or
#      failed at max_attempts — nothing pending forever), and a tenant
#      whose demand fits inside its quota binds ALL of it;
#   F2 DRF convergence: no tenant's dominant share exceeds its quota
#      (+ the one-pod granularity the gate admits at the boundary);
#   F3 preemption budgets never exceeded: evictions charged per tenant
#      stay within the configured lifetime budget.
#
# The first 8 seeds ride tier-1; the rest of the 64-seed matrix runs in
# the CI fairness job (-m slow exclusion keeps tier-1's budget).

def _fairness_fleet(rng: random.Random) -> TelemetryStore:
    store = TelemetryStore()
    now = time.time()
    for i in range(rng.randint(4, 8)):
        m = make_tpu_node(f"v4-{i}", chips=4, generation="v4")
        m.heartbeat = now
        store.put(m)
    for i in range(rng.randint(2, 5)):
        m = make_tpu_node(f"v5e-{i}", chips=8,
                          generation=rng.choice(("v5e", "v5p")))
        m.heartbeat = now
        store.put(m)
    return store


@pytest.mark.parametrize(
    "seed",
    [pytest.param(s, marks=() if s < 8 else (pytest.mark.slow,))
     for s in range(64)])
def test_fairness_drain_invariants(seed):
    from yoda_scheduler_tpu.utils.labels import tenant_of

    rng = random.Random(200_000 + seed)
    store = _fairness_fleet(rng)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    cap_chips = sum(len(m.chips) for m in store.list())
    # every 4th seed runs the PREEMPTION regime: the uncapped scavenger
    # tenant pre-fills the cluster at low priority, so the tenants' wave
    # can only get in by evicting — exercising the budget gate for real
    # (a 64-seed instrumented sweep of this matrix evicted 21 victims
    # and quota-rejected 1526 cycles; the planner's budget route-around
    # leaves the whole-plan denial gate as a rare multi-victim backstop
    # — 1 denial across the sweep)
    preempt_regime = seed % 4 == 3
    # random quota split: 3 capped tenants + one uncapped scavenger
    # (whose preemption budget is what the preempt regime fuzzes)
    q = sorted(rng.uniform(0.08, 0.35) for _ in range(3))
    quotas = (("acme", q[2], rng.choice((0, 1, 2, -1))),
              ("beta", q[1], rng.choice((0, 1, -1))),
              ("gamma/ml", q[0], rng.choice((0, 2))),
              ("scav", 0.0, rng.choice((0, 1, 2, 3))))
    sched = Scheduler(cluster, SchedulerConfig(
        max_attempts=4, telemetry_max_age_s=3600.0, degraded_mode=False,
        policy_objective=rng.choice(("makespan", "avg-jct",
                                     "finish-time-fairness")),
        drf_fairness=True, tenant_quotas=quotas,
        preemption_budget_window_s=0.0,  # lifetime budgets: F3 is exact
        starvation_after_s=3600.0,
        workload_classes=(("light", (("v4", 0.9), ("v5e", 2.0))),),
        rng_seed=seed), clock=HybridClock())
    # per-tenant demand: "fits" tenants stay under quota capacity
    # (F1 asserts they bind everything), others oversubscribe ~1.5x
    pods = []
    fits: dict[str, bool] = {}
    demand: dict[str, int] = {}
    for tenant, quota, _ in quotas:
        fit = rng.random() < 0.5
        fits[tenant] = fit
        chips_budget = int(quota * cap_chips)
        target = (max(chips_budget - 2, 1) if fit
                  else int(chips_budget * 1.5) + 2)
        got = 0
        while got < target:
            # fit tenants submit singles only: the bind-all assertion
            # is about FAIRNESS, and a 2-chip pod stranded by free-chip
            # fragmentation would fail it for a non-fairness reason
            # (that gap is ROADMAP item 4's defragmenter)
            chips = 1 if fit else rng.choice((1, 1, 2))
            if fit and got + chips > chips_budget - 1:
                break
            labels = {"scv/number": str(chips), "tpu/accelerator": "tpu",
                      "scv/tenant": tenant}
            if rng.random() < 0.4:
                labels["scv/class"] = "light"
            if rng.random() < 0.3:
                labels["scv/memory"] = str(rng.choice((1000, 4000)))
            if preempt_regime:
                # the tenants' wave arrives at HIGH priority against a
                # full cluster: only preemption (budget willing) fits it
                labels["scv/priority"] = str(rng.randint(5, 9))
            elif rng.random() < 0.3:
                labels["scv/priority"] = str(rng.randint(1, 9))
            pods.append(Pod(f"{tenant.replace('/', '-')}-{len(pods)}",
                            labels=labels))
            got += chips
        demand[tenant] = got
    # the uncapped scavenger: a light leftover-soak in the quota
    # regimes, a cluster-filling low-priority flood in the preempt one
    n_scav = cap_chips if preempt_regime else rng.randint(4, 12)
    scavs = [Pod(f"scav-{i}", labels={
        "scv/number": "1", "tpu/accelerator": "tpu",
        "scv/tenant": "scav",
        "scv/priority": str(rng.randint(0, 3))})
        for i in range(n_scav)]
    if preempt_regime:
        for p in scavs:
            sched.submit(p)
        sched.run_until_idle(max_cycles=30_000)
    else:
        pods.extend(scavs)
    rng.shuffle(pods)
    for p in pods:
        sched.submit(p)
    sched.run_until_idle(max_cycles=30_000)
    if preempt_regime:
        pods.extend(scavs)  # invariants cover the flood too

    # F1: everything resolves; in-quota tenants bind ALL their demand
    unresolved = [p.name for p in pods
                  if p.phase not in (PodPhase.BOUND, PodPhase.FAILED)]
    assert not unresolved, f"seed {seed}: unresolved {unresolved}"
    book = sched.policy.book
    book.refresh()
    # bind-all is a FAIRNESS guarantee, so it only binds when capacity
    # could have served everyone: when total demand exceeds the
    # cluster, somebody legitimately loses on capacity, quota headroom
    # or not (and the preempt regime pre-fills the cluster by design)
    total_demand = sum(demand.values()) + n_scav
    capacity_open = (not preempt_regime
                     and total_demand <= cap_chips - 2)
    for tenant, quota, _ in quotas:
        if quota <= 0.0 or tenant not in fits:
            continue
        mine = [p for p in pods if tenant_of(p) == tenant]
        if fits[tenant] and capacity_open:
            unbound = [p.name for p in mine if p.phase != PodPhase.BOUND]
            assert not unbound, (
                f"seed {seed}: tenant {tenant} starved inside its quota "
                f"(demand {demand[tenant]} of {int(quota * cap_chips)} "
                f"chips): {unbound}")
    # F2: shares never exceed quota (+ the gate's one-pod granularity is
    # ON the admit side, so the bound share itself must sit at/below cap)
    for tenant, quota, _ in quotas:
        if quota <= 0.0:
            continue  # uncapped tenant: no share ceiling to assert
        share = book.dominant_share(tenant)
        assert share <= quota + 1e-9, (
            f"seed {seed}: tenant {tenant} share {share:.4f} exceeds "
            f"quota {quota:.4f}")
    # F3: preemption budgets never exceeded (lifetime window)
    for tenant, _, budget in quotas:
        if budget < 0:
            continue
        evicted = sched.metrics.labeled_counter(
            "preemption_victims_total", {"tenant": tenant})
        assert evicted <= budget, (
            f"seed {seed}: tenant {tenant} lost {evicted} pods to "
            f"preemption, budget {budget}")
        assert sched.policy.budgets.spent(
            tenant, sched.clock.time()) <= max(budget, 0)
    # the chip-level invariants hold under the policy plugins too
    _check_invariants([p for p in pods], store, seed)
