"""Node admission (nodeSelector + taints/tolerations) tests.

The reference delegated these checks to the kube-scheduler it embedded
(upstream NodeAffinity/TaintToleration run beside the yoda plugin —
reference pkg/register/register.go:10-12); the standalone engine provides
them via plugins/admission.py. Unit layer: toleration matching semantics.
Integration layer: end-to-end routing through Scheduler + FakeCluster and
through the watch cache over live HTTP (node objects carry the meta).
"""

import time

from yoda_scheduler_tpu.scheduler import FakeCluster, Scheduler, SchedulerConfig
from yoda_scheduler_tpu.scheduler.framework import Code, CycleState, NodeInfo
from yoda_scheduler_tpu.scheduler.plugins import NodeAdmission
from yoda_scheduler_tpu.scheduler.plugins.admission import tolerates, untolerated
from yoda_scheduler_tpu.telemetry import TelemetryStore, make_tpu_node
from yoda_scheduler_tpu.utils import Pod, PodPhase


def mk_pod(name="p", selector=None, tolerations=(), labels=None):
    return Pod(name, labels=dict(labels or {"scv/number": "1"}),
               node_selector=dict(selector or {}),
               tolerations=tuple(tolerations))


def ni(name="n", labels=None, taints=(), metrics=None):
    return NodeInfo(name=name, metrics=metrics, labels=dict(labels or {}),
                    taints=tuple(taints))


TAINT_NS = {"key": "dedicated", "value": "ml", "effect": "NoSchedule"}
TAINT_NE = {"key": "out", "value": "", "effect": "NoExecute"}
TAINT_PREFER = {"key": "aging", "value": "", "effect": "PreferNoSchedule"}


class TestTolerationMatching:
    def test_equal_match(self):
        assert tolerates({"key": "dedicated", "operator": "Equal",
                          "value": "ml", "effect": "NoSchedule"}, TAINT_NS)

    def test_equal_value_mismatch(self):
        assert not tolerates({"key": "dedicated", "operator": "Equal",
                              "value": "web", "effect": "NoSchedule"}, TAINT_NS)

    def test_exists_ignores_value(self):
        assert tolerates({"key": "dedicated", "operator": "Exists",
                          "effect": "NoSchedule"}, TAINT_NS)

    def test_empty_effect_matches_all_effects(self):
        assert tolerates({"key": "dedicated", "operator": "Equal",
                          "value": "ml", "effect": ""}, TAINT_NS)

    def test_effect_mismatch(self):
        assert not tolerates({"key": "dedicated", "operator": "Equal",
                              "value": "ml", "effect": "NoExecute"}, TAINT_NS)

    def test_tolerate_everything(self):
        # empty key + Exists is the universal toleration
        assert tolerates({"key": "", "operator": "Exists", "effect": ""},
                         TAINT_NS)
        assert tolerates({"key": "", "operator": "Exists", "effect": ""},
                         TAINT_NE)

    def test_empty_key_equal_is_invalid_no_match(self):
        assert not tolerates({"key": "", "operator": "Equal", "value": ""},
                             TAINT_NS)

    def test_untolerated_filters_by_effect(self):
        pod = mk_pod(tolerations=[{"key": "dedicated", "operator": "Exists",
                                   "effect": "", "value": ""}])
        bad = untolerated(pod, (TAINT_NS, TAINT_NE, TAINT_PREFER),
                          ("NoSchedule", "NoExecute"))
        assert bad == [TAINT_NE]


class TestAdmissionPlugin:
    def test_selector_subset_required(self):
        p = NodeAdmission()
        pod = mk_pod(selector={"pool": "tpu", "zone": "a"})
        ok = ni(labels={"pool": "tpu", "zone": "a", "extra": "x"})
        miss = ni(labels={"pool": "tpu"})
        wrong = ni(labels={"pool": "tpu", "zone": "b"})
        assert p.filter(CycleState(), pod, ok).ok
        assert p.filter(CycleState(), pod, miss).code == Code.UNSCHEDULABLE
        assert p.filter(CycleState(), pod, wrong).code == Code.UNSCHEDULABLE

    def test_no_selector_no_taints_passes(self):
        assert NodeAdmission().filter(CycleState(), mk_pod(), ni()).ok

    def test_noschedule_taint_blocks_without_toleration(self):
        p = NodeAdmission()
        st = p.filter(CycleState(), mk_pod(), ni(taints=[TAINT_NS]))
        assert st.code == Code.UNSCHEDULABLE and "dedicated" in st.message

    def test_toleration_admits(self):
        p = NodeAdmission()
        pod = mk_pod(tolerations=[{"key": "dedicated", "operator": "Equal",
                                   "value": "ml", "effect": "NoSchedule"}])
        assert p.filter(CycleState(), pod, ni(taints=[TAINT_NS])).ok

    def test_prefer_noschedule_never_blocks_but_scores_lower(self):
        p = NodeAdmission()
        pod = mk_pod()
        tainted = ni(taints=[TAINT_PREFER])
        assert p.filter(CycleState(), pod, tainted).ok
        s_tainted, _ = p.score(CycleState(), pod, tainted)
        s_clean, _ = p.score(CycleState(), pod, ni())
        assert s_tainted < s_clean


def _cluster(names):
    store = TelemetryStore()
    now = time.time()
    for n in names:
        m = make_tpu_node(n, chips=4)
        m.heartbeat = now + 1e8
        store.put(m)
    c = FakeCluster(store)
    c.add_nodes_from_telemetry()
    return c


class TestSchedulerIntegration:
    def test_selector_routes_to_labeled_node(self):
        c = _cluster(["a", "b", "c"])
        c.set_node_meta("b", labels={"pool": "gold"})
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9))
        pod = mk_pod("want-gold", selector={"pool": "gold"})
        sched.submit(pod)
        sched.run_until_idle()
        assert pod.phase == PodPhase.BOUND and pod.node == "b"

    def test_taint_excludes_node(self):
        c = _cluster(["a", "b"])
        c.set_node_meta("a", taints=[TAINT_NS])
        c.set_node_meta("b", taints=[TAINT_NS])
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9,
                                             max_attempts=1))
        blocked = mk_pod("no-tol")
        tolerant = mk_pod("tol", tolerations=[
            {"key": "dedicated", "operator": "Exists", "effect": "",
             "value": ""}])
        sched.submit(blocked)
        sched.submit(tolerant)
        sched.run_until_idle()
        assert blocked.phase == PodPhase.FAILED
        assert tolerant.phase == PodPhase.BOUND

    def test_meta_change_invalidates_cached_verdicts(self):
        # a node labeled AFTER a pod went unschedulable must be re-offered:
        # set_node_meta bumps the node's change counter, so cached NodeInfos
        # and the unschedulable-class memo can't serve the stale verdict
        c = _cluster(["a"])
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9,
                                             max_attempts=0))
        pod = mk_pod("waits", selector={"pool": "gold"})
        sched.submit(pod)
        for _ in range(3):
            sched.run_one()
        assert pod.phase == PodPhase.PENDING
        c.set_node_meta("a", labels={"pool": "gold"})
        sched.run_until_idle()
        assert pod.phase == PodPhase.BOUND and pod.node == "a"

    def test_prefer_noschedule_is_last_resort(self):
        c = _cluster(["t1", "clean"])
        c.set_node_meta("t1", taints=[TAINT_PREFER])
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9))
        pod = mk_pod("picky")
        sched.submit(pod)
        sched.run_until_idle()
        assert pod.phase == PodPhase.BOUND and pod.node == "clean"


class TestPreemptionRespectsAdmission:
    def test_no_evictions_on_inadmissible_nodes(self):
        """A high-priority pod that cannot pass admission anywhere (all
        nodes tainted, no toleration) must NOT trigger preemption: evicting
        victims on a node the preemptor can never land on would disrupt
        workloads every cycle while the pod stays Pending."""
        c = _cluster(["a", "b"])
        for n in ("a", "b"):
            c.set_node_meta(n, taints=[TAINT_NS])
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9,
                                             max_attempts=1))
        # fill both nodes with low-priority TOLERATING pods
        fillers = []
        for n in ("a", "b"):
            f = mk_pod(f"fill-{n}", labels={"scv/number": "4"},
                       tolerations=[{"key": "dedicated", "operator": "Exists",
                                     "effect": "", "value": ""}])
            fillers.append(f)
            sched.submit(f)
        sched.run_until_idle()
        assert all(f.phase == PodPhase.BOUND for f in fillers)
        # high-priority pod without a toleration: unschedulable, NO victims
        hp = mk_pod("hp", labels={"scv/number": "1", "scv/priority": "9"})
        sched.submit(hp)
        sched.run_until_idle()
        assert hp.phase == PodPhase.FAILED
        assert all(f.phase == PodPhase.BOUND for f in fillers), \
            "preemption must not evict for an inadmissible preemptor"
        assert sched.metrics.counters.get("pods_evicted_total", 0) == 0

    def test_preemption_targets_only_admissible_nodes(self):
        """With one selectable node and one not, preemption plans victims
        only on the node matching the preemptor's nodeSelector."""
        from yoda_scheduler_tpu.scheduler.core import HybridClock

        c = _cluster(["sel", "other"])
        c.set_node_meta("sel", labels={"pool": "gold"})
        # the evicted victim can never re-fit (both nodes full): bound
        # attempts + virtual backoff clock keep run_until_idle finite
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9,
                                             max_attempts=3),
                          clock=HybridClock())
        f_sel = mk_pod("f-sel", labels={"scv/number": "4"})
        f_other = mk_pod("f-other", labels={"scv/number": "4"})
        sched.submit(f_sel)
        sched.submit(f_other)
        sched.run_until_idle()
        by_node = {f_sel.node: f_sel, f_other.node: f_other}
        hp = mk_pod("hp", labels={"scv/number": "1", "scv/priority": "9"},
                    selector={"pool": "gold"})
        sched.submit(hp)
        sched.run_until_idle()
        assert hp.phase == PodPhase.BOUND and hp.node == "sel"
        assert by_node["other"].phase == PodPhase.BOUND, \
            "victim must come from the admissible node only"


def _wait_for(cond, timeout=10.0, step=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(step)
    return False


class _LiveScheduler:
    """Context manager: run the real serve loop (KubeClient + watch cache
    over live HTTP) against a FakeApiServer in a daemon thread."""

    def __init__(self, server):
        import threading

        from yoda_scheduler_tpu.k8s.client import (
            KubeClient, run_scheduler_against_cluster)

        self._stop = threading.Event()
        self._t = threading.Thread(
            target=run_scheduler_against_cluster,
            args=(KubeClient(server.url), [(SchedulerConfig(), None)]),
            kwargs={"metrics_port": None, "poll_s": 0.05,
                    "stop_event": self._stop},
            daemon=True)

    def __enter__(self):
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._t.join(timeout=5.0)


class TestLiveTransport:
    def test_meta_flows_through_watch_cache_and_gates_binds(self):
        """Node labels/taints travel API server -> watch cache -> NodeInfo:
        a nodeSelector pod lands on the labeled node and an untolerated
        NoSchedule taint keeps the other node off-limits, over real HTTP."""
        from fake_apiserver import FakeApiServer

        with FakeApiServer() as server:
            server.state.add_node("gold", labels={"pool": "gold"})
            server.state.add_node(
                "fenced", taints=[{"key": "dedicated", "value": "ml",
                                   "effect": "NoSchedule"}])
            for n in ("gold", "fenced"):
                server.state.put_metrics(make_tpu_node(n, chips=4).to_cr())
            server.state.add_pod({
                "metadata": {"name": "sel", "namespace": "default",
                             "labels": {"scv/number": "1"},
                             "ownerReferences": [{"kind": "ReplicaSet",
                                                  "name": "rs",
                                                  "controller": True}]},
                "spec": {"schedulerName": "yoda-scheduler",
                         "nodeSelector": {"pool": "gold"}},
                "status": {"phase": "Pending"},
            })
            # no toleration: of the two nodes only "gold" is admissible
            server.state.add_pod({
                "metadata": {"name": "plain", "namespace": "default",
                             "labels": {"scv/number": "1"},
                             "ownerReferences": [{"kind": "ReplicaSet",
                                                  "name": "rs",
                                                  "controller": True}]},
                "spec": {"schedulerName": "yoda-scheduler"},
                "status": {"phase": "Pending"},
            })
            with _LiveScheduler(server):
                assert _wait_for(lambda: all(
                    (server.state.pod(n) or {}).get("spec", {}).get("nodeName")
                    for n in ("sel", "plain"))), "pods never bound"
                assert server.state.pod("sel")["spec"]["nodeName"] == "gold"
                assert server.state.pod("plain")["spec"]["nodeName"] == "gold"

    def test_cordon_flows_through_watch_cache(self):
        """Node spec.unschedulable travels API server -> reflector ->
        NodeInfo over real HTTP: the cordoned node never receives a
        bind even though its telemetry is healthy."""
        from fake_apiserver import FakeApiServer

        with FakeApiServer() as server:
            server.state.add_node("corded", unschedulable=True)
            server.state.add_node("open")
            for n in ("corded", "open"):
                server.state.put_metrics(make_tpu_node(n, chips=4).to_cr())
            for i in range(2):
                server.state.add_pod({
                    "metadata": {"name": f"w{i}", "namespace": "default",
                                 "labels": {"scv/number": "1"},
                                 "ownerReferences": [{
                                     "kind": "ReplicaSet", "name": "rs",
                                     "controller": True}]},
                    "spec": {"schedulerName": "yoda-scheduler"},
                    "status": {"phase": "Pending"},
                })
            with _LiveScheduler(server):
                # both pods fit the open node; waiting for BOTH means a
                # late wrong bind cannot slip past the assertion
                assert _wait_for(lambda: all(
                    (server.state.pod(f"w{i}") or {})
                    .get("spec", {}).get("nodeName") for i in range(2))), \
                    "pods never bound"
                for i in range(2):
                    node = server.state.pod(f"w{i}")["spec"]["nodeName"]
                    assert node == "open", f"w{i} bound {node}"


class TestManifestParsing:
    def test_from_manifest_selector_and_tolerations(self):
        pod = Pod.from_manifest({
            "metadata": {"name": "x", "labels": {"scv/number": "1"}},
            "spec": {
                "schedulerName": "yoda-scheduler",
                "nodeSelector": {"pool": "gold"},
                "tolerations": [
                    {"key": "dedicated", "operator": "Equal", "value": "ml",
                     "effect": "NoSchedule"},
                    {"operator": "Exists"},
                ],
            },
        })
        assert pod.node_selector == {"pool": "gold"}
        assert pod.tolerations[0]["key"] == "dedicated"
        # defaults fill in: operator Equal, empty effect matches everything
        assert pod.tolerations[1] == {"key": "", "operator": "Exists",
                                      "value": "", "effect": ""}


class TestNodeAffinity:
    def _pod(self, terms):
        return Pod.from_manifest({
            "metadata": {"name": "a", "labels": {"scv/number": "1"}},
            "spec": {
                "schedulerName": "yoda-scheduler",
                "affinity": {"nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": terms}}},
            },
        })

    def test_expression_operators(self):
        from yoda_scheduler_tpu.scheduler.plugins.admission import (
            affinity_matches)

        pod = self._pod([{"matchExpressions": [
            {"key": "pool", "operator": "In", "values": ["gold", "silver"]},
            {"key": "cordoned", "operator": "DoesNotExist"},
            {"key": "gen", "operator": "Gt", "values": ["4"]},
        ]}])
        assert affinity_matches(pod, {"pool": "gold", "gen": "5"})
        assert not affinity_matches(pod, {"pool": "bronze", "gen": "5"})
        assert not affinity_matches(pod, {"pool": "gold", "gen": "4"})
        assert not affinity_matches(
            pod, {"pool": "gold", "gen": "5", "cordoned": "y"})

    def test_terms_or_together(self):
        from yoda_scheduler_tpu.scheduler.plugins.admission import (
            affinity_matches)

        pod = self._pod([
            {"matchExpressions": [
                {"key": "pool", "operator": "In", "values": ["gold"]}]},
            {"matchExpressions": [
                {"key": "zone", "operator": "Exists"}]},
        ])
        assert affinity_matches(pod, {"pool": "gold"})
        assert affinity_matches(pod, {"zone": "a"})
        assert not affinity_matches(pod, {"pool": "silver"})

    def test_scheduler_routes_by_affinity(self):
        c = _cluster(["a", "b"])
        c.set_node_meta("b", labels={"gen": "6"})
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9))
        pod = self._pod([{"matchExpressions": [
            {"key": "gen", "operator": "Gt", "values": ["5"]}]}])
        sched.submit(pod)
        sched.run_until_idle()
        assert pod.phase == PodPhase.BOUND and pod.node == "b"

    def test_unknown_operator_matches_nothing(self):
        from yoda_scheduler_tpu.scheduler.plugins.admission import (
            affinity_matches)

        pod = self._pod([{"matchExpressions": [
            {"key": "pool", "operator": "Inn", "values": ["gold"]}]}])
        assert not affinity_matches(pod, {"pool": "gold"})


class TestSpecPriority:
    def test_spec_priority_feeds_label(self):
        pod = Pod.from_manifest({
            "metadata": {"name": "p"},
            "spec": {"schedulerName": "yoda-scheduler", "priority": 7}})
        assert pod.labels["scv/priority"] == "7"

    def test_label_wins_over_spec(self):
        pod = Pod.from_manifest({
            "metadata": {"name": "p", "labels": {"scv/priority": "2"}},
            "spec": {"schedulerName": "yoda-scheduler", "priority": 7}})
        assert pod.labels["scv/priority"] == "2"

    def test_well_known_priority_classes(self):
        pod = Pod.from_manifest({
            "metadata": {"name": "p"},
            "spec": {"schedulerName": "yoda-scheduler",
                     "priorityClassName": "system-cluster-critical"}})
        assert pod.labels["scv/priority"] == "2000000000"

    def test_no_priority_no_label(self):
        pod = Pod.from_manifest({
            "metadata": {"name": "p"},
            "spec": {"schedulerName": "yoda-scheduler"}})
        assert "scv/priority" not in pod.labels

    def test_matchfields_and_empty_terms_match_nothing(self):
        from yoda_scheduler_tpu.scheduler.plugins.admission import (
            affinity_matches)

        pinned = Pod.from_manifest({
            "metadata": {"name": "p", "labels": {"scv/number": "1"}},
            "spec": {"schedulerName": "yoda-scheduler", "affinity": {
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [{"matchFields": [
                            {"key": "metadata.name", "operator": "In",
                             "values": ["node-5"]}]}]}}}},
        })
        # metadata.name matchFields ARE modelled: the term matches only
        # the named node (and nothing when no node name is supplied —
        # match-all would scatter a node-pinned pod across the fleet)
        assert not affinity_matches(pinned, {"any": "labels"})
        assert affinity_matches(pinned, {}, "node-5")
        assert not affinity_matches(pinned, {}, "node-6")
        empty = Pod.from_manifest({
            "metadata": {"name": "e", "labels": {"scv/number": "1"}},
            "spec": {"schedulerName": "yoda-scheduler", "affinity": {
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [{}]}}}},
        })
        assert not affinity_matches(empty, {"any": "labels"})

    def test_malformed_affinity_never_crashes_parse(self):
        pod = Pod.from_manifest({
            "metadata": {"name": "m", "labels": {"scv/number": "1"}},
            "spec": {"schedulerName": "yoda-scheduler",
                     "affinity": {"nodeAffinity": ["notadict"]}}})
        assert pod.node_affinity == ()

    def test_int_values_coerced_to_strings(self):
        from yoda_scheduler_tpu.scheduler.plugins.admission import (
            affinity_matches)

        pod = Pod.from_manifest({
            "metadata": {"name": "i", "labels": {"scv/number": "1"}},
            "spec": {"schedulerName": "yoda-scheduler", "affinity": {
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [{"matchExpressions": [
                            {"key": "gen", "operator": "In",
                             "values": [5]}]}]}}}},
        })
        assert affinity_matches(pod, {"gen": "5"})


class TestPreferredAffinity:
    def _pod(self, prefs):
        return Pod.from_manifest({
            "metadata": {"name": "p", "labels": {"scv/number": "1"}},
            "spec": {"schedulerName": "yoda-scheduler", "affinity": {
                "nodeAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution":
                        prefs}}},
        })

    def test_weighted_score(self):
        p = NodeAdmission()
        pod = self._pod([
            {"weight": 50, "preference": {"matchExpressions": [
                {"key": "pool", "operator": "In", "values": ["gold"]}]}},
            {"weight": 10, "preference": {"matchExpressions": [
                {"key": "zone", "operator": "Exists"}]}},
        ])
        both, _ = p.score(CycleState(), pod,
                          ni(labels={"pool": "gold", "zone": "a"}))
        one, _ = p.score(CycleState(), pod, ni(labels={"zone": "a"}))
        none, _ = p.score(CycleState(), pod, ni())
        assert (both, one, none) == (60.0, 10.0, 0.0)

    def test_scheduler_prefers_weighted_node(self):
        c = _cluster(["plain", "preferred"])
        c.set_node_meta("preferred", labels={"pool": "gold"})
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9))
        pod = self._pod([{"weight": 100, "preference": {"matchExpressions": [
            {"key": "pool", "operator": "In", "values": ["gold"]}]}}])
        sched.submit(pod)
        sched.run_until_idle()
        assert pod.phase == PodPhase.BOUND and pod.node == "preferred"

    def test_preference_never_blocks(self):
        # no node matches the preference: the pod still binds somewhere
        c = _cluster(["a"])
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9))
        pod = self._pod([{"weight": 100, "preference": {"matchExpressions": [
            {"key": "pool", "operator": "In", "values": ["gold"]}]}}])
        sched.submit(pod)
        sched.run_until_idle()
        assert pod.phase == PodPhase.BOUND

    def test_malformed_entries_dropped(self):
        pod = self._pod([
            {"weight": "high", "preference": {}},   # non-int weight
            {"weight": 500, "preference": {}},      # out of API range
            {"weight": 0, "preference": {}},        # out of API range
            "notadict",
        ])
        assert pod.preferred_affinity == ()


class TestMatchFields:
    def test_metadata_name_pins_to_node(self):
        """matchFields on metadata.name (the only field the API accepts)
        pins a pod to named nodes."""
        c = _cluster(["a", "b", "c"])
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9))
        pod = Pod.from_manifest({
            "metadata": {"name": "pinned", "labels": {"scv/number": "1"}},
            "spec": {"schedulerName": "yoda-scheduler", "affinity": {
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [{"matchFields": [
                            {"key": "metadata.name", "operator": "In",
                             "values": ["b"]}]}]}}}},
        })
        sched.submit(pod)
        sched.run_until_idle()
        assert pod.phase == PodPhase.BOUND and pod.node == "b"

    def test_metadata_name_notin_excludes(self):
        c = _cluster(["a", "b"])
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9))
        pod = Pod.from_manifest({
            "metadata": {"name": "avoids-a", "labels": {"scv/number": "1"}},
            "spec": {"schedulerName": "yoda-scheduler", "affinity": {
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [{"matchFields": [
                            {"key": "metadata.name", "operator": "NotIn",
                             "values": ["a"]}]}]}}}},
        })
        sched.submit(pod)
        sched.run_until_idle()
        assert pod.phase == PodPhase.BOUND and pod.node == "b"

    def test_unknown_field_still_matches_nothing(self):
        from yoda_scheduler_tpu.scheduler.plugins.admission import (
            affinity_matches)

        pod = Pod.from_manifest({
            "metadata": {"name": "p", "labels": {"scv/number": "1"}},
            "spec": {"schedulerName": "yoda-scheduler", "affinity": {
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [{"matchFields": [
                            {"key": "spec.unschedulable", "operator": "In",
                             "values": ["false"]}]}]}}}},
        })
        assert not affinity_matches(pod, {}, "any-node")

    def test_preferred_affinity_matchfields_scores_by_name(self):
        """metadata.name matchFields in a PREFERRED term must also resolve
        against the node name in scoring."""
        p = NodeAdmission()
        pod = Pod.from_manifest({
            "metadata": {"name": "p", "labels": {"scv/number": "1"}},
            "spec": {"schedulerName": "yoda-scheduler", "affinity": {
                "nodeAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution": [
                        {"weight": 40, "preference": {"matchFields": [
                            {"key": "metadata.name", "operator": "In",
                             "values": ["fav"]}]}}]}}},
        })
        s_fav, _ = p.score(CycleState(), pod, ni(name="fav"))
        s_other, _ = p.score(CycleState(), pod, ni(name="other"))
        assert (s_fav, s_other) == (40.0, 0.0)

    def test_malformed_matchfields_shape_unmatchable(self):
        from yoda_scheduler_tpu.scheduler.plugins.admission import (
            affinity_matches)

        pod = Pod.from_manifest({
            "metadata": {"name": "p", "labels": {"scv/number": "1"}},
            "spec": {"schedulerName": "yoda-scheduler", "affinity": {
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [{
                            "matchExpressions": [
                                {"key": "pool", "operator": "Exists"}],
                            "matchFields": {"key": "metadata.name",
                                            "operator": "In",
                                            "values": ["n"]}}]}}}},
        })
        # a malformed node pin must not be dropped: the term stays
        # unmatchable even though its matchExpressions would pass
        assert not affinity_matches(pod, {"pool": "x"}, "n")


class TestNodeUnschedulable:
    """kubectl cordon (Node spec.unschedulable) — upstream's
    NodeUnschedulable plugin, which the reference inherited from the
    embedded kube-scheduler. Checked directly, not only via the
    auto-added node.kubernetes.io/unschedulable taint (the node
    controller may lag or be disabled); pods tolerating that taint keep
    upstream's escape hatch."""

    def test_cordon_excludes_node(self):
        c = _cluster(["a", "b"])
        c.set_node_meta("a", unschedulable=True)
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9))
        pod = mk_pod("p1")
        sched.submit(pod)
        sched.run_until_idle()
        assert pod.phase == PodPhase.BOUND and pod.node == "b"

    def test_fully_cordoned_cluster_fails_pod(self):
        c = _cluster(["a", "b"])
        c.set_node_meta("a", unschedulable=True)
        c.set_node_meta("b", unschedulable=True)
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9,
                                             max_attempts=1))
        pod = mk_pod("p1")
        sched.submit(pod)
        sched.run_until_idle()
        assert pod.phase == PodPhase.FAILED

    def test_unschedulable_toleration_admits(self):
        c = _cluster(["a"])
        c.set_node_meta("a", unschedulable=True)
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9,
                                             max_attempts=1))
        pod = mk_pod("daemon", tolerations=[
            {"key": "node.kubernetes.io/unschedulable",
             "operator": "Exists", "effect": "NoSchedule", "value": ""}])
        sched.submit(pod)
        sched.run_until_idle()
        assert pod.phase == PodPhase.BOUND and pod.node == "a"

    def test_uncordon_wakes_pending_pod(self):
        # cordon state flips through set_node_meta, which bumps the
        # node's change counter: the unschedulable-class memo must not
        # serve the stale verdict after the uncordon
        c = _cluster(["a"])
        c.set_node_meta("a", unschedulable=True)
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9,
                                             max_attempts=0))
        pod = mk_pod("waits")
        sched.submit(pod)
        for _ in range(3):
            sched.run_one()
        assert pod.phase == PodPhase.PENDING
        c.set_node_meta("a", unschedulable=False)
        sched.run_until_idle()
        assert pod.phase == PodPhase.BOUND and pod.node == "a"

    def test_preemption_never_plans_victims_on_cordoned_node(self):
        """Both nodes full of low-priority victims, one node cordoned
        after they bound: the high-priority pod must preempt on the
        schedulable node only — evicting on the cordoned node would
        disrupt a workload for a bind that can never follow."""
        from yoda_scheduler_tpu.scheduler.core import HybridClock

        c = _cluster(["cord", "ok"])
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9,
                                             max_attempts=3),
                          clock=HybridClock())
        f1 = mk_pod("f1", labels={"scv/number": "4"})
        f2 = mk_pod("f2", labels={"scv/number": "4"})
        sched.submit(f1)
        sched.submit(f2)
        sched.run_until_idle()
        by_node = {f1.node: f1, f2.node: f2}
        c.set_node_meta("cord", unschedulable=True)
        hp = mk_pod("hp", labels={"scv/number": "1", "scv/priority": "9"})
        sched.submit(hp)
        sched.run_until_idle()
        assert hp.phase == PodPhase.BOUND and hp.node == "ok"
        assert by_node["cord"].phase == PodPhase.BOUND, \
            "victim must never come from the cordoned node"

    def test_admissible_helper_respects_cordon(self):
        from yoda_scheduler_tpu.scheduler.plugins.admission import admissible
        from yoda_scheduler_tpu.scheduler.framework import NodeInfo
        pod = mk_pod("hi")
        assert not admissible(pod, NodeInfo(name="x", metrics=None,
                                            unschedulable=True))
        assert admissible(pod, NodeInfo(name="x", metrics=None))

    def test_api_parse_carries_unschedulable(self):
        from yoda_scheduler_tpu.k8s.client import _node_meta_from_api
        labels, taints, alloc, unsched = _node_meta_from_api({
            "metadata": {"name": "n", "labels": {"a": "b"}},
            "spec": {"unschedulable": True},
        })
        assert unsched is True and labels == {"a": "b"}
        *_, unsched2 = _node_meta_from_api({"metadata": {"name": "n"}})
        assert unsched2 is False


class TestNodePorts:
    """Upstream NodePorts plugin parity: container hostPorts are
    node-exclusive per (port, protocol, overlapping hostIP); the
    reference inherited this from the embedded kube-scheduler."""

    def _pod(self, name, ports, prio=None):
        labels = {"scv/number": "1"}
        if prio is not None:
            labels["scv/priority"] = str(prio)
        return Pod(name, labels=labels, host_ports=tuple(ports))

    def test_conflict_routes_to_free_node(self):
        c = _cluster(["a", "b"])
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9))
        p1 = self._pod("p1", [(8080, "TCP", "")])
        p2 = self._pod("p2", [(8080, "TCP", "")])
        sched.submit(p1)
        sched.submit(p2)
        sched.run_until_idle()
        assert p1.phase == PodPhase.BOUND and p2.phase == PodPhase.BOUND
        assert p1.node != p2.node

    def test_conflict_fails_when_no_free_node(self):
        c = _cluster(["a"])
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9,
                                             max_attempts=1))
        p1 = self._pod("p1", [(443, "TCP", "")])
        p2 = self._pod("p2", [(443, "TCP", "10.0.0.1")])  # wildcard overlap
        sched.submit(p1)
        sched.submit(p2)
        sched.run_until_idle()
        assert p1.phase == PodPhase.BOUND
        assert p2.phase == PodPhase.FAILED

    def test_different_protocol_coexists(self):
        c = _cluster(["a"])
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9,
                                             max_attempts=1))
        p1 = self._pod("p1", [(53, "TCP", "")])
        p2 = self._pod("p2", [(53, "UDP", "")])
        sched.submit(p1)
        sched.submit(p2)
        sched.run_until_idle()
        assert p1.phase == PodPhase.BOUND and p2.phase == PodPhase.BOUND
        assert p1.node == p2.node == "a"

    def test_distinct_ips_coexist(self):
        c = _cluster(["a"])
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9,
                                             max_attempts=1))
        p1 = self._pod("p1", [(80, "TCP", "10.0.0.1")])
        p2 = self._pod("p2", [(80, "TCP", "10.0.0.2")])
        sched.submit(p1)
        sched.submit(p2)
        sched.run_until_idle()
        assert p1.phase == PodPhase.BOUND and p2.phase == PodPhase.BOUND

    def test_preemption_evicts_port_holder(self):
        from yoda_scheduler_tpu.scheduler.core import HybridClock

        c = _cluster(["a"])
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9,
                                             max_attempts=3),
                          clock=HybridClock())
        low = self._pod("low", [(9000, "TCP", "")], prio=1)
        sched.submit(low)
        sched.run_until_idle()
        assert low.phase == PodPhase.BOUND
        hi = self._pod("hi", [(9000, "TCP", "")], prio=9)
        sched.submit(hi)
        sched.run_until_idle()
        assert hi.phase == PodPhase.BOUND and hi.node == "a"
        assert low.phase != PodPhase.BOUND, \
            "the conflicting low-priority holder must have been evicted"

    def test_manifest_parse(self):
        p = Pod.from_manifest({
            "metadata": {"name": "x"},
            "spec": {"containers": [
                {"ports": [{"hostPort": 80, "protocol": "UDP",
                            "hostIP": "1.2.3.4"},
                           {"containerPort": 8080}]},
            ], "initContainers": [{"ports": [{"hostPort": 81}]}]},
        })
        assert p.host_ports == ((80, "UDP", "1.2.3.4"), (81, "TCP", ""))

    def test_wildcard_0000_overlaps_specific_ip(self):
        c = _cluster(["a"])
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9,
                                             max_attempts=1))
        p1 = self._pod("p1", [(80, "TCP", "0.0.0.0")])
        p2 = self._pod("p2", [(80, "TCP", "10.0.0.1")])
        sched.submit(p1)
        sched.submit(p2)
        sched.run_until_idle()
        assert p1.phase == PodPhase.BOUND
        assert p2.phase == PodPhase.FAILED, \
            "0.0.0.0 is the bind-all address and overlaps every hostIP"

    def test_nominated_hold_protects_freed_port(self):
        """The steal window: a preemption's victim drains GRACEFULLY
        (real API-server eviction), the victim finally disappears, and a
        lower-priority port claimant's cycle runs before the nominated
        preemptor's backoff expires. The freed port must be held for the
        preemptor — the ports twin of the cpu/mem nominated hold;
        without it the claimant binds the port and the preemptor must
        preempt a second time (churn)."""
        from yoda_scheduler_tpu.scheduler.core import HybridClock

        c = _cluster(["a"])

        # graceful eviction: the victim keeps its binding while draining
        real_evict = c.evict

        def graceful_evict(pod):
            pod.terminating = True

        c.evict = graceful_evict
        sched = Scheduler(c, SchedulerConfig(telemetry_max_age_s=1e9,
                                             max_attempts=6),
                          clock=HybridClock())
        low = self._pod("low", [(9000, "TCP", "")], prio=1)
        sched.submit(low)
        sched.run_until_idle()
        assert low.phase == PodPhase.BOUND
        hi = self._pod("hi", [(9000, "TCP", "")], prio=9)
        sched.submit(hi)
        sched.run_one()      # hi preempts: low starts draining, hi nominated
        assert low.terminating and low.phase == PodPhase.BOUND
        sched.run_one()      # hi's retry parks: waiting for victims
        mid = self._pod("mid", [(9000, "TCP", "")], prio=5)
        sched.submit(mid)
        real_evict(low)      # drain completes: the window is open
        sched.run_one()      # mid's cycle runs first (hi still in backoff)
        assert mid.phase != PodPhase.BOUND, \
            "mid must not steal the port held for the nominated preemptor"
        sched.run_until_idle()
        assert hi.phase == PodPhase.BOUND and hi.node == "a"
        assert mid.phase != PodPhase.BOUND
        # the discriminating assertion: without the hold mid binds inside
        # the window and a SECOND preemption (of mid) is needed
        assert sched.metrics.counters.get("pods_evicted_total", 0) == 1, \
            "only the original holder may be evicted; the hold must stop " \
            "the steal/preempt-again churn"
