"""KV-cache autoregressive generation (models/generate.py): greedy decode
must reproduce the full-forward argmax token-for-token, the cache must stay
GQA-sized, sampling must be shape/determinism-correct, and the whole loop
must run jitted over a sharded mesh."""

import jax
import jax.numpy as jnp
import pytest

from yoda_scheduler_tpu.models.llama import (
    LlamaConfig, init_llama, llama_forward)
from yoda_scheduler_tpu.models.generate import (
    KVCache, decode_step, generate, make_generate_fn, prefill)

CFG = LlamaConfig.tiny()


@pytest.fixture(scope="module")
def params():
    return init_llama(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def prompt():
    return jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                              CFG.vocab_size)


@pytest.fixture(scope="module")
def tp_sharded(params):
    """(mesh, tp/dp-sharded params) — one sharded compile shared by the
    sharded-decode tests."""
    from yoda_scheduler_tpu.parallel import llama_shardings, make_mesh

    mesh = make_mesh({"dp": 2, "tp": 2})
    sharded = jax.tree.map(lambda x, sh: jax.device_put(x, sh), params,
                           llama_shardings(mesh, CFG))
    return mesh, sharded


def _greedy_reference(params, prompt, n):
    toks = prompt
    out = []
    for _ in range(n):
        logits = llama_forward(params, toks, CFG)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        out.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


class TestGreedyDecode:
    def test_matches_full_forward_token_for_token(self, params, prompt):
        want = _greedy_reference(params, prompt, 8)
        got = jax.jit(lambda p, t: generate(p, t, CFG, 8))(params, prompt)
        assert jnp.array_equal(want, got)

    def test_prefill_then_stepwise_decode(self, params, prompt):
        cache = KVCache.zeros(CFG, 2, 32)
        logits, cache = prefill(params, prompt, cache, CFG)
        tok = jnp.argmax(logits, axis=-1)
        logits2, cache = decode_step(params, tok, cache, CFG)
        assert int(cache.length) == prompt.shape[1] + 1
        want = _greedy_reference(params, prompt, 2)
        assert jnp.array_equal(tok, want[:, 0])
        assert jnp.array_equal(jnp.argmax(logits2, axis=-1), want[:, 1])

    def test_cache_is_gqa_sized(self):
        cache = KVCache.zeros(CFG, 2, 32)
        assert cache.k.shape == (CFG.n_layers, 2, 32, CFG.n_kv_heads,
                                 CFG.head_dim)
        assert CFG.n_kv_heads < CFG.n_heads  # tiny() is genuinely GQA


class TestSampling:
    def test_temperature_sampling_is_deterministic_per_key(self, params,
                                                           prompt):
        f = make_generate_fn(CFG, 6, temperature=0.8)
        a = f(params, prompt, key=jax.random.PRNGKey(7))
        b = f(params, prompt, key=jax.random.PRNGKey(7))
        c = f(params, prompt, key=jax.random.PRNGKey(8))
        assert a.shape == (2, 6)
        assert jnp.array_equal(a, b)
        assert not jnp.array_equal(a, c)

    def test_sampling_without_key_raises(self, params, prompt):
        with pytest.raises(ValueError, match="requires"):
            generate(params, prompt, CFG, 4, temperature=0.5)

    def test_max_len_too_small_raises(self, params, prompt):
        with pytest.raises(ValueError, match="max_len"):
            generate(params, prompt, CFG, 8, max_len=16)


class TestShardedDecode:
    def test_generate_over_tp_mesh_matches_single_device(self, params,
                                                         prompt,
                                                         tp_sharded):
        single = jax.jit(lambda p, t: generate(p, t, CFG, 6))(params, prompt)
        _, sharded_params = tp_sharded
        got = jax.jit(lambda p, t: generate(p, t, CFG, 6))(
            sharded_params, prompt)
        # sharded collectives reorder the bf16 reductions, so a late token
        # can flip on a near-tie; the early tokens must agree exactly
        assert jnp.array_equal(single[:, :4], got[:, :4])
        assert got.shape == single.shape


def test_sliding_window_inference_matches_training():
    """A windowed model's cached-generation logits must match the
    training-path forward exactly — inference silently attending beyond
    the window would diverge from what was trained."""
    import dataclasses

    from yoda_scheduler_tpu.models.llama import (
        LlamaConfig, init_llama, llama_forward)
    from yoda_scheduler_tpu.models.generate import KVCache, prefill

    cfg = dataclasses.replace(LlamaConfig.tiny(), sliding_window=16)
    params = init_llama(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 48), 0,
                                cfg.vocab_size)
    train_logits = llama_forward(params, tokens, cfg)
    cache = KVCache.zeros(cfg, 1, 64)
    gen_logits, cache = prefill(params, tokens, cache, cfg)
    err = jnp.max(jnp.abs(train_logits[0, -1] - gen_logits[0]))
    assert float(err) < 1e-4


class TestRollingCache:
    """Rolling (ring-buffer) KV cache for sliding-window models: O(window)
    decode HBM with outputs IDENTICAL to the full cache — the window
    masks the same positions either way."""

    def _setup(self, window=32, prompt_len=48):
        import dataclasses

        from yoda_scheduler_tpu.models.llama import LlamaConfig, init_llama

        cfg = dataclasses.replace(LlamaConfig.tiny(),
                                  sliding_window=window)
        params = init_llama(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1),
                                    (2, prompt_len), 0, cfg.vocab_size)
        return cfg, params, prompt

    def test_rolling_matches_full_cache(self):
        from yoda_scheduler_tpu.models.generate import generate

        cfg, params, prompt = self._setup()
        full = generate(params, prompt, cfg, 24, rolling=False)
        roll = generate(params, prompt, cfg, 24, rolling=True)
        assert jnp.array_equal(full, roll)

    def test_short_prompt_partially_warm_ring(self):
        # prompt < window: unwritten slots (-1) must never attend
        from yoda_scheduler_tpu.models.generate import generate

        cfg, params, prompt = self._setup(window=32, prompt_len=16)
        full = generate(params, prompt, cfg, 40, rolling=False)
        roll = generate(params, prompt, cfg, 40, rolling=True)
        assert jnp.array_equal(full, roll)

    def test_ring_is_window_sized(self):
        from yoda_scheduler_tpu.models.generate import (
            KVCache, RollingKVCache, prefill)

        cfg, params, prompt = self._setup(window=32, prompt_len=48)
        pre = KVCache.zeros(cfg, 2, 48)
        _, pre = prefill(params, prompt, pre, cfg)
        ring = RollingKVCache.from_prefill(pre, 32)
        assert ring.k.shape[2] == 32  # not prompt+new sized
        assert int(ring.next_pos) == 48

    def test_auto_rolling_kicks_in_for_long_generations(self):
        # window < prompt+new -> rolling is the default path; the result
        # must still match an explicit full-cache run
        from yoda_scheduler_tpu.models.generate import generate

        cfg, params, prompt = self._setup(window=32, prompt_len=40)
        auto = generate(params, prompt, cfg, 24)  # rolling=None -> auto
        full = generate(params, prompt, cfg, 24, rolling=False)
        assert jnp.array_equal(auto, full)

    def test_rolling_without_window_raises(self):
        from yoda_scheduler_tpu.models.generate import generate
        from yoda_scheduler_tpu.models.llama import LlamaConfig, init_llama

        cfg = LlamaConfig.tiny()
        params = init_llama(cfg, jax.random.PRNGKey(0))
        prompt = jnp.zeros((1, 8), jnp.int32)
        with pytest.raises(ValueError, match="sliding_window"):
            generate(params, prompt, cfg, 4, rolling=True)


class TestEagerDecode:
    """generate(eager=True): the Python-driven decode loop (one donated
    jitted dispatch per token — the mode for backends whose compiler
    cannot handle a KV-writing while-loop, and for per-token serving
    control) must produce token-identical output to the lax.scan path in
    every regime."""

    def test_eager_matches_scan_greedy(self, params, prompt):
        want = generate(params, prompt, CFG, 6)
        got = generate(params, prompt, CFG, 6, eager=True)
        assert (got == want).all()

    def test_eager_matches_scan_sampled(self, params, prompt):
        k = jax.random.PRNGKey(7)
        want = generate(params, prompt, CFG, 6, temperature=0.8, key=k)
        got = generate(params, prompt, CFG, 6, temperature=0.8, key=k,
                       eager=True)
        assert (got == want).all()

    def test_eager_matches_scan_rolling(self):
        from dataclasses import replace

        cfg = replace(CFG, sliding_window=8)
        params = init_llama(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        want = generate(params, prompt, cfg, 6, rolling=True)
        got = generate(params, prompt, cfg, 6, rolling=True, eager=True)
        assert (got == want).all()

    def test_eager_over_tp_mesh_matches_scan(self, prompt, tp_sharded):
        """The serving-relevant combination: eager per-token dispatch
        with tp-sharded params/caches must produce the same tokens as
        the scan path under the same sharding. The two sides are
        differently-compiled programs (one whole-program GSPMD jit vs
        per-step jits), so bf16 reduction order can flip a near-tie on
        a late token — only the early tokens must agree exactly, like
        the single-device comparison above."""
        _, sharded_params = tp_sharded
        want = jax.jit(lambda p, t: generate(p, t, CFG, 6))(
            sharded_params, prompt)
        got = generate(sharded_params, prompt, CFG, 6, eager=True)
        assert jnp.array_equal(want[:, :4], got[:, :4])
        assert got.shape == want.shape
