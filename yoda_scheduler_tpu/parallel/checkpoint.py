"""Checkpoint/resume for sharded training state (orbax-backed).

The *scheduler* is stateless by design — its caches rebuild from the API
server on restart (SURVEY §5 "Checkpoint/resume": nothing to build there).
The *workloads* it places are long-running training jobs, and elastic
recovery for them means: persist (step, params, opt_state) with their
shardings, restore onto a possibly different slice, and continue bit-exact.
This module is that workload-side capability.

Design notes (TPU-first):
- saves go through orbax's OCDBT/Zarr path, which writes per-shard from
  each host — no gather to host 0, so checkpoint bandwidth scales with the
  slice instead of bottlenecking on one HBM->host link
- restore takes an *abstract* state (ShapeDtypeStructs + NamedShardings),
  so arrays land directly on their target devices with their target
  layout; resharding onto a different mesh shape is just restoring with
  different shardings
- the manager keeps the last N steps and garbage-collects older ones —
  the elastic-recovery posture for preemptible TPU slices
"""

from __future__ import annotations

import os

import jax


def _abstract_like(tree):
    """ShapeDtypeStruct pytree (with shardings) from a concrete or abstract
    template."""
    def one(x):
        sharding = getattr(x, "sharding", None)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
    return jax.tree.map(one, tree)


class TrainCheckpointer:
    """Save/restore (step, params, opt_state) for the sharded train steps in
    parallel/train.py and parallel/pipeline.py.

    Usage:
        ckpt = TrainCheckpointer(dir, max_to_keep=3)
        ckpt.save(step, params, opt_state)
        step, params, opt_state = ckpt.restore((params0, opt0))  # latest
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, params, opt_state) -> None:
        """Persist the state and block until written. Synchronous on
        purpose: the train steps donate their (params, opt_state) buffers,
        so an async save could still be reading them when the next
        step_fn call invalidates them."""
        state = {"params": params, "opt_state": opt_state}
        saved = self.manager.save(step, args=self._ocp.args.StandardSave(state))
        if not saved:
            # orbax's should_save guard silently skips steps <= latest; a
            # skipped save after restoring an older step would resume from
            # divergent weights on the next crash — surface it instead
            raise ValueError(
                f"checkpoint step {step} was not saved (latest existing step"
                f" is {self.latest_step()}; orbax skips non-increasing"
                " steps). After restoring an older step, delete the newer"
                " checkpoints or save under a fresh step number.")
        self.manager.wait_until_finished()

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self.manager.all_steps())

    def restore(self, template, step: int | None = None):
        """Restore (step, params, opt_state). `template` is a
        (params, opt_state) pytree — concrete arrays or ShapeDtypeStructs —
        whose shapes/dtypes/shardings define the restore layout (typically
        the output of the train step's init_fn)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint steps under {self.directory}")
        params_t, opt_t = template
        abstract = {"params": _abstract_like(params_t),
                    "opt_state": _abstract_like(opt_t)}
        state = self.manager.restore(
            step, args=self._ocp.args.StandardRestore(abstract))
        return step, state["params"], state["opt_state"]

    def close(self) -> None:
        self.manager.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
