"""Round-2 regression tests: preemption victim safety and routing.

Covers the round-1 review findings — gang members must never be preemption
victims (evicting one strands its bound peers), evicted victims must route
back to THEIR owning profile's engine (not the preemptor's), and the
descheduler must refuse ownerless pods on clusters where eviction is a
permanent DELETE.
"""

from yoda_scheduler_tpu.scheduler import (
    FakeCluster, MultiProfileScheduler, Scheduler, SchedulerConfig)
from yoda_scheduler_tpu.scheduler.core import FakeClock
from yoda_scheduler_tpu.scheduler.deschedule import Descheduler
from yoda_scheduler_tpu.telemetry import (
    TelemetryStore, make_tpu_node, make_v4_slice)
from yoda_scheduler_tpu.utils import Pod, PodPhase


def mk_cluster(nodes, clock=None):
    store = TelemetryStore()
    clock = clock or FakeClock(start=1000.0)
    for n in nodes:
        n.heartbeat = clock.time()
        store.put(n)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    return cluster, clock


def mk_sched(nodes, config=None):
    cluster, clock = mk_cluster(nodes)
    return Scheduler(cluster, config or SchedulerConfig(max_attempts=3),
                     clock=clock)


def refresh(sched):
    for m in sched.cluster.telemetry.list():
        m.heartbeat = sched.clock.time()
        sched.cluster.telemetry.put(m)


class TestGangVictimProtection:
    def test_bound_gang_members_are_not_preempted(self):
        """A high-priority pod must NOT evict a bound gang member even when
        that is the only way to fit — a partial gang deadlocks its peers."""
        nodes = make_v4_slice("s", "2x2x2")  # 2 hosts x 4 chips
        sched = mk_sched(nodes)
        gang = [
            Pod(f"g-w{i}", labels={
                "tpu/gang-name": "g", "tpu/gang-size": "2",
                "scv/number": "4", "scv/priority": "1"})
            for i in range(2)
        ]
        for p in gang:
            sched.submit(p)
        sched.run_until_idle(max_cycles=50)
        assert all(p.phase == PodPhase.BOUND for p in gang)

        refresh(sched)
        hi = Pod("hi", labels={"scv/number": "4", "scv/priority": "9"})
        sched.submit(hi)
        sched.run_until_idle(max_cycles=60)
        # the cluster is fully held by the gang: hi must fail WITHOUT
        # evicting any gang member
        assert all(p.phase == PodPhase.BOUND for p in gang)
        assert hi.phase == PodPhase.FAILED
        assert sched.metrics.counters.get("preemptions_total", 0) == 0

    def test_non_gang_victim_still_preempted_next_to_gang(self):
        """Gang exclusion must not disable preemption of ordinary pods."""
        nodes = make_v4_slice("s", "2x2x2") + [make_tpu_node("solo", chips=4)]
        sched = mk_sched(nodes)
        plain = Pod("plain", labels={"scv/number": "4", "scv/priority": "1"})
        gang = [
            Pod(f"g-w{i}", labels={
                "tpu/gang-name": "g", "tpu/gang-size": "2",
                "scv/number": "4", "scv/priority": "1"})
            for i in range(2)
        ]
        sched.submit(plain)
        for p in gang:
            sched.submit(p)
        sched.run_until_idle(max_cycles=50)
        assert plain.phase == PodPhase.BOUND and plain.node == "solo"
        assert all(p.phase == PodPhase.BOUND for p in gang)

        refresh(sched)
        hi = Pod("hi", labels={"scv/number": "4", "scv/priority": "9"})
        sched.submit(hi)
        sched.run_until_idle(max_cycles=60)
        assert hi.phase == PodPhase.BOUND
        # the plain pod was the victim; the gang survived
        assert all(p.phase == PodPhase.BOUND for p in gang)
        assert plain.phase != PodPhase.BOUND


class TestVictimRouting:
    def test_victim_requeues_into_owning_profile(self):
        """Profile A's preemption of profile B's pod must put the victim back
        into B's engine, not A's."""
        cluster, clock = mk_cluster([make_tpu_node("n", chips=4)])
        sched = MultiProfileScheduler(cluster, [
            (SchedulerConfig(max_attempts=3), None),
            (SchedulerConfig(scheduler_name="yoda-scheduler2",
                             max_attempts=3), None),
        ], clock=clock)
        victim = Pod("victim", labels={"scv/number": "4", "scv/priority": "1"},
                     scheduler_name="yoda-scheduler2")
        assert sched.submit(victim)
        sched.run_until_idle()
        assert victim.phase == PodPhase.BOUND

        hi = Pod("hi", labels={"scv/number": "4", "scv/priority": "9"},
                 scheduler_name="yoda-scheduler")
        assert sched.submit(hi)
        sched.run_until_idle(max_cycles=80)
        assert hi.phase == PodPhase.BOUND
        a = sched.engine("yoda-scheduler")
        b = sched.engine("yoda-scheduler2")
        # the victim went back through B: B saw two submissions (original +
        # post-eviction requeue), A saw only its own pod
        assert b.metrics.counters["pods_submitted_total"] == 2
        assert a.metrics.counters["pods_submitted_total"] == 1
        assert a.metrics.counters.get("preempt_victims_unrouted_total", 0) == 0
        # and B (not A) now owns the pending victim's failure record
        assert victim.key in b.failed or b.tracks(victim.key)
        assert not a.tracks(victim.key)

    def test_standalone_engine_counts_unroutable_victims(self):
        """A single engine evicting a foreign-profile pod (bound out-of-band)
        must not swallow it into its own queue."""
        sched = mk_sched([make_tpu_node("n", chips=4)])
        foreign = Pod("foreign", labels={"scv/number": "4", "scv/priority": "1"},
                      scheduler_name="somebody-else")
        sched.cluster.bind(foreign, "n", [(x, y, 0) for x in range(2)
                                          for y in range(2)])
        hi = Pod("hi", labels={"scv/number": "4", "scv/priority": "9"})
        sched.submit(hi)
        sched.run_until_idle(max_cycles=60)
        assert hi.phase == PodPhase.BOUND
        assert sched.metrics.counters["preempt_victims_unrouted_total"] == 1
        assert not sched.tracks(foreign.key)


class _NoRequeueCluster(FakeCluster):
    """A FakeCluster behaving like a real API server for eviction semantics:
    evict is a permanent DELETE, nothing recreates the pod."""
    supports_local_requeue = False


class TestDescheduleOwnerless:
    def _slice_sched(self):
        nodes = make_v4_slice("s1", "2x2x4") + [make_tpu_node("solo", chips=4)]
        store = TelemetryStore()
        clock = FakeClock(start=1000.0)
        for n in nodes:
            n.heartbeat = clock.time()
            store.put(n)
        cluster = _NoRequeueCluster(store)
        cluster.add_nodes_from_telemetry()
        sched = Scheduler(cluster, SchedulerConfig(max_attempts=3),
                          clock=clock)
        return sched, nodes

    def test_ownerless_pod_not_descheduled_without_local_requeue(self):
        sched, nodes = self._slice_sched()
        stray = Pod("stray", labels={"scv/number": "1",
                                     "tpu/accelerator": "tpu"})
        assert not stray.has_controller
        sched.cluster.bind(stray, nodes[0].node, [(0, 0, 0)])
        plan = Descheduler(sched).plan()
        assert not plan.victims  # deleting it would destroy the workload

    def test_controlled_pod_still_descheduled(self):
        sched, nodes = self._slice_sched()
        managed = Pod("managed", labels={"scv/number": "1",
                                         "tpu/accelerator": "tpu"},
                      has_controller=True)
        sched.cluster.bind(managed, nodes[0].node, [(0, 0, 0)])
        plan = Descheduler(sched).plan()
        assert [p.key for p in plan.victims] == ["default/managed"]


class TestNominations:
    def test_nomination_released_when_node_stops_fitting(self):
        """A preemptor whose nominated node loses its chips must release the
        hold instead of blocking the node's capacity forever."""
        sched = mk_sched([make_tpu_node("n", chips=4)],
                         config=SchedulerConfig())  # max_attempts=0: never fails
        lo = Pod("lo", labels={"scv/number": "4", "scv/priority": "1"})
        sched.submit(lo)
        sched.run_until_idle()
        assert lo.phase == PodPhase.BOUND
        hi = Pod("hi", labels={"scv/number": "4", "scv/priority": "9"})
        sched.submit(hi)
        # one cycle: preempt + nominate
        sched.run_one()
        assert sched.allocator.nomination_of(hi.key) is not None
        # the node's telemetry goes stale before hi can bind
        sched.clock.advance(120.0)
        sched.run_one()  # hi's retry: nominated node infeasible
        assert sched.allocator.nomination_of(hi.key) is None

    def test_planning_respects_other_preemptors_holds(self):
        """Two preemptors must not be 'proven' to fit in the same hole."""
        from yoda_scheduler_tpu.scheduler.plugins.preempt import PriorityPreemption
        from yoda_scheduler_tpu.scheduler.framework import NodeInfo
        from yoda_scheduler_tpu.utils.labels import WorkloadSpec

        sched = mk_sched([make_tpu_node("n", chips=8)])
        v1 = Pod("v1", labels={"scv/number": "4", "scv/priority": "1"})
        v2 = Pod("v2", labels={"scv/number": "4", "scv/priority": "1"})
        sched.submit(v1)
        sched.submit(v2)
        sched.run_until_idle()
        assert v1.phase == PodPhase.BOUND and v2.phase == PodPhase.BOUND
        # p1 (prio 9, 4 chips) preempted v1 and holds a nomination
        sched.allocator.nominate("default/p1", "n", 4, 9)
        sched.cluster.evict(v1)
        # p2 (prio 9, 8 chips) plans: only v2's 4 chips are actually
        # evictable beyond p1's hold — 8 can never be freed for p2
        plugin = PriorityPreemption(sched.allocator)
        m = sched.cluster.telemetry.get("n")
        node = NodeInfo(name="n", metrics=m, pods=sched.cluster.pods_on("n"))
        plan = plugin._plan_eviction(
            WorkloadSpec(chips=8, priority=9), 9, node,
            pod_key="default/p2")
        assert plan is None  # pre-fix: would evict v2 for nothing


def test_from_manifest_parses_owner_references():
    controlled = Pod.from_manifest({
        "metadata": {"name": "a", "ownerReferences": [
            {"kind": "ReplicaSet", "name": "rs", "controller": True}]},
        "spec": {},
    })
    bare = Pod.from_manifest({"metadata": {"name": "b"}, "spec": {}})
    non_controller_ref = Pod.from_manifest({
        "metadata": {"name": "c", "ownerReferences": [
            {"kind": "ConfigMap", "name": "cm"}]},
        "spec": {},
    })
    assert controlled.has_controller
    assert not bare.has_controller
    assert not non_controller_ref.has_controller
