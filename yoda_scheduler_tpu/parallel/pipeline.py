"""Pipeline parallelism (`pp` mesh axis) for the Llama workload.

GPipe-style microbatch pipelining, TPU-native: the stacked per-layer params
(models/llama.py keeps every layer's weights on one leading axis for the
scan-over-layers forward) shard that leading axis over `pp`, so each pipeline
stage holds a contiguous block of layers. Activations circulate stage-to-stage
with `jax.lax.ppermute` — XLA lowers this onto neighbour ICI links (pp is the
outermost mesh axis, parallel/mesh.py:24, so stage boundaries are also where
DCN hops land on multi-host slices, the right place for the rarest transfers).

The pipeline is written with *partial-manual* shard_map: manual over `pp`
only, while tp/fsdp/dp stay auto — GSPMD keeps partitioning the per-stage
matmuls (Megatron tp splits, fsdp gathers) inside each pipeline step, so
pp composes with the rest of the 3-D parallelism without hand-written
collectives. The whole pipelined loss is differentiated by JAX as one
program: the backward pass is automatically the reverse pipeline (the
transpose of a `ppermute` shift is the opposite shift).

Schedule: plain GPipe — M microbatches through P stages in M + P - 1 ticks,
bubble fraction (P-1)/(M+P-1). Each tick every stage runs its layer block
(invalid ticks are masked; XLA executes them as the price of SPMD, which is
exactly the pipeline bubble).

The reference scheduler has no parallelism of its own (SURVEY §2.3) — this
is workload-side capability: the pjit programs whose gang/topology placement
the scheduler optimises (BASELINE scenario 4, multi-host v4-32).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..models.llama import LlamaConfig, init_llama, rms_norm, transformer_layer
from ..ops.attention import manual_region_attention
from .sharding import batch_spec, llama_param_specs


def _pvary(x, axis: str = "pp"):
    """Promote a device-invariant value to varying over `axis`."""
    try:
        return jax.lax.pcast(x, axis, to="varying")
    except (AttributeError, TypeError):  # pragma: no cover - older jax
        return jax.lax.pvary(x, (axis,))


def llama_pipeline_param_specs(config: LlamaConfig | None = None) -> dict:
    """llama_param_specs with the stacked-layer leading axis sharded over
    `pp` — each stage materialises only its own layer block."""
    specs = llama_param_specs(config)
    specs["layers"] = {
        name: P("pp", *spec[1:]) for name, spec in specs["layers"].items()
    }
    return specs


def llama_pipeline_shardings(mesh, config: LlamaConfig | None = None) -> dict:
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        llama_pipeline_param_specs(config),
        is_leaf=lambda x: isinstance(x, P),
    )


def _pipeline_apply(layers, x_mb, config: LlamaConfig, mesh, pp: int,
                    num_microbatches: int, attn_impl, remat: bool):
    """Run the pipelined layer stack. layers: per-layer stacked params with
    the leading axis sharded over pp; x_mb: [M, mb, S, d] microbatched
    activations, replicated over pp. Returns (y_mb [M, mb, S, d], aux)."""
    M = num_microbatches

    def stage_fn(layers_local, x):
        """One pipeline tick on this stage: scan its local layer block."""
        def layer_body(carry, layer):
            x, aux = carry
            y, a = transformer_layer(x, layer, config, attn_impl)
            return (y, aux + a), None
        if remat:
            layer_body = jax.checkpoint(layer_body)
        # the aux init must be varying over pp: the MoE load-balance aux is
        # computed from the (pp-varying) activations, and an invariant init
        # would make the scan carry types mismatch
        (y, aux), _ = jax.lax.scan(layer_body, (x, _pvary(jnp.float32(0))),
                                   layers_local)
        return y, aux

    act_dtype = x_mb.dtype

    def body(layers_local, x_mb):
        stage = jax.lax.axis_index("pp")
        shift = [(i, (i + 1) % pp) for i in range(pp)]
        # promote the microbatches to varying-over-pp once, while still f32
        # (see the caller's cast): the transpose of this pvary is a psum of
        # the activation cotangent, and a bf16 psum emitted inside the
        # region crashes XLA-CPU's AllReducePromotion pass (its reduction
        # body carries a sharding_constraint the pass cannot clone)
        x_mb = _pvary(x_mb).astype(act_dtype)
        # scan carries must enter with their steady-state varying-over-pp
        # type, so promote the zero inits explicitly
        state = _pvary(jnp.zeros(x_mb.shape[1:], x_mb.dtype))
        outputs = _pvary(jnp.zeros(x_mb.shape, x_mb.dtype))
        aux0 = _pvary(jnp.float32(0))

        def tick(carry, t):
            state, outputs, aux = carry
            # stage 0 injects microbatch t; everyone else consumes what the
            # previous stage sent last tick
            inject = jax.lax.dynamic_index_in_dim(x_mb, t % M, 0,
                                                  keepdims=False)
            inp = jnp.where(stage == 0, inject, state)
            out, a = stage_fn(layers_local, inp)
            # stage s holds microbatch t - s this tick; mask the bubble
            valid = jnp.logical_and(t - stage >= 0, t - stage < M)
            aux = aux + jnp.where(valid, a, 0.0)
            # the last stage retires microbatch t - (pp-1)
            oidx = jnp.clip(t - (pp - 1), 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(outputs, oidx, 0,
                                               keepdims=False)
            retire = jnp.logical_and(stage == pp - 1, t - (pp - 1) >= 0)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(retire, out, cur), oidx, 0)
            state = jax.lax.ppermute(out, "pp", shift)
            return (state, outputs, aux), None

        (_, outputs, aux), _ = jax.lax.scan(
            tick, (state, outputs, aux0), jnp.arange(M + pp - 1))
        # outputs are valid only on the last stage, aux only per-stage;
        # psum replicates both back over pp for the (auto-sharded) lm head
        outputs = jnp.where(stage == pp - 1, outputs, 0)
        # replicate the retired microbatches back over pp for the lm head.
        # f32 psum: XLA-CPU's AllReducePromotion pass crashes cloning a bf16
        # all-reduce, and on TPU the promotion pass would upcast it anyway
        outputs = jax.lax.psum(outputs.astype(jnp.float32), "pp")
        return outputs.astype(x_mb.dtype), jax.lax.psum(aux, "pp")

    return shard_map(
        body,
        mesh=mesh,
        axis_names={"pp"},
        in_specs=(jax.tree.map(lambda _: P("pp"), layers), P()),
        out_specs=(P(), P()),
    )(layers, x_mb.astype(jnp.float32))


def pipelined_llama_loss(params: dict, tokens: jax.Array,
                         config: LlamaConfig, mesh,
                         num_microbatches: int | None = None,
                         remat: bool = True) -> jax.Array:
    """Next-token cross-entropy with the layer stack pipelined over `pp`.

    Same math as models.llama.llama_loss (full-sequence CE with the final
    position masked); embed and lm head run outside the pipeline region,
    auto-sharded (they replicate over pp, shard over fsdp/tp as usual).
    """
    pp = mesh.shape["pp"]
    M = num_microbatches or max(2 * pp, 2)
    B, S = tokens.shape
    if config.n_layers % pp:
        raise ValueError(
            f"n_layers={config.n_layers} not divisible by pp={pp}")
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    if mesh.shape.get("sp", 1) > 1:
        raise ValueError("pipeline step runs with sp=1 (ring attention's own "
                         "shard_map does not nest inside the pp region)")
    if config.sliding_window is not None:
        # manual_region_attention attends globally: silently dropping the
        # window would make the pp path diverge from single-path training
        raise ValueError(
            "sliding_window is not supported on the pipeline path yet")
    attn_impl = manual_region_attention

    x = params["embed"][tokens]                     # [B, S, d]
    x_mb = x.reshape(M, B // M, S, x.shape[-1])
    # keep the batch shard on the microbatch-local axis, not the M axis
    x_mb = jax.lax.with_sharding_constraint(
        x_mb, NamedSharding(mesh, P(None, ("dp", "fsdp"), None, None)))
    y_mb, aux = _pipeline_apply(params["layers"], x_mb, config, mesh, pp, M,
                                attn_impl, remat)
    y = y_mb.reshape(B, S, -1)
    y = jax.lax.with_sharding_constraint(
        y, NamedSharding(mesh, P(("dp", "fsdp"), None, None)))

    y = rms_norm(y, params["final_norm"], config.norm_eps)
    logits = (y @ params["lm_head"]).astype(jnp.float32)
    targets = jnp.roll(tokens, -1, axis=1)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = (jnp.arange(S) < S - 1).astype(nll.dtype)[None, :]
    ce = jnp.sum(nll * mask) / (B * (S - 1))
    # aux accumulates once per (layer, microbatch) and moe_ffn's
    # load-balance statistic is batch-size independent, so normalise by M
    # as well as n_layers to match llama_loss's regularisation strength
    return ce + config.moe_aux_weight * (aux / (config.n_layers * M))


def build_pipelined_llama_train_step(config: LlamaConfig, mesh,
                                     num_microbatches: int | None = None,
                                     learning_rate: float = 3e-4,
                                     remat: bool = True):
    """Pipelined counterpart of train.build_llama_train_step: returns
    (init_fn, step_fn, batch_sharding) with params staged over pp."""
    from .train import _shard_opt_state_like

    param_sh = llama_pipeline_shardings(mesh, config)
    batch_sh = NamedSharding(mesh, batch_spec(sp=False))
    tx = optax.adamw(learning_rate)

    loss_fn = partial(pipelined_llama_loss, config=config, mesh=mesh,
                      num_microbatches=num_microbatches, remat=remat)

    def _init(key):
        params = init_llama(config, key)
        return params, tx.init(params)

    opt_sh = _shard_opt_state_like(tx, config, param_sh, mesh)
    init_fn = jax.jit(_init, out_shardings=(param_sh, opt_sh))

    def _step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step_fn = jax.jit(
        _step,
        in_shardings=(param_sh, opt_sh, batch_sh),
        out_shardings=(param_sh, opt_sh, None),
        donate_argnums=(0, 1),
    )
    return init_fn, step_fn, batch_sh
