"""First-party sniffer publisher (telemetry/publisher.py) against the live
fake API server — VERDICT r2 item 3: the previous publisher was untested
inline YAML whose PUT carried no resourceVersion, so a real API server
rejected every update after the first create and all nodes went
permanently stale.

Covers: create-on-404, update with resourceVersion carry-over, 409
conflict recovery (concurrent writer between GET and PUT), the
422-without-rv contract itself, and the full loop — publisher publishes ->
scheduler watch cache ingests -> pod binds over real HTTP.
"""

import threading
import time

import pytest

from yoda_scheduler_tpu.k8s.client import ApiError, KubeClient, METRICS_PATH
from yoda_scheduler_tpu.telemetry import TelemetryStore, make_tpu_node
from yoda_scheduler_tpu.telemetry.publisher import CrPublisher
from yoda_scheduler_tpu.scheduler import SchedulerConfig

from fake_apiserver import FakeApiServer


@pytest.fixture
def server():
    with FakeApiServer() as s:
        yield s


def wait_for(cond, timeout=10.0, step=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(step)
    return False


def test_publish_creates_then_updates_with_rv(server):
    client = KubeClient(server.url)
    pub = CrPublisher(client)
    m = make_tpu_node("n1", chips=4)
    pub.publish(m)  # 404 -> POST
    cr1 = server.state.objects["metrics"]["n1"]
    assert cr1["status"]["chips"][0]["hbm_free_mb"] == m.chips[0].hbm_free_mb

    # second publish must UPDATE (PUT with carried rv), not stall on 409/422
    m2 = make_tpu_node("n1", chips=4, hbm_free_mb=1234)
    pub.publish(m2)
    cr2 = server.state.objects["metrics"]["n1"]
    assert cr2["status"]["chips"][0]["hbm_free_mb"] == 1234
    assert cr2["metadata"]["resourceVersion"] != cr1["metadata"]["resourceVersion"]


def test_put_without_resourceversion_is_rejected(server):
    """The API contract the old inline publisher violated: a bare PUT
    (no resourceVersion) must NOT be accepted as an update."""
    client = KubeClient(server.url)
    CrPublisher(client).publish(make_tpu_node("n1", chips=4))
    bare = make_tpu_node("n1", chips=4, hbm_free_mb=42).to_cr()
    with pytest.raises(ApiError) as ei:
        client.request("PUT", f"{METRICS_PATH}/n1", bare)
    assert ei.value.status == 422
    # and the CR kept its old data (no silent stale refresh)
    cr = server.state.objects["metrics"]["n1"]
    assert cr["status"]["chips"][0]["hbm_free_mb"] != 42


def test_conflict_between_get_and_put_retries(server):
    client = KubeClient(server.url)
    pub = CrPublisher(client)
    pub.publish(make_tpu_node("n1", chips=4))
    # a concurrent writer will bump the rv after our GET: inject one 409
    # on the PUT — the publisher must re-GET and succeed
    server.state.fail("/tpunodemetrics/n1", 409, times=1, method="PUT")
    pub.publish(make_tpu_node("n1", chips=4, hbm_free_mb=777))
    cr = server.state.objects["metrics"]["n1"]
    assert cr["status"]["chips"][0]["hbm_free_mb"] == 777


def test_recreate_after_deletion_mid_conflict(server):
    """PUT 409 followed by the CR being DELETED before the re-GET: the POST
    retry must not carry the stale resourceVersion the earlier PUT attempt
    stamped (real API servers reject creates with an rv set)."""
    client = KubeClient(server.url)
    pub = CrPublisher(client)
    pub.publish(make_tpu_node("n1", chips=4))
    # conflict on PUT, then the object vanishes before the publisher re-GETs
    server.state.fail("/tpunodemetrics/n1", 409, times=1, method="PUT")
    real_fault = client.request

    deleted = {"done": False}

    def deleting_request(method, path, body=None, **kw):
        if (method == "GET" and path.endswith("/tpunodemetrics/n1")
                and deleted["done"] is False
                and any(f[2] == 0 for f in server.state.faults)):
            # the 409 has fired: now delete the CR so the re-GET 404s
            server.state.remove("metrics", "n1")
            deleted["done"] = True
        return real_fault(method, path, body, **kw)

    client.request = deleting_request
    pub.publish(make_tpu_node("n1", chips=4, hbm_free_mb=888))
    cr = server.state.objects["metrics"]["n1"]
    assert cr["status"]["chips"][0]["hbm_free_mb"] == 888


def test_persistent_conflicts_raise(server):
    client = KubeClient(server.url)
    pub = CrPublisher(client, max_conflict_retries=2)
    pub.publish(make_tpu_node("n1", chips=4))
    server.state.fail("/tpunodemetrics/n1", 409, times=50, method="PUT")
    with pytest.raises(ApiError) as ei:
        pub.publish(make_tpu_node("n1", chips=4))
    assert ei.value.status == 409


def test_lost_create_race_recovers(server):
    """POST hits 409 (another publisher created first): re-GET and update."""
    client = KubeClient(server.url)
    pub = CrPublisher(client)
    calls = {"n": 0}
    orig_request = client.request

    def racing_request(method, path, body=None, **kw):
        if method == "GET" and path.endswith("/tpunodemetrics/n1"):
            calls["n"] += 1
            if calls["n"] == 1:
                # someone else creates between our GET(404) and POST
                def create_now():
                    server.state.put_metrics(
                        make_tpu_node("n1", chips=4).to_cr())
                result_is_404 = "n1" not in server.state.objects["metrics"]
                if result_is_404:
                    try:
                        return orig_request(method, path, body, **kw)
                    finally:
                        create_now()
        return orig_request(method, path, body, **kw)

    client.request = racing_request
    pub.publish(make_tpu_node("n1", chips=4, hbm_free_mb=555))
    cr = server.state.objects["metrics"]["n1"]
    assert cr["status"]["chips"][0]["hbm_free_mb"] == 555


def test_publisher_feeds_scheduler_end_to_end(server):
    """The full real-cluster telemetry loop over live HTTP: the publisher
    writes the CR -> the scheduler's watch cache ingests it -> a pending
    pod binds. Without the publisher the serve loop has NO telemetry
    source at all (VERDICT r2 missing #2)."""
    from yoda_scheduler_tpu.k8s.client import run_scheduler_against_cluster

    server.state.add_node("n1")
    server.state.add_pod({
        "metadata": {"name": "p1", "namespace": "default",
                     "labels": {"scv/number": "2"},
                     "ownerReferences": [{"kind": "ReplicaSet", "name": "rs",
                                          "controller": True}]},
        "spec": {"schedulerName": "yoda-scheduler"},
        "status": {"phase": "Pending"},
    })
    client = KubeClient(server.url)
    stop = threading.Event()
    t = threading.Thread(
        target=run_scheduler_against_cluster,
        args=(client, [(SchedulerConfig(pod_initial_backoff_s=0.05,
                                        pod_max_backoff_s=0.2), None)]),
        kwargs={"metrics_port": None, "poll_s": 0.05, "stop_event": stop},
        daemon=True)
    t.start()
    try:
        # no telemetry yet: the pod must NOT bind
        time.sleep(0.4)
        assert not (server.state.pod("p1") or {}).get(
            "spec", {}).get("nodeName")
        # the sniffer publisher comes up (separate client, as in the
        # DaemonSet) and publishes twice — create, then rv-carried update
        pub_client = KubeClient(server.url)
        pub = CrPublisher(pub_client)
        pub.publish(make_tpu_node("n1", chips=4))
        pub.publish(make_tpu_node("n1", chips=4))
        assert wait_for(lambda: (server.state.pod("p1") or {}).get(
            "spec", {}).get("nodeName") == "n1"), \
            "pod never bound after telemetry publication"
    finally:
        stop.set()
        t.join(timeout=5.0)
