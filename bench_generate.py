"""Inference bench on the real TPU chip: Llama prefill latency + KV-cache
decode throughput (BASELINE #4's serving scenario — bench_mfu.py covers
training, this covers generation: models/generate.py).

Measures, for the same ~950M Llama shape bench_mfu.py trains, at
B in {1, 8} with a 2048-token prompt and 512 generated tokens:

- prefill wall ms (prompt -> seeded KV cache, one full forward)
- steady-state decode tokens/s/chip: an EAGER loop over a donated jitted
  decode step — one dispatch per token, with the emitted token computed
  inside the jit (only device handles cycle through Python; no per-token
  host readback). Timed as the slope between a short and a long step
  segment (sized from `new_tokens`: warm=max(2,N/32), n1=max(4,N/10),
  n2=the rest) so pipeline-fill and sync overhead cancel; the median of
  3 independently re-seeded slopes is reported. NOT a lax.scan:
  compiling any while-loop
  whose body writes the KV cache (dynamic_update_slice) wedges this
  environment's TPU-tunnel compiler indefinitely — bisected in
  tools/debug_generate_hang.py / debug_generate_hang2.py (trivial-body
  scans, prefill, and a lone decode step all compile; scan-of-decode
  hangs even at length 4, layer loop scanned or unrolled, cache large
  or small, and hangs in lower()/compile, not execution)
- the same pair under Mistral-style sliding-window attention
  (window=1024): the cache stays full-size, but attention reads mask to
  the window

vs_baseline: cached decode against NO-KV-cache generation (re-running the
full prefix forward per token) — the optimization a naive port would
ship without; the reference publishes no numbers of its own (BASELINE.md).

Sanity guard: decode at small batch is weights-bandwidth-bound; a sample
whose implied HBM read rate (param bytes x steps/s) exceeds the chip's
spec bandwidth is re-measured and then nulled, never committed
(bench_mfu.py's above-peak rule, bandwidth edition).

Run WITHOUT JAX_PLATFORMS=cpu for real numbers; on a CPU host it falls
back to a tiny shape so the harness completes. Output: ONE JSON line.
"""

from __future__ import annotations

import json
import time
from functools import partial

from bench_util import (
    detect_tpu,
    honor_cpu_platform,
    make_budget,
    make_checkpoint,
    make_progress,
    make_sync,
    probe_devices,
    start_watchdog,
)

_progress = make_progress("bench_generate")
BUDGET_S, _remaining = make_budget("BENCH_GEN_BUDGET_S", 480)

_progress("importing jax")
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

honor_cpu_platform(jax)
_sync = make_sync(jax, jnp)
_progress("jax imported")

# spec-sheet HBM bandwidth per chip, GB/s (the decode sanity ceiling):
# cloud.google.com/tpu/docs/system-architecture-tpu-vm
HBM_GBPS = {
    "v6": 1640.0,       # v6e (Trillium)
    "v5p": 2765.0,
    "v5 lite": 819.0,   # v5e
    "v5e": 819.0,
    "v4": 1228.0,
    "v3": 900.0,
    "v2": 700.0,
}


def hbm_gbps(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for sub, bw in HBM_GBPS.items():
        if sub in kind:
            return bw
    return None


def _median_time(fn, reps: int = 3) -> float:
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        _sync(fn())
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def _serving_config(on_tpu: bool):
    from yoda_scheduler_tpu.models.llama import LlamaConfig

    if on_tpu:
        # the shape bench_mfu.py trains (so the two artifacts describe one
        # model), ~950M params
        return LlamaConfig(vocab_size=32000, dim=2048, n_layers=16,
                           n_heads=16, n_kv_heads=16, ffn_dim=5632,
                           max_seq_len=4096)
    return LlamaConfig.tiny()


def _bench_one(params, config, batch: int, prompt_len: int, new_tokens: int,
               window: int | None, bw_peak_gbps: float | None,
               param_bytes: int) -> dict:
    """One (batch, window) cell: prefill ms + steady-state decode tok/s.
    Windowed cells decode against the ROLLING ring cache (O(window) HBM,
    models/generate.py RollingKVCache) — the capability the window
    exists for; full-attention cells use the prompt+new-sized cache."""
    from dataclasses import replace

    from yoda_scheduler_tpu.models.generate import (
        KVCache, RollingKVCache, decode_step, decode_step_rolling, prefill)

    cfg = replace(config, sliding_window=window)
    rolling = window is not None and window < prompt_len + new_tokens
    max_len = prompt_len + new_tokens
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len),
                                0, cfg.vocab_size, jnp.int32)

    prefill_j = jax.jit(lambda p, t, c: prefill(p, t, c, cfg))
    # rolling: the prefill cache is prompt-sized and temporary; the ring
    # it folds into is window-sized
    cache0 = KVCache.zeros(cfg, batch, prompt_len if rolling else max_len)
    logits, cache = prefill_j(params, prompt, cache0)  # compile
    _sync(logits)
    _progress(f"B={batch} window={window}: prefill compiled")
    t_prefill = _median_time(lambda: prefill_j(params, prompt, cache0)[0])
    del logits, cache  # measure_decode re-seeds; these are never stepped

    # steady state from the seeded cache, driven eagerly (see module
    # docstring: scan-of-decode wedges this backend's compiler). The
    # emitted token is computed INSIDE the step jit so the loop is one
    # dispatch per token; logits and cache are donated so each step
    # updates its buffers in place instead of copying the cache.
    step_fn = decode_step_rolling if rolling else decode_step

    @partial(jax.jit, donate_argnums=(1, 2))
    def fused_step(params, logits, cache):
        tok = jnp.argmax(logits, axis=-1)
        return step_fn(params, tok, cache, cfg)

    def run_steps(n, logits, cache):
        t0 = time.perf_counter()
        for _ in range(n):
            logits, cache = fused_step(params, logits, cache)
        _sync(logits)
        return time.perf_counter() - t0, logits, cache

    # warm the dispatch path, then slope between two segment lengths —
    # t(N) = fixed + N*per_step, so (t2-t1)/(n2-n1) cancels the fixed
    # pipeline-fill + final-sync cost. The segments (plus the one compile
    # step) stay inside the `new_tokens` budget the full cache was sized
    # for; the ring cache has no budget but uses the same plan.
    warm = max(2, new_tokens // 32)
    n1 = max(4, new_tokens // 10)
    n2 = new_tokens - warm - n1 - 1
    n = n2 - n1
    # the slope needs a strictly longer second segment; with the
    # clamped minimums above that requires new_tokens >= 14
    assert n > 0, f"new_tokens={new_tokens} too small for slope plan"

    fold_j = jax.jit(RollingKVCache.from_prefill, static_argnums=1)

    def measure_decode():
        """Seed a fresh cache via prefill, then time the eager slope.
        Re-seeding matters: fused_step donates, so a measurement consumes
        its logits/cache and a re-measure cannot reuse them."""
        logits, cache = prefill_j(params, prompt, cache0)
        if rolling:
            cache = fold_j(cache, window)
        logits, cache = fused_step(params, logits, cache)  # compile
        _sync(logits)
        _, logits, cache = run_steps(warm, logits, cache)
        t1, logits, cache = run_steps(n1, logits, cache)
        t2, logits, cache = run_steps(n2, logits, cache)
        return max(t2 - t1, 1e-9)

    # median of 3 independent slopes: one transient stall (tunnel
    # hiccup, host GC) in a single 400-step segment would otherwise
    # silently understate tokens/s — and the checkpoint would then
    # pin the bad number across retries
    t_decode = sorted(measure_decode() for _ in range(3))[1]
    _progress(f"B={batch} window={window}: decode timed")
    tok_s = batch * n / t_decode

    # bandwidth sanity: each decode step must stream the weights once
    # (batch amortises, so the ceiling only binds meaningfully at B=1,
    # where weight reads dominate)
    implied_gbps = (param_bytes * (n / t_decode)) / 1e9
    suspect = (bw_peak_gbps is not None and batch == 1
               and implied_gbps > 1.2 * bw_peak_gbps)
    if suspect:
        _progress(f"B={batch}: {tok_s:.0f} tok/s implies "
                  f"{implied_gbps:.0f} GB/s > spec {bw_peak_gbps:.0f}; "
                  "re-measuring")
        t_decode = sorted(measure_decode() for _ in range(3))[1]
        tok_s = batch * n / t_decode
        implied_gbps = (param_bytes * (n / t_decode)) / 1e9
        if implied_gbps > 1.2 * bw_peak_gbps:
            return {"batch": batch, "window": window,
                    "prefill_ms": round(t_prefill * 1e3, 1),
                    "decode_tokens_per_sec": None,
                    "error": "implied HBM rate above spec; sample nulled"}
    return {
        "batch": batch,
        "window": window,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "steps_timed": n,
        "prefill_ms": round(t_prefill * 1e3, 1),
        "decode_step_ms": round(t_decode * 1e3 / n, 3),
        "decode_tokens_per_sec": round(tok_s, 1),
        "implied_weights_gbps_lower_bound": round(
            param_bytes * (n / t_decode) / 1e9, 1),
    }


def _no_cache_baseline(params, config, batch: int, prompt_len: int) -> dict:
    """Tokens/s of generation WITHOUT a KV cache: the full prefix forward
    re-runs per token (what a naive port ships). Driven eagerly — one
    jitted full-forward step per token, like the cached cells (the
    scan-wedge precaution, module docstring) — and timed as the slope
    between generating 2 and 4 tokens so fixed overhead cancels."""
    from yoda_scheduler_tpu.models.llama import llama_forward

    prompt = jax.random.randint(jax.random.PRNGKey(2), (batch, prompt_len),
                                0, config.vocab_size, jnp.int32)

    @partial(jax.jit, donate_argnums=(0,))
    def nc_step(toks):
        logits = llama_forward(params, toks, config)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        return jnp.concatenate([toks[:, 1:], nxt[:, None]], axis=1)

    def gen_time(n):
        toks = prompt + 0  # fresh donatable buffer per measurement
        t0 = time.perf_counter()
        for _ in range(n):
            toks = nc_step(toks)
        _sync(toks)
        return time.perf_counter() - t0

    gen_time(1)  # compile
    t2 = sorted(gen_time(2) for _ in range(3))[1]
    t4 = sorted(gen_time(4) for _ in range(3))[1]
    per_tok = max(t4 - t2, 1e-9) / 2
    return {"batch": batch, "prompt_len": prompt_len,
            "tokens_per_sec": round(batch / per_tok, 2),
            "per_token_ms": round(per_tok * 1e3, 1)}


def main() -> None:
    watchdog = start_watchdog("llama_decode_tokens_per_sec", "tok/s",
                              BUDGET_S)
    devices = probe_devices(jax, "llama_decode_tokens_per_sec", "tok/s",
                            _progress)
    on_tpu = detect_tpu(devices)
    _progress(f"backend={jax.default_backend()} on_tpu={on_tpu}")

    from yoda_scheduler_tpu.models.llama import init_llama

    config = _serving_config(on_tpu)
    params = init_llama(config, jax.random.PRNGKey(0))
    param_bytes = sum(x.size * x.dtype.itemsize
                      for x in jax.tree.leaves(params))
    n_params = sum(x.size for x in jax.tree.leaves(params))
    _progress(f"params: {n_params / 1e6:.0f}M ({param_bytes / 1e9:.2f} GB)")

    prompt_len, new_tokens = (2048, 512) if on_tpu else (64, 16)
    window = 1024 if on_tpu else 32
    bw = hbm_gbps(devices[0].device_kind) if on_tpu else None
    ckpt = make_checkpoint("BENCH_GEN_CKPT", "BENCH_GENERATE.ckpt.json",
                           _progress)
    ckpt.bind_context(device_kind=devices[0].device_kind, on_tpu=on_tpu,
                      n_params=n_params, prompt_len=prompt_len,
                      new_tokens=new_tokens)

    cells = []
    for b in (1, 8):
        for w in (None, window):
            saved = ckpt.get(f"cell.b{b}.w{w}")
            if saved is not None:
                _progress(f"cell B={b} window={w}: reusing checkpointed "
                          "section")
                cells.append(saved)
                continue
            if cells and _remaining() < 90:
                cells.append({"batch": b, "window": w,
                              "skipped": "budget"})
                continue
            try:
                cell = _bench_one(params, config, b, prompt_len,
                                  new_tokens, w, bw, param_bytes)
                cells.append(cell)
                if "error" not in cell:  # errors re-measure on retry
                    ckpt.put(f"cell.b{b}.w{w}", cell)
            except Exception as e:
                cells.append({"batch": b, "window": w,
                              "error": f"{type(e).__name__}: {str(e)[:160]}"})

    baseline = ckpt.get("baseline")
    if baseline is not None:
        _progress("no-cache baseline: reusing checkpointed section")
    elif _remaining() > 60:
        try:
            # batch MUST match the headline cell (B=8) — vs_baseline is a
            # cache-vs-no-cache comparison, not a batch comparison
            baseline = _no_cache_baseline(params, config, 8, prompt_len)
            ckpt.put("baseline", baseline)
        except Exception as e:
            baseline = {"error": f"{type(e).__name__}: {str(e)[:160]}"}

    headline = next(
        (c for c in cells
         if c.get("batch") == 8 and c.get("window") is None
         and c.get("decode_tokens_per_sec")), None)
    vs = None
    if (headline and baseline
            and isinstance(baseline.get("tokens_per_sec"), (int, float))
            and baseline["tokens_per_sec"] > 0):
        vs = round(headline["decode_tokens_per_sec"]
                   / baseline["tokens_per_sec"], 2)
    watchdog.cancel()
    if (not any("error" in c for c in cells)
            and not (isinstance(baseline, dict) and "error" in baseline)):
        ckpt.clear()  # clean run: the artifact now owns the numbers
    print(json.dumps({
        "metric": "llama_decode_tokens_per_sec",
        "value": headline["decode_tokens_per_sec"] if headline else None,
        "unit": "tok/s",
        # vs_baseline: KV-cache decode against no-cache generation at the
        # same batch (the reference publishes nothing — BASELINE.md)
        "vs_baseline": vs,
        "backend": jax.default_backend(),
        "model_params": n_params,
        "device_kind": devices[0].device_kind,
        "cells": cells,
        "no_cache_baseline": baseline,
    }))


if __name__ == "__main__":
    main()
