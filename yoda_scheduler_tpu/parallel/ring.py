"""Ring attention: causal attention over a sequence sharded on the `sp` axis.

Long-context sequence/context parallelism for the transformer workloads:
each device of the `sp` mesh axis holds a contiguous sequence chunk of
Q/K/V. K/V chunks rotate around the ring with `jax.lax.ppermute` (XLA maps
this onto neighbour ICI links) while each device merges every chunk into
its local queries' attention state — full causal attention with O(S/n)
activation memory per device, overlap-friendly, never materialising the
global [S, S] score matrix.

Per-chunk compute routes through the fused Pallas flash kernel on TPU
(`ops.attention.flash_attention_with_lse` — the LSE output is exactly the
statistic that makes partial attentions mergeable), with the plain-XLA
reference used on interpret-mode backends (shard_map's varying-manual-axes
checker rejects interpret-mode pallas calls there). Causality is exploited
structurally: a rotation whose source chunk lies entirely in the local
queries' future contributes nothing and is skipped (`lax.switch` — the
compute halves versus attending every chunk; the ppermute still runs, the
ring must keep turning).

Written with shard_map + collectives (not raw RDMA) so the identical code
runs on a CPU test mesh and a TPU pod slice.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..ops import attention as _attn

_NEG_INF = -1e30


def _chunk_attention(q, k, v, causal: bool):
    """One q-chunk vs one k/v-chunk: (out, lse) in fp32. Kernel on compiled
    backends; XLA reference under interpret mode (see module docstring).
    The dispatch reads through the attention module (not a by-value import)
    so the TPU-lowering tests' monkeypatch of `_use_interpret` governs this
    path too."""
    if _attn._use_interpret():
        out, lse = _attn.reference_attention_with_lse(q, k, v, causal=causal)
    else:
        out, lse = _attn.flash_attention_with_lse(q, k, v, causal=causal)
    return out.astype(jnp.float32), lse.astype(jnp.float32)[..., None]


def _ring_body(q, k, v, axis_name: str, axis_size: int):
    """Per-shard body under shard_map. q,k,v: [B, H, S/n, D] local chunks.

    Merge state over normalized per-chunk partials (out_i, lse_i):
    the exact combination is out = Σ_i softmax_i(lse_i)·out_i, maintained
    online as (acc, r, m) with m the running max lse —
    acc = Σ out_i·exp(lse_i - m), r = Σ exp(lse_i - m)."""
    rank = jax.lax.axis_index(axis_name)
    qf = q.astype(jnp.float32)
    # derive carries from qf so they inherit q's varying-manual-axes type —
    # literals would be device-invariant and fail the loop carry type check
    m = qf[..., :1] * 0.0 + _NEG_INF
    r = qf[..., :1] * 0.0
    acc = qf * 0.0

    def skip(q, k, v):
        # source chunk entirely in the future: contributes nothing. lse of
        # -inf makes the merge a no-op (beta = exp(-inf - m) = 0).
        z = q.astype(jnp.float32)
        return z * 0.0, z[..., :1] * 0.0 + _NEG_INF

    def full(q, k, v):
        # source chunk entirely in the past: every (q, k) pair is live
        return _chunk_attention(q, k, v, causal=False)

    def diag(q, k, v):
        # the local chunk itself: standard causal attention
        return _chunk_attention(q, k, v, causal=True)

    def step(i, carry):
        m, r, acc, k, v = carry
        # after i rotations we hold the chunk originally on rank - i
        src = (rank - i) % axis_size
        case = jnp.where(src == rank, 2, jnp.where(src < rank, 1, 0))
        out_i, lse_i = jax.lax.switch(case, (skip, full, diag), q, k, v)
        m_new = jnp.maximum(m, lse_i)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(lse_i - m_new)
        acc_new = acc * alpha + out_i * beta
        r_new = r * alpha + beta
        # rotate kv to the next rank (the final rotated copy is unused;
        # rotating every step keeps the loop body uniform)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        return m_new, r_new, acc_new, k, v

    m, r, acc, _, _ = jax.lax.fori_loop(0, axis_size, step, (m, r, acc, k, v))
    return (acc / jnp.maximum(r, 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name: str = "sp"):
    """Causal attention with q,k,v [B, H, S, D], S sharded over `axis_name`.

    Call under jit with the global arrays; shard_map splits them per the
    specs and the ring runs over the mesh axis.
    """
    axis_size = mesh.shape[axis_name]
    seq = q.shape[2]
    if seq % axis_size:
        raise ValueError(f"seq {seq} not divisible by {axis_name}={axis_size}")
    # GQA: grouped KV flows through untouched when its head axis still
    # splits over 'tp'; otherwise broadcast to full heads first (the
    # pre-GQA behavior) so tp configs that worked before keep working
    h, kvh = q.shape[1], k.shape[1]
    if kvh != h and kvh % mesh.shape.get("tp", 1):
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    spec = P(("dp", "fsdp"), "tp", axis_name, None)
    body = functools.partial(_ring_body, axis_name=axis_name,
                             axis_size=axis_size)
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)


ring_attention.handles_gqa = True  # per-chunk compute is GQA-aware


def make_ring_attn(mesh, axis_name: str = "sp"):
    """attn_impl adapter for models.llama.llama_forward."""
    def attn(q, k, v):
        return ring_attention(q, k, v, mesh, axis_name)
    attn.handles_gqa = True
    return attn
