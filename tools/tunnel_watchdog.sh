#!/bin/bash
# Round-long TPU tunnel watchdog (VERDICT r4 next-round #2).
#
# The chip came back at unknown times in rounds 3-4 and the perf refresh
# never ran. This loop probes the tunnel with a hard timeout every
# PROBE_INTERVAL_S (default 1500s = 25min), logs every attempt to
# tools/tunnel_watchdog.log, and on FIRST success runs
# tools/chip_session.sh (which refreshes BENCH_MFU.json +
# BENCH_GENERATE.json or fails loudly without touching them).
#
# Usage:  nohup tools/tunnel_watchdog.sh &      # run for the whole round
# The log is committed at end of round either way: it is the proof that
# the tunnel either opened (and the session ran) or never did.
set -uo pipefail
cd "$(dirname "$0")/.."
LOG=tools/tunnel_watchdog.log
INTERVAL="${PROBE_INTERVAL_S:-1500}"
PROBE_TIMEOUT="${PROBE_TIMEOUT_S:-90}"

log() { echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) $*" >> "$LOG"; }

log "watchdog start (interval=${INTERVAL}s probe_timeout=${PROBE_TIMEOUT}s)"
attempt=0
while true; do
    attempt=$((attempt + 1))
    if timeout "$PROBE_TIMEOUT" python -c "
import jax
from bench_util import detect_tpu
ds = jax.devices()
assert detect_tpu(ds), f'devices are not TPU: {ds}'
print(ds)
" >> "$LOG" 2>&1; then
        log "attempt $attempt: TPU REACHABLE - running chip_session.sh"
        if bash tools/chip_session.sh >> "$LOG" 2>&1; then
            log "chip_session.sh SUCCEEDED - artifacts refreshed"
            exit 0
        else
            log "chip_session.sh FAILED (rc=$?) - will retry next probe"
        fi
    else
        log "attempt $attempt: tunnel down (probe rc=$? - timeout or no TPU)"
    fi
    sleep "$INTERVAL"
done
