"""ICI torus topology model: slice shapes, sub-block enumeration, bin-packing.

This is new TPU-native capability with no counterpart in the GPU reference
(its cards are an unordered list, reference pkg/yoda/filter/filter.go:22).
TPU chips within a pod slice form an ICI torus (v4: 3-D, e.g. a v4-32 slice
is 2x2x4 chips over 4 hosts); XLA collectives ride ICI only between chips
that are torus neighbours, so placement quality = does a job get an
*axis-aligned contiguous sub-block* of the torus, and does packing leave the
remaining free chips in large contiguous blocks for future jobs.

Pure functions over coordinate sets — trivially unit-testable, no k8s types.
"""

from __future__ import annotations

from functools import lru_cache
from itertools import permutations, product

Coord = tuple[int, int, int]
Shape = tuple[int, int, int]


def parse_topology(spec: str) -> Shape:
    """'2x2x4' -> (2, 2, 4); '2x2' -> (2, 2, 1); '4' -> (4, 1, 1)."""
    parts = [p.strip() for p in spec.lower().split("x") if p.strip()]
    if not parts or len(parts) > 3:
        raise ValueError(f"bad topology spec: {spec!r}")
    dims = [int(p) for p in parts]
    if any(d <= 0 for d in dims):
        raise ValueError(f"bad topology spec: {spec!r}")
    while len(dims) < 3:
        dims.append(1)
    return tuple(dims)  # type: ignore[return-value]


def format_topology(shape: Shape) -> str:
    return "x".join(str(d) for d in shape)


def chips_in(shape: Shape) -> int:
    x, y, z = shape
    return x * y * z


def all_coords(shape: Shape) -> list[Coord]:
    return list(product(range(shape[0]), range(shape[1]), range(shape[2])))


def host_blocks(slice_shape: Shape, host_shape: Shape = (2, 2, 1)) -> list[list[Coord]]:
    """Partition a slice torus into per-host chip blocks (v4 boards hold a
    2x2x1 block of 4 chips). Host order follows z-major placement, matching
    how Cloud TPU assigns workers along the slice."""
    hx, hy, hz = host_shape
    sx, sy, sz = slice_shape
    if sx % hx or sy % hy or sz % hz:
        raise ValueError(f"slice {slice_shape} not divisible by host block {host_shape}")
    blocks: list[list[Coord]] = []
    for bz in range(sz // hz):
        for by in range(sy // hy):
            for bx in range(sx // hx):
                blocks.append(
                    [
                        (bx * hx + dx, by * hy + dy, bz * hz + dz)
                        for dz in range(hz)
                        for dy in range(hy)
                        for dx in range(hx)
                    ]
                )
    return blocks


@lru_cache(maxsize=None)
def _factor_shapes(n: int) -> tuple[Shape, ...]:
    """All (x, y, z) with x*y*z == n — candidate block shapes for n chips."""
    out = []
    for x in range(1, n + 1):
        if n % x:
            continue
        rem = n // x
        for y in range(1, rem + 1):
            if rem % y:
                continue
            out.append((x, y, rem // y))
    return tuple(out)


@lru_cache(maxsize=8192)
def enumerate_subblocks(shape: Shape, n_chips: int) -> tuple[tuple[Coord, Shape], ...]:
    """All axis-aligned sub-blocks of exactly `n_chips` chips inside `shape`,
    as (origin, block_shape) pairs. Small closed world (slices are tiny:
    <=4096 chips, jobs request small factors), so brute force is fine and
    exact — no heuristics to go wrong. Cached: the scheduler asks for the
    same (shape, n) thousands of times per burst."""
    out: list[tuple[Coord, Shape]] = []
    sx, sy, sz = shape
    for bx, by, bz in _factor_shapes(n_chips):
        if bx > sx or by > sy or bz > sz:
            continue
        for ox in range(sx - bx + 1):
            for oy in range(sy - by + 1):
                for oz in range(sz - bz + 1):
                    out.append(((ox, oy, oz), (bx, by, bz)))
    return tuple(out)


@lru_cache(maxsize=65536)
def _block_coords(origin: Coord, block: Shape) -> frozenset[Coord]:
    ox, oy, oz = origin
    bx, by, bz = block
    return frozenset(
        (ox + dx, oy + dy, oz + dz)
        for dx in range(bx)
        for dy in range(by)
        for dz in range(bz)
    )


def _compactness(block: Shape) -> int:
    """Prefer cube-ish blocks — lower is better. For fixed volume, the sum of
    dimensions is minimised by the most cube-like factorization, which has the
    shortest ICI diameter (a 2x2x2 beats an 8x1x1 for the same 8 chips)."""
    bx, by, bz = block
    return bx + by + bz


def _best_placement(
    slice_shape: Shape,
    free: frozenset[Coord],
    candidate_shapes: tuple[Shape, ...],
) -> tuple[Coord, Shape, frozenset[Coord]] | None:
    """Shared placement search (pure, uncached — the public entry points
    below carry the cache): try every candidate block shape at every origin;
    keep the placement that (1) minimises leftover fragmentation, (2)
    prefers compact shapes (short ICI diameter), (3) carves from the low
    corner. Returns (origin, block_shape, coords) or None."""
    sx, sy, sz = slice_shape
    best: tuple[tuple, Coord, Shape, set[Coord]] | None = None
    for block in candidate_shapes:
        bx, by, bz = block
        if bx > sx or by > sy or bz > sz:
            continue
        for ox in range(sx - bx + 1):
            for oy in range(sy - by + 1):
                for oz in range(sz - bz + 1):
                    coords = _block_coords((ox, oy, oz), block)
                    if not coords <= free:
                        continue
                    frag = fragmentation_after(slice_shape, frozenset(free - coords))
                    key = (frag, _compactness(block), oz, oy, ox)
                    if best is None or key < best[0]:
                        best = (key, (ox, oy, oz), block, coords)
    if best is None:
        return None
    return best[1], best[2], best[3]


@lru_cache(maxsize=131072)
def _best_fit_cached(slice_shape: Shape, free: frozenset[Coord], n_chips: int):
    if _native_on():
        from . import native

        out = native.best_fit_block(slice_shape, free, n_chips)
        if out is not NotImplemented:
            return out
    return _best_placement(slice_shape, free, _factor_shapes(n_chips))


def best_fit_block(
    slice_shape: Shape,
    free: set[Coord],
    n_chips: int,
) -> tuple[Coord, Shape, frozenset[Coord]] | None:
    """Best contiguous block of exactly `n_chips` free chips, any shape
    whose volume is n_chips. Uses the native engine when built
    (native/placement.cc), pure Python otherwise — identical results."""
    return _best_fit_cached(slice_shape, frozenset(free), n_chips)


@lru_cache(maxsize=131072)
def _fits_shape_cached(slice_shape: Shape, free: frozenset[Coord], req_shape: Shape):
    if _native_on():
        from . import native

        out = native.fits_shape(slice_shape, free, req_shape)
        if out is not NotImplemented:
            return out
    return _best_placement(slice_shape, free,
                           tuple(sorted(set(permutations(req_shape)))))


def fits_shape(slice_shape: Shape, free: set[Coord], req_shape: Shape) -> tuple[Coord, Shape, frozenset[Coord]] | None:
    """Place an exact requested block shape (any axis permutation) into free
    space. Used for the ``tpu/topology`` label."""
    return _fits_shape_cached(slice_shape, frozenset(free), req_shape)


@lru_cache(maxsize=1)
def _native_on() -> bool:
    try:
        from . import native

        return native.available()
    except Exception:
        return False


def largest_free_block(shape: Shape, free: set[Coord]) -> int:
    """Size of the largest axis-aligned sub-block fully inside `free`."""
    return _largest_free_block(shape, frozenset(free))


@lru_cache(maxsize=131072)
def _largest_free_block(shape: Shape, free: frozenset[Coord]) -> int:
    if _native_on():
        from . import native

        out = native.largest_free_block(shape, free)
        if out is not NotImplemented:
            return out
    if not free:
        return 0
    best = 1
    max_n = len(free)
    # check decreasing sizes; early-out at first found
    for n in range(max_n, 0, -1):
        if n <= best:
            break
        for origin, block in enumerate_subblocks(shape, n):
            if _block_coords(origin, block) <= free:
                best = n
                break
    return best


def fragmentation_after(shape: Shape, free: set[Coord]) -> float:
    """0 = perfectly contiguous free space, 1 = fully fragmented.
    Defined as 1 - largest_free_block / |free| (0 when nothing free)."""
    if not free:
        return 0.0
    return 1.0 - largest_free_block(shape, frozenset(free)) / len(free)


@lru_cache(maxsize=131072)
def _contiguity_cached(shape: Shape, free: frozenset[Coord], n_chips: int) -> float:
    fit = _best_fit_cached(shape, free, n_chips)
    if fit is None:
        return 0.0
    _, _, coords = fit
    frag = fragmentation_after(shape, free - coords)
    return 100.0 * (1.0 - frag)


def contiguity_score(shape: Shape, free: set[Coord], n_chips: int) -> float:
    """How well can a `n_chips` job land contiguously in `free`? 0..100.

    100: an exact-fit contiguous block exists and taking the best one leaves
    zero extra fragmentation. Decreases with induced fragmentation; 0 when no
    contiguous block exists (job would span non-adjacent chips — XLA
    collectives would hop through occupied chips' links).
    """
    return _contiguity_cached(shape, frozenset(free), n_chips)
