"""Heterogeneity model: workload-class throughput ratios across
accelerator generations, and the score plugin they drive.

Gavel's core observation (PAPERS.md, arXiv:2008.09213) is that the
throughput RATIO between accelerator generations is workload-dependent —
a memory-bound embedding job gains little from a faster MXU while a
compute-bound transformer gains a lot — so "a chip is a chip" scoring
leaves throughput on the table exactly in the mixed fleets this
scheduler targets. The model here is Gavel's throughput matrix reduced
to placement time:

    ratio(workload class, generation) -> relative throughput

with three sources, in precedence order: per-class operator overrides
(`workloadClasses` config), the generation catalog's compute proxy
(clock x MXU count, normalised to v4 = 1.0), and 1.0 for anything
unknown (no data never steers a ranking — the same rule the duty-cycle
scorer follows).

The OBJECTIVE (config `policyObjective`) shapes how ratios become score
weights, Gavel/Tesserae's pluggable-policy idea at single-placement
granularity:

- ``makespan``: score by normalised throughput r/r_best — every job
  leans toward its fastest generation, maximising aggregate throughput.
- ``avg-jct``: the same affinity, additionally boosted for SMALL jobs
  (x (1 + 1/chips)): when a fast chip is contended, the shortest
  queue-clearing job wins it — the placement-time shadow of
  shortest-job-first, which minimises average JCT.
- ``finish-time-fairness``: the affinity scaled by the tenant's DRF
  deficit (x (1 + (fair - share)/fair when below fair share)): tenants
  running behind their entitlement get first claim on the fast
  generations, pulling their finish times back toward the fair rate.
"""

from __future__ import annotations

from ..framework import CycleState, NodeInfo, NO_BATCH, ScorePlugin, Status
from ..columnar import HAVE_NUMPY, np
from ...topology.generations import GENERATIONS
from ...utils.labels import WorkloadSpec, tenant_of

OBJECTIVES = ("makespan", "avg-jct", "finish-time-fairness")

# generation key used for nodes that report no TPU generation: GPU nodes
# score under the "gpu" class row; nodes with no telemetry identity at
# all fall back to the neutral ratio
GPU_KEY = "gpu"
_UNKNOWN = "unknown"


def throughput_class(spec: WorkloadSpec) -> str:
    """The workload class a spec scores under: the declared scv/class
    label when present, else a coarse spec-derived class (gpu / gang /
    multi / single) so classless fleets still get sane defaults. Pure
    function of the spec — every spec-keyed memo covers it."""
    if spec.workload_class is not None:
        return spec.workload_class
    if spec.accelerator == "gpu":
        return "gpu"
    if spec.is_gang:
        return "gang"
    return "multi" if spec.chips > 1 else "single"


def generation_key(metrics) -> str:
    """The generation axis of the throughput matrix for one node:
    tpu_generation when reported, else the accelerator kind ("gpu"),
    else unknown (neutral)."""
    if metrics is None:
        return _UNKNOWN
    return metrics.tpu_generation or metrics.accelerator or _UNKNOWN


def _catalog_ratios() -> dict[str, float]:
    """Default per-generation ratios from the catalog's compute proxy
    (clock x MXUs), normalised to v4 = 1.0. A proxy, not a measurement —
    operators with profiled workloads override per class in config."""
    v4 = GENERATIONS["v4"]
    base = float(v4.clock_mhz * v4.mxus)
    return {name: (g.clock_mhz * g.mxus) / base
            for name, g in GENERATIONS.items()}


class ThroughputModel:
    """ratio(class, generation) with operator overrides over catalog
    defaults. `classes` maps workload class -> {generation: ratio}
    (the config `workloadClasses` block, already plain floats)."""

    def __init__(self, classes: dict[str, dict[str, float]] | None = None):
        self._defaults = _catalog_ratios()
        self._classes: dict[str, dict[str, float]] = {
            str(c): {str(g): float(r) for g, r in (gens or {}).items()}
            for c, gens in (classes or {}).items()}
        self._best: dict[str, float] = {}

    def ratio(self, wclass: str, gen: str) -> float:
        """Relative throughput of `wclass` on `gen`; 1.0 when neither
        the class row nor the catalog knows the generation (no data
        never steers)."""
        row = self._classes.get(wclass)
        if row is not None:
            r = row.get(gen)
            if r is not None:
                return r
            # a class row that names ANY generation is authoritative for
            # its workload: generations it omits score the catalog proxy
        return self._defaults.get(gen, 1.0)

    def best(self, wclass: str) -> float:
        """The class's best ratio over every KNOWN generation (override
        row keys + catalog) — the r_best that normalises scores to
        "fraction of this job's peak throughput". Memoised per class;
        the model is immutable after construction."""
        hit = self._best.get(wclass)
        if hit is None:
            known = set(self._defaults)
            row = self._classes.get(wclass)
            if row:
                known |= set(row)
            hit = max((self.ratio(wclass, g) for g in known), default=1.0)
            hit = max(hit, 1e-9)
            self._best[wclass] = hit
        return hit


class HeterogeneityScore(ScorePlugin):
    """Score nodes by the pod's class-vs-generation throughput ratio,
    shaped by the configured objective (module docstring).

    ABSOLUTE semantics (normalize_kind identity), like the topology and
    fragmentation scorers: the term is already on a bounded 0..100*k
    scale and must only TIP choices between otherwise-comparable nodes —
    min-max would amplify a 2% throughput difference to the full 0-100
    swing and stomp the capacity signals."""

    name = "heterogeneity-score"
    normalize_kind = "identity"
    # telemetry-blackout degraded mode does NOT drop this scorer: the
    # generation of a node is inventory, not a live quality number —
    # last-known generation is exactly as true during a blackout.

    def __init__(self, model: ThroughputModel, objective: str,
                 weight: int = 4, policy=None) -> None:
        if objective not in OBJECTIVES:
            raise ValueError(
                f"policyObjective must be one of {OBJECTIVES}, "
                f"got {objective!r}")
        self.model = model
        self.objective = objective
        self.weight = weight
        self.policy = policy  # PolicyEngine: DRF shares for the fairness objective
        # score-memo contract (core's score section): for the static
        # objectives the raw score is a pure function of the node's
        # generation (inside the node serial) and the pod's spec — clean
        # nodes' scores may be replayed verbatim. finish-time-fairness
        # folds in live tenant shares, which move with every bind the
        # version vector attributes to OTHER nodes, so it must not
        # declare — those cycles score fully, every time.
        if objective != "finish-time-fairness":
            self.score_inputs = "node"

    def equivalence_key(self, pod):
        """Batch-cycle contract: the static objectives read only the
        spec (class + chips) and node state — classmates (same spec by
        construction) are interchangeable. finish-time-fairness scores
        move with the tenant's live share, which OUR OWN batch commits
        shift mid-batch — never batch those pods."""
        if self.objective == "finish-time-fairness":
            return NO_BATCH
        return ()

    # ------------------------------------------------------------- scoring
    def _factor(self, spec: WorkloadSpec, pod) -> float:
        """The objective's pod-level multiplier (node-independent, so it
        distributes over the per-node ratio — computed once per cycle
        via the state memo in score/score_batch)."""
        if self.objective == "avg-jct":
            return 1.0 + 1.0 / max(spec.chips, 1)
        if self.objective == "finish-time-fairness" and self.policy is not None:
            book = self.policy.book
            if book is not None:
                tenant = tenant_of(pod)
                fair = self.policy.fair_share(tenant)
                share = book.dominant_share(tenant)
                if fair > 0.0 and share < fair:
                    return 1.0 + (fair - share) / fair
        return 1.0

    _FKEY = "hetero_factor"

    def _cycle_factor(self, state: CycleState, spec, pod) -> float:
        f = state.read_or(self._FKEY)
        if f is None:
            f = self._factor(spec, pod)
            state.write(self._FKEY, f)
        return f

    def score(self, state: CycleState, pod, node: NodeInfo) -> tuple[float, Status]:
        spec: WorkloadSpec = state.read("workload_spec")
        wclass = throughput_class(spec)
        r = self.model.ratio(wclass, generation_key(node.metrics))
        f = self._cycle_factor(state, spec, pod)
        # EDIT IN LOCKSTEP with score_batch: same expression, same
        # operation order, so the vectorized form agrees bit-for-bit
        return 100.0 * r / self.model.best(wclass) * f, Status.success()

    def score_batch(self, state: CycleState, pod, table, rows):
        """Columnar form: one ratio lookup per interned generation id,
        broadcast through the gen/accel columns. Written op-for-op like
        the scalar path (elementwise IEEE ops in the same order), so
        floats agree bit-for-bit — pinned by tests/test_policy.py."""
        if not HAVE_NUMPY:
            return None
        spec: WorkloadSpec = state.read("workload_spec")
        wclass = throughput_class(spec)
        interned = table.intern_table()
        # per-intern-id ratio vector (a handful of strings cluster-wide);
        # ids are dense [0, len) by construction of the intern table
        vec = np.ones(len(interned) + 1, dtype=np.float64)
        for s, i in interned.items():
            vec[i] = self.model.ratio(wclass, s)
        empty = table.intern_of("")
        gen = table.gen[rows]
        accel = table.accel[rows]
        # a node reporting no tpu_generation scores under its
        # accelerator kind, exactly generation_key's fallback
        ids = np.where(gen == empty, accel, gen) if empty >= 0 else gen
        # no-telemetry rows carry the -2 sentinel in BOTH columns; a
        # profile without the telemetry filter can legally rank such a
        # node, and a negative index would silently read some OTHER
        # interned string's ratio — route it to the pad slot, whose
        # value is the scalar path's neutral 1.0 (generation_key(None))
        ids = np.where(ids >= 0, ids, len(interned))
        r = vec[ids]
        f = self._cycle_factor(state, spec, pod)
        return 100.0 * r / self.model.best(wclass) * f

    def native_score_args(self, state: CycleState, pod, table):
        """Fused-kernel capability hook: the kernel knows the telemetry
        and fragmentation folds only — adding a kind means an ABI bump,
        and the mixed-cycle contract already keeps placements bit-exact
        (kernel-born raws fold with this plugin's Python raws in profile
        order, core._fold_scores). Deliberate None."""
        return None

    def normalize(self, state: CycleState, pod, scores) -> None:
        return None  # absolute semantics (class docstring)
