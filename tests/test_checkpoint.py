"""Checkpoint/resume of sharded train state (parallel/checkpoint.py):
round-trip preserves values AND shardings; resuming from a checkpoint
continues training bit-exact vs an uninterrupted run."""

import jax
import jax.numpy as jnp
import pytest

from yoda_scheduler_tpu.models.llama import LlamaConfig
from yoda_scheduler_tpu.parallel import build_llama_train_step, make_mesh
from yoda_scheduler_tpu.parallel.checkpoint import TrainCheckpointer

CFG = LlamaConfig.tiny()


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"dp": 2, "fsdp": 2, "tp": 2})


@pytest.fixture(scope="module")
def step_bits(mesh):
    init_fn, step_fn, batch_sh = build_llama_train_step(CFG, mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(5), (8, 64), 0,
                           CFG.vocab_size), batch_sh)
    return init_fn, step_fn, tokens


class TestRoundTrip:
    def test_values_and_shardings_survive(self, tmp_path, step_bits):
        init_fn, step_fn, tokens = step_bits
        params, opt = init_fn(jax.random.PRNGKey(0))
        params, opt, _ = step_fn(params, opt, tokens)
        with TrainCheckpointer(str(tmp_path / "ckpt")) as ckpt:
            ckpt.save(1, params, opt)
            fresh_p, fresh_o = init_fn(jax.random.PRNGKey(9))
            step, rp, ro = ckpt.restore((fresh_p, fresh_o))
        assert step == 1
        jax.tree.map(
            lambda a, b: None if bool(jnp.array_equal(a, b)) else
            pytest.fail("restored params differ"), params, rp)
        # shardings preserved (tp split on wq survives the round trip)
        assert rp["layers"]["wq"].sharding == params["layers"]["wq"].sharding

    def test_restore_without_checkpoint_raises(self, tmp_path, step_bits):
        init_fn, _, _ = step_bits
        params, opt = init_fn(jax.random.PRNGKey(0))
        with TrainCheckpointer(str(tmp_path / "empty")) as ckpt:
            with pytest.raises(FileNotFoundError):
                ckpt.restore((params, opt))

    def test_non_increasing_save_raises_not_silently_skips(
            self, tmp_path, step_bits):
        # orbax's should_save guard skips steps <= latest; a silent skip
        # after restoring an older step would resume from divergent weights
        init_fn, _, _ = step_bits
        params, opt = init_fn(jax.random.PRNGKey(0))
        with TrainCheckpointer(str(tmp_path / "skip")) as ckpt:
            ckpt.save(3, params, opt)
            with pytest.raises(ValueError, match="not saved"):
                ckpt.save(3, params, opt)

    def test_max_to_keep_garbage_collects(self, tmp_path, step_bits):
        init_fn, _, _ = step_bits
        params, opt = init_fn(jax.random.PRNGKey(0))
        with TrainCheckpointer(str(tmp_path / "gc"), max_to_keep=2) as ckpt:
            for s in (1, 2, 3):
                ckpt.save(s, params, opt)
            assert ckpt.all_steps() == [2, 3]
            assert ckpt.latest_step() == 3


class TestResume:
    def test_resume_is_bit_exact(self, tmp_path, step_bits):
        init_fn, step_fn, tokens = step_bits
        with TrainCheckpointer(str(tmp_path / "resume")) as ckpt:
            # uninterrupted: 4 steps, checkpointing mid-run (save must
            # happen before step_fn donates the buffers)
            params, opt = init_fn(jax.random.PRNGKey(0))
            for i in range(4):
                if i == 2:
                    ckpt.save(2, params, opt)
                params, opt, loss = step_fn(params, opt, tokens)
            want = float(loss)
            # "crash", restore at step 2 into a fresh process state, continue
            fresh = init_fn(jax.random.PRNGKey(3))
            _, rp, ro = ckpt.restore(fresh)
        for _ in range(2):
            rp, ro, loss = step_fn(rp, ro, tokens)
        assert float(loss) == want
