"""Geometric torus carving over the HOST grid of a pod slice.

torus.py models chips within one host's view of a slice; this module
models the slice's *hosts* as a 2-D/3-D torus with wraparound links and
carves gang demand as contiguous axis-aligned host blocks. A v4 slice of
8x8x1 chips is a 4x4x1 grid of 4-chip hosts; a gang of 8 members wants 8
of those hosts as one block so its collectives ride ICI, not DCN.

Three planes compute the same carve — scalar Python (the reference),
numpy (window-sum feasibility over all origins at once), and the native
kernel (native/carveplane.cc via topology/carvenative.py) — op-for-op
bit-identical, the placement.cc discipline (parity fuzz in
tests/test_torus_carve.py). The fallback chain is native <- numpy <-
scalar; every plane scores candidate blocks by the SAME all-integer key:

  (-bisection_links, exposed_free_surface, compactness, bz, by, bx,
   oz, oy, ox)

maximising the carved block's ICI bisection bandwidth first (a full-ring
carve keeps its wraparound links and doubles the cut), then nestling the
block against occupied/boundary cells (the corner heuristic: minimal
free surface left exposed keeps the REMAINING free space consolidated),
then preferring cube-ish shapes and the low corner. The key totally
orders candidates, so the minimum is unique and iteration order cannot
matter — that is what makes three independent implementations provably
identical rather than accidentally so.

Wraparound: a torus axis has distinct wrap links only when its extent is
>= 3 (at extent 2 the wrap link coincides with the direct link, at 1
there is no link), so ``wrap_of`` derives per-axis wrap from the grid.
Pure integer functions over coordinate sets, lru-cached like torus.py.
"""

from __future__ import annotations

from functools import lru_cache

from .torus import Shape, chips_in, _factor_shapes

Coord = tuple[int, int, int]
Wrap = tuple[bool, bool, bool]

try:
    import numpy as np
except Exception:  # pragma: no cover - numpy-less install
    np = None


def host_grid(slice_shape: Shape, host_block: Shape) -> Shape:
    """The slice's host-grid shape: chips per axis over the host block's
    contribution per axis (host_blocks tiles exactly this grid)."""
    sx, sy, sz = slice_shape
    hx, hy, hz = host_block
    if sx % hx or sy % hy or sz % hz:
        raise ValueError(
            f"slice {slice_shape} not divisible by host block {host_block}")
    return (sx // hx, sy // hy, sz // hz)


def host_coord(index: int, grid: Shape) -> Coord:
    """Host index -> host-grid coordinate. Inverse of the host_blocks
    enumeration order (bz outer, by, bx inner), which is also the order
    make_slice assigns host_index in."""
    gx, gy, _ = grid
    return (index % gx, (index // gx) % gy, index // (gx * gy))


def wrap_of(grid: Shape) -> Wrap:
    """Per-axis wraparound: distinct wrap links exist only at extent >= 3."""
    return (grid[0] >= 3, grid[1] >= 3, grid[2] >= 3)


def bisection_links(block: Shape, grid: Shape, wrap: Wrap) -> int:
    """ICI links crossing the carved block's narrowest bisection: cutting
    perpendicular to axis a severs volume/block[a] host-to-host links,
    doubled when the block spans axis a's full wrapped ring (its wrap
    links are then internal and cross the same cut). 0 for a single
    host — no internal links to bisect."""
    vol = chips_in(block)
    best = 0
    for a in range(3):
        if block[a] <= 1:
            continue
        cross = vol // block[a]
        if wrap[a] and block[a] == grid[a]:
            cross *= 2
        if best == 0 or cross < best:
            best = cross
    return best


def bisection_gbps(block: Shape, grid: Shape, wrap: Wrap,
                   ici_gbps: float) -> float:
    """The carved block's bisection bandwidth in GB/s: links times the
    generation's per-link ICI rate (what the MLPerf-style all-reduce
    actually rides)."""
    return bisection_links(block, grid, wrap) * float(ici_gbps)


@lru_cache(maxsize=65536)
def _block_coords(origin: Coord, block: Shape, grid: Shape) -> frozenset:
    """Block cells with per-axis modular wrap (identity when the origin
    range already keeps the block in-grid)."""
    ox, oy, oz = origin
    bx, by, bz = block
    gx, gy, gz = grid
    return frozenset(
        ((ox + dx) % gx, (oy + dy) % gy, (oz + dz) % gz)
        for dx in range(bx) for dy in range(by) for dz in range(bz))


def _origins(dim: int, b: int, wrapped: bool) -> range:
    """Candidate origins along one axis: a full-span block is one
    placement; a wrapped axis admits every origin (blocks may cross the
    seam); a flat axis admits only in-bounds origins."""
    if b == dim:
        return range(1)
    if wrapped:
        return range(dim)
    return range(dim - b + 1)


def _exposure(grid: Shape, free: frozenset, origin: Coord, block: Shape,
              wrap: Wrap, coords: frozenset) -> int:
    """Free cells adjacent to the block's faces, outside the block —
    wrap-aware (a full-span axis has no outside along it; a flat axis's
    out-of-grid side exposes nothing). The corner heuristic minimises
    this: a carve hugging occupied cells and boundaries leaves the
    remaining free space in one large region instead of splitting it."""
    gx, gy, gz = grid
    dims = (gx, gy, gz)
    exp = 0
    for (x, y, z) in coords:
        for a, d in ((0, 1), (0, -1), (1, 1), (1, -1), (2, 1), (2, -1)):
            n = [x, y, z]
            n[a] += d
            if wrap[a]:
                n[a] %= dims[a]
            elif not 0 <= n[a] < dims[a]:
                continue
            nc = (n[0], n[1], n[2])
            if nc in coords:
                continue
            if nc in free:
                exp += 1
    return exp


def _key(grid: Shape, free: frozenset, origin: Coord, block: Shape,
         wrap: Wrap, coords: frozenset) -> tuple:
    ox, oy, oz = origin
    bx, by, bz = block
    return (-bisection_links(block, grid, wrap),
            _exposure(grid, free, origin, block, wrap, coords),
            bx + by + bz, bz, by, bx, oz, oy, ox)


def _carve_scalar(grid: Shape, free: frozenset, n_hosts: int,
                  wrap: Wrap):
    """Reference carve: every factor shape at every admissible origin,
    unique minimum of the total-order key. Returns (origin, block,
    coords, links) or None."""
    gx, gy, gz = grid
    best = None
    for block in _factor_shapes(n_hosts):
        bx, by, bz = block
        if bx > gx or by > gy or bz > gz:
            continue
        for oz in _origins(gz, bz, wrap[2]):
            for oy in _origins(gy, by, wrap[1]):
                for ox in _origins(gx, bx, wrap[0]):
                    origin = (ox, oy, oz)
                    coords = _block_coords(origin, block, grid)
                    if not coords <= free:
                        continue
                    k = _key(grid, free, origin, block, wrap, coords)
                    if best is None or k < best[0]:
                        best = (k, origin, block, coords)
    if best is None:
        return None
    return best[1], best[2], best[3], -best[0][0]


def _carve_numpy(grid: Shape, free: frozenset, n_hosts: int, wrap: Wrap):
    """numpy twin: feasibility (the dominant subset test, |shapes| x
    |origins| of them) vectorised as wrap-aware window sums over the
    free-cell grid; the few surviving origins score through the SAME
    integer key helpers as the scalar plane, so the keys — and therefore
    the unique minimum — are identical by construction."""
    if np is None:
        return _carve_scalar(grid, free, n_hosts, wrap)
    gx, gy, gz = grid
    arr = np.zeros((gx, gy, gz), dtype=np.int32)
    for (x, y, z) in free:
        arr[x, y, z] = 1
    best = None
    for block in _factor_shapes(n_hosts):
        bx, by, bz = block
        if bx > gx or by > gy or bz > gz:
            continue
        # window[o] = free cells inside the block at origin o (wrapped
        # roll; flat axes mask out-of-bounds origins below)
        win = arr
        for axis, b in ((0, bx), (1, by), (2, bz)):
            if b > 1:
                win = sum(np.roll(win, -k, axis=axis) for k in range(b))
        feas = win == (bx * by * bz)
        for oz in _origins(gz, bz, wrap[2]):
            for oy in _origins(gy, by, wrap[1]):
                for ox in _origins(gx, bx, wrap[0]):
                    if not feas[ox, oy, oz]:
                        continue
                    origin = (ox, oy, oz)
                    coords = _block_coords(origin, block, grid)
                    k = _key(grid, free, origin, block, wrap, coords)
                    if best is None or k < best[0]:
                        best = (k, origin, block, coords)
    if best is None:
        return None
    return best[1], best[2], best[3], -best[0][0]


@lru_cache(maxsize=1)
def _native_on() -> bool:
    try:
        from . import carvenative

        return carvenative.available()
    except Exception:
        return False


@lru_cache(maxsize=131072)
def _carve_cached(grid: Shape, free: frozenset, n_hosts: int, wrap: Wrap):
    if _native_on():
        from . import carvenative

        out = carvenative.carve_block(grid, free, n_hosts, wrap)
        if out is not NotImplemented:
            return out
    if np is not None:
        return _carve_numpy(grid, free, n_hosts, wrap)
    return _carve_scalar(grid, free, n_hosts, wrap)


def carve_block(grid: Shape, free, n_hosts: int, wrap: Wrap | None = None,
                plane: str | None = None):
    """Best contiguous axis-aligned block of exactly `n_hosts` free
    hosts on the wrapped host grid, or None. Returns (origin,
    block_shape, coords, bisection_links). `plane` forces an
    implementation for the parity tests ("scalar" | "numpy" |
    "native"); None takes the native <- numpy <- scalar chain."""
    if n_hosts <= 0 or n_hosts > chips_in(grid):
        return None
    w = wrap if wrap is not None else wrap_of(grid)
    f = frozenset(free)
    if plane == "scalar":
        return _carve_scalar(grid, f, n_hosts, w)
    if plane == "numpy":
        return _carve_numpy(grid, f, n_hosts, w)
    if plane == "native":
        from . import carvenative

        return carvenative.carve_block(grid, f, n_hosts, w)
    return _carve_cached(grid, f, n_hosts, w)


@lru_cache(maxsize=131072)
def _largest_carvable(grid: Shape, free: frozenset, wrap: Wrap) -> int:
    if _native_on():
        from . import carvenative

        out = carvenative.largest_carvable(grid, free, wrap)
        if out is not NotImplemented:
            return out
    gx, gy, gz = grid
    for n in range(len(free), 0, -1):
        for block in _factor_shapes(n):
            bx, by, bz = block
            if bx > gx or by > gy or bz > gz:
                continue
            for oz in _origins(gz, bz, wrap[2]):
                for oy in _origins(gy, by, wrap[1]):
                    for ox in _origins(gx, bx, wrap[0]):
                        if _block_coords((ox, oy, oz), block, grid) <= free:
                            return n
    return 0


def largest_carvable(grid: Shape, free, wrap: Wrap | None = None) -> int:
    """Volume of the largest whole-host block carvable from `free` — the
    geometric capacity metric the FragmentationScore term, the defrag
    controller, and scale-down shape conservation all steer by."""
    w = wrap if wrap is not None else wrap_of(grid)
    return _largest_carvable(grid, frozenset(free), w)
