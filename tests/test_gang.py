"""Gang scheduling + multi-host slice placement — BASELINE scenario 4
(Llama-2-7B on a multi-host v4-32 pod slice) and its failure modes."""

import pytest

from yoda_scheduler_tpu.scheduler import FakeCluster, Scheduler, SchedulerConfig
from yoda_scheduler_tpu.scheduler.core import FakeClock
from yoda_scheduler_tpu.telemetry import FakePublisher, TelemetryStore, make_tpu_node, make_v4_slice
from yoda_scheduler_tpu.utils import Pod, PodPhase


def mk_sched(nodes, config=None):
    store = TelemetryStore()
    pub = FakePublisher(store)
    clock = FakeClock(start=1000.0)
    for n in nodes:
        store.put(n)
        n.heartbeat = clock.time()
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    return Scheduler(cluster, config or SchedulerConfig(), clock=clock), clock


def gang_pods(name, size, chips=4, mem="16000"):
    return [
        Pod(
            f"{name}-worker-{i}",
            labels={
                "tpu/gang-name": name,
                "tpu/gang-size": str(size),
                "scv/number": str(chips),
                "scv/memory": mem,
            },
        )
        for i in range(size)
    ]


def refresh(sched):
    for m in sched.cluster.telemetry.list():
        m.heartbeat = sched.clock.time()


class TestGangAdmission:
    def test_v4_32_gang_binds_atomically_on_one_slice(self):
        # BASELINE #4: 4 workers x 4 chips on a 4-host v4-32 slice
        nodes = make_v4_slice("v4-32-a", "2x2x4") + [make_tpu_node("standalone", chips=4)]
        sched, _ = mk_sched(nodes)
        workers = gang_pods("llama", 4)
        for w in workers:
            sched.submit(w)
        sched.run_until_idle(max_cycles=100)
        assert all(w.phase == PodPhase.BOUND for w in workers)
        hosts = {w.node for w in workers}
        assert len(hosts) == 4
        assert all(h.startswith("v4-32-a-host-") for h in hosts)
        # every chip of the slice claimed exactly once
        all_chips = [c for w in workers for c in w.labels["tpu/assigned-chips"].split(";")]
        assert len(all_chips) == 16 and len(set(all_chips)) == 16

    def test_no_partial_gang_before_completion(self):
        nodes = make_v4_slice("s", "2x2x4")
        sched, clock = mk_sched(nodes)
        workers = gang_pods("job", 4)
        # submit only 2 of 4 workers
        for w in workers[:2]:
            sched.submit(w)
        for _ in range(4):
            refresh(sched)
            info = sched.queue.pop(now=clock.time())
            if info:
                sched.schedule_one(info)
            clock.advance(0.5)
        # nothing bound; both parked in Permit
        assert all(w.phase == PodPhase.PENDING for w in workers[:2])
        assert len(sched.waiting) == 2
        # remaining workers arrive -> whole gang binds together
        for w in workers[2:]:
            sched.submit(w)
        sched.run_until_idle(max_cycles=100)
        assert all(w.phase == PodPhase.BOUND for w in workers)

    def test_gang_timeout_rolls_back_reservations(self):
        nodes = make_v4_slice("s", "2x2x4")
        sched, clock = mk_sched(nodes, SchedulerConfig(gang_timeout_s=10.0, max_attempts=2))
        workers = gang_pods("doomed", 4)
        for w in workers[:2]:  # the rest never arrive
            sched.submit(w)
        for _ in range(3):
            refresh(sched)
            info = sched.queue.pop(now=clock.time())
            if info:
                sched.schedule_one(info)
        assert len(sched.waiting) == 2
        clock.advance(30.0)  # past the permit deadline
        sched.check_waiting()
        assert len(sched.waiting) == 0
        assert sched.metrics.counters["gang_timeouts_total"] == 1
        # reservations released: a non-gang 16-chip-per-host job can use the slice
        refresh(sched)
        free_pod = Pod("free", labels={"scv/number": "4"})
        sched.submit(free_pod)
        info = sched.queue.pop(now=clock.time())
        while info is not None and info.pod.name != "free":
            info = sched.queue.pop(now=clock.time())
        assert info is not None
        assert sched.schedule_one(info) == "bound"

    def test_two_gangs_compete_one_slice_each(self):
        nodes = make_v4_slice("sliceA", "2x2x4") + make_v4_slice("sliceB", "2x2x4")
        sched, _ = mk_sched(nodes)
        g1 = gang_pods("jobA", 4)
        g2 = gang_pods("jobB", 4)
        for w in g1 + g2:
            sched.submit(w)
        sched.run_until_idle(max_cycles=200)
        assert all(w.phase == PodPhase.BOUND for w in g1 + g2)
        slices1 = {w.node.rsplit("-host-", 1)[0] for w in g1}
        slices2 = {w.node.rsplit("-host-", 1)[0] for w in g2}
        assert len(slices1) == 1 and len(slices2) == 1
        assert slices1 != slices2
        assert sched.bin_pack_utilization() == pytest.approx(100.0)

    def test_doomed_gang_fails_all_members_promptly(self):
        """One member is individually unsatisfiable (impossible HBM), the
        other three are fine: once the bad member exhausts its attempts
        the gang is doomed — parked peers fail immediately and backoff
        peers fail fast at their next cycle. Nothing cycles forever on
        the park->timeout->requeue path, which counts no attempts (found
        by the r5 randomized fuzz)."""
        nodes = make_v4_slice("s", "2x2x4")
        sched, clock = mk_sched(nodes, SchedulerConfig(
            gang_timeout_s=10.0, max_attempts=2))
        workers = gang_pods("half", 4)
        workers[3].labels["scv/memory"] = "999999999"  # can never fit
        for w in workers:
            sched.submit(w)
        sched.run_until_idle(max_cycles=500)
        assert all(w.phase == PodPhase.FAILED for w in workers)
        assert not sched.waiting
        assert not any(sched.tracks(w.key) for w in workers)
        # every reservation rolled back: the slice hosts other work again
        free_pod = Pod("free", labels={"scv/number": "4"})
        sched.submit(free_pod)
        sched.run_until_idle(max_cycles=50)
        assert free_pod.phase == PodPhase.BOUND

    def test_doomed_gang_revives_on_resubmission(self):
        """Failing once must not poison the gang name: fresh incarnations
        of the members (the serve loop resubmits recreated pods) assemble
        and bind."""
        nodes = make_v4_slice("s", "2x2x4")
        sched, clock = mk_sched(nodes, SchedulerConfig(
            gang_timeout_s=10.0, max_attempts=2))
        workers = gang_pods("phoenix", 4)
        workers[0].labels["scv/memory"] = "999999999"
        for w in workers:
            sched.submit(w)
        sched.run_until_idle(max_cycles=500)
        assert all(w.phase == PodPhase.FAILED for w in workers)
        retry = gang_pods("phoenix", 4)  # corrected incarnations
        for w in retry:
            sched.submit(w)
        sched.run_until_idle(max_cycles=500)
        assert all(w.phase == PodPhase.BOUND for w in retry)

    def test_gang_too_big_for_any_slice_fails_cleanly(self):
        nodes = make_v4_slice("s", "2x2x2")  # only 2 hosts
        sched, _ = mk_sched(nodes, SchedulerConfig(max_attempts=2))
        workers = gang_pods("big", 4)
        for w in workers:
            sched.submit(w)
        sched.run_until_idle(max_cycles=200)
        assert all(w.phase == PodPhase.FAILED for w in workers)
        assert sched.bin_pack_utilization() == 0.0


class TestCandidateNarrowing:
    def test_chosen_slice_narrows_the_scan(self):
        """Once the first member fixes the slice, later member cycles
        must only evaluate that slice's hosts (the engine skips the
        filter chain for everything else) — and still bind correctly."""
        nodes = (make_v4_slice("sliceA", "2x2x4")
                 + make_v4_slice("sliceB", "2x2x4")
                 + [make_tpu_node(f"lone-{i}", chips=4) for i in range(6)])
        sched, _ = mk_sched(nodes)
        workers = gang_pods("g", 4)
        for w in workers:
            sched.submit(w)
        sched.run_one()  # first member: reserves and fixes a slice
        chosen = sched.gang_permit.gangs.chosen_slice("g")
        assert chosen in ("sliceA", "sliceB")
        sched.run_one()  # second member: narrowed cycle
        t = sched.traces.recent(1)[0]
        scanned = set(t.filter_verdicts)
        assert scanned, "second member must scan real nodes"
        assert all(n.startswith(chosen) for n in scanned), scanned
        sched.run_until_idle(max_cycles=50)
        assert all(w.phase == PodPhase.BOUND for w in workers)
        assert all(w.node.startswith(chosen) for w in workers)

    def test_first_member_skips_undersized_slices(self):
        """With no chosen slice yet, narrowing keeps only gang-sized
        slices: a 2-host slice never enters a 4-member gang's scan."""
        nodes = (make_v4_slice("big", "2x2x4")      # 4 hosts
                 + make_v4_slice("small", "2x2x2"))  # 2 hosts
        sched, _ = mk_sched(nodes)
        for w in gang_pods("g", 4):
            sched.submit(w)
        sched.run_one()
        t = sched.traces.recent(1)[0]
        assert t.filter_verdicts and all(
            n.startswith("big") for n in t.filter_verdicts), t.filter_verdicts
