"""Policy engine (scheduler/policy/): heterogeneity-aware placement +
multi-tenant DRF fairness with preemption budgets (ISSUE 9).

Covers: throughput model defaults/overrides/objectives, scalar-vs-batch
score parity (bit-exact), DRF book incremental-vs-rebuild equality and
hierarchy, quota gating + event-driven wake, fairness queue ordering,
preemption budgets (never exceeded; PDBs still honored), the policy-off
bit-identical default, cross-tenant batch audit, fleet-replica DRF-book
agreement, and the registry/config wiring."""

import random
import time

import pytest

from yoda_scheduler_tpu.scheduler import FakeCluster, Scheduler, SchedulerConfig
from yoda_scheduler_tpu.scheduler.core import FakeClock, HybridClock, default_profile
from yoda_scheduler_tpu.scheduler.framework import CycleState, NO_BATCH
from yoda_scheduler_tpu.scheduler.policy import (
    DRFBook,
    HeterogeneityScore,
    PolicyEngine,
    TenantFairnessSort,
    TenantQuotaGate,
    ThroughputModel,
    throughput_class,
)
from yoda_scheduler_tpu.scheduler.policy.fairness import (
    PreemptionBudgets,
    TenantQuota,
    _ancestors,
)
from yoda_scheduler_tpu.scheduler.registry import build_profile, merge_enablement
from yoda_scheduler_tpu.telemetry import TelemetryStore, make_gpu_node, make_tpu_node
from yoda_scheduler_tpu.utils import Pod, PodPhase
from yoda_scheduler_tpu.utils.labels import spec_for, tenant_of


def _store(v4=3, v5e=3, gpu=0):
    store = TelemetryStore()
    now = time.time()
    for i in range(v4):
        m = make_tpu_node(f"v4-{i}", chips=4, generation="v4")
        m.heartbeat = now
        store.put(m)
    for i in range(v5e):
        m = make_tpu_node(f"v5e-{i}", chips=8, generation="v5e")
        m.heartbeat = now
        store.put(m)
    for i in range(gpu):
        m = make_gpu_node(f"g{i}", cards=4)
        m.heartbeat = now
        store.put(m)
    return store


def _cluster(**kw):
    c = FakeCluster(_store(**kw))
    c.add_nodes_from_telemetry()
    return c


def _pod(name, tenant=None, wclass=None, chips=1, mem=None, prio=None,
         **labels):
    lab = {"scv/number": str(chips), "tpu/accelerator": "tpu"}
    if tenant:
        lab["scv/tenant"] = tenant
    if wclass:
        lab["scv/class"] = wclass
    if mem is not None:
        lab["scv/memory"] = str(mem)
    if prio is not None:
        lab["scv/priority"] = str(prio)
    lab.update(labels)
    return Pod(name, labels=lab)


# ------------------------------------------------------------ model
class TestThroughputModel:
    def test_catalog_defaults_normalised_to_v4(self):
        m = ThroughputModel()
        assert m.ratio("anything", "v4") == 1.0
        # v5p/v6e are faster than v4 on the clock*mxu proxy
        assert m.ratio("x", "v5p") > 1.0
        assert m.ratio("x", "unknown-gen") == 1.0  # no data never steers

    def test_class_overrides_beat_catalog(self):
        m = ThroughputModel({"train": {"v5e": 3.0, "v4": 1.0}})
        assert m.ratio("train", "v5e") == 3.0
        assert m.best("train") == 3.0
        # other classes keep catalog defaults
        assert m.ratio("serve", "v4") == 1.0

    def test_best_covers_catalog_and_overrides(self):
        m = ThroughputModel({"c": {"weird-gen": 9.0}})
        assert m.best("c") == 9.0

    def test_throughput_class_label_and_fallback(self):
        assert throughput_class(spec_for(_pod("a", wclass="train"))) == "train"
        assert throughput_class(spec_for(_pod("b"))) == "single"
        assert throughput_class(spec_for(_pod("c", chips=2))) == "multi"
        gpu = Pod("g", labels={"tpu/accelerator": "gpu", "scv/number": "1"})
        assert throughput_class(spec_for(gpu)) == "gpu"

    def test_malformed_class_label_rejected(self):
        from yoda_scheduler_tpu.utils.labels import LabelError

        with pytest.raises(LabelError):
            spec_for(Pod("x", labels={"scv/class": ""}))


class TestHeterogeneityScore:
    def test_objective_validated(self):
        with pytest.raises(ValueError):
            HeterogeneityScore(ThroughputModel(), "mispeled")

    def test_makespan_steers_to_fast_generation(self):
        cfg = SchedulerConfig(
            policy_objective="makespan", telemetry_max_age_s=1e9,
            workload_classes=(("train", (("v5e", 2.0), ("v4", 0.9))),),
            max_attempts=3)
        sched = Scheduler(_cluster(), cfg, clock=HybridClock())
        pods = [_pod(f"p{i}", wclass="train") for i in range(8)]
        for p in pods:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in pods)
        assert all(p.node.startswith("v5e") for p in pods), \
            [(p.name, p.node) for p in pods]

    def test_chip_agnostic_default_unchanged(self):
        """policyObjective unset builds NO policy plugins at all."""
        profile, _, _ = default_profile(SchedulerConfig())
        names = {type(p).__name__ for pt in (
            profile.pre_filter, profile.score, [profile.queue_sort])
            for p in (pt if isinstance(pt, list) else [pt])}
        assert "HeterogeneityScore" not in names
        assert "TenantQuotaGate" not in names
        assert "TenantFairnessSort" not in names
        assert profile.policy is None

    def test_scalar_vs_batch_scores_bit_exact(self):
        """score() and score_batch() must agree bit-for-bit (the columnar
        parity contract every batch scorer carries)."""
        cfg = SchedulerConfig(
            policy_objective="avg-jct", telemetry_max_age_s=1e9,
            workload_classes=(("train", (("v5e", 1.7), ("v4", 1.0))),),
            max_attempts=3)
        cluster = _cluster(gpu=2)
        sched = Scheduler(cluster, cfg, clock=HybridClock())
        # drive one pod through so the columnar table exists and is synced
        warm = _pod("warm", wclass="train")
        sched.submit(warm)
        sched.run_until_idle()
        table = sched._columnar
        vers = sched._cluster_versions()
        snapshot = sched.snapshot()
        assert table.sync(snapshot, vers, sched._changes_since_vers)
        het = next(p for p in sched.profile.score
                   if isinstance(p, HeterogeneityScore))
        pod = _pod("probe", wclass="train", chips=2)
        state = CycleState()
        state.write("workload_spec", spec_for(pod))
        infos = snapshot.list()
        rows = table.rows_for(infos)
        batch = het.score_batch(state, pod, table, rows)
        for i, ni in enumerate(infos):
            s, st = het.score(state, pod, ni)
            assert st.ok
            assert s == batch[i], (ni.name, s, batch[i])

    def test_batch_no_telemetry_row_scores_neutral(self):
        """A row with the -2 no-telemetry sentinel must score the
        scalar path's neutral 1.0 — a negative index into the ratio
        vector would silently read another generation's ratio (review
        finding)."""
        cfg = SchedulerConfig(
            policy_objective="makespan", telemetry_max_age_s=1e9,
            workload_classes=(("t", (("v5e", 2.0), ("v4", 1.0))),),
            max_attempts=2)
        cluster = _cluster(v4=2, v5e=1)
        cluster.add_node("bare")  # member with NO telemetry at all
        sched = Scheduler(cluster, cfg, clock=HybridClock())
        warm = _pod("warm", wclass="t")
        sched.submit(warm)
        sched.run_until_idle()
        table = sched._columnar
        snapshot = sched.snapshot()
        assert table.sync(snapshot, sched._cluster_versions(),
                          sched._changes_since_vers)
        het = next(p for p in sched.profile.score
                   if isinstance(p, HeterogeneityScore))
        pod = _pod("probe", wclass="t")
        state = CycleState()
        state.write("workload_spec", spec_for(pod))
        infos = snapshot.list()
        rows = table.rows_for(infos)
        batch = het.score_batch(state, pod, table, rows)
        for i, ni in enumerate(infos):
            s, _ = het.score(state, pod, ni)
            assert s == batch[i], (ni.name, s, batch[i])
        bare_i = next(i for i, ni in enumerate(infos)
                      if ni.name == "bare")
        assert batch[bare_i] == 100.0 * 1.0 / het.model.best("t")

    def test_columnar_vs_scalar_placements_identical(self):
        def run(columnar):
            cfg = SchedulerConfig(
                policy_objective="makespan", columnar=columnar,
                native_plane=False, telemetry_max_age_s=1e9,
                workload_classes=(("t", (("v5e", 1.9), ("v4", 1.0))),),
                max_attempts=3)
            sched = Scheduler(_cluster(), cfg, clock=HybridClock())
            pods = [_pod(f"p{i}", wclass="t", mem=1000 + i)
                    for i in range(24)]
            for p in pods:
                sched.submit(p)
            sched.run_until_idle()
            return [(p.name, p.node) for p in pods]

        assert run(True) == run(False)

    def test_native_plane_placements_identical_with_policy(self):
        """The native fused scan folds heterogeneity raws in Python
        (mixed-cycle contract): placements must match the numpy and
        scalar planes exactly. Skips when the kernel isn't built."""
        from yoda_scheduler_tpu.scheduler.nativeplane import FusedPlane

        try:
            plane = FusedPlane.load()
        except Exception:
            plane = None
        if plane is None:
            pytest.skip("native plane not built")

        def run(native, columnar=True):
            cfg = SchedulerConfig(
                policy_objective="makespan", columnar=columnar,
                native_plane=native, telemetry_max_age_s=1e9,
                workload_classes=(("t", (("v5e", 1.9), ("v4", 1.0))),),
                max_attempts=3)
            sched = Scheduler(_cluster(), cfg, clock=HybridClock())
            pods = [_pod(f"p{i}", wclass="t", mem=1000 + i)
                    for i in range(24)]
            for p in pods:
                sched.submit(p)
            sched.run_until_idle()
            return [(p.name, p.node) for p in pods]

        nat = run(True)
        assert nat == run(False)
        assert nat == run(False, columnar=False)


# ------------------------------------------------------------ DRF book
class TestDRFBook:
    def _filled(self, n_binds=10, seed=0):
        cluster = _cluster()
        sched = Scheduler(cluster, SchedulerConfig(
            telemetry_max_age_s=1e9, max_attempts=3), clock=HybridClock())
        rng = random.Random(seed)
        pods = [_pod(f"p{i}", tenant=rng.choice(("a", "a/ml", "b")),
                     mem=1000) for i in range(n_binds)]
        for p in pods:
            sched.submit(p)
        sched.run_until_idle()
        return cluster, pods

    def test_incremental_matches_rebuild(self):
        cluster, pods = self._filled(12)
        book = DRFBook(cluster)
        book.refresh()  # first refresh = rebuild
        # mutate: evict a few, refresh incrementally, compare to fresh book
        for p in pods[:4]:
            if p.phase == PodPhase.BOUND:
                cluster.evict(p)
        book.refresh()
        fresh = DRFBook(cluster)
        fresh.refresh()
        assert book._usage == fresh._usage
        assert book._levels == fresh._levels  # hierarchical rollup too
        assert book.repairs >= 1  # the second refresh repaired, not rebuilt
        assert fresh.rebuilds == 1

    def test_hierarchical_usage_aggregates_descendants(self):
        cluster, _ = self._filled(10)
        book = DRFBook(cluster)
        book.refresh()
        a = book.usage_of("a")
        aml = book.usage_of("a/ml")
        direct = book._usage.get("a", [0, 0])
        assert a[0] == direct[0] + aml[0]
        assert a[1] == direct[1] + aml[1]

    def test_dominant_share_is_max_axis(self):
        cluster = _cluster(v4=1, v5e=0)  # 4 chips, 4*32768 HBM
        p = _pod("x", tenant="t", mem=30000)
        cluster.bind(p, "v4-0", [(0, 0, 0)])
        book = DRFBook(cluster)
        book.refresh()
        # chips: 1/4 = 0.25; hbm: 30000/131072 ≈ 0.229 -> chips dominate
        assert book.dominant_share("t") == pytest.approx(0.25)

    def test_quota_breach_trips_flight_once_per_episode(self):
        from yoda_scheduler_tpu.utils.obs import FlightRecorder, Metrics

        cluster = _cluster(v4=1, v5e=0)
        cluster.bind(_pod("x", tenant="t"), "v4-0", [(0, 0, 0)])
        flight = FlightRecorder()
        book = DRFBook(cluster, metrics=Metrics(), flight=flight,
                       quotas={"t": TenantQuota("t", quota=0.1)})
        book.refresh()
        book.refresh()  # same episode: no second trip
        trips = [e for e in flight.snapshot()
                 if e["kind"] == "tenant_quota_breach"]
        assert len(trips) == 1
        assert trips[0]["tenant"] == "t"

    def test_ancestors(self):
        assert list(_ancestors("a/b/c")) == ["a/b/c", "a/b", "a"]
        assert list(_ancestors("solo")) == ["solo"]


# ------------------------------------------------------------ quota gate
class TestQuotaGate:
    def _sched(self, quotas, **cfg_kw):
        cfg = SchedulerConfig(
            drf_fairness=True, tenant_quotas=quotas,
            telemetry_max_age_s=1e9, max_attempts=2, **cfg_kw)
        return Scheduler(_cluster(), cfg, clock=HybridClock())

    def test_cap_enforced_exactly(self):
        # 36 chips total; acme capped at 0.25 -> 9 chips
        sched = self._sched((("acme", 0.25, -1),))
        pods = [_pod(f"a{i}", tenant="acme") for i in range(20)]
        for p in pods:
            sched.submit(p)
        sched.run_until_idle()
        bound = [p for p in pods if p.phase == PodPhase.BOUND]
        assert len(bound) == 9
        sched.policy.book.refresh()
        assert sched.policy.book.dominant_share("acme") <= 0.25 + 1e-9
        assert sched.metrics.labeled_counter(
            "tenant_quota_rejections_total", {"tenant": "acme"}) > 0

    def test_unquotad_tenant_work_conserving(self):
        sched = self._sched((("acme", 0.25, -1),))
        pods = [_pod(f"b{i}", tenant="beta") for i in range(20)]
        for p in pods:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in pods)

    def test_hierarchical_parent_caps_children(self):
        # parent acme capped at 0.25 (9 chips); children split under it
        sched = self._sched((("acme", 0.25, -1),))
        pods = ([_pod(f"m{i}", tenant="acme/ml") for i in range(8)]
                + [_pod(f"s{i}", tenant="acme/serve") for i in range(8)])
        for p in pods:
            sched.submit(p)
        sched.run_until_idle()
        bound = [p for p in pods if p.phase == PodPhase.BOUND]
        assert len(bound) == 9
        sched.policy.book.refresh()
        assert sched.policy.book.dominant_share("acme") <= 0.25 + 1e-9

    def test_quota_rejection_wakes_on_pod_deleted(self):
        """An over-quota pod re-enters the active queue when capacity
        frees (event-driven requeue through the gate's hints)."""
        sched = self._sched((("acme", 0.25, -1),), rng_seed=3)
        first = [_pod(f"a{i}", tenant="acme") for i in range(9)]
        for p in first:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in first)
        extra = _pod("extra", tenant="acme")
        sched.submit(extra)
        assert sched.run_one() in ("unschedulable", None)
        assert extra.phase == PodPhase.PENDING
        # freeing one acme pod emits POD_DELETED -> the gate hints QUEUE
        sched.cluster.evict(first[0])
        sched.run_until_idle()
        assert extra.phase == PodPhase.BOUND

    def test_gang_gated_on_whole_gang_demand(self):
        """A gang's members hold no cluster-truth usage while parked at
        Permit, so per-member gating would admit each against the same
        headroom and the completed gang would bind past the cap — the
        gate charges the WHOLE gang demand per member instead (review
        finding)."""
        from yoda_scheduler_tpu.telemetry import make_v4_slice

        store = TelemetryStore()
        now = time.time()
        for m in make_v4_slice("s0", "2x2x4"):  # 4 hosts x 4 chips
            m.heartbeat = now
            store.put(m)
        cluster = FakeCluster(store)
        cluster.add_nodes_from_telemetry()
        # 16 chips; acme capped at 0.5 -> 8 chips. A 4x4-chip gang (16
        # chips) must be REJECTED whole, not admitted member-by-member.
        cfg = SchedulerConfig(
            drf_fairness=True, tenant_quotas=(("acme", 0.5, -1),),
            telemetry_max_age_s=1e9, max_attempts=2, gang_timeout_s=0.5)
        sched = Scheduler(cluster, cfg, clock=HybridClock())
        gang = [Pod(f"g{i}", labels={
            "scv/number": "4", "tpu/accelerator": "tpu",
            "scv/tenant": "acme", "tpu/gang-name": "big",
            "tpu/gang-size": "4"}) for i in range(4)]
        for p in gang:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase != PodPhase.BOUND for p in gang)
        sched.policy.book.refresh()
        assert sched.policy.book.dominant_share("acme") == 0.0
        # a 2x4 gang (8 chips == the cap) fits
        small = [Pod(f"s{i}", labels={
            "scv/number": "4", "tpu/accelerator": "tpu",
            "scv/tenant": "acme", "tpu/gang-name": "ok",
            "tpu/gang-size": "2"}) for i in range(2)]
        for p in small:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in small)
        sched.policy.book.refresh()
        assert sched.policy.book.dominant_share("acme") <= 0.5 + 1e-9

    def test_concurrent_gangs_cannot_share_headroom(self):
        """Two same-tenant gangs racing through Permit: the first
        admitted gang holds an engine-local in-flight claim, so the
        second is gated against headroom that already accounts for it
        (review finding) — exactly one binds under a cap that fits one."""
        from yoda_scheduler_tpu.telemetry import make_v4_slice

        store = TelemetryStore()
        now = time.time()
        for s in ("s0", "s1"):
            for m in make_v4_slice(s, "2x2x4"):
                m.heartbeat = now
                store.put(m)
        cluster = FakeCluster(store)
        cluster.add_nodes_from_telemetry()
        # 32 chips; acme capped at 0.25 -> 8 chips: ONE 2x4-chip gang
        cfg = SchedulerConfig(
            drf_fairness=True, tenant_quotas=(("acme", 0.25, -1),),
            telemetry_max_age_s=1e9, max_attempts=2, gang_timeout_s=0.5)
        sched = Scheduler(cluster, cfg, clock=HybridClock())
        gangs = []
        for g in ("g1", "g2"):
            gangs.append([Pod(f"{g}m{i}", labels={
                "scv/number": "4", "tpu/accelerator": "tpu",
                "scv/tenant": "acme", "tpu/gang-name": g,
                "tpu/gang-size": "2"}) for i in range(2)])
        # interleave members so both gangs are in flight together
        for a, b in zip(*gangs):
            sched.submit(a)
            sched.submit(b)
        sched.run_until_idle()
        bound_gangs = sum(
            all(p.phase == PodPhase.BOUND for p in g) for g in gangs)
        assert bound_gangs == 1, [(p.name, p.phase) for g in gangs
                                  for p in g]
        sched.policy.book.refresh()
        assert sched.policy.book.dominant_share("acme") <= 0.25 + 1e-9

    def test_unquotad_gang_records_no_inflight_claim(self):
        """With no positive quota on the tenant's path the in-flight
        ledger is never consulted — recording claims there would leak
        unboundedly under churning never-binding gangs (review
        finding)."""
        cfg = SchedulerConfig(drf_fairness=True, telemetry_max_age_s=1e9,
                              max_attempts=2, gang_timeout_s=0.2)
        sched = Scheduler(_cluster(v4=1, v5e=0), cfg, clock=HybridClock())
        # an unsatisfiable gang (needs 3 hosts; fleet has 1): never binds
        gang = [Pod(f"g{i}", labels={
            "scv/number": "4", "tpu/accelerator": "tpu",
            "scv/tenant": "acme", "tpu/gang-name": "doomed",
            "tpu/gang-size": "3"}) for i in range(3)]
        for p in gang:
            sched.submit(p)
        sched.run_until_idle()
        assert sched.policy._gang_inflight == {}

    def test_gate_equivalence_contract(self):
        sched = self._sched((("acme", 0.25, -1),))
        gate = next(p for p in sched.profile.pre_filter
                    if isinstance(p, TenantQuotaGate))
        assert gate.equivalence_key(_pod("q", tenant="acme")) is NO_BATCH
        assert gate.equivalence_key(_pod("q2", tenant="acme/ml")) is NO_BATCH
        assert gate.equivalence_key(_pod("f", tenant="free")) == ("free",)


# ------------------------------------------------------------ fairness sort
class TestFairnessSort:
    def test_lower_share_tenant_pops_first(self):
        cluster = _cluster(v4=2, v5e=0)  # 8 chips
        cfg = SchedulerConfig(drf_fairness=True, telemetry_max_age_s=1e9,
                              max_attempts=3)
        sched = Scheduler(cluster, cfg, clock=HybridClock())
        # give tenant "rich" a head start of 3 bound chips
        for i in range(3):
            cluster.bind(_pod(f"pre{i}", tenant="rich"), "v4-0",
                         [(i % 2, i // 2, 0)])
        sched.policy.book.refresh()
        rich = _pod("rich-pod", tenant="rich")
        poor = _pod("poor-pod", tenant="poor")
        sched.submit(rich)  # submitted FIRST: FIFO would pop it first
        sched.submit(poor)
        assert sched.run_one() == "bound"
        assert poor.phase == PodPhase.BOUND
        assert rich.phase == PodPhase.PENDING

    def test_priority_still_strictly_first(self):
        cluster = _cluster(v4=2, v5e=0)
        cfg = SchedulerConfig(drf_fairness=True, telemetry_max_age_s=1e9,
                              max_attempts=3)
        sched = Scheduler(cluster, cfg, clock=HybridClock())
        for i in range(3):
            cluster.bind(_pod(f"pre{i}", tenant="rich"), "v4-0",
                         [(i % 2, i // 2, 0)])
        sched.policy.book.refresh()
        hi = _pod("hi", tenant="rich", prio=9)
        lo = _pod("lo", tenant="poor", prio=1)
        sched.submit(lo)
        sched.submit(hi)
        assert sched.run_one() == "bound"
        assert hi.phase == PodPhase.BOUND  # priority beats share

    def test_sort_equivalence_carries_tenant(self):
        cfg = SchedulerConfig(drf_fairness=True, telemetry_max_age_s=1e9)
        sched = Scheduler(_cluster(), cfg, clock=HybridClock())
        srt = sched.profile.queue_sort
        assert isinstance(srt, TenantFairnessSort)
        assert srt.equivalence_key(_pod("a", tenant="x")) == ("x",)
        ns_pod = Pod("n", labels={"scv/number": "1"}, namespace="teamns")
        assert srt.equivalence_key(ns_pod) == ("teamns",)


# ------------------------------------------------------------ budgets
class TestPreemptionBudgets:
    def _preempt_rig(self, budget, window_s=0.0):
        """2 nodes fully packed with low-prio 'victim' tenant pods; the
        high-prio tenant then preempts its way in."""
        cluster = _cluster(v4=2, v5e=0)  # 2 nodes x 4 chips
        cfg = SchedulerConfig(
            drf_fairness=True,
            tenant_quotas=(("victims", 0.0, budget),),
            preemption_budget_window_s=window_s,
            telemetry_max_age_s=1e9, max_attempts=2)
        sched = Scheduler(cluster, cfg, clock=HybridClock())
        low = [_pod(f"low{i}", tenant="victims", prio=1) for i in range(8)]
        for p in low:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in low)
        return cluster, sched, low

    def test_budget_never_exceeded(self):
        _, sched, _ = self._preempt_rig(budget=2)
        highs = [_pod(f"hi{i}", tenant="vip", prio=9) for i in range(5)]
        for p in highs:
            sched.submit(p)
        sched.run_until_idle()
        evicted = sched.metrics.labeled_counter(
            "preemption_victims_total", {"tenant": "victims"})
        assert evicted == 2  # the budget, exactly
        # with the planner's route-around predicate, an exhausted
        # budget means NO plan is even proposed (pods drop out of the
        # victim pools) — the preemptors beyond the budget resolve as
        # ordinary unschedulable failures, and the whole-plan denial
        # counter only fires for multi-victim overdraws
        # (test_plan_all_or_nothing pins that side)
        assert sum(p.phase == PodPhase.BOUND for p in highs) == 2
        assert all(p.phase in (PodPhase.BOUND, PodPhase.FAILED)
                   for p in highs)
        now = sched.clock.time()
        assert sched.policy.budgets.spent("victims", now) <= 2

    def test_unlimited_budget_keeps_preempting(self):
        _, sched, _ = self._preempt_rig(budget=-1)
        highs = [_pod(f"hi{i}", tenant="vip", prio=9) for i in range(3)]
        for p in highs:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in highs)
        assert sched.metrics.labeled_counter(
            "preemption_victims_total", {"tenant": "victims"}) >= 3

    def test_planner_routes_around_exhausted_budget(self):
        """A tenant with zero remaining budget contributes no victims:
        the planner picks an admissible plan on another node instead of
        proposing one the whole-plan gate must refuse (review finding)."""
        cluster = _cluster(v4=2, v5e=0)  # v4-0, v4-1: 4 chips each
        cfg = SchedulerConfig(
            drf_fairness=True,
            tenant_quotas=(("frozen", 0.0, 0),),  # budget ZERO
            preemption_budget_window_s=0.0,
            telemetry_max_age_s=1e9, max_attempts=2)
        sched = Scheduler(cluster, cfg, clock=HybridClock())
        frozen = [_pod(f"f{i}", tenant="frozen", prio=1) for i in range(4)]
        soft = [_pod(f"s{i}", tenant="soft", prio=1) for i in range(4)]
        for p in frozen + soft:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in frozen + soft)
        hi = _pod("hi", tenant="vip", prio=9)
        sched.submit(hi)
        sched.run_until_idle()
        assert hi.phase == PodPhase.BOUND
        # every victim came from the budget-unlimited tenant
        assert all(p.phase == PodPhase.BOUND for p in frozen)
        assert sched.metrics.labeled_counter(
            "preemption_victims_total", {"tenant": "frozen"}) == 0
        assert sched.metrics.labeled_counter(
            "preemption_victims_total", {"tenant": "soft"}) >= 1

    def test_window_refills(self):
        quotas = {"t": TenantQuota("t", preemption_budget=1)}
        b = PreemptionBudgets(quotas, window_s=10.0)
        v = _pod("v", tenant="t")
        assert b.admits([v], now=0.0)
        b.charge([v], now=0.0)
        assert not b.admits([v], now=5.0)
        assert b.admits([v], now=11.0)  # window rolled past the charge

    def test_plan_all_or_nothing(self):
        quotas = {"t": TenantQuota("t", preemption_budget=1)}
        b = PreemptionBudgets(quotas, window_s=0.0)
        vs = [_pod("v1", tenant="t"), _pod("v2", tenant="t")]
        assert not b.admits(vs, now=0.0)  # 2 victims > budget 1
        assert b.spent("t", 0.0) == 0     # nothing half-charged

    def test_pdbs_still_honored_with_budgets(self):
        """Budgets layer ON TOP of the PDB ledger: within budget, the
        planner still prefers victims that violate no PDB."""
        from yoda_scheduler_tpu.utils.pdb import DisruptionBudget

        cluster = _cluster(v4=2, v5e=0)
        cfg = SchedulerConfig(
            drf_fairness=True,
            tenant_quotas=(("victims", 0.0, 4),),
            preemption_budget_window_s=0.0,
            telemetry_max_age_s=1e9, max_attempts=2, rng_seed=5)
        sched = Scheduler(cluster, cfg, clock=HybridClock())
        low = []
        for i in range(8):
            p = _pod(f"low{i}", tenant="victims", prio=1)
            if i < 4:
                p.labels["app"] = "protected"
            low.append(p)
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in low)
        cluster.set_pdbs([DisruptionBudget(
            name="protect", namespace="default",
            match_labels=frozenset({("app", "protected")}),
            max_unavailable=0)])
        hi = _pod("hi", tenant="vip", prio=9)
        sched.submit(hi)
        sched.run_until_idle()
        assert hi.phase == PodPhase.BOUND
        evicted = [p for p in low if p.phase != PodPhase.BOUND]
        assert evicted and all(
            p.labels.get("app") != "protected" for p in evicted), \
            [(p.name, p.labels.get("app")) for p in evicted]


# ------------------------------------------------------------ starvation
class TestStarvation:
    def test_trip_recorded_once_per_pod(self):
        cfg = SchedulerConfig(
            drf_fairness=True, tenant_quotas=(("acme", 0.01, -1),),
            starvation_after_s=5.0, telemetry_max_age_s=1e9,
            max_attempts=0)
        sched = Scheduler(_cluster(), cfg, clock=FakeClock())
        p = _pod("starving", tenant="acme", chips=4)
        sched.submit(p)
        for _ in range(6):
            sched.run_one()
            sched.clock.advance(3.0)
        assert sched.metrics.labeled_counter(
            "tenant_starvation_trips_total", {"tenant": "acme"}) == 1
        trips = [e for e in sched.flight.snapshot()
                 if e["kind"] == "tenant_starvation"]
        assert len(trips) == 1
        assert trips[0]["pod"] == p.key


# ------------------------------------------------------- default parity
class TestPolicyOffParity:
    def _trace(self, cfg):
        sched = Scheduler(_cluster(gpu=2), cfg, clock=HybridClock())
        rng = random.Random(7)
        pods = []
        for i in range(40):
            roll = rng.random()
            if roll < 0.5:
                pods.append(_pod(f"p{i}", chips=rng.choice((1, 2))))
            elif roll < 0.8:
                pods.append(_pod(f"p{i}", mem=rng.choice((4000, 16000))))
            else:
                pods.append(Pod(f"p{i}", labels={
                    "tpu/accelerator": "gpu", "scv/number": "1"}))
        for p in pods:
            sched.submit(p)
        sched.run_until_idle()
        return [(p.name, p.node, p.labels.get("tpu/assigned-chips"))
                for p in pods]

    def test_unset_objective_bit_identical(self):
        """With policyObjective unset and no tenants, placements are
        bit-identical to the pre-policy default (the acceptance
        criterion CI re-proves on the tier-1 leg)."""
        base = self._trace(SchedulerConfig(
            telemetry_max_age_s=1e9, max_attempts=3))
        explicit_off = self._trace(SchedulerConfig(
            telemetry_max_age_s=1e9, max_attempts=3,
            policy_objective="", drf_fairness=False, tenant_quotas=()))
        roundtrip = self._trace(SchedulerConfig.from_profile({
            "schedulerName": "yoda-scheduler",
            "pluginConfig": [{"name": "yoda-tpu", "args": {
                "telemetryMaxAgeSeconds": 1e9}}],
        }).with_(max_attempts=3))
        assert base == explicit_off == roundtrip

    def test_bad_objective_rejected_at_load(self):
        with pytest.raises(ValueError):
            SchedulerConfig.from_profile({
                "pluginConfig": [{"name": "yoda-tpu", "args": {
                    "policyObjective": "makspan"}}]})

    def test_config_roundtrip_parses_policy_block(self):
        cfg = SchedulerConfig.from_profile({
            "pluginConfig": [{"name": "yoda-tpu", "args": {
                "policyObjective": "finish-time-fairness",
                "heterogeneityWeight": 7,
                "workloadClasses": {"train": {"v4": 1.0, "v5e": 1.8}},
                "drfFairness": True,
                "tenants": {"acme": {"quota": 0.5,
                                     "preemptionBudget": 3},
                            "acme/ml": {"quota": 0.25}},
                "preemptionBudgetWindowSeconds": 120,
                "starvationAfterSeconds": 600,
            }}]})
        assert cfg.policy_objective == "finish-time-fairness"
        assert cfg.heterogeneity_weight == 7
        assert dict(cfg.workload_classes)["train"] == (
            ("v4", 1.0), ("v5e", 1.8))
        assert cfg.drf_fairness
        assert ("acme", 0.5, 3) in cfg.tenant_quotas
        assert ("acme/ml", 0.25, -1) in cfg.tenant_quotas
        assert cfg.preemption_budget_window_s == 120
        assert cfg.starvation_after_s == 600


# ------------------------------------------------------------ batching
class TestCrossTenantBatch:
    def test_pop_batch_never_mixes_tenants(self):
        """Two unquota'd tenants, identical specs: the equivalence keys
        carry the tenant, so a batch gather stays within one tenant."""
        cfg = SchedulerConfig(drf_fairness=True, telemetry_max_age_s=1e9,
                              batch_max_pods=32, max_attempts=3)
        sched = Scheduler(_cluster(), cfg, clock=HybridClock())
        for i in range(8):
            sched.submit(_pod(f"a{i}", tenant="alpha"))
            sched.submit(_pod(f"b{i}", tenant="beta"))
        batch = sched.queue.pop_batch(now=sched.clock.time(), max_pods=32)
        assert len(batch) > 1, "same-tenant classmates should batch"
        tenants = {tenant_of(i.pod) for i in batch}
        assert len(tenants) == 1, tenants

    def test_batched_vs_perpod_outcomes_with_policy(self):
        """Cross-tenant batch soundness (ISSUE 9 satellite, amended by
        ISSUE 13): with exact-at-pop DRF, per-pod cycles re-read shares
        after EVERY bind while a batch advances one tenant's classmates
        together — so inter-tenant interleaving (and with it exact node
        assignment) may differ by up to batchMaxPods, the same
        batch-granularity fairness trade PR 3 documents for priority
        bands. (Under PR 9's entry-time sampling both modes froze the
        order at submit, which is exactly the staleness ISSUE 13
        deletes.) What must hold in both modes: every pod binds, no
        tenant's total moves, and quota'd tenants still never batch
        (the gate's NO_BATCH keeps caps exact)."""
        def run(batch_max):
            cfg = SchedulerConfig(
                policy_objective="makespan", drf_fairness=True,
                workload_classes=(("t", (("v5e", 1.9), ("v4", 1.0))),),
                batch_max_pods=batch_max, telemetry_max_age_s=1e9,
                max_attempts=3)
            sched = Scheduler(_cluster(), cfg, clock=HybridClock())
            pods = []
            for t in ("alpha", "beta"):
                for i in range(10):
                    pods.append(_pod(f"{t}{i}", tenant=t, wclass="t"))
            for p in pods:
                sched.submit(p)
            sched.run_until_idle()
            return pods

        batched, perpod = run(32), run(1)
        for pods in (batched, perpod):
            assert all(p.node is not None for p in pods)

        def per_tenant(pods):
            out = {}
            for p in pods:
                out[tenant_of(p)] = out.get(tenant_of(p), 0) + 1
            return out

        assert per_tenant(batched) == per_tenant(perpod)

    def test_quotad_tenant_never_batches(self):
        cfg = SchedulerConfig(
            drf_fairness=True, tenant_quotas=(("capped", 0.5, -1),),
            batch_max_pods=32, telemetry_max_age_s=1e9, max_attempts=3)
        sched = Scheduler(_cluster(), cfg, clock=HybridClock())
        for i in range(6):
            sched.submit(_pod(f"c{i}", tenant="capped"))
        batch = sched.queue.pop_batch(now=sched.clock.time(), max_pods=32)
        assert len(batch) == 1  # the quota gate votes NO_BATCH

    def test_finish_time_fairness_scorer_never_batches(self):
        het = HeterogeneityScore(ThroughputModel(), "finish-time-fairness")
        assert het.equivalence_key(_pod("x")) is NO_BATCH
        het2 = HeterogeneityScore(ThroughputModel(), "makespan")
        assert het2.equivalence_key(_pod("x")) == ()


# ------------------------------------------------------------ fleet
class TestFleetDRF:
    @pytest.mark.slow
    def test_replica_books_agree_with_cluster_truth(self):
        """Shared DRF accounting under optimistic multi-replica commits:
        each replica's book reads cluster truth, so after a contended
        drain (409s resolved) every book reports identical shares — and
        they equal a fresh book built from the final cluster state."""
        from yoda_scheduler_tpu.scheduler.fleet import FleetCoordinator

        cluster = _cluster(v4=6, v5e=6)
        cfg = SchedulerConfig(
            drf_fairness=True, tenant_quotas=(("acme", 0.5, -1),),
            telemetry_max_age_s=1e9, max_attempts=4,
            fleet_replicas=2, fleet_mode="free-for-all")
        fleet = FleetCoordinator(cluster, cfg, clock=HybridClock())
        pods = [_pod(f"p{i}", tenant=("acme" if i % 2 else "beta"))
                for i in range(40)]
        for p in pods:
            fleet.submit(p)
        fleet.run_until_idle()
        truth = DRFBook(cluster)
        truth.refresh()
        for rep in fleet.replicas:
            book = rep.engine.policy.book
            book.refresh()
            for t in ("acme", "beta"):
                assert book.dominant_share(t) == pytest.approx(
                    truth.dominant_share(t))
        # the quota held fleet-wide, not per replica
        assert truth.dominant_share("acme") <= 0.5 + 1e-9


# ------------------------------------------------------------ registry
class TestRegistryWiring:
    def test_policy_plugins_buildable_by_name(self):
        cfg = SchedulerConfig(drf_fairness=True,
                              policy_objective="makespan",
                              telemetry_max_age_s=1e9)
        enabled = merge_enablement({
            "queueSort": {"enabled": [{"name": "tenant-fairness-sort"}],
                          "disabled": [{"name": "priority-sort"}]},
            "preFilter": {"enabled": [{"name": "tenant-quota-gate"}]},
            "score": {"enabled": [{"name": "heterogeneity-score"}]},
        })
        profile = build_profile(cfg, enabled)
        assert isinstance(profile.queue_sort, TenantFairnessSort)
        assert any(isinstance(p, TenantQuotaGate)
                   for p in profile.pre_filter)
        het = [p for p in profile.score
               if isinstance(p, HeterogeneityScore)]
        assert len(het) == 1
        assert profile.policy is not None
        # the three share ONE policy engine (one DRF book)
        assert profile.queue_sort.policy is profile.policy
        assert het[0].policy is profile.policy

    def test_knobs_enforce_through_a_plugins_block(self):
        """The shipped ConfigMap carries a `plugins:` block, which
        routes profile assembly through build_profile instead of
        default_profile — the policy KNOBS must wire the plugins in
        there too, or drfFairness/policyObjective would silently build
        an engine nothing consults (review finding)."""
        cfg = SchedulerConfig(
            drf_fairness=True, tenant_quotas=(("acme", 0.5, -1),),
            policy_objective="makespan", telemetry_max_age_s=1e9)
        # the default enablement, as merge_enablement produces it for a
        # config.yaml that names only the stock plugins
        profile = build_profile(cfg, merge_enablement({}))
        assert isinstance(profile.queue_sort, TenantFairnessSort)
        assert any(isinstance(p, TenantQuotaGate)
                   for p in profile.pre_filter)
        assert any(isinstance(p, HeterogeneityScore)
                   for p in profile.score)
        assert profile.policy is not None
        # ...and a custom queue sort explicitly enabled is NOT stomped
        profile2 = build_profile(cfg, merge_enablement({
            "queueSort": {"enabled": [{"name": "tenant-fairness-sort"}],
                          "disabled": [{"name": "*"}]}}))
        assert isinstance(profile2.queue_sort, TenantFairnessSort)

    def test_enabling_policy_plugin_without_knobs_builds_engine(self):
        cfg = SchedulerConfig(telemetry_max_age_s=1e9)
        enabled = merge_enablement({
            "preFilter": {"enabled": [{"name": "tenant-quota-gate"}]}})
        profile = build_profile(cfg, enabled)
        assert profile.policy is not None

    def test_metrics_exposition_includes_tenant_series(self):
        cfg = SchedulerConfig(
            drf_fairness=True, tenant_quotas=(("acme", 0.9, -1),),
            telemetry_max_age_s=1e9, max_attempts=2)
        sched = Scheduler(_cluster(), cfg, clock=HybridClock())
        for i in range(4):
            sched.submit(_pod(f"p{i}", tenant="acme"))
        sched.run_until_idle()
        text = sched.metrics.render_prometheus()
        assert 'tenant_dominant_share{tenant="acme"}' in text
        assert "# HELP yoda_tpu_tenant_dominant_share" in text
