"""Lease-based leader election + per-shard scheduling leases.

The reference inherits leader election from upstream kube-scheduler,
configured lease 15s / renew 10s / retry 2s (reference
deploy/yoda-scheduler.yaml:10-17). Native equivalent over the
coordination.k8s.io/v1 Lease API with the same timing defaults, injectable
clock + client so the state machine is unit-testable without a cluster.

Fleet extension (scheduler/fleet.py): instead of one leader and idle
standbys, node-pool SHARDS map to leases (``yoda-shard-<i>``) and every
replica schedules concurrently, holding the leases for its shards. Two
mechanisms make that safe:

- **fencing epochs**: every lease carries ``leaseTransitions``, bumped on
  each change of holder. A bind carries ``(lease, holder, transitions)``
  as its fencing token and the authority (fake_apiserver /
  FakeCluster.lease_authority) rejects commits whose token is stale —
  a replica that lost its lease mid-bind (split-brain, GC pause past the
  lease duration) cannot silently write.
- **sub-second renewal**: the Lease API's ``leaseDurationSeconds`` is an
  integer — PR 4 noted sub-second configs truncated to 0 (= instantly
  expired). Durations now serialize as ``ceil`` (never 0) and the exact
  float rides a ``yodaDurationMs`` spec extension that this module's own
  expiry checks prefer (a real apiserver drops the unknown field, leaving
  the integer ceiling — strictly safer, never looser). Renewal retries
  are jittered 0.5-1.5x so a replica fleet doesn't thundering-herd the
  Lease objects.
"""

from __future__ import annotations

import logging
import math
import random
import socket
import threading
import time
import uuid

log = logging.getLogger("yoda-tpu.le")

LEASE_PATH = ("/apis/coordination.k8s.io/v1/namespaces/{ns}/leases/{name}")
SHARD_LEASE_PREFIX = "yoda-shard-"
# replica liveness heartbeats (dynamic shard rebalancing): each fleet
# replica renews `yoda-replica-<idx>` alongside its shard leases. A
# replica holding a foreign shard (crash takeover) watches the PREFERRED
# owner's heartbeat and hands the shard back the moment that replica is
# provably alive again — without this, takeover ownership was sticky
# forever. A distinct prefix so a heartbeat can never be mistaken for a
# fencing lease.
REPLICA_HB_PREFIX = "yoda-replica-"


def _duration_fields(duration_s: float) -> dict:
    """Serialize a float lease duration: integer-second API field (ceil,
    never the 0 a truncation produced) + the exact float extension."""
    return {
        "leaseDurationSeconds": max(int(math.ceil(duration_s)), 1),
        "yodaDurationMs": int(duration_s * 1000),
    }


def _duration_of(spec: dict, default_s: float) -> float:
    ms = spec.get("yodaDurationMs")
    if ms is not None:
        try:
            return float(ms) / 1000.0
        except (TypeError, ValueError):
            pass
    return float(spec.get("leaseDurationSeconds", default_s))


class LeaderElector:
    def __init__(self, client, name: str = "yoda-tpu-scheduler",
                 namespace: str = "kube-system",
                 lease_duration_s: float = 15.0,
                 renew_deadline_s: float = 10.0,
                 retry_period_s: float = 2.0,
                 identity: str | None = None,
                 clock=time) -> None:
        self.client = client
        self.path = LEASE_PATH.format(ns=namespace, name=name)
        self.name = name
        self.namespace = namespace
        self.lease_duration_s = lease_duration_s
        self.renew_deadline_s = renew_deadline_s
        self.retry_period_s = retry_period_s
        self.identity = identity or f"{socket.gethostname()}_{uuid.uuid4().hex[:8]}"
        self.clock = clock
        self.is_leader = False
        # fencing epoch: the lease's leaseTransitions while we hold it —
        # carried on binds so a stale ex-leader's commits are rejectable
        self.transitions = 0
        self._rng = random.Random()

    # ------------------------------------------------------------ lease CRUD
    def _get(self) -> dict | None:
        try:
            return self.client.request("GET", self.path)
        except Exception:
            return None

    def _create(self) -> bool:
        body = {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": self._spec(1),
        }
        try:
            self.client.request(
                "POST",
                f"/apis/coordination.k8s.io/v1/namespaces/{self.namespace}/leases",
                body)
            self.transitions = 1
            return True
        except Exception:
            return False

    def _update(self, lease: dict, transitions: int) -> bool:
        lease = dict(lease)
        lease["spec"] = self._spec(transitions)
        try:
            self.client.request("PUT", self.path, lease)
            self.transitions = transitions
            return True
        except Exception:
            return False

    def _spec(self, transitions: int) -> dict:
        now = self.clock.time()
        return {
            "holderIdentity": self.identity,
            **_duration_fields(self.lease_duration_s),
            "renewTime": _micro_time(now),
            "acquireTime": _micro_time(now),
            # bumped on every change of HOLDER (client-go semantics):
            # the fencing epoch carried on binds
            "leaseTransitions": transitions,
        }

    def fence(self) -> tuple[str, str, int] | None:
        """Fencing token for binds: (lease name, holder, transitions) —
        None while not leading."""
        if not self.is_leader:
            return None
        return (self.name, self.identity, self.transitions)

    # --------------------------------------------------------- state machine
    def try_acquire_or_renew(self) -> bool:
        lease = self._get()
        if lease is None:
            acquired = self._create()
            self.is_leader = acquired
            return acquired
        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity")
        prev_transitions = int(spec.get("leaseTransitions", 0) or 0)
        if holder == self.identity:
            self.is_leader = self._update(lease, prev_transitions or 1)
            return self.is_leader
        renew = _parse_micro_time(spec.get("renewTime"))
        expired = (renew is None or
                   self.clock.time() - renew > _duration_of(
                       spec, self.lease_duration_s))
        # a change of holder bumps the fencing epoch: the previous
        # holder's in-flight binds carry the old transitions count and
        # the authority rejects them
        if expired and self._update(lease, prev_transitions + 1):
            log.info("%s acquired expired lease from %s", self.identity, holder)
            self.is_leader = True
            return True
        self.is_leader = False
        return False

    def _jittered(self, period: float) -> float:
        # 0.5-1.5x: candidate fleets must not retry in lockstep
        return period * self._rng.uniform(0.5, 1.5)

    def run_until_leader(self, stop: threading.Event) -> None:
        """Block until we hold the lease (retry every retry_period_s,
        jittered), then keep renewing in a daemon thread; on renew
        failure, release leadership and set `stop` (the reference
        posture: losing the lease kills the process so a standby takes
        over)."""
        while not stop.is_set() and not self.try_acquire_or_renew():
            stop.wait(self._jittered(self.retry_period_s))
        if stop.is_set():
            return
        log.info("became leader: %s", self.identity)

        def renew_loop():
            # retry every retry_period (jittered); step down only after
            # the renew deadline elapses without ONE success — a single
            # dropped request must not kill the only scheduler replica
            # (client-go semantics, reference deploy/yoda-scheduler.yaml
            # :12-17 timing)
            last_success = self.clock.time()
            while not stop.wait(self._jittered(self.retry_period_s)):
                if self.try_acquire_or_renew():
                    last_success = self.clock.time()
                elif self.clock.time() - last_success > self.renew_deadline_s:
                    log.error("lost leadership (no renew within %.0fs); stopping",
                              self.renew_deadline_s)
                    stop.set()
                    return

        threading.Thread(target=renew_loop, daemon=True).start()


class ShardLeaseManager:
    """Leases-per-shard over the k8s Lease API: the wire twin of
    scheduler/fleet.py's LocalLeaseStore upkeep. A fleet replica owns a
    set of shard leases (``yoda-shard-<i>``), renews them sub-second, and
    carries each shard's fencing token on binds into that shard's nodes.

    ``preferred`` names the shards this replica tries to ACQUIRE when they
    are free or expired (None = any); owned shards are always renewed.
    Lost shards (renew failed: another holder, or the PUT raced a
    takeover's resourceVersion bump) simply leave ``owned`` — the caller's
    fence_provider then aborts the one in-flight commit and schedules the
    shard's pods unfenced/elsewhere. step() is synchronous and cheap; the
    caller decides the cadence (sub-second for sub-second durations)."""

    def __init__(self, client, shard_count: int,
                 identity: str | None = None,
                 namespace: str = "kube-system",
                 prefix: str = SHARD_LEASE_PREFIX,
                 lease_duration_s: float = 1.0,
                 preferred: set[int] | None = None,
                 clock=time, replica_count: int | None = None,
                 replica_idx: int | None = None,
                 rebalance: bool = False) -> None:
        self.client = client
        self.shard_count = shard_count
        self.identity = identity or f"{socket.gethostname()}_{uuid.uuid4().hex[:8]}"
        self.namespace = namespace
        self.prefix = prefix
        self.lease_duration_s = lease_duration_s
        self.preferred = preferred
        self.clock = clock
        self.owned: dict[int, int] = {}  # shard -> transitions epoch
        # dynamic rebalancing (needs the fleet geometry): shard s's
        # preferred owner is replica s % replica_count; this replica
        # heartbeats `yoda-replica-<replica_idx>` and releases foreign
        # shards whose preferred owner's heartbeat is live again
        self.replica_count = replica_count
        self.replica_idx = replica_idx
        self.rebalance = rebalance and replica_count is not None \
            and replica_idx is not None
        # shard -> first instant its lease was observed ABSENT: the
        # orphan guard — a preferrer that died before ever creating its
        # lease must not leave the shard unowned forever
        self._absent_since: dict[int, float] = {}
        # per-step heartbeat-liveness memo (see _hb_live)
        self._hb_memo: dict[int, bool] = {}
        self.rebalance_releases = 0
        self.takeovers = 0

    def _name(self, shard: int) -> str:
        return f"{self.prefix}{shard}"

    def _path(self, shard: int) -> str:
        return LEASE_PATH.format(ns=self.namespace, name=self._name(shard))

    def _spec(self, transitions: int) -> dict:
        now = self.clock.time()
        return {
            "holderIdentity": self.identity,
            **_duration_fields(self.lease_duration_s),
            "renewTime": _micro_time(now),
            "acquireTime": _micro_time(now),
            "leaseTransitions": transitions,
        }

    def fence(self, shard: int) -> tuple[str, str, int] | None:
        epoch = self.owned.get(shard)
        if epoch is None:
            return None
        return (self._name(shard), self.identity, epoch)

    def validate_fence(self, fence: tuple) -> bool:
        """Authority-side check (shared interface with LocalLeaseStore so
        FakeCluster.lease_authority can be either): does the named lease
        still belong to this holder at this epoch?"""
        name, holder, epoch = fence
        try:
            lease = self.client.request(
                "GET", LEASE_PATH.format(ns=self.namespace, name=name))
        except Exception:
            return False
        spec = (lease or {}).get("spec", {})
        return (spec.get("holderIdentity") == holder
                and int(spec.get("leaseTransitions", 0) or 0) == int(epoch))

    def step(self) -> None:
        """One upkeep pass: heartbeat (when rebalancing), renew every
        owned shard (dropping the lost), release foreign shards whose
        preferred owner is alive again, then try to acquire free/expired
        shards this replica prefers — plus provably-orphaned ones."""
        now = self.clock.time()
        self._hb_memo.clear()  # liveness re-read once per pass
        if self.rebalance:
            self._heartbeat()
        for shard in list(self.owned):
            if not self._renew(shard):
                self.owned.pop(shard, None)
                log.warning("%s lost shard lease %d", self.identity, shard)
        if self.rebalance:
            for shard in list(self.owned):
                pref = shard % self.replica_count
                if pref == self.replica_idx:
                    continue
                if self._hb_live(pref):
                    # the preferred owner is provably back: hand the
                    # shard over (epoch retired so our in-flight fences
                    # die with it) instead of staying sticky forever
                    if self._release(shard):
                        self.owned.pop(shard, None)
                        self.rebalance_releases += 1
                        log.info("%s released shard %d to replica %d",
                                 self.identity, shard, pref)
        for shard in range(self.shard_count):
            if shard in self.owned:
                continue
            if self.preferred is not None and shard not in self.preferred:
                if self.rebalance and self._hb_live(
                        shard % self.replica_count):
                    # alive preferrer: the shard is theirs to (re)take —
                    # acquiring it would instantly undo a rebalance
                    # release (see ShardLeaseManager step docstring)
                    self._absent_since.pop(shard, None)
                    continue
                lease = self._get(shard)
                if lease is None:
                    # absent: leave it to its preferrer — unless
                    # rebalancing is on AND it has stayed absent past a
                    # full lease duration (the preferrer died before
                    # ever creating it): the orphan guard claims it.
                    # Without rebalancing there is no handback either,
                    # so claiming here would permanently rob a peer
                    # that merely started late.
                    first = self._absent_since.setdefault(shard, now)
                    if not self.rebalance \
                            or now - first <= self.lease_duration_s:
                        continue
                else:
                    self._absent_since.pop(shard, None)
                    # non-preferred shards are only taken over once
                    # their holder has provably expired (crash takeover)
                    if not self._lease_expired(lease):
                        continue
            if self._acquire(shard):
                self._absent_since.pop(shard, None)
                if self.owned.get(shard, 1) > 1:
                    self.takeovers += 1

    # ------------------------------------------------------------- internals
    def _get(self, shard: int) -> dict | None:
        try:
            return self.client.request("GET", self._path(shard))
        except Exception:
            return None

    def _lease_expired(self, lease: dict) -> bool:
        spec = lease.get("spec", {})
        renew = _parse_micro_time(spec.get("renewTime"))
        return (renew is None or self.clock.time() - renew >
                _duration_of(spec, self.lease_duration_s))

    def _expired(self, shard: int) -> bool:
        lease = self._get(shard)
        if lease is None:
            return False  # absent = never owned; leave it to its preferrer
        return self._lease_expired(lease)

    # ------------------------------------------- heartbeats + rebalancing
    def _hb_path(self, idx: int) -> str:
        return LEASE_PATH.format(ns=self.namespace,
                                 name=f"{REPLICA_HB_PREFIX}{idx}")

    def _hb_live(self, idx: int) -> bool:
        """Is replica `idx` provably alive (its heartbeat lease held and
        unexpired)? Identity is NOT checked: any incarnation serving the
        index counts — the handoff goes to the slot, not the process.
        Memoized per step() pass: `shard % replica_count` takes at most
        replica_count distinct values, so without the memo a 32-shard
        fleet would re-GET the same one or two heartbeat leases once per
        shard per upkeep tick."""
        memo = self._hb_memo
        if idx in memo:
            return memo[idx]
        try:
            lease = self.client.request("GET", self._hb_path(idx),
                                        timeout=3.0, retries=0)
        except Exception:
            memo[idx] = False
            return False
        spec = (lease or {}).get("spec", {}) or {}
        memo[idx] = bool(spec.get("holderIdentity")) \
            and not self._lease_expired(lease)
        return memo[idx]

    def _heartbeat(self) -> None:
        """Acquire-or-renew this replica's own liveness lease. A fresh
        incarnation waits out the dead one's remaining duration (the
        conservative read: liveness must never be claimable early)."""
        try:
            lease = self.client.request(
                "GET", self._hb_path(self.replica_idx),
                timeout=3.0, retries=0)
        except Exception:
            lease = None
        if lease is None:
            body = {"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                    "metadata": {
                        "name": f"{REPLICA_HB_PREFIX}{self.replica_idx}",
                        "namespace": self.namespace},
                    "spec": self._spec(1)}
            try:
                self.client.request(
                    "POST",
                    f"/apis/coordination.k8s.io/v1/namespaces/"
                    f"{self.namespace}/leases", body)
            except Exception:
                pass
            return
        spec = lease.get("spec", {})
        if spec.get("holderIdentity") != self.identity \
                and not self._lease_expired(lease):
            return  # a live foreign incarnation still owns the slot
        transitions = int(spec.get("leaseTransitions", 0) or 0)
        if spec.get("holderIdentity") != self.identity:
            transitions += 1
        lease = dict(lease)
        lease["spec"] = self._spec(max(transitions, 1))
        try:
            self.client.request("PUT", self._hb_path(self.replica_idx),
                                lease)
        except Exception:
            pass

    def _release(self, shard: int) -> bool:
        """Voluntarily give a shard up: holder cleared, epoch bumped (our
        in-flight fencing tokens are stale from this instant), renewTime
        cleared so the next claimant sees it immediately acquirable."""
        lease = self._get(shard)
        if lease is None:
            return False
        spec = lease.get("spec", {})
        if spec.get("holderIdentity") != self.identity or int(
                spec.get("leaseTransitions", 0) or 0) != self.owned.get(
                    shard):
            return False  # already taken over: nothing of ours to release
        lease = dict(lease)
        lease["spec"] = {
            "holderIdentity": None,
            **_duration_fields(self.lease_duration_s),
            "renewTime": None, "acquireTime": None,
            "leaseTransitions": int(spec.get("leaseTransitions", 0) or 0)
            + 1,
        }
        try:
            self.client.request("PUT", self._path(shard), lease)
            return True
        except Exception:
            return False

    def _renew(self, shard: int) -> bool:
        lease = self._get(shard)
        if lease is None:
            return False
        spec = lease.get("spec", {})
        if spec.get("holderIdentity") != self.identity or int(
                spec.get("leaseTransitions", 0) or 0) != self.owned[shard]:
            return False  # taken over: our epoch is history
        lease = dict(lease)
        lease["spec"] = self._spec(self.owned[shard])
        try:
            self.client.request("PUT", self._path(shard), lease)
            return True
        except Exception:
            return False

    def _acquire(self, shard: int) -> bool:
        lease = self._get(shard)
        if lease is None:
            body = {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "metadata": {"name": self._name(shard),
                             "namespace": self.namespace},
                "spec": self._spec(1),
            }
            try:
                self.client.request(
                    "POST",
                    f"/apis/coordination.k8s.io/v1/namespaces/"
                    f"{self.namespace}/leases", body)
                self.owned[shard] = 1
                return True
            except Exception:
                return False
        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity")
        renew = _parse_micro_time(spec.get("renewTime"))
        expired = (renew is None or self.clock.time() - renew >
                   _duration_of(spec, self.lease_duration_s))
        if holder != self.identity and not expired:
            return False
        transitions = int(spec.get("leaseTransitions", 0) or 0)
        if holder != self.identity:
            transitions += 1  # change of holder = fencing epoch bump
        lease = dict(lease)
        lease["spec"] = self._spec(max(transitions, 1))
        try:
            # the resourceVersion-conditional PUT is the tie-break: two
            # racing claimants of an expired lease are serialized by the
            # apiserver's optimistic concurrency (loser gets 409)
            self.client.request("PUT", self._path(shard), lease)
            self.owned[shard] = max(transitions, 1)
            return True
        except Exception:
            return False


def _micro_time(t: float) -> str:
    from datetime import datetime, timezone

    return datetime.fromtimestamp(t, timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%f") + "Z"


def _parse_micro_time(s: str | None) -> float | None:
    if not s:
        return None
    from datetime import datetime, timezone

    try:
        return datetime.strptime(
            s.replace("Z", ""), "%Y-%m-%dT%H:%M:%S.%f").replace(
                tzinfo=timezone.utc).timestamp()
    except ValueError:
        return None
