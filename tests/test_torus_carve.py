"""Geometric torus placement (topology/carve.py + scheduler wiring).

Three layers under test:

- carve arithmetic: wraparound origins, full-ring bisection doubling,
  degenerate 1xN axes, occupied-corner rotation — against the scalar
  reference plane;
- plane parity: scalar / numpy / native must be op-for-op bit-identical
  (the placement.cc discipline) across a randomized fuzz, and the pure-
  Python largest_carvable must agree with the native kernel;
- scheduler integration: the torusPlacement knob (default OFF, env
  YODA_TORUS), carve-narrowed gang placement landing contiguous blocks,
  multi-slice partitions, the advisory safety valve, the geometric
  FragmentationScore term, descheduler torus reassembly, provisioner
  slice drain, add_pool topology validation, and columnar host coords.
"""

from __future__ import annotations

import random
import time

import pytest

from yoda_scheduler_tpu.topology import carve as C
from yoda_scheduler_tpu.topology import carvenative
from yoda_scheduler_tpu.topology.carve import (
    bisection_links,
    carve_block,
    host_coord,
    host_grid,
    largest_carvable,
    wrap_of,
)

T0 = 1_000_000.0


def all_cells(grid):
    gx, gy, gz = grid
    return frozenset((x, y, z) for x in range(gx) for y in range(gy)
                     for z in range(gz))


# ------------------------------------------------------------ carve arithmetic
class TestCarveArithmetic:
    def test_host_grid_divides_and_rejects(self):
        assert host_grid((8, 8, 1), (2, 2, 1)) == (4, 4, 1)
        assert host_grid((8, 8, 4), (2, 2, 1)) == (4, 4, 4)
        with pytest.raises(ValueError):
            host_grid((8, 7, 1), (2, 2, 1))

    def test_host_coord_inverts_enumeration(self):
        grid = (4, 4, 2)
        seen = set()
        for i in range(4 * 4 * 2):
            c = host_coord(i, grid)
            assert all(0 <= c[a] < grid[a] for a in range(3))
            seen.add(c)
        assert len(seen) == 32  # bijective over the grid
        # bz outer, by, bx inner — the host_blocks / make_slice order
        assert host_coord(0, grid) == (0, 0, 0)
        assert host_coord(1, grid) == (1, 0, 0)
        assert host_coord(4, grid) == (0, 1, 0)
        assert host_coord(16, grid) == (0, 0, 1)

    def test_wrap_needs_extent_three(self):
        assert wrap_of((4, 3, 1)) == (True, True, False)
        assert wrap_of((2, 2, 2)) == (False, False, False)

    def test_full_ring_carve_doubles_bisection(self):
        grid = (4, 4, 1)
        wrap = wrap_of(grid)
        # a 4x1 ring spans the full wrapped x-axis: its wrap links are
        # internal and cross the same cut -> 1 * 2
        assert bisection_links((4, 1, 1), grid, wrap) == 2
        # a 2x2 block wraps nothing: min cut severs 2 links
        assert bisection_links((2, 2, 1), grid, wrap) == 2
        # a 2x1 line: one link, no doubling (2 < extent 4)
        assert bisection_links((2, 1, 1), grid, wrap) == 1
        # a single host has no internal links
        assert bisection_links((1, 1, 1), grid, wrap) == 0

    def test_wraparound_carve_crosses_the_seam(self):
        """Only the seam-crossing pair is free: a flat grid has no such
        block, the wrapped grid carves it."""
        free = frozenset({(3, 0, 0), (0, 0, 0)})
        flat = carve_block((4, 1, 1), free, 2,
                           wrap=(False, False, False), plane="scalar")
        assert flat is None
        out = carve_block((4, 1, 1), free, 2,
                          wrap=(True, False, False), plane="scalar")
        assert out is not None
        origin, block, coords, links = out
        assert coords == free and block == (2, 1, 1)

    def test_degenerate_1xn_axis(self):
        grid = (1, 5, 1)
        free = all_cells(grid)
        out = carve_block(grid, free, 3, plane="scalar")
        assert out is not None and out[1] == (1, 3, 1)
        # the full 1x5 ring is carvable whole, and its bisection doubles
        whole = carve_block(grid, free, 5, plane="scalar")
        assert whole is not None and whole[3] == 2
        assert largest_carvable(grid, free) == 5

    def test_occupied_corner_rotates_the_carve(self):
        grid = (4, 4, 1)
        free = all_cells(grid) - {(0, 0, 0)}
        out = carve_block(grid, free, 4, wrap=(False, False, False),
                          plane="scalar")
        assert out is not None
        assert (0, 0, 0) not in out[2] and out[2] <= free

    def test_whole_grid_carve(self):
        grid = (2, 2, 2)
        out = carve_block(grid, all_cells(grid), 8, plane="scalar")
        assert out is not None
        assert out[1] == (2, 2, 2) and len(out[2]) == 8

    def test_infeasible_and_degenerate_requests(self):
        grid = (4, 4, 1)
        free = all_cells(grid)
        assert carve_block(grid, free, 0, plane="scalar") is None
        assert carve_block(grid, free, 17, plane="scalar") is None
        assert carve_block(grid, frozenset(), 1, plane="scalar") is None
        assert largest_carvable(grid, frozenset()) == 0

    def test_corner_heuristic_hugs_occupancy(self):
        """Free space is an L; the 2-carve must take the arm tip that
        leaves the rest in one block, never split the corner."""
        grid = (3, 3, 1)
        free = frozenset({(0, 0, 0), (1, 0, 0), (2, 0, 0), (0, 1, 0),
                          (0, 2, 0)})
        out = carve_block(grid, free, 2, wrap=(False, False, False),
                          plane="scalar")
        assert out is not None
        # remaining free space stays carvable at volume 3 (a full arm)
        assert largest_carvable(grid, free - out[2],
                                wrap=(False, False, False)) == 3


# ------------------------------------------------------------------ parity
def random_case(rng):
    grid = (rng.randint(1, 4), rng.randint(1, 4), rng.randint(1, 3))
    cells = sorted(all_cells(grid))
    free = frozenset(c for c in cells if rng.random() < 0.7)
    n = rng.randint(1, len(cells))
    return grid, free, n


class TestPlaneParity:
    def test_scalar_numpy_parity_fuzz(self):
        rng = random.Random(20260807)
        for _ in range(200):
            grid, free, n = random_case(rng)
            s = carve_block(grid, free, n, plane="scalar")
            v = carve_block(grid, free, n, plane="numpy")
            assert s == v, (grid, sorted(free), n, s, v)

    def test_scalar_native_parity_fuzz(self):
        if not carvenative.available():
            pytest.skip("native carve plane not built")
        rng = random.Random(777)
        for _ in range(200):
            grid, free, n = random_case(rng)
            s = carve_block(grid, free, n, plane="scalar")
            nat = carve_block(grid, free, n, plane="native")
            assert nat is not NotImplemented
            assert s == nat, (grid, sorted(free), n, s, nat)

    def test_largest_carvable_native_vs_python(self, monkeypatch):
        if not carvenative.available():
            pytest.skip("native carve plane not built")
        rng = random.Random(42)
        cases = [random_case(rng)[:2] for _ in range(60)]
        native = [carvenative.largest_carvable(g, f, wrap_of(g))
                  for g, f in cases]
        assert NotImplemented not in native
        # force the pure-Python scan: disable the native plane and drop
        # the memo caches that may hold native-computed values
        monkeypatch.setenv("YODA_NO_NATIVE", "1")
        C._native_on.cache_clear()
        C._largest_carvable.cache_clear()
        try:
            py = [largest_carvable(g, f) for g, f in cases]
        finally:
            monkeypatch.delenv("YODA_NO_NATIVE")
            C._native_on.cache_clear()
            C._largest_carvable.cache_clear()
        assert py == native

    def test_fallback_chain_reaches_scalar(self, monkeypatch):
        """With the native plane off, the default chain still carves —
        and identically to the scalar reference."""
        monkeypatch.setenv("YODA_NO_NATIVE", "1")
        C._native_on.cache_clear()
        C._carve_cached.cache_clear()
        try:
            grid = (4, 4, 1)
            free = all_cells(grid) - {(1, 1, 0)}
            assert carve_block(grid, free, 4) \
                == carve_block(grid, free, 4, plane="scalar")
        finally:
            monkeypatch.delenv("YODA_NO_NATIVE")
            C._native_on.cache_clear()
            C._carve_cached.cache_clear()


# ------------------------------------------------------ scheduler integration
from yoda_scheduler_tpu.scheduler import (  # noqa: E402
    FakeCluster, Scheduler, SchedulerConfig)
from yoda_scheduler_tpu.scheduler.core import FakeClock  # noqa: E402
from yoda_scheduler_tpu.scheduler.deschedule import Descheduler  # noqa: E402
from yoda_scheduler_tpu.scheduler.framework import CycleState  # noqa: E402
from yoda_scheduler_tpu.scheduler.plugins import (  # noqa: E402
    FragmentationScore)
from yoda_scheduler_tpu.telemetry import (  # noqa: E402
    TelemetryStore, make_slice, make_tpu_node)
from yoda_scheduler_tpu.utils import Pod, PodPhase  # noqa: E402


def mk(nodes, torus=True, **cfg):
    store = TelemetryStore()
    clock = FakeClock(start=T0)
    for m in nodes:
        m.heartbeat = clock.time()
        store.put(m)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    cfg.setdefault("telemetry_max_age_s", 1e9)
    sched = Scheduler(cluster,
                      SchedulerConfig(torus_placement=torus, **cfg),
                      clock=clock)
    return sched


def gang_pods(name, size, chips=4):
    return [Pod(f"{name}-w{i}", labels={
        "tpu/gang-name": name, "tpu/gang-size": str(size),
        "scv/number": str(chips), "tpu/accelerator": "tpu"})
        for i in range(size)]


def host_coords_of(pods, grid):
    return frozenset(host_coord(int(p.node.rsplit("-host-", 1)[1]), grid)
                     for p in pods)


class TestKnob:
    def test_default_off_env_opt_in(self, monkeypatch):
        monkeypatch.delenv("YODA_TORUS", raising=False)
        assert SchedulerConfig().torus_placement is False
        monkeypatch.setenv("YODA_TORUS", "1")
        assert SchedulerConfig().torus_placement is True

    def test_profile_camelcase_knob(self):
        cfg = SchedulerConfig.from_profile({"pluginConfig": [
            {"name": "yoda-tpu", "args": {"torusPlacement": True}}]})
        assert cfg.torus_placement is True

    def test_off_profile_carries_no_carver(self):
        sched = mk([make_tpu_node("a")], torus=False)
        assert sched.gang_permit.carver is None
        for p in sched.profile.score:
            if isinstance(p, FragmentationScore):
                assert p.carver is None and p.score_inputs == "node"

    def test_on_profile_arms_carver(self):
        sched = mk([make_tpu_node("a")], torus=True)
        assert sched.gang_permit.carver is not None
        armed = [p for p in sched.profile.score
                 if isinstance(p, FragmentationScore)]
        assert armed and all(p.carver is not None
                             and p.score_inputs == "node+slice_usage"
                             for p in armed)

    def test_sliceless_fleet_places_identically_on_and_off(self):
        """On a fleet with no slice geometry the carver never fires:
        every placement must be bit-identical to the knob-off engine."""
        def run(torus):
            nodes = [make_tpu_node(f"n{i}", chips=4) for i in range(6)]
            sched = mk(nodes, torus=torus)
            pods = [Pod(f"p{i}", labels={"scv/number": str(1 + i % 3),
                                         "tpu/accelerator": "tpu"})
                    for i in range(10)]
            pods += gang_pods("g", 3)
            for p in pods:
                sched.submit(p)
            sched.run_until_idle()
            return {p.name: (p.phase, p.node,
                             tuple(sorted(p.assigned_chips())))
                    for p in pods}
        assert run(False) == run(True)


class TestGangCarve:
    def test_single_slice_gang_lands_contiguous_block(self):
        """8x8x1 v4 slice = 4x4x1 host grid. Hosts (1,0) and (2,0) are
        dented by unevictable residents; the carved gang of 4 must land
        as one contiguous block of the remaining free hosts."""
        nodes = make_slice("s1", "8x8x1", generation="v4")
        sched = mk(nodes)
        for h in (1, 2):
            m = sched.cluster.telemetry.get(f"s1-host-{h}")
            sched.cluster.bind(
                Pod(f"pin{h}", labels={"scv/number": "4",
                                       "scv/priority": "9",
                                       "tpu/accelerator": "tpu"}),
                f"s1-host-{h}", sorted(m.healthy_coords()))
        gang = gang_pods("g", 4)
        for p in gang:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in gang), \
            [(p.name, p.phase) for p in gang]
        grid = (4, 4, 1)
        coords = host_coords_of(gang, grid)
        assert len(coords) == 4
        # the occupied hosts are out, and the set is itself a carvable
        # block (carve over exactly these cells uses them all)
        assert {(1, 0, 0), (2, 0, 0)}.isdisjoint(coords)
        out = carve_block(grid, coords, 4)
        assert out is not None and out[2] == coords
        assert sched.metrics.counters.get("torus_carves_total", 0) >= 1
        assert sched.metrics.counters.get(
            "torus_carve_bisection_gbps_sum", 0) > 0

    def test_multislice_gang_carves_per_slice_blocks(self):
        nodes = (make_slice("s0", "2x2x4", generation="v4")
                 + make_slice("s1", "2x2x4", generation="v4"))
        sched = mk(nodes, gang_timeout_s=30.0)
        gang = gang_pods("g", 8)
        for p in gang:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in gang)
        assert {p.node.rsplit("-host-", 1)[0] for p in gang} \
            == {"s0", "s1"}
        assert sched.metrics.counters.get(
            "torus_multislice_plans_total", 0) >= 1

    def test_unsatisfiable_carve_degrades_to_legacy(self):
        """A pre-set carve naming vanished hosts must not wedge the
        gang: the intersection comes up empty, the carve clears, and
        the legacy candidates place the gang anyway."""
        nodes = make_slice("s1", "2x2x4", generation="v4")
        sched = mk(nodes)
        sched.gang_permit.gangs.set_carve(
            "g", {"s1": frozenset({"gone-host-0", "gone-host-1"})})
        gang = gang_pods("g", 2)
        for p in gang:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in gang)
        assert not sched.gang_permit.gangs.carve_of("g")


class TestDcnAwareMultiSlice:
    """ISSUE 19 satellite: multi-slice carve plans rank follow-up slices
    by DCN proximity to the already-chosen set. The distance is a proxy
    over slice ids (pool prefix + numeric suffix = provisioning
    adjacency); the single-slice path is untouched."""

    def test_dcn_distance_proxy(self):
        from yoda_scheduler_tpu.scheduler.carve import (_DCN_FAR,
                                                        dcn_distance)

        assert dcn_distance("vp-3", "vp-3") == 0
        assert dcn_distance("vp-3", "vp-5") == 2
        assert dcn_distance("vp-5", "vp-3") == 2      # symmetric
        assert dcn_distance("vp-3", "wq-3") == _DCN_FAR
        assert dcn_distance("vp-3", "vp-x") == _DCN_FAR
        assert dcn_distance("solo", "vp-3") == _DCN_FAR
        # any finite suffix gap ranks below one pool cross
        assert dcn_distance("vp-0", "vp-999999") < _DCN_FAR

    def test_multislice_prefers_dcn_near_slices(self):
        """Three equal slices: vp-0, vp-1, vp-9. A gang of 8 needs two.
        The anchor (largest carvable, tie on id) is vp-0; the DCN term
        must pick vp-1 over vp-9 — without it the tie would fall to
        carvable volume + id and still pass, so the far slice is made
        IDENTICAL in capacity and the near one is only reachable
        through the distance key."""
        nodes = (make_slice("vp-0", "2x2x4", generation="v4")
                 + make_slice("vp-1", "2x2x4", generation="v4")
                 + make_slice("vp-9", "2x2x4", generation="v4"))
        sched = mk(nodes, gang_timeout_s=30.0)
        gang = gang_pods("g", 8)
        for p in gang:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in gang)
        used = {p.node.rsplit("-host-", 1)[0] for p in gang}
        assert used == {"vp-0", "vp-1"}
        # the observed DCN span is the suffix gap of the chosen pair
        h = sched.metrics.histograms.get("torus_multislice_dcn_span")
        assert h is not None and max(h.samples()) == 1.0

    def test_single_slice_carve_ignores_dcn(self):
        """A gang that fits one slice must never pay the multi-slice
        machinery: same three slices, gang of 4 — single carve, no
        multislice plan, no span observation (the parity leg of the
        satellite: _carve_single is untouched by the DCN change)."""
        nodes = (make_slice("vp-0", "2x2x4", generation="v4")
                 + make_slice("vp-9", "2x2x4", generation="v4"))
        sched = mk(nodes, gang_timeout_s=30.0)
        gang = gang_pods("g", 4)
        for p in gang:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in gang)
        assert len({p.node.rsplit("-host-", 1)[0] for p in gang}) == 1
        assert sched.metrics.counters.get(
            "torus_multislice_plans_total", 0) == 0
        assert "torus_multislice_dcn_span" not in sched.metrics.histograms

    def test_foreign_pool_slices_still_combine_when_forced(self):
        """DCN-far is a preference, not a veto: when only foreign-pool
        slices remain, the plan still covers the gang."""
        nodes = (make_slice("vp-0", "2x2x4", generation="v4")
                 + make_slice("wq-0", "2x2x4", generation="v4"))
        sched = mk(nodes, gang_timeout_s=30.0)
        gang = gang_pods("g", 8)
        for p in gang:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in gang)
        from yoda_scheduler_tpu.scheduler.carve import _DCN_FAR
        h = sched.metrics.histograms.get("torus_multislice_dcn_span")
        assert h is not None and max(h.samples()) == float(_DCN_FAR)


class TestGeometricFragTerm:
    def _plugin(self, sched):
        return next(p for p in sched.profile.score
                    if isinstance(p, FragmentationScore))

    def test_pristine_slice_host_is_penalised(self):
        sched = mk(make_slice("s1", "8x8x1", generation="v4"))
        plugin = self._plugin(sched)
        snap = sched.snapshot()
        state = CycleState()
        state.write("snapshot", snap)
        # every host fully free: denting ANY of them shrinks the last
        # largest carvable block (the whole 16-host grid)
        assert plugin._geometric_term(state, snap.get("s1-host-0")) \
            == -100.0

    def test_already_dented_host_is_free_to_pack(self):
        sched = mk(make_slice("s1", "8x8x1", generation="v4"))
        sched.cluster.bind(
            Pod("stray", labels={"scv/number": "1",
                                 "tpu/accelerator": "tpu"}),
            "s1-host-0", [(0, 0, 0)])
        plugin = self._plugin(sched)
        snap = sched.snapshot()
        state = CycleState()
        state.write("snapshot", snap)
        # host 0 is no longer whole: packing MORE onto it costs nothing
        assert plugin._geometric_term(state, snap.get("s1-host-0")) == 0.0


class TestTorusReassembly:
    def test_descheduler_compacts_strays_into_the_low_corner(self):
        """No standalone capacity: strays scattered over a 4x4 host
        grid move via strategy 3 onto already-dented hosts, destinations
        filling in host-coordinate order (low corner first)."""
        nodes = make_slice("s1", "8x8x1", generation="v4")
        sched = mk(nodes)
        # host 0 is already dented (the designated dump host); hosts 5
        # and 10 hold sole-resident strays whose eviction makes their
        # hosts whole again (chips carry GLOBAL slice coords — bind a
        # real one of each host's block)
        for h in (0, 5, 10):
            m = sched.cluster.telemetry.get(f"s1-host-{h}")
            sched.cluster.bind(
                Pod(f"stray{h}", labels={"scv/number": "1",
                                         "tpu/accelerator": "tpu"}),
                f"s1-host-{h}", [sorted(m.healthy_coords())[0]])
        desched = Descheduler(sched)
        plan = desched.plan()
        keys = {p.key for p in plan.victims}
        assert {"default/stray5", "default/stray10"} <= keys
        for k in ("default/stray5", "default/stray10"):
            assert plan.strategies[k] == "torus-reassembly"
            # low-corner compaction: both strays route to the one
            # already-dented host, host 0 at coordinate (0,0,0)
            assert plan.destinations[k] == "s1-host-0"

    def test_knob_off_never_reassembles(self):
        nodes = make_slice("s1", "8x8x1", generation="v4")
        sched = mk(nodes, torus=False)
        for h in (0, 5, 10):
            m = sched.cluster.telemetry.get(f"s1-host-{h}")
            sched.cluster.bind(
                Pod(f"stray{h}", labels={"scv/number": "1",
                                         "tpu/accelerator": "tpu"}),
                f"s1-host-{h}", [sorted(m.healthy_coords())[0]])
        plan = Descheduler(sched).plan()
        # no standalone destinations and no torus strategy: empty plan
        assert not plan.victims


class TestProvisionerSliceGeometry:
    def _capacity_sched(self, torus=True, **cfg):
        from yoda_scheduler_tpu.chaos import SimulatedProvider
        from yoda_scheduler_tpu.scheduler.capacity import (
            FakeBackend, NodeTemplate)
        store = TelemetryStore()
        clock = FakeClock(start=0.0)
        solo = make_tpu_node("solo", chips=4)
        solo.heartbeat = clock.time()
        store.put(solo)
        cluster = FakeCluster(store)
        cluster.add_nodes_from_telemetry()
        cfg.setdefault("telemetry_max_age_s", 1e9)
        cfg.setdefault("provisioner_interval_s", 0.5)
        cfg.setdefault("scale_down_cooldown_s", 1.0)
        cfg.setdefault("provisioner_hysteresis_s", 1.0)
        sched = Scheduler(cluster,
                          SchedulerConfig(torus_placement=torus, **cfg),
                          clock=clock)
        provider = SimulatedProvider(
            FakeBackend(cluster, orphan_router=sched.submit),
            clock=clock, latency_s=(0.1, 0.2))
        sched.provisioner.attach_provider(provider)
        sched.provisioner.add_pool(NodeTemplate(
            pool="sl", chips=4, hosts=2, slice_topology="2x2x2",
            max_nodes=4))
        return sched, clock, cluster, provider

    def test_add_pool_validates_slice_topology(self):
        from yoda_scheduler_tpu.scheduler.capacity import NodeTemplate
        sched, clock, cluster, provider = self._capacity_sched()
        # 2x2x4 holds 16 chips; 2 hosts x 4 chips provision only 8
        with pytest.raises(ValueError, match="16 chips"):
            sched.provisioner.add_pool(NodeTemplate(
                pool="bad", chips=4, hosts=2, slice_topology="2x2x4",
                max_nodes=4))
        # z on a 2-D generation is degenerate: the catalog rejects it
        # (volume matches — 2 hosts x 8 chips — so only the rank fails)
        with pytest.raises(ValueError, match="2-D"):
            sched.provisioner.add_pool(NodeTemplate(
                pool="bad2", chips=8, hosts=2, slice_topology="2x4x2",
                generation="v5e", max_nodes=4))

    def _drive(self, sched, clock, until, budget=120.0):
        while clock.time() < budget:
            if sched.run_one() is not None:
                continue
            if until():
                return True
            clock.advance(0.25)
        return until()

    def _provision_slice_with_stray(self, torus):
        """One slice provisioned for a gang; the gang leaves, a stray
        stays behind on one host — the slice is busy but reclaimable."""
        sched, clock, cluster, provider = self._capacity_sched(torus=torus)
        gang = gang_pods("g", 2)
        for p in gang:
            sched.submit(p)
        assert self._drive(
            sched, clock,
            lambda: all(p.phase == PodPhase.BOUND for p in gang))
        stray = Pod("stray", labels={"scv/number": "1",
                                     "tpu/accelerator": "tpu"})
        host = gang[0].node
        m = cluster.telemetry.get(host)
        for p in gang:
            cluster.evict(p)
            sched.forget(p.key)
        cluster.bind(stray, host, [sorted(m.healthy_coords())[0]])
        return sched, clock, cluster, provider, stray

    def test_slice_drain_migrates_stray_and_releases_whole_slice(self):
        sched, clock, cluster, provider, stray = \
            self._provision_slice_with_stray(torus=True)
        assert self._drive(sched, clock,
                           lambda: len(provider.released) == 2), \
            (provider.released, stray.phase, stray.node)
        # the stray landed OUTSIDE the slice, on the standalone node
        assert stray.phase == PodPhase.BOUND and stray.node == "solo"
        drains = sched.metrics.labeled_counters.get(
            "provisioner_slice_drains_total", {})
        assert sum(drains.values()) >= 1
        kinds = [e["kind"] for e in sched.flight.snapshot()]
        assert "slice_drain" in kinds

    def test_knob_off_slice_never_drains(self):
        sched, clock, cluster, provider, stray = \
            self._provision_slice_with_stray(torus=False)
        t0 = clock.time()
        while clock.time() < t0 + 30.0:
            sched.run_one()
            clock.advance(0.25)
        assert not provider.released
        assert stray.node != "solo"
        assert not sched.metrics.labeled_counters.get(
            "provisioner_slice_drains_total")


class TestColumnarHostCoords:
    def test_slice_hosts_carry_grid_coords(self):
        pytest.importorskip("numpy")
        nodes = make_slice("s1", "8x8x1", generation="v4") \
            + [make_tpu_node("solo", chips=4)]
        sched = mk(nodes, columnar=True)
        # the table syncs lazily with the first scheduling cycle
        sched.submit(Pod("p", labels={"scv/number": "1",
                                      "tpu/accelerator": "tpu"}))
        sched.run_until_idle()
        table = sched._columnar
        assert table is not None and table.index
        for i in range(16):
            row = table.index[f"s1-host-{i}"]
            assert (table.host_cx[row], table.host_cy[row],
                    table.host_cz[row]) == host_coord(i, (4, 4, 1))
        solo = table.index["solo"]
        assert (table.host_cx[solo], table.host_cy[solo],
                table.host_cz[solo]) == (-1, -1, -1)
