"""Descheduler (scheduler/deschedule.py): slice defragmentation must free
blocked gang slices, never strand a pod, and respect its safety rails."""

import time

from yoda_scheduler_tpu.scheduler import FakeCluster, Scheduler, SchedulerConfig
from yoda_scheduler_tpu.scheduler.core import FakeClock
from yoda_scheduler_tpu.scheduler.deschedule import Descheduler
from yoda_scheduler_tpu.telemetry import TelemetryStore, make_tpu_node, make_v4_slice
from yoda_scheduler_tpu.utils import Pod, PodPhase


def mk(*nodes, config=None):
    store = TelemetryStore()
    clock = FakeClock(start=1000.0)
    for n in nodes:
        n.heartbeat = clock.time()
        store.put(n)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    sched = Scheduler(cluster, config or SchedulerConfig(max_attempts=3),
                      clock=clock)
    return sched


def refresh(sched):
    """Re-stamp heartbeats against the fake clock (the sniffer daemon's
    periodic publish) — gang timeouts advance the clock past max age."""
    for m in sched.cluster.telemetry.list():
        m.heartbeat = sched.clock.time()
        sched.cluster.telemetry.put(m)


def gang_pods(name, size, chips=4):
    return [Pod(f"{name}-w{i}", labels={
        "tpu/gang-name": name, "tpu/gang-size": str(size),
        "scv/number": str(chips), "tpu/accelerator": "tpu"})
        for i in range(size)]


class TestSliceConservation:
    def test_stray_pod_moves_off_slice_then_gang_fits(self):
        nodes = make_v4_slice("s1", "2x2x4") + [make_tpu_node("solo", chips=4)]
        sched = mk(*nodes)
        n_hosts = len(nodes) - 1
        # a small low-priority pod lands on the slice (force it there)
        stray = Pod("stray", labels={"scv/number": "1",
                                     "tpu/accelerator": "tpu"})
        slice_node = nodes[0].node
        sched.cluster.bind(stray, slice_node, [(0, 0, 0)])
        # the whole-slice gang cannot fit: one host is dented
        gang = gang_pods("g", n_hosts)
        for p in gang:
            sched.submit(p)
        sched.run_until_idle()
        assert not all(p.phase == PodPhase.BOUND for p in gang)

        refresh(sched)
        desched = Descheduler(sched)
        plan = desched.run_once()
        assert [p.key for p in plan.victims] == ["default/stray"]
        assert "gang slice s1" in plan.reasons["default/stray"]
        sched.run_until_idle()
        refresh(sched)
        # stray re-placed on the standalone node, slice now whole
        assert stray.phase == PodPhase.BOUND and stray.node == "solo"
        # the gang binds on its clean slice
        gang2 = gang_pods("g2", n_hosts)
        for p in gang2:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in gang2)

    def test_high_priority_pod_is_protected(self):
        nodes = make_v4_slice("s1", "2x2x4") + [make_tpu_node("solo", chips=4)]
        sched = mk(*nodes)
        vip = Pod("vip", labels={"scv/number": "1", "scv/priority": "9",
                                 "tpu/accelerator": "tpu"})
        sched.cluster.bind(vip, nodes[0].node, [(0, 0, 0)])
        assert not Descheduler(sched, protect_priority=5).plan()

    def test_gang_members_are_never_victims(self):
        nodes = make_v4_slice("s1", "2x2x4")
        sched = mk(*nodes)
        gang = gang_pods("g", len(nodes))
        for p in gang:
            sched.submit(p)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in gang)
        assert not Descheduler(sched).plan()

    def test_no_eviction_when_nowhere_else_fits(self):
        # only the slice exists; evicting would strand the pod
        nodes = make_v4_slice("s1", "2x2x4")
        sched = mk(*nodes)
        stray = Pod("stray", labels={"scv/number": "1",
                                     "tpu/accelerator": "tpu"})
        sched.cluster.bind(stray, nodes[0].node, [(0, 0, 0)])
        assert not Descheduler(sched).plan()

    def test_eviction_budget_caps_a_pass(self):
        nodes = make_v4_slice("s1", "2x2x4") + [
            make_tpu_node(f"solo{i}", chips=4) for i in range(4)]
        sched = mk(*nodes)
        n_hosts = len(nodes) - 4
        for i in range(min(4, n_hosts)):
            p = Pod(f"stray{i}", labels={"scv/number": "1",
                                         "tpu/accelerator": "tpu"})
            sched.cluster.bind(p, nodes[i].node, [(0, 0, 0)])
        plan = Descheduler(sched, max_evictions_per_pass=2).plan()
        assert len(plan.victims) == 2

    def test_maximally_contiguous_node_is_not_churned(self):
        # 3 free chips on a 2x2 board cannot form a volume-3 box; they are
        # already as contiguous as the shape allows — no eviction loop
        sched = mk(make_tpu_node("a", chips=4), make_tpu_node("b", chips=4))
        for node in ("a", "b"):
            p = Pod(f"stray-{node}", labels={"scv/number": "1",
                                             "tpu/accelerator": "tpu"})
            sched.cluster.bind(p, node, [(0, 0, 0)])
        assert not Descheduler(sched).plan()

    def test_foreign_profile_pods_are_not_victims(self):
        nodes = make_v4_slice("s1", "2x2x4") + [make_tpu_node("solo", chips=4)]
        sched = mk(*nodes)
        foreign = Pod("theirs", labels={"scv/number": "1",
                                        "tpu/accelerator": "tpu"},
                      scheduler_name="other-profile")
        sched.cluster.bind(foreign, nodes[0].node, [(0, 0, 0)])
        assert not Descheduler(sched).plan()

    def test_two_victims_cannot_share_one_free_slot(self):
        # two strays on the slice, but the only standalone destination has
        # exactly one free chip -> plan must take one victim, not two
        nodes = make_v4_slice("s1", "2x2x4") + [make_tpu_node("solo", chips=4)]
        sched = mk(*nodes)
        for i in range(2):
            p = Pod(f"stray{i}", labels={"scv/number": "1",
                                         "tpu/accelerator": "tpu"})
            sched.cluster.bind(p, nodes[i].node, [(0, 0, 0)])
        filler = Pod("filler", labels={"scv/number": "3", "scv/priority": "9",
                                       "tpu/accelerator": "tpu"})
        sched.cluster.bind(filler, "solo", [(0, 0, 0), (0, 1, 0), (1, 0, 0)])
        plan = Descheduler(sched).plan()
        assert len(plan.victims) == 1

    def test_no_churn_when_protected_pod_causes_fragmentation(self):
        # 2x4 node: a protected vip fills the middle rows; the movable
        # stray's eviction cannot enlarge any free block beyond what its
        # own chips already form -> it must not be victimised
        sched = mk(make_tpu_node("a", chips=8), make_tpu_node("b", chips=8))
        vip = Pod("vip", labels={"scv/number": "4", "scv/priority": "9",
                                 "tpu/accelerator": "tpu"})
        sched.cluster.bind(vip, "a", [(1, 0, 0), (0, 1, 0), (1, 1, 0),
                                      (0, 2, 0)])
        stray = Pod("stray", labels={"scv/number": "2",
                                     "tpu/accelerator": "tpu"})
        sched.cluster.bind(stray, "a", [(0, 3, 0), (1, 3, 0)])
        # free: (0,0) and (1,2) — fragmented, but not the stray's doing
        assert not Descheduler(sched).plan()

    def test_cooldown_prevents_repeat_eviction(self):
        nodes = make_v4_slice("s1", "2x2x4") + [make_tpu_node("solo", chips=4)]
        sched = mk(*nodes)
        stray = Pod("stray", labels={"scv/number": "1",
                                     "tpu/accelerator": "tpu"})
        sched.cluster.bind(stray, nodes[0].node, [(0, 0, 0)])
        d = Descheduler(sched, cooldown_s=300.0)
        assert d.run_once()
        # scheduler puts it back on the slice (simulating a re-placement)
        sched.run_until_idle()
        sched.cluster.evict(stray)
        sched.cluster.bind(stray, nodes[0].node, [(0, 0, 0)])
        refresh(sched)
        assert not d.plan()          # within cooldown
        sched.clock.advance(301.0)
        refresh(sched)
        assert d.plan()              # cooldown expired

    def test_descheduled_metric_increments(self):
        nodes = make_v4_slice("s1", "2x2x4") + [make_tpu_node("solo", chips=4)]
        sched = mk(*nodes)
        stray = Pod("stray", labels={"scv/number": "1",
                                     "tpu/accelerator": "tpu"})
        sched.cluster.bind(stray, nodes[0].node, [(0, 0, 0)])
        Descheduler(sched).run_once()
        assert sched.metrics.counters["pods_descheduled_total"] == 1
