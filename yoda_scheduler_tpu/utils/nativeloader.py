"""Shared hardened loader for the native library (libyodaplace.so).

Both native kernels — the torus placement engine (topology/native.py)
and the fused scheduling kernel (scheduler/nativeplane.py) — live in one
shared object but must degrade INDEPENDENTLY: an old .so built before
the fused kernel existed still serves placement, and a .so with a stale
fused-kernel ABI falls back to the numpy path without touching torus
search. So the dlopen/candidate-path logic is shared here, while symbol
resolution is per kernel: ``bind_symbols`` returns None for exactly the
kernel whose symbols are missing, never process-wide.

No build-time dependency: ``make native`` produces the library; a
pure-Python install (no g++) simply gets None everywhere and loses
nothing but speed.
"""

from __future__ import annotations

import ctypes
import os
import threading

_LIB_NAME = "libyodaplace.so"
_ENV_PATH = "YODA_PLACEMENT_LIB"

_lock = threading.Lock()
_cached: dict[str, "ctypes.CDLL | None"] = {}


def _candidates() -> list[str]:
    here = os.path.dirname(__file__)
    return [
        os.environ.get(_ENV_PATH, ""),
        os.path.abspath(os.path.join(here, "..", "..", "native", _LIB_NAME)),
        os.path.join(here, "..", "topology", _LIB_NAME),
    ]


def load_library() -> "ctypes.CDLL | None":
    """dlopen the shared native library, trying the env override first.
    An unloadable candidate (wrong arch, truncated file) is skipped, not
    fatal — the next candidate may still work. Cached per process."""
    with _lock:
        if "lib" in _cached:
            return _cached["lib"]
        lib = None
        for c in _candidates():
            if c and os.path.exists(c):
                try:
                    lib = ctypes.CDLL(c)
                    break
                except OSError:
                    continue  # wrong arch / corrupt build: try the next
        _cached["lib"] = lib
        return lib


def bind_symbols(symbols: dict) -> "ctypes.CDLL | None":
    """Resolve one kernel's symbol set against the shared library:
    ``{name: (restype, argtypes | None)}``. Returns the library with
    those symbols configured, or None when the library is absent OR any
    symbol is missing — a per-KERNEL verdict, so a stale .so degrades
    only the kernel it predates."""
    lib = load_library()
    if lib is None:
        return None
    for name, (restype, argtypes) in symbols.items():
        try:
            fn = getattr(lib, name)
        except AttributeError:
            return None  # this kernel is newer than the built library
        fn.restype = restype
        if argtypes is not None:
            fn.argtypes = argtypes
    return lib
