"""Device-mesh construction for dp/fsdp/tp/sp parallelism.

The reference has no parallelism of its own (SURVEY §2.3) — but the
workloads this scheduler places are pjit programs over a
``jax.sharding.Mesh``, and the scheduler's job is to hand them contiguous
ICI blocks those meshes map onto. This module is the workload-side
counterpart: it builds meshes whose axis order puts the most
communication-hungry axis (tp) innermost, where Cloud TPU device order
gives torus-neighbour ICI links.
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh

# outer-to-inner order: tp innermost (all-reduce every layer) rides the
# fastest ICI neighbourhoods; ep's all-to-all pair next; pp outermost —
# stage-boundary transfers are the rarest and tolerate DCN between hosts,
# with dp just inside it
AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")


def mesh_shape_for(n_devices: int, tp: int = 1, sp: int = 1, fsdp: int | None = None,
                   dp: int | None = None, pp: int = 1, ep: int = 1) -> dict[str, int]:
    """Fill in unspecified axes to cover n_devices: fsdp absorbs what dp
    doesn't claim."""
    fixed = tp * sp * pp * ep
    rest = n_devices // fixed
    if rest * fixed != n_devices:
        raise ValueError(
            f"pp*ep*sp*tp={fixed} does not divide {n_devices} devices")
    if dp is None and fsdp is None:
        dp, fsdp = 1, rest
    elif dp is None:
        dp = rest // fsdp
    elif fsdp is None:
        fsdp = rest // dp
    if dp * fsdp * fixed != n_devices:
        raise ValueError(
            f"pp*dp*fsdp*ep*sp*tp = {pp}*{dp}*{fsdp}*{ep}*{sp}*{tp}"
            f" != {n_devices} devices")
    return {"pp": pp, "dp": dp, "fsdp": fsdp, "ep": ep, "sp": sp, "tp": tp}


def _check_axes(shape: dict[str, int]) -> None:
    unknown = set(shape) - set(AXIS_ORDER)
    if unknown:
        raise ValueError(
            f"unknown mesh axes {sorted(unknown)}; valid: {AXIS_ORDER}")


def make_mesh(shape: dict[str, int] | None = None, devices=None, **axes) -> Mesh:
    """Build a Mesh. `shape` maps axis name -> size in AXIS_ORDER; axes not
    named get size 1 (kept in the mesh so PartitionSpecs always resolve)."""
    if shape is None:
        shape = axes or None
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = mesh_shape_for(len(devices))
    _check_axes(shape)
    sizes = [shape.get(a, 1) for a in AXIS_ORDER]
    want = math.prod(sizes)
    if want > len(devices):
        raise ValueError(f"mesh {shape} wants {want} devices, have {len(devices)}")
    grid = np.asarray(devices[:want]).reshape(sizes)
    return Mesh(grid, AXIS_ORDER)


def make_hybrid_mesh(ici_shape: dict[str, int],
                     dcn_shape: dict[str, int] | None = None,
                     devices=None) -> Mesh:
    """Multi-host/multi-slice mesh split across the two interconnect
    tiers: `ici_shape` axes stay INSIDE one slice (all-reduce-heavy:
    tp/sp/fsdp ride ICI torus links), `dcn_shape` axes cross slices
    (pp/dp tolerate the slower data-centre network) — the scaling-book
    recipe for multi-pod training, and the workload-side mirror of the
    scheduler's multi-slice gang placement, which hands one contiguous
    ICI block per slice and leaves only the dcn axes' traffic to cross
    the cut.

    Delegates the device grid to jax's mesh_utils.create_hybrid_device_
    mesh: granule = a SLICE (`Device.slice_index`; falls back to
    process-as-granule where the platform doesn't set it, so multi-host
    v4/v5p slices keep hosting ICI axes larger than one host), exact
    per-granule device counts enforced, and topology-aware device
    ordering inside each granule. Every granule must hold exactly
    prod(ici_shape) devices and the granule count must equal
    prod(dcn_shape)."""
    dcn_shape = dcn_shape or {}
    overlap = set(ici_shape) & set(dcn_shape)
    if overlap:
        raise ValueError(f"axes {sorted(overlap)} listed in both tiers")
    _check_axes({**ici_shape, **dcn_shape})
    devices = list(devices if devices is not None else jax.devices())
    from jax.experimental import mesh_utils

    ici_sizes = [ici_shape.get(a, 1) for a in AXIS_ORDER]
    dcn_sizes = [dcn_shape.get(a, 1) for a in AXIS_ORDER]
    grid = mesh_utils.create_hybrid_device_mesh(
        ici_sizes, dcn_sizes, devices=devices,
        process_is_granule=not hasattr(devices[0], "slice_index"))
    return Mesh(grid, AXIS_ORDER)
