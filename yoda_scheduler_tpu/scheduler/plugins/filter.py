"""Filter plugin: node feasibility from live telemetry + allocation ledger.

Capability parity with the reference's three predicates
(pkg/yoda/filter/filter.go):
- PodFitsNumber (filter.go:11-16)  -> enough unclaimed healthy chips
- PodFitsMemory (filter.go:18-33)  -> >= N chips with free HBM >= scv/memory
- PodFitsClock  (filter.go:35-50)  -> >= N chips with clock >= scv/clock
  (>= semantics, resolving the ==-vs->= inconsistency; SURVEY §3.3)

plus TPU-native predicates the reference has no equivalent for:
- telemetry freshness (stale sniffer = unschedulable, not trusted)
- accelerator-type partition for mixed GPU+TPU clusters (BASELINE #5)
- allocation awareness: chips already claimed by bound pods and pending
  gang reservations are not offered twice (the reference re-offered the
  same cards until the live telemetry caught up)
- exact ICI block shape for ``tpu/topology`` requests
- gang pods only land on slices big enough for the whole gang, and stick
  to the slice the gang's first member chose.
"""

from __future__ import annotations

import time

from ..framework import (
    ClusterEvent,
    CycleState,
    EnqueueExtensions,
    FilterPlugin,
    NODE_ADDED,
    NODE_TELEMETRY_UPDATED,
    NodeInfo,
    POD_DELETED,
    QUEUE,
    SKIP,
    Status,
)
from ...topology.torus import fits_shape, parse_topology, best_fit_block
from ...utils.labels import WorkloadSpec
from .allocator import ChipAllocator, _node_shape
from .gang import GangCoordinator, bound_gang_members


class TelemetryFilter(FilterPlugin, EnqueueExtensions):
    name = "telemetry-filter"
    # advertises a verdict input that moves with TIME rather than with any
    # cluster version counter (telemetry staleness): the feasible-class
    # memo repair (core._repair_feasible) re-verifies staleness on
    # unchanged nodes only when an active filter declares this — profiles
    # without a staleness gate (reference emulation) must not have one
    # silently imposed on their repaired lists
    time_dependent = True

    def __init__(self, allocator: ChipAllocator, gangs: GangCoordinator | None = None,
                 telemetry_max_age_s: float = 60.0, require_contiguous: bool = False) -> None:
        self.allocator = allocator
        self.gangs = gangs
        self.max_age = telemetry_max_age_s
        self.require_contiguous = require_contiguous
        # verdict cache: the full capacity verdict (including its message
        # string — f-string builds dominate failing full-scan cycles at
        # 1000 nodes) per (spec, node serial, pending version, hold).
        # WorkloadSpec is a frozen dataclass, so it hashes by value and
        # identical label classes share entries. Time-dependent (staleness)
        # and externally-stateful (gang) checks stay OUTSIDE the cache.
        self._verdict_cache: dict[str, dict[tuple, Status]] = {}
        self._verdict_slots = 8

    def forget_nodes(self, gone: set[str]) -> None:
        for n in gone:
            self._verdict_cache.pop(n, None)

    def equivalence_key(self, pod):
        """Batch-cycle contract: every predicate here reads the parsed
        WorkloadSpec (chips / HBM / clock / accelerator / generation —
        all inside the engine's memo key) against node state; gang slice
        checks never apply because gang pods are excluded upstream by
        GangPermit's NO_BATCH vote."""
        return ()

    # ------------------------------------------------- queueing hints
    def events_to_register(self) -> tuple:
        """Events that can cure a capacity/staleness rejection: chips
        freed by a departing pod, a node joining, or a telemetry update.
        Deliberately NOT PodBound — binds only consume capacity, so a
        bind storm must not thundering-herd chip-starved pods back into
        the filter chain."""
        return (POD_DELETED, NODE_ADDED, NODE_TELEMETRY_UPDATED)

    def queueing_hint(self, event: ClusterEvent, pod) -> str:
        if event.kind != NODE_TELEMETRY_UPDATED:
            return QUEUE  # freed chips / a fresh node can always help
        old, new = event.old, event.new
        if new is None:
            return SKIP  # telemetry deletion never frees capacity
        if old is None:
            return QUEUE  # first report for this node = new capacity
        # a periodic republish with unchanged capacity must SKIP — the
        # sniffer fleet re-puts every few seconds, and waking every
        # parked pod each time would reintroduce the retry storm the
        # backoff existed to prevent. QUEUE only when the update could
        # flip a verdict this plugin produces:
        if (new.accelerator != old.accelerator
                or new.tpu_generation != old.tpu_generation
                or new.slice_id != old.slice_id
                or new.num_hosts != old.num_hosts):
            return QUEUE  # partition / slice-shape change
        if new.heartbeat - old.heartbeat > self.max_age:
            # the node skipped at least one max_age window: a pod may
            # have been rejected on staleness that this report cures
            return QUEUE
        nh, oh = new.healthy_chips(), old.healthy_chips()
        if len(nh) > len(oh):
            return QUEUE  # chips recovered
        if (max((c.hbm_free_mb for c in nh), default=0)
                > max((c.hbm_free_mb for c in oh), default=0)):
            return QUEUE  # freed HBM can cure a memory-class rejection
        if (max((c.clock_mhz for c in nh), default=0)
                > max((c.clock_mhz for c in oh), default=0)):
            return QUEUE
        return SKIP

    def native_filter_args(self, state: CycleState, pod, table):
        """Fused-kernel capability hook (framework.FilterPlugin): the
        same predicate PARAMETERS filter_batch evaluates, handed to the
        native kernel instead of computed in numpy. The veto set is
        filter_batch's exactly — anything the columns don't express
        keeps the pod off the native path entirely."""
        spec: WorkloadSpec = state.read("workload_spec")
        if spec.is_gang or spec.topology is not None:
            return None
        if self.require_contiguous and spec.chips > 1:
            return None
        if self.allocator.has_holds():
            return None
        args = {"tel_filter": 1, "max_age": float(self.max_age)}
        if spec.accelerator is not None:
            args["use_accel"] = 1
            args["accel_id"] = table.intern_of(spec.accelerator)
        if spec.tpu_generation is not None:
            args["use_gen"] = 1
            args["gen_id"] = table.intern_of(spec.tpu_generation)
        return args

    def filter_batch(self, state: CycleState, pod, table, rows=None):
        """Columnar verdicts for the capacity/staleness predicates —
        one boolean per node (whole table, or the `rows` subset the
        memo-repair paths re-filter), all in a handful of numpy calls.
        Bails (None) for everything the columns don't express: gang
        slice state, exact-topology / contiguity block search, and
        nominated-capacity holds. Predicate-for-predicate identical to
        `filter` for the pods it accepts (the checks are
        order-independent: a node passes iff it passes every one)."""
        spec: WorkloadSpec = state.read("workload_spec")
        if spec.is_gang or spec.topology is not None:
            return None
        if self.require_contiguous and spec.chips > 1:
            return None
        if self.allocator.has_holds():
            return None
        now = state.read_or("now")
        if now is None:
            now = time.time()
        if rows is None:
            valid, hb = table.valid, table.heartbeat
            accel, gen, fc = table.accel, table.gen, table.free_count
            _, qcount = table.qual(spec.min_free_mb, spec.min_clock_mhz)
        else:
            valid, hb = table.valid[rows], table.heartbeat[rows]
            accel, gen = table.accel[rows], table.gen[rows]
            fc = table.free_count[rows]
            q = (table.chip_free[rows]
                 & (table.chip_hbm_free[rows] >= spec.min_free_mb)
                 & (table.chip_clock[rows] >= spec.min_clock_mhz))
            qcount = q.sum(axis=1)
        # telemetry present + fresh (schema.stale: age > max_age);
        # blackout degraded mode waives freshness, same as `filter`
        if state.read_or("degraded"):
            ok = valid.copy()
        else:
            ok = valid & ((now - hb) <= self.max_age)
        if spec.accelerator is not None:
            ok &= accel == table.intern_of(spec.accelerator)
        if spec.tpu_generation is not None:
            ok &= gen == table.intern_of(spec.tpu_generation)
        # unclaimed-healthy-chip count, then the per-chip HBM/clock class
        # floors (allocator.class_stats' columnar twin); holds are zero
        # by the gate above
        ok &= fc >= spec.chips
        ok &= qcount >= spec.chips
        return ok

    def filter(self, state: CycleState, pod, node: NodeInfo) -> Status:
        spec: WorkloadSpec = state.read("workload_spec")
        m = node.metrics
        # telemetry presence: reference returns Unschedulable "Node:%v scv is not exist"
        # on cache miss (pkg/yoda/scheduler.go:80-84)
        if m is None:
            return Status.unschedulable(f"{node.name}: no accelerator telemetry")
        # degraded mode (engine-detected telemetry blackout): the WHOLE
        # feed is dark, so "stale" carries no per-node signal — waive the
        # gate and schedule off last-known capacity (the capacity
        # predicates below still apply) instead of rejecting every node
        if m.stale(now=state.read_or("now", time.time()),
                   max_age_s=self.max_age) \
                and not state.read_or("degraded"):
            return Status.unschedulable(f"{node.name}: telemetry stale")
        if spec.is_gang:
            return self._filter_checked(state, spec, pod, node, m)
        hold = self.allocator.holds_for(spec, node, pod.key,
                                        now=state.read_or("now"))
        key = (spec, node.serial,
               self.allocator.pending_version(node.name), hold)
        slot = self._verdict_cache.get(node.name)
        if slot is not None:
            hit = slot.get(key)
            if hit is not None:
                return hit
        st = self._filter_checked(state, spec, pod, node, m, hold)
        slot = self._verdict_cache.setdefault(node.name, {})
        slot[key] = st
        while len(slot) > self._verdict_slots:
            slot.pop(next(iter(slot)))
        return st

    def _filter_checked(self, state: CycleState, spec: WorkloadSpec, pod,
                        node: NodeInfo, m, hold: int | None = None) -> Status:
        if spec.accelerator is not None and m.accelerator != spec.accelerator:
            return Status.unschedulable(
                f"{node.name}: accelerator {m.accelerator} != requested {spec.accelerator}"
            )
        if spec.tpu_generation is not None and m.tpu_generation != spec.tpu_generation:
            return Status.unschedulable(
                f"{node.name}: generation {m.tpu_generation or 'unset'}"
                f" != requested {spec.tpu_generation}"
            )

        # gang constraints: the gang fits one slice (and sticks to the
        # chosen one) — or, when no single slice can host it, follows its
        # multi-slice plan within per-slice quotas (GangPermit.pre_filter)
        if spec.is_gang:
            if not m.slice_id:
                return Status.unschedulable(f"{node.name}: gang pod needs a pod-slice node")
            plan_quota = (self.gangs.quota_left(spec.gang_name, m.slice_id)
                          if self.gangs is not None else None)
            if plan_quota is not None:
                if plan_quota <= 0:
                    return Status.unschedulable(
                        f"{node.name}: slice {m.slice_id} quota filled for "
                        f"gang {spec.gang_name}"
                    )
            else:
                if m.num_hosts < spec.gang_size:
                    return Status.unschedulable(
                        f"{node.name}: slice {m.slice_id} has {m.num_hosts} hosts < gang size {spec.gang_size}"
                    )
                if self.gangs is not None:
                    chosen = self.gangs.chosen_slice(spec.gang_name)
                    if chosen is None:
                        # partially-bound gang (peer bind failure / scheduler
                        # restart): members already on a slice pin the choice
                        # even though the coordinator's state is gone
                        _, chosen, _ = bound_gang_members(state, spec.gang_name)
                    if chosen is not None and chosen != m.slice_id:
                        return Status.unschedulable(
                            f"{node.name}: gang {spec.gang_name} is placing on slice {chosen}"
                        )

        # chips-count predicate over *unclaimed* healthy chips, minus
        # capacity held for nominated preemptors of >= priority (upstream
        # nominated-pod semantics: don't schedule into a freshly-preempted
        # hole that a higher-priority pod is entitled to)
        free = self.allocator.free_coords(node)
        if hold is None:
            hold = self.allocator.holds_for(spec, node, pod.key,
                                            now=state.read_or("now"))
        if len(free) - hold < spec.chips:
            return Status.unschedulable(
                f"{node.name}: {len(free)} unclaimed healthy chips"
                + (f" ({hold} held for nominated preemptors)" if hold else "")
                + f" < {spec.chips} requested"
            )

        # per-chip memory + clock predicates over unclaimed healthy chips
        # (aggregates memoised per (node state, label class) — see
        # allocator.ClassStats)
        stats = self.allocator.class_stats(node, spec.min_free_mb,
                                           spec.min_clock_mhz)
        if stats.count - hold < spec.chips:
            return Status.unschedulable(
                f"{node.name}: only {stats.count} chips satisfy "
                f"hbm>={spec.min_free_mb}MB clock>={spec.min_clock_mhz}MHz "
                f"(need {spec.chips})"
            )

        # exact topology request must fit contiguously
        if spec.topology is not None:
            if fits_shape(_node_shape(m), stats.qcoords,
                          parse_topology(spec.topology)) is None:
                return Status.unschedulable(
                    f"{node.name}: no free contiguous {spec.topology} block"
                )
        elif self.require_contiguous and spec.chips > 1:
            if best_fit_block(_node_shape(m), stats.qcoords, spec.chips) is None:
                return Status.unschedulable(
                    f"{node.name}: no contiguous block of {spec.chips} chips"
                )

        return Status.success()
