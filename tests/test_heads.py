"""Intra-replica parallel scheduling heads (scheduler/heads.py).

Pins the three contracts the HeadSet makes:

- scheduleHeads=1 (the default) is the classic loop BIT-IDENTICAL: no
  queue lock, no filter, no workers — placements match a plain engine
  chip-for-chip (the YODA_SCHEDULE_HEADS=1 CI leg re-runs tier-1 under
  the knob to hold this at suite scale).
- scheduleHeads>1 shares ONE chip allocator across heads (the multi.py
  co-hosted-profiles contract): a head's Reserve is visible to every
  sibling BEFORE the wire round-trip, so same-node concurrent picks
  stop colliding and the authority's 409 is the cross-replica backstop,
  not the intra-replica common path.
- work segregation rides the queue's `exclude` predicate: worker heads
  never pop gang pods, excluded pods are DEFERRED not consumed (heap
  re-push / DRF top-only defer), and the bounded per-head dispatch
  window caps async binds in flight per head.
"""

import random
import threading
import time

import pytest

from yoda_scheduler_tpu.scheduler import (
    FakeCluster,
    FleetCoordinator,
    HeadSet,
    Scheduler,
    SchedulerConfig,
)
from yoda_scheduler_tpu.scheduler.core import FakeClock
from yoda_scheduler_tpu.scheduler.queue import DRFShardedQueue
from yoda_scheduler_tpu.telemetry import (
    TelemetryStore, make_gpu_node, make_tpu_node, make_v4_slice)
from yoda_scheduler_tpu.utils import Pod, PodPhase


# ------------------------------------------------------------------ fixtures
def _rig(n_standalone=3):
    store = TelemetryStore()
    metrics = list(make_v4_slice("s0", "2x2x4"))
    for i in range(n_standalone):
        metrics.append(make_tpu_node(f"t{i}", chips=4))
    metrics.append(make_gpu_node("g0", cards=8))
    for m in metrics:
        m.heartbeat = 0.0
        store.put(m)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    return store, cluster


def _workload(seed, n_tpu=18, n_gpu=5):
    rng = random.Random(seed)
    pods = [Pod(f"c{i}", labels={"tpu/accelerator": "tpu",
                                 "scv/number": "1"}) for i in range(n_tpu)]
    pods += [Pod(f"g{i}", labels={"tpu/accelerator": "gpu",
                                  "scv/number": "1"}) for i in range(n_gpu)]
    rng.shuffle(pods)
    return pods


def _placements(pods):
    return {p.key: (p.node, tuple(sorted(p.assigned_chips())))
            for p in pods}


def _cfg(**kw):
    return SchedulerConfig(telemetry_max_age_s=1e9, **kw)


def _drive_headset(hs, pods, seed=0, budget=5000):
    rng = random.Random(seed)
    clock = hs.primary.clock
    for _ in range(budget):
        if all(p.phase in (PodPhase.BOUND, PodPhase.FAILED) for p in pods):
            return
        if hs.step(rng) is None:
            wake = hs.next_wake_at()
            clock.advance(max((wake or clock.time() + 0.1)
                              - clock.time(), 0.01))
    raise AssertionError("headset drive budget exhausted")


# ------------------------------------------------------------ 1-head parity
def test_schedule_heads_one_is_bit_identical_to_classic_engine():
    _s, base_cluster = _rig()
    base = Scheduler(base_cluster, _cfg(), clock=FakeClock())
    base_pods = _workload(7)
    for p in base_pods:
        base.submit(p)
    base.run_until_idle()

    _s, cluster = _rig()
    eng = Scheduler(cluster, _cfg(schedule_heads=1), clock=FakeClock())
    hs = HeadSet(eng, 1)
    pods = _workload(7)
    for p in pods:
        eng.submit(p)
    _drive_headset(hs, pods)
    assert _placements(pods) == _placements(base_pods)
    # and NOTHING was armed: no queue lock, no filter, no workers
    assert eng.queue._mh_lock is None
    assert eng.head_filter is None
    assert hs.heads == [eng]


def test_schedule_heads_one_under_fleet_is_bit_identical():
    _s, base_cluster = _rig()
    base = Scheduler(base_cluster, _cfg(), clock=FakeClock())
    base_pods = _workload(11)
    for p in base_pods:
        base.submit(p)
    base.run_until_idle()

    _s, cluster = _rig()
    fleet = FleetCoordinator(cluster, _cfg(schedule_heads=1),
                             replicas=1, clock=FakeClock())
    pods = _workload(11)
    for p in pods:
        fleet.submit(p)
    fleet.run_until_idle()
    assert _placements(pods) == _placements(base_pods)
    assert fleet.replicas[0].headset is None


# --------------------------------------------------------- multi-head drain
@pytest.mark.parametrize("heads", [2, 4])
def test_multi_head_deterministic_drain_binds_all(heads):
    _s, cluster = _rig()
    eng = Scheduler(cluster, _cfg(schedule_heads=heads), clock=FakeClock())
    hs = HeadSet(eng, heads)
    pods = _workload(3)
    for p in pods:
        eng.submit(p)
    _drive_headset(hs, pods, seed=heads)
    assert all(p.phase == PodPhase.BOUND for p in pods)
    # every head shares the PRIMARY's allocator (and therefore sees
    # sibling reservations pre-commit)
    assert all(h.allocator is eng.allocator for h in hs.heads)
    st = hs.stats()
    assert st["pods_scheduled_total"] == len(pods)
    assert sum(st["per_head_binds"]) == len(pods)
    # shared-allocator reservations make intra-process chip collisions
    # structurally impossible in the deterministic interleave
    assert st["bind_conflicts_total"] == 0
    # no chip double-booked in cluster truth
    seen = {}
    for p in pods:
        for c in p.assigned_chips():
            key = (p.node, c)
            assert key not in seen, f"{key} owned by {seen[key]} and {p.name}"
            seen[key] = p.name


def test_multi_head_same_seed_same_placements():
    results = []
    for _ in range(2):
        _s, cluster = _rig()
        eng = Scheduler(cluster, _cfg(schedule_heads=3), clock=FakeClock())
        hs = HeadSet(eng, 3)
        pods = _workload(5)
        for p in pods:
            eng.submit(p)
        _drive_headset(hs, pods, seed=42)
        results.append(_placements(pods))
    assert results[0] == results[1]


def test_multi_head_threaded_drain_no_double_bind():
    _s, cluster = _rig()
    eng = Scheduler(cluster, _cfg(schedule_heads=4), clock=None)
    hs = HeadSet(eng, 4)
    pods = _workload(9, n_tpu=24, n_gpu=8)
    for p in pods:
        eng.submit(p)
    stop = threading.Event()
    hs.start_workers(stop)
    try:
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            eng.run_one()
            if all(p.phase in (PodPhase.BOUND, PodPhase.FAILED)
                   for p in pods):
                break
            time.sleep(0.001)
    finally:
        stop.set()
        hs.join()
    bound = [p for p in pods if p.phase == PodPhase.BOUND]
    assert bound, "threaded drain bound nothing"
    # cluster truth: each pod bound at most once, chips disjoint per node
    seen_pod, seen_chip = {}, {}
    for node in cluster.node_names():
        for q in cluster.pods_on(node):
            assert q.key not in seen_pod, f"{q.key} double-bound"
            seen_pod[q.key] = node
            for c in q.assigned_chips():
                key = (node, c)
                assert key not in seen_chip, f"chip {key} double-booked"
                seen_chip[key] = q.key


# ------------------------------------------------------------- segregation
def test_worker_heads_never_pop_gang_pods():
    _s, cluster = _rig()
    eng = Scheduler(cluster, _cfg(schedule_heads=2), clock=FakeClock())
    hs = HeadSet(eng, 2)
    worker = hs.heads[1]
    gang = [Pod(f"m{i}", labels={"tpu/accelerator": "tpu",
                                 "scv/number": "1",
                                 "tpu/gang-name": "g1",
                                 "tpu/gang-size": "2"})
            for i in range(2)]
    for p in gang:
        eng.submit(p)
    # the WORKER alone can never bind a gang member
    for _ in range(50):
        worker.run_one()
    assert all(p.phase == PodPhase.PENDING for p in gang)
    # the full headset (primary included) drains it
    _drive_headset(hs, gang, seed=1)
    assert all(p.phase == PodPhase.BOUND for p in gang)


def test_excluded_pod_is_deferred_not_consumed():
    _s, cluster = _rig()
    eng = Scheduler(cluster, _cfg(), clock=FakeClock())
    eng.queue.enable_multi_head()
    a = Pod("a", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    b = Pod("b", labels={"tpu/accelerator": "tpu", "scv/number": "1"})
    eng.queue.add(a, now=0.0)
    eng.queue.add(b, now=0.0)
    got = eng.queue.pop(now=1.0, exclude=lambda i: i.pod.name == "a")
    assert got is not None and got.pod.name == "b"
    # "a" was deferred, not dropped: a later unfiltered pop returns it
    got2 = eng.queue.pop(now=1.0)
    assert got2 is not None and got2.pod.name == "a"


# --------------------------------------- segregation x sharded DRF queue
class TestDRFShardedDefer:
    """Multi-head `exclude` against the DRFShardedQueue (satellite:
    deferred entries keep their exact DRF position and are never
    double-popped — the sharded queue's top-only defer contract)."""

    def _drf_eng(self):
        _s, cluster = _rig()
        eng = Scheduler(cluster, _cfg(drf_fairness=True),
                        clock=FakeClock())
        assert isinstance(eng.queue, DRFShardedQueue)
        eng.queue.enable_multi_head()
        return eng

    @staticmethod
    def _pod(name, tenant):
        return Pod(name, labels={"tpu/accelerator": "tpu",
                                 "scv/number": "1",
                                 "scv/tenant": tenant})

    def test_excluded_drf_pick_defers_whole_cycle(self):
        """The sharded queue must NOT dig past an excluded DRF pick
        (that would corrupt the bands' tenant counts): the head sits
        the cycle out, and the deferred entry keeps its exact
        position for the next eligible pop."""
        eng = self._drf_eng()
        a = self._pod("a", "acme")
        b = self._pod("b", "bux")
        eng.queue.add(a, now=0.0)
        eng.queue.add(b, now=0.5)
        pick = eng.queue.peek(now=1.0)
        assert pick is not None
        first = pick.pod.name
        # a head that doesn't own the DRF pick gets None — top-only
        # defer, never the runner-up from another tenant's band
        got = eng.queue.pop(now=1.0,
                            exclude=lambda i: i.pod.name == first)
        assert got is None
        # nothing was consumed and the band counts stayed truthful
        assert len(eng.queue) == 2
        live = eng.queue.drf_stats()["bands"]
        assert sum(n for t in live.values() for n in t.values()) == 2
        # the deferred entry kept its exact DRF position: an
        # unfiltered pop returns the very pod the defer skipped
        got2 = eng.queue.pop(now=1.0)
        assert got2 is not None and got2.pod.name == first
        got3 = eng.queue.pop(now=1.0)
        assert got3 is not None and got3.pod.name != first
        assert eng.queue.pop(now=1.0) is None

    def test_interleaved_heads_partition_exactly_once(self):
        """Two heads with complementary exclude predicates draining a
        mixed-tenant backlog: every pod is popped exactly once by the
        head that owns it — no double-pop, no loss, even though each
        deferred cycle returns None to the non-owning head."""
        eng = self._drf_eng()
        pods = [self._pod(f"p{i}", "acme" if i % 3 else "bux")
                for i in range(12)]
        for i, p in enumerate(pods):
            eng.queue.add(p, now=0.1 * i)
        owns = lambda info, h: hash(info.pod.name) % 2 == h
        popped: dict[int, list[str]] = {0: [], 1: []}
        idle = 0
        for cycle in range(200):
            head = cycle % 2
            got = eng.queue.pop(
                now=10.0, exclude=lambda i, h=head: not owns(i, h))
            if got is None:
                idle += 1
                if idle > 4 and not len(eng.queue):
                    break
                continue
            idle = 0
            assert owns(got, head)  # segregation honored
            popped[head].append(got.pod.name)
        drained = popped[0] + popped[1]
        assert sorted(drained) == sorted(p.name for p in pods)
        assert len(set(drained)) == len(pods)  # exactly once
        assert len(eng.queue) == 0

    def test_defer_preserves_at_pop_share_order(self):
        """A defer must not perturb exact-at-pop DRF: after tenant
        shares diverge (acme holds bound chips), the pick is the poor
        tenant's pod both before and after an interposed defer."""
        eng = self._drf_eng()
        cluster = eng.cluster
        for i in range(3):
            cluster.bind(self._pod(f"pre{i}", "acme"), "t0", [(i, 0, 0)])
        eng.policy.book.refresh()
        rich = self._pod("rich", "acme")
        poor = self._pod("poor", "free")
        eng.queue.add(rich, now=0.0)
        eng.queue.add(poor, now=0.5)
        # DRF pick is the poor tenant despite FIFO favoring rich
        assert eng.queue.pop(
            now=1.0, exclude=lambda i: i.pod.name == "poor") is None
        got = eng.queue.pop(now=1.0)
        assert got is not None and got.pod.name == "poor"


# ------------------------------------------------------- dispatch window
class _StallCluster(FakeCluster):
    """bind_async parks the commit until the test flushes it — the
    in-flight window is then directly observable."""

    def __init__(self, store):
        super().__init__(store)
        self.parked = []

    def bind_async(self, pod, node, assigned_chips=None, on_fail=None,
                   on_success=None, fence=None):
        self.parked.append((pod, node, assigned_chips, on_fail,
                            on_success, fence))

    def flush_one(self):
        pod, node, chips, on_fail, on_success, fence = self.parked.pop(0)
        try:
            self.bind(pod, node, chips, fence=fence)
        except Exception as e:
            if on_fail:
                on_fail(pod, node, e)
            return
        if on_success:
            on_success(pod, node)


def test_head_dispatch_depth_bounds_inflight_binds():
    store, _ = _rig()
    cluster = _StallCluster(store)
    cluster.add_nodes_from_telemetry()
    eng = Scheduler(cluster, _cfg(head_dispatch_depth=2,
                                  batch_max_pods=1), clock=None)
    for i in range(6):
        eng.submit(Pod(f"p{i}", labels={"tpu/accelerator": "tpu",
                                        "scv/number": "1"}))
    done = threading.Event()

    def drive():
        for _ in range(20):
            eng.run_one()
        done.set()

    t = threading.Thread(target=drive, daemon=True)
    t.start()
    time.sleep(0.5)
    # the engine thread is PARKED on the window semaphore with exactly
    # `head_dispatch_depth` dispatches outstanding
    assert len(cluster.parked) == 2
    assert not done.is_set()
    # flushing frees window slots one for one
    while not done.is_set() or cluster.parked:
        if cluster.parked:
            cluster.flush_one()
        time.sleep(0.005)
    t.join(timeout=5.0)
    assert len([p for p in cluster.all_pods()]) == 6


# ------------------------------------------------------------------ config
def test_schedule_heads_env_and_profile_knobs(monkeypatch):
    monkeypatch.setenv("YODA_SCHEDULE_HEADS", "4")
    monkeypatch.setenv("YODA_HEAD_DISPATCH", "8")
    cfg = SchedulerConfig()
    assert cfg.schedule_heads == 4
    assert cfg.head_dispatch_depth == 8
    monkeypatch.delenv("YODA_SCHEDULE_HEADS")
    monkeypatch.delenv("YODA_HEAD_DISPATCH")
    assert SchedulerConfig().schedule_heads == 1
    cfg = SchedulerConfig.from_profile({"pluginConfig": [
        {"name": "yoda-tpu",
         "args": {"scheduleHeads": 3, "headDispatchDepth": 5}}]})
    assert cfg.schedule_heads == 3
    assert cfg.head_dispatch_depth == 5


def test_fleet_composes_heads_per_replica():
    _s, cluster = _rig()
    fleet = FleetCoordinator(cluster, _cfg(schedule_heads=2),
                             replicas=2, clock=FakeClock())
    assert all(r.headset is not None and r.headset.n == 2
               for r in fleet.replicas)
    pods = _workload(13)
    for p in pods:
        fleet.submit(p)
    fleet.run_until_idle()
    assert all(p.phase == PodPhase.BOUND for p in pods)
    stats = fleet.fleet_stats()
    assert "heads" in stats
    assert stats["pods_scheduled_total"] == len(pods)
