"""Regression tests for the round-2 ADVICE findings (ADVICE.md):

1. (medium) terminating victims must not defeat nominated-capacity holds:
   a pod in graceful termination (deletionTimestamp set) stays in the watch
   cache holding its chips, the preemptor's nomination survives the drain
   window, and a lower-priority pod cannot steal the freed hole.
2. (medium) a bind failure (API outage outlasting the client retry budget)
   must roll back the reservation and requeue the pod — not strand it.
3. (low) a pod deleted externally while queued/parked releases its
   nomination hold, queue entry, and gang state via forget().
4. (low) persistent 410 Gone on watch must not become a tight LIST loop.
5. (low) KubeCluster.stop() joins reflector threads / closes streams.

Plus VERDICT round-2 item 9: poll-mode resync prunes vanished-node
telemetry symmetrically with the watch path.
"""

from __future__ import annotations

import json
import threading
import time

from yoda_scheduler_tpu.k8s.client import KubeClient, KubeCluster, Reflector
from yoda_scheduler_tpu.scheduler import FakeCluster, Scheduler, SchedulerConfig
from yoda_scheduler_tpu.scheduler.core import FakeClock
from yoda_scheduler_tpu.telemetry import TelemetryStore, make_tpu_node
from yoda_scheduler_tpu.utils import Pod, PodPhase


def _store_with(node: str = "n1", chips: int = 4) -> TelemetryStore:
    store = TelemetryStore()
    m = make_tpu_node(node, chips=chips)
    m.heartbeat = time.time() + 1e8  # never stale under FakeClock starts
    store.put(m)
    return store


def mk_sched(cluster, **cfg_kw):
    cfg = SchedulerConfig(telemetry_max_age_s=1e9, **cfg_kw)
    clock = FakeClock(start=time.time())
    return Scheduler(cluster, cfg, clock=clock), clock


# --------------------------------------------------------------- ADVICE #2
class FlakyBindCluster(FakeCluster):
    """FakeCluster whose bind raises on chosen attempts (apiserver outage
    outlasting the KubeClient retry budget)."""

    def __init__(self, telemetry, fail_times=0, fail_on=()):
        super().__init__(telemetry)
        self.fail_times = fail_times      # fail the first N attempts
        self.fail_on = set(fail_on)       # and/or specific attempt numbers
        self.bind_attempts = 0

    def bind(self, pod, node, assigned_chips=None):
        self.bind_attempts += 1
        if self.fail_times > 0 or self.bind_attempts in self.fail_on:
            self.fail_times = max(0, self.fail_times - 1)
            raise RuntimeError("apiserver outage")
        super().bind(pod, node, assigned_chips)


class TestBindFailure:
    def test_bind_failure_requeues_and_recovers(self):
        cluster = FlakyBindCluster(_store_with(), fail_times=1)
        cluster.add_nodes_from_telemetry()
        sched, clock = mk_sched(cluster)
        pod = Pod("p", labels={"scv/number": "2"})
        sched.submit(pod)

        assert sched.run_one() == "bind-error"
        # not stranded: still tracked, not failed, reservation rolled back
        assert sched.tracks(pod.key)
        assert pod.key not in sched.failed
        assert sched.allocator.assignment_of(pod) is None
        assert sched.allocator.pending_chip_count("n1") == 0
        assert sched.metrics.counters.get("bind_errors_total") == 1

        clock.advance(2.0)  # past the first backoff
        assert sched.run_one() == "bound"
        assert pod.phase == PodPhase.BOUND
        assert cluster.bind_attempts == 2

    def test_bind_failure_does_not_leak_nomination(self):
        """The preemptor keeps its nomination across a transient bind
        failure (the entitlement is consumed only on a successful bind)."""
        cluster = FlakyBindCluster(_store_with(), fail_times=1)
        cluster.add_nodes_from_telemetry()
        sched, clock = mk_sched(cluster)
        pod = Pod("hi", labels={"scv/number": "4", "scv/priority": "9"})
        sched.allocator.nominate(pod.key, "n1", 4, 9)
        sched.submit(pod)
        assert sched.run_one() == "bind-error"
        assert sched.allocator.nomination_of(pod.key) is not None
        clock.advance(2.0)
        assert sched.run_one() == "bound"
        assert sched.allocator.nomination_of(pod.key) is None

    def test_anchor_bind_failure_rejects_waiting_gang_peers(self):
        """If the gang-completing member's bind fails, parked peers must
        roll back immediately (reservations released, requeued) instead of
        sitting at Permit until the deadline."""
        store = TelemetryStore()
        from yoda_scheduler_tpu.telemetry import make_v4_slice

        for m in make_v4_slice("s1", "2x2x2"):
            m.heartbeat = time.time() + 1e8
            store.put(m)
        cluster = FlakyBindCluster(store, fail_times=1)
        cluster.add_nodes_from_telemetry()
        sched, clock = mk_sched(cluster, gang_timeout_s=30.0)
        gang = [
            Pod(f"g-{i}", labels={
                "tpu/gang-name": "g", "tpu/gang-size": "2",
                "scv/number": "4", "tpu/accelerator": "tpu"})
            for i in range(2)
        ]
        for p in gang:
            sched.submit(p)
        assert sched.run_one() == "waiting"      # first member parks
        assert sched.run_one() == "bind-error"   # anchor bind fails
        # whole gang rolled back: no parked pods, no pending reservations
        assert sched.waiting == {}
        assert all(sched.allocator.assignment_of(p) is None for p in gang)
        # gang recovers after backoff
        clock.advance(3.0)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in gang)

    def test_peer_bind_failure_recovers_via_bound_member_count(self):
        """A PEER's bind failing after the anchor bound must not strand a
        half-bound gang: the retrying peer counts already-bound members
        from the cluster snapshot and re-admits onto their slice."""
        store = TelemetryStore()
        from yoda_scheduler_tpu.telemetry import make_v4_slice

        for m in make_v4_slice("s1", "2x2x2"):
            m.heartbeat = time.time() + 1e8
            store.put(m)
        # attempt 1 = anchor (gang-completing member), attempt 2 = the peer
        cluster = FlakyBindCluster(store, fail_on={2})
        cluster.add_nodes_from_telemetry()
        sched, clock = mk_sched(cluster, gang_timeout_s=30.0)
        gang = [
            Pod(f"g-{i}", labels={
                "tpu/gang-name": "g", "tpu/gang-size": "2",
                "scv/number": "4", "tpu/accelerator": "tpu"})
            for i in range(2)
        ]
        for p in gang:
            sched.submit(p)
        assert sched.run_one() == "waiting"   # g-0 parks
        assert sched.run_one() == "bound"     # g-1 binds; g-0's bind fails
        bound_now = [p for p in gang if p.phase == PodPhase.BOUND]
        assert len(bound_now) == 1            # half-bound for the moment
        clock.advance(3.0)
        sched.run_until_idle()
        assert all(p.phase == PodPhase.BOUND for p in gang)
        # both landed on the same slice
        nodes = {p.node for p in gang}
        assert all(n.startswith("s1-") for n in nodes)


# --------------------------------------------------------------- ADVICE #1
class GracefulCluster(FakeCluster):
    """Evict marks the pod terminating (graceful deletion) instead of
    removing it — the KubeCluster write-through behaviour on a real API
    server. finish() completes the termination."""

    supports_local_requeue = False

    def evict(self, pod):
        with self._lock:
            pod.terminating = True
            self._bump(pod.node)

    def finish(self, pod):
        FakeCluster.evict(self, pod)


class TestNominationSurvivesDrain:
    def _setup(self, **cfg_kw):
        cluster = GracefulCluster(_store_with(chips=4))
        cluster.add_nodes_from_telemetry()
        sched, clock = mk_sched(cluster, **cfg_kw)
        victim = Pod("victim", labels={"scv/number": "4", "scv/priority": "0"})
        sched.submit(victim)
        assert sched.run_one() == "bound"
        return cluster, sched, clock, victim

    def test_preemptor_waits_out_victim_drain(self):
        cluster, sched, clock, victim = self._setup()
        pre = Pod("pre", labels={"scv/number": "4", "scv/priority": "9"})
        sched.submit(pre)
        assert sched.run_one() == "preempting"
        assert victim.terminating
        assert sched.allocator.nomination_of(pre.key) is not None

        # victim still draining: the nominated node fails the filter, but
        # the hold must SURVIVE (this was the round-2 defect: released on
        # the first non-ok verdict)
        assert sched.run_one() == "unschedulable"
        assert sched.allocator.nomination_of(pre.key) is not None
        # and no second preemption round was planned
        assert sched.metrics.counters.get("preemptions_total") == 1

        cluster.finish(victim)
        clock.advance(2.0)
        assert sched.run_one() == "bound"
        assert pre.phase == PodPhase.BOUND
        assert sched.allocator.nomination_of(pre.key) is None

    def test_lower_priority_pod_cannot_steal_the_hole(self):
        # max_attempts lets the permanently-blocked thief fail out so
        # run_until_idle terminates
        cluster, sched, clock, victim = self._setup(max_attempts=6)
        pre = Pod("pre", labels={"scv/number": "4", "scv/priority": "9"})
        sched.submit(pre)
        assert sched.run_one() == "preempting"
        assert sched.run_one() == "unschedulable"  # drain window

        # victim finishes; an opportunist shows up first. The drain event
        # wakes the parked preemptor immediately (victim-drain queueing
        # hint), and its priority puts it AHEAD of the thief in the pop
        # order — the hole is consumed by its owner before the thief ever
        # gets a cycle
        cluster.finish(victim)
        thief = Pod("thief", labels={"scv/number": "2", "scv/priority": "1"})
        sched.submit(thief)
        assert sched.run_one() == "bound"
        assert pre.phase == PodPhase.BOUND
        clock.advance(2.0)
        sched.run_until_idle()
        assert thief.phase != PodPhase.BOUND


# --------------------------------------------------------------- ADVICE #3
class TestForget:
    def test_forget_releases_nomination_and_queue_entry(self):
        cluster = GracefulCluster(_store_with(chips=4))
        cluster.add_nodes_from_telemetry()
        sched, clock = mk_sched(cluster)
        victim = Pod("victim", labels={"scv/number": "4", "scv/priority": "0"})
        sched.submit(victim)
        sched.run_one()
        pre = Pod("pre", labels={"scv/number": "4", "scv/priority": "9"})
        sched.submit(pre)
        assert sched.run_one() == "preempting"
        assert sched.allocator.nomination_of(pre.key) is not None

        sched.forget(pre.key)  # external DELETE observed by the serve loop
        assert sched.allocator.nomination_of(pre.key) is None
        assert not sched.tracks(pre.key)
        # the freed capacity is usable by anyone again
        cluster.finish(victim)
        late = Pod("late", labels={"scv/number": "4"})
        sched.submit(late)
        sched.run_until_idle()
        assert late.phase == PodPhase.BOUND

    def test_forget_parked_gang_member_fails_the_gang(self):
        """A vanished parked member must reset the gang — its key left in
        the coordinator would let a re-formed gang 'complete' with a
        phantom member and bind size-1 real pods."""
        store = TelemetryStore()
        from yoda_scheduler_tpu.telemetry import make_v4_slice

        for m in make_v4_slice("s1", "2x2x2"):
            m.heartbeat = time.time() + 1e8
            store.put(m)
        cluster = FakeCluster(store)
        cluster.add_nodes_from_telemetry()
        sched, clock = mk_sched(cluster, gang_timeout_s=30.0)
        a = Pod("a", labels={"tpu/gang-name": "g", "tpu/gang-size": "2",
                             "scv/number": "4", "tpu/accelerator": "tpu"})
        sched.submit(a)
        assert sched.run_one() == "waiting"
        assert sched.allocator.assignment_of(a) is not None

        sched.forget(a.key)
        assert not sched.tracks(a.key)
        assert sched.allocator.assignment_of(a) is None
        assert sched.allocator.pending_chip_count("s1-host-0") == 0

        # a single later member must NOT complete against the phantom
        b = Pod("b", labels={"tpu/gang-name": "g", "tpu/gang-size": "2",
                             "scv/number": "4", "tpu/accelerator": "tpu"})
        sched.submit(b)
        assert sched.run_one() == "waiting"
        assert b.phase != PodPhase.BOUND

    def test_queue_remove_heap_and_backoff(self):
        cluster = FakeCluster(_store_with())
        cluster.add_nodes_from_telemetry()
        sched, clock = mk_sched(cluster)
        for i in range(3):
            sched.submit(Pod(f"p{i}", labels={"scv/priority": str(i)}))
        assert sched.queue.remove("default/p1")
        assert not sched.queue.contains("default/p1")
        assert len(sched.queue) == 2
        # heap order intact after removal: highest priority pops first
        assert sched.queue.pop(now=clock.time()).pod.name == "p2"
        assert sched.queue.pop(now=clock.time()).pod.name == "p0"


# ----------------------------------------------------- watch-cache semantics
def _pod_obj(name, rv="1", uid="u1", node=None, terminating=False,
             phase="Running"):
    o = {
        "metadata": {"name": name, "namespace": "default",
                     "resourceVersion": rv, "uid": uid,
                     "labels": {"scv/number": "1"}},
        "spec": {"schedulerName": "yoda-scheduler"},
        "status": {"phase": phase},
    }
    if node:
        o["spec"]["nodeName"] = node
    if terminating:
        o["metadata"]["deletionTimestamp"] = "2026-01-01T00:00:00Z"
    return o


class TestTerminatingInWatchCache:
    def _cluster(self):
        client = KubeClient("https://fake",
                            transport=lambda m, p, b, t: (200, b"{}"))
        return KubeCluster(client, TelemetryStore(), watch=True)

    def test_evict_marks_terminating_and_keeps_chips(self):
        cluster = self._cluster()
        cluster._node_event("ADDED", {"metadata": {"name": "n1"}})
        cluster._pod_event("ADDED", _pod_obj("v", uid="u1", node="n1"))
        victim = cluster.pods_on("n1")[0]
        cluster.evict(victim)
        # still holding the node (graceful drain), flagged terminating
        on_node = cluster.pods_on("n1")
        assert len(on_node) == 1 and on_node[0].terminating
        assert cluster.pending_pods() == []

    def test_stale_modified_event_cannot_resurrect_nonterminating(self):
        cluster = self._cluster()
        cluster._node_event("ADDED", {"metadata": {"name": "n1"}})
        cluster._pod_event("ADDED", _pod_obj("v", uid="u1", node="n1"))
        cluster.evict(cluster.pods_on("n1")[0])
        # in-flight pre-delete MODIFIED (no deletionTimestamp) arrives late
        cluster._pod_event("MODIFIED", _pod_obj("v", rv="9", uid="u1",
                                                node="n1"))
        assert cluster.pods_on("n1")[0].terminating
        # the real termination event flows through normally
        cluster._pod_event("DELETED", _pod_obj("v", rv="10", uid="u1",
                                               node="n1", terminating=True))
        assert cluster.pods_on("n1") == []

    def test_terminating_pending_pod_is_not_schedulable_intake(self):
        cluster = self._cluster()
        cluster._pod_event("ADDED", _pod_obj("p", uid="u2", phase="Pending",
                                             terminating=True))
        assert cluster.pending_pods() == []
        assert "default/p" in cluster.known_pod_keys()


class TestQueuedPodDeletedGracefully:
    def test_serve_loop_forgets_terminating_queued_pod(self):
        """A pod deleted externally (graceful) while QUEUED must be
        forgotten before its final DELETED event — the engine must not
        later bind the deleting pod from its stale queued object."""
        from tests.fake_apiserver import FakeApiServer
        from yoda_scheduler_tpu.k8s.client import run_scheduler_against_cluster
        from yoda_scheduler_tpu.scheduler import SchedulerConfig

        def manifest(name, chips):
            return {"metadata": {
                        "name": name, "namespace": "default",
                        "labels": {"scv/number": chips},
                        "ownerReferences": [{"kind": "ReplicaSet",
                                             "name": "rs",
                                             "controller": True}]},
                    "spec": {"schedulerName": "yoda-scheduler"},
                    "status": {"phase": "Pending"}}

        def wait_for(cond, timeout=10.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if cond():
                    return True
                time.sleep(0.02)
            return False

        with FakeApiServer() as srv:
            srv.state.graceful_deletion = True
            srv.state.add_node("n1")
            srv.state.put_metrics(make_tpu_node("n1", chips=4).to_cr())
            srv.state.add_pod(manifest("blocker", "4"))
            client = KubeClient(srv.url)
            stop = threading.Event()
            t = threading.Thread(
                target=run_scheduler_against_cluster,
                args=(client, [(SchedulerConfig(
                    pod_initial_backoff_s=0.05, pod_max_backoff_s=0.2,
                    preemption=False), None)]),
                kwargs={"metrics_port": None, "poll_s": 0.05,
                        "stop_event": stop},
                daemon=True)
            t.start()
            try:
                assert wait_for(lambda: (srv.state.pod("blocker") or {})
                                .get("spec", {}).get("nodeName"))
                # q queues unschedulable (node full), then is deleted
                srv.state.add_pod(manifest("q", "4"))
                time.sleep(0.3)  # let it enter the queue and back off
                client.evict(Pod("q"))  # graceful: deletionTimestamp set
                assert wait_for(lambda: (srv.state.pod("q") or {})[
                    "metadata"].get("deletionTimestamp"))
                # capacity frees while q is still terminating
                client.evict(Pod("blocker"))
                srv.state.finish_termination("default/blocker")
                time.sleep(0.6)  # would be plenty for a stale bind
                assert not (srv.state.pod("q") or {}).get(
                    "spec", {}).get("nodeName"), \
                    "engine bound a deleting pod from its stale queue entry"
                # a fresh pod CAN use the capacity
                srv.state.add_pod(manifest("fresh", "4"))
                assert wait_for(lambda: (srv.state.pod("fresh") or {})
                                .get("spec", {}).get("nodeName") == "n1")
            finally:
                stop.set()
                t.join(timeout=5.0)


# --------------------------------------------------------------- ADVICE #4
class TestWatchExpiredBackoff:
    def test_persistent_410_does_not_tight_loop_lists(self):
        list_calls = [0]

        def transport(method, path, body, timeout):
            list_calls[0] += 1
            return 200, json.dumps(
                {"items": [], "metadata": {"resourceVersion": "5"}}).encode()

        def stream(method, path, timeout):
            return iter([json.dumps({"type": "ERROR", "object": {
                "kind": "Status", "code": 410}}).encode() + b"\n"])

        client = KubeClient("https://fake", transport=transport,
                            stream_transport=stream)
        refl = Reflector(client, "/api/v1/pods", lambda i: None,
                         lambda t, o: None, backoff_s=0.05, max_backoff_s=0.2)
        stop = threading.Event()
        t = threading.Thread(target=refl.run, args=(stop,), daemon=True)
        t.start()
        time.sleep(0.4)
        stop.set()
        t.join(timeout=2.0)
        # unbounded: hundreds of LISTs in 0.4s; with backoff: first re-list
        # immediate, then 0.05/0.1/0.2/0.2... => well under 12
        assert list_calls[0] < 12


# ------------------------------------------------- poll resync symmetrical
class TestPollResyncPrunes:
    def test_vanished_node_telemetry_pruned(self):
        m = make_tpu_node("gone", chips=4)
        phase = ["with-node"]

        def transport(method, path, body, timeout):
            if "tpunodemetrics" in path:
                items = [m.to_cr()] if phase[0] == "with-node" else []
            elif "nodes" in path:
                items = ([{"metadata": {"name": "gone"}}]
                         if phase[0] == "with-node" else [])
            else:
                items = []
            return 200, json.dumps(
                {"items": items, "metadata": {"resourceVersion": "1"}}).encode()

        client = KubeClient("https://fake", transport=transport)
        store = TelemetryStore()
        cluster = KubeCluster(client, store, watch=False)
        cluster.resync()
        assert store.get("gone") is not None
        phase[0] = "node-vanished"
        cluster.resync()
        assert store.get("gone") is None
        assert cluster.node_names() == []


# --------------------------------------------------------------- ADVICE #5
class TestStopJoinsThreads:
    def test_stop_terminates_reflectors_promptly(self):
        from tests.fake_apiserver import FakeApiServer

        with FakeApiServer() as srv:
            srv.state.add_node("n1")
            client = KubeClient(srv.url)
            cluster = KubeCluster(client, TelemetryStore(), watch=True)
            cluster.start()
            assert cluster.wait_synced(5.0)
            t0 = time.monotonic()
            cluster.stop()
            assert time.monotonic() - t0 < 5.0
            for t in cluster._threads:
                t.join(timeout=3.0)
            assert not any(t.is_alive() for t in cluster._threads)
