from .client import KubeClient, KubeCluster, run_scheduler_against_cluster
from .leaderelect import LeaderElector

__all__ = ["KubeClient", "KubeCluster", "run_scheduler_against_cluster", "LeaderElector"]
