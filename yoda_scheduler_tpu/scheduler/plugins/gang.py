"""Gang scheduling: all-or-nothing admission of multi-host pod-slice jobs.

New TPU-native capability (SURVEY §7 "hard part (1)"): a v4-32 Llama job is
one worker pod per host of a 4-host slice; binding 3 of 4 workers deadlocks
the job while holding 12 chips. The k8s framework scores nodes one pod at a
time, so cross-pod state lives in a shared GangCoordinator and admission
goes through Permit:

- first member to Reserve picks the slice (members' Filter then sticks to it)
- every member's Permit returns WAIT until the gang is complete
- the last member's arrival approves all waiting members (bind together)
- timeout or any member's failure rejects the whole gang: all reservations
  roll back, the chosen slice resets, everything requeues with backoff.
"""

from __future__ import annotations

import threading

from ..framework import (
    CANDIDATE_NODES_KEY,
    ClusterEvent,
    CycleState,
    EnqueueExtensions,
    GANG_MEMBER_ARRIVED,
    NO_BATCH,
    NODE_TELEMETRY_UPDATED,
    PermitPlugin,
    POD_DELETED,
    PreFilterPlugin,
    QUEUE,
    ReservePlugin,
    SKIP,
    Status,
)
from ...utils.labels import GANG_NAME_LABEL, WorkloadSpec, spec_for
from ...utils.pod import Pod


def bound_gang_members(state: CycleState, gang: str) -> tuple[set[str], str | None, dict[str, int]]:
    """(pod keys, a slice id, per-slice member counts) of gang members
    ALREADY BOUND in the cluster, from this cycle's snapshot — cluster
    truth, not coordinator state.

    This is what lets a gang survive partial binds: if a peer's bind fails
    after the anchor bound (API outage mid-gang), or the scheduler restarts
    mid-assembly, the coordinator's waiting set is gone but the bound
    members are still visible on their nodes. A retrying member counts them
    toward gang completeness and sticks to their slice(s). Cached per cycle
    in CycleState (one snapshot scan per gang per cycle).

    Caveat: gang names must be unique per job — reusing a name while an
    older gang's pods are still bound would let the new gang 'complete'
    against them."""
    key = "gang_bound:" + gang
    cached = state.read_or(key)
    if cached is not None:
        return cached
    keys: set[str] = set()
    slice_id: str | None = None
    by_slice: dict[str, int] = {}
    snapshot = state.read_or("snapshot")
    if snapshot is not None:
        for ni in snapshot.list():
            for p in ni.pods:
                if (p.labels.get(GANG_NAME_LABEL) == gang
                        and not p.terminating):
                    keys.add(p.key)
                    if ni.metrics is not None and ni.metrics.slice_id:
                        slice_id = ni.metrics.slice_id
                        by_slice[slice_id] = by_slice.get(slice_id, 0) + 1
    result = (keys, slice_id, by_slice)
    state.write(key, result)
    return result


class GangCoordinator:
    """Shared cross-cycle gang state (gang name -> members/slice/plan)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._slice: dict[str, str] = {}          # gang -> chosen slice id
        self._waiting: dict[str, set[str]] = {}   # gang -> waiting pod keys
        # multi-slice placement plan (set when no single slice can host the
        # whole gang): gang -> {slice_id: member quota}; `placed` counts
        # members that RESERVED onto each slice (decremented on unreserve,
        # kept across bind — a bound member still occupies its quota slot)
        self._plan: dict[str, dict[str, int]] = {}
        self._placed: dict[str, dict[str, int]] = {}
        # geometric carve (scheduler/carve.py, torusPlacement knob):
        # gang -> {slice_id: frozenset(host names)} — advisory narrowing
        # for _write_candidates, torn down with the rest of the gang
        # state so a failed assembly re-carves against fresh capacity
        self._carve: dict[str, dict[str, frozenset]] = {}

    def chosen_slice(self, gang: str) -> str | None:
        with self._lock:
            return self._slice.get(gang)

    def choose_slice(self, gang: str, slice_id: str) -> None:
        with self._lock:
            self._slice.setdefault(gang, slice_id)

    # -------------------------------------------------- multi-slice plans
    def set_plan(self, gang: str, plan: dict[str, int],
                 pre_placed: dict[str, int] | None = None) -> None:
        with self._lock:
            self._plan[gang] = dict(plan)
            self._placed[gang] = dict(pre_placed or {})

    def plan_of(self, gang: str) -> dict[str, int] | None:
        with self._lock:
            p = self._plan.get(gang)
            return dict(p) if p is not None else None

    def quota_left(self, gang: str, slice_id: str) -> int | None:
        """Remaining member slots on `slice_id` under the gang's plan;
        None when the gang has no multi-slice plan."""
        with self._lock:
            plan = self._plan.get(gang)
            if plan is None:
                return None
            placed = self._placed.get(gang, {})
            return plan.get(slice_id, 0) - placed.get(slice_id, 0)

    def quotas_left(self, gang: str) -> dict[str, int] | None:
        """All slices' remaining quotas in ONE lock round-trip (the
        per-node narrowing pass would otherwise take the lock O(nodes)
        times per cycle); None when the gang has no plan. Slices absent
        from the dict have no quota (same verdict as quota_left <= 0)."""
        with self._lock:
            plan = self._plan.get(gang)
            if plan is None:
                return None
            placed = self._placed.get(gang, {})
            return {sid: q - placed.get(sid, 0) for sid, q in plan.items()}

    # ------------------------------------------------- geometric carves
    def set_carve(self, gang: str, carve: dict[str, frozenset]) -> None:
        with self._lock:
            self._carve[gang] = dict(carve)

    def carve_of(self, gang: str) -> dict[str, frozenset] | None:
        with self._lock:
            c = self._carve.get(gang)
            return dict(c) if c is not None else None

    def clear_carve(self, gang: str) -> None:
        with self._lock:
            self._carve.pop(gang, None)

    def record_placement(self, gang: str, slice_id: str, delta: int = 1) -> None:
        with self._lock:
            if gang in self._plan:
                placed = self._placed.setdefault(gang, {})
                placed[slice_id] = max(placed.get(slice_id, 0) + delta, 0)

    def add_waiting(self, gang: str, pod_key: str) -> int:
        with self._lock:
            s = self._waiting.setdefault(gang, set())
            s.add(pod_key)
            return len(s)

    def waiting_members(self, gang: str) -> set[str]:
        with self._lock:
            return set(self._waiting.get(gang, set()))

    def reset(self, gang: str) -> set[str]:
        """Tear down gang state; returns the members that were waiting."""
        with self._lock:
            members = self._waiting.pop(gang, set())
            self._slice.pop(gang, None)
            self._plan.pop(gang, None)
            self._placed.pop(gang, None)
            self._carve.pop(gang, None)
            return members


class GangPermit(PermitPlugin, ReservePlugin, PreFilterPlugin,
                 EnqueueExtensions):
    name = "gang-permit"

    # --------------------------------------------------- queueing hints
    def events_to_register(self) -> tuple:
        """A parked gang member becomes schedulable when a sibling
        (re)arrives (assembly can complete / a doomed gang revives), or
        when slice capacity frees up (a departing pod or recovered chips
        can make a big-enough slice appear)."""
        return (GANG_MEMBER_ARRIVED, POD_DELETED, NODE_TELEMETRY_UPDATED)

    def queueing_hint(self, event: ClusterEvent, pod) -> str:
        if event.kind == GANG_MEMBER_ARRIVED:
            # only the arriving member's OWN gang benefits — other gangs'
            # members stay parked (their assembly state is unchanged)
            if event.gang and event.gang == pod.labels.get(GANG_NAME_LABEL):
                return QUEUE
            return SKIP
        return QUEUE  # capacity events: a slice may now fit the gang

    def __init__(self, gangs: GangCoordinator, timeout_s: float = 30.0,
                 allocator=None, elastic=None, carver=None) -> None:
        self.gangs = gangs
        self.timeout_s = timeout_s
        self.allocator = allocator  # ChipAllocator, for multi-slice planning
        # ElasticGangs controller (scheduler/elastic/): None = classic
        # all-or-nothing admission, placements bit-identical
        self.elastic = elastic
        # TorusCarver (scheduler/carve.py): None = classic free-host-count
        # planning, placements bit-identical (the torusPlacement knob)
        self.carver = carver

    def equivalence_key(self, pod):
        """Batch-cycle contract: gang members carry cross-pod assembly
        state (chosen slice, plan quotas, Permit parking) and NEVER batch;
        for everything else this plugin's PreFilter/Reserve/Permit hooks
        are immediate no-op successes."""
        if GANG_NAME_LABEL in pod.labels:
            return NO_BATCH
        return ()

    # PreFilter: when no single slice can host the whole gang, partition it
    # across slices (VERDICT r2 item 5) — fewest slices, largest chunks
    # first, which minimises the number of cross-slice DCN hops the job's
    # collectives must take (intra-slice traffic rides ICI; every extra
    # slice adds a DCN boundary).
    def pre_filter(self, state: CycleState, pod: Pod, snapshot) -> Status:
        spec: WorkloadSpec = state.read("workload_spec")
        if not spec.is_gang or self.allocator is None:
            return Status.success()
        self._maybe_carve(state, pod, snapshot, spec)
        st = self._maybe_plan(state, pod, snapshot, spec)
        if not st.ok:
            return st
        cand = self._write_candidates(state, spec, snapshot)
        if not cand:
            # no node can possibly host a member: fail HERE with the
            # narrowing's reason — the engine's scan would otherwise
            # skip every node and report an empty "no feasible node"
            return Status.unschedulable(
                f"gang {spec.gang_name}: no pod-slice node survives "
                "slice narrowing (membership / chosen slice / plan "
                f"quotas / {spec.gang_size} gang-sized slices)")
        return st

    def _maybe_carve(self, state: CycleState, pod: Pod, snapshot,
                     spec: WorkloadSpec) -> None:
        """Geometric narrowing (torusPlacement knob): carve the gang as
        contiguous host blocks before the legacy planner runs. A
        multi-slice carve fixes the plan too (quota accounting rides the
        existing machinery); a single-slice carve leaves slice choice to
        the first Reserve as usual. Skipped once assembly is underway or
        members are already bound — re-forming a partially-bound gang is
        the legacy path's job (its slice is pinned by cluster truth, a
        fresh carve could contradict it)."""
        if self.carver is None:
            return
        gang = spec.gang_name
        if (self.gangs.carve_of(gang) is not None
                or self.gangs.plan_of(gang) is not None
                or self.gangs.chosen_slice(gang) is not None
                or self.gangs.waiting_members(gang)):
            return
        bound, _, _ = bound_gang_members(state, gang)
        if bound:
            return
        carve = self.carver.carve_gang(state, pod, snapshot, spec,
                                       state.read_or("now"),
                                       state.read_or("degraded"))
        if carve is None:
            return
        self.gangs.set_carve(gang, carve)
        if len(carve) > 1:
            self.gangs.set_plan(
                gang, {sid: len(hosts) for sid, hosts in carve.items()})

    def _maybe_plan(self, state: CycleState, pod: Pod, snapshot,
                    spec: WorkloadSpec) -> Status:
        if self.gangs.plan_of(spec.gang_name) is not None:
            return Status.success()  # plan already fixed
        if (self.gangs.chosen_slice(spec.gang_name) is not None
                or self.gangs.waiting_members(spec.gang_name)):
            # single-slice assembly already underway: parked peers' chip
            # reservations make their slice LOOK short of free hosts, so
            # planning now would wrongly split a gang that fits one slice
            # (and pay an O(nodes) scan per member cycle for nothing)
            return Status.success()
        now = state.read_or("now")
        free_hosts: dict[str, int] = {}  # slice -> hosts that fit a member
        for ni in snapshot.list():
            m = ni.metrics
            if m is None or not m.slice_id:
                continue
            if (now is not None and m.stale(now=now)
                    and not state.read_or("degraded")):
                # blackout degraded mode: last-known slice capacity is
                # the best (only) planning input available
                continue
            if spec.accelerator is not None and m.accelerator != spec.accelerator:
                continue
            if (spec.tpu_generation is not None
                    and m.tpu_generation != spec.tpu_generation):
                continue
            stats = self.allocator.class_stats(ni, spec.min_free_mb,
                                               spec.min_clock_mhz)
            hold = self.allocator.holds_for(spec, ni, pod.key, now=now)
            if stats.count - hold >= spec.chips:
                free_hosts[m.slice_id] = free_hosts.get(m.slice_id, 0) + 1
                if (m.num_hosts >= spec.gang_size
                        and free_hosts[m.slice_id] >= spec.gang_size):
                    # single-slice path (chosen_slice mechanism); no plan
                    return Status.success()
        # account members already bound (partial re-form): their slices are
        # part of the plan and their slots pre-filled
        _, _, bound_by_slice = bound_gang_members(state, spec.gang_name)
        remaining = spec.gang_size - sum(bound_by_slice.values())
        plan = dict(bound_by_slice)
        for sid, count in sorted(free_hosts.items(),
                                 key=lambda kv: (-kv[1], kv[0])):
            if remaining <= 0:
                break
            take = min(count, remaining)
            if take > 0:
                plan[sid] = plan.get(sid, 0) + take
                remaining -= take
        if remaining > 0 or len(plan) <= 1:
            # cannot place even across slices (Filter will fail the pod and
            # preemption may run), or a single slice suffices after all
            return Status.success()
        self.gangs.set_plan(spec.gang_name, plan,
                            pre_placed=bound_by_slice)
        return Status.success()

    def _write_candidates(self, state: CycleState, spec: WorkloadSpec,
                          snapshot) -> frozenset:
        """Narrow the engine's filter scan to the nodes that can possibly
        host this gang member (framework.CANDIDATE_NODES_KEY). Hoists the
        cheap, eviction-invariant predicates of TelemetryFilter's gang
        branch — slice membership, plan quotas, the chosen (or
        bound-member-pinned) slice, gang-sized slices — so a 4-host
        placement stops paying a full-cluster filter fan-out per member
        cycle. Must stay aligned with _filter_checked's gang rejections:
        every node skipped here would be rejected there."""
        gang = spec.gang_name
        quotas = self.gangs.quotas_left(gang)
        chosen = self.gangs.chosen_slice(gang) if quotas is None else None
        if quotas is None and chosen is None:
            # members already bound pin the slice even when the
            # coordinator's state is gone (restart / peer bind failure)
            _, chosen, _ = bound_gang_members(state, gang)
        names = []
        for ni in snapshot.list():
            m = ni.metrics
            if m is None or not m.slice_id:
                continue
            if quotas is not None:
                if quotas.get(m.slice_id, 0) <= 0:
                    continue
            elif chosen is not None:
                if m.slice_id != chosen:
                    continue
            elif m.num_hosts < spec.gang_size:
                continue
            names.append(ni.name)
        carve = (self.gangs.carve_of(gang)
                 if self.carver is not None else None)
        if carve:
            # geometric narrowing: only the carved blocks' hosts. Safety
            # valve: if the carve no longer intersects the feasible set
            # (host lost since the carve), drop it and keep the legacy
            # candidates — the gang degrades instead of wedging
            allowed = set()
            for hosts in carve.values():
                allowed.update(hosts)
            narrowed = [n for n in names if n in allowed]
            if narrowed:
                names = narrowed
            else:
                self.gangs.clear_carve(gang)
        cand = frozenset(names)
        state.write(CANDIDATE_NODES_KEY, cand)
        return cand

    # Reserve: the first member fixes the slice choice for the whole gang
    # (single-slice path) or consumes its planned slice's quota.
    def reserve(self, state: CycleState, pod: Pod, node: str) -> Status:
        spec: WorkloadSpec = state.read("workload_spec")
        if spec.is_gang:
            snapshot = state.read_or("snapshot")
            node_info = snapshot.get(node) if snapshot is not None else None
            if node_info is not None and node_info.metrics is not None:
                slice_id = node_info.metrics.slice_id
                if self.gangs.plan_of(spec.gang_name) is not None:
                    self.gangs.record_placement(spec.gang_name, slice_id)
                else:
                    self.gangs.choose_slice(spec.gang_name, slice_id)
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node: str) -> None:
        spec = state.read_or("workload_spec")
        if spec is None or not getattr(spec, "is_gang", False):
            return None
        snapshot = state.read_or("snapshot")
        node_info = snapshot.get(node) if snapshot is not None else None
        if node_info is not None and node_info.metrics is not None:
            self.gangs.record_placement(spec.gang_name,
                                        node_info.metrics.slice_id, delta=-1)
        return None

    def permit(self, state: CycleState, pod: Pod, node: str) -> tuple[Status, float]:
        spec: WorkloadSpec = state.read("workload_spec")
        if not spec.is_gang:
            return Status.success(), 0.0
        n_waiting = self.gangs.add_waiting(spec.gang_name, pod.key)
        # members already bound in the cluster count toward completeness:
        # this re-admits stragglers of a partially-bound gang (peer bind
        # failure, scheduler restart mid-assembly) instead of parking them
        # at 1/N forever
        bound, _, _ = bound_gang_members(state, spec.gang_name)
        n_bound = len(bound - {pod.key})
        n = n_waiting + n_bound
        if n >= spec.gang_size:
            # gang complete: this pod proceeds; the engine approves the rest
            return Status.success(), 0.0
        if self.elastic is not None and spec.gang_min > 0:
            self.elastic.note_member_seen(spec.gang_name,
                                          state.read_or("now"))
            if n_bound >= spec.gang_min:
                # GROW: the gang already runs at (at least) min in
                # cluster truth — assembly is over, each further member
                # binds the moment it places (the engine counts the
                # bind via elastic.on_member_bound)
                return Status.success(), 0.0
            if (n >= spec.gang_min
                    and self.elastic.deadline_pressed(
                        spec, state.read_or("now"))):
                # deadline/SLO pressure: waiting for full assembly risks
                # the start deadline — admit at the current (>= min)
                # size; the engine approves the parked peers
                self.elastic.note_admitted_at_min(
                    spec.gang_name, initial=n_waiting, reason="deadline")
                return Status.success(), 0.0
        return Status.wait(
            f"gang {spec.gang_name}: {n}/{spec.gang_size} members placed"
        ), self.timeout_s

    # ------------------------------------------------------------ engine hooks
    def peers_to_approve(self, pod: Pod) -> set[str]:
        """After `pod`'s Permit succeeded, which waiting pods bind with it."""
        try:
            spec = spec_for(pod)
        except Exception:
            return set()
        if not spec.is_gang:
            return set()
        members = self.gangs.reset(spec.gang_name)
        members.discard(pod.key)
        return members

    def gang_of(self, pod: Pod) -> str | None:
        try:
            spec = spec_for(pod)
        except Exception:
            return None
        return spec.gang_name

    def fail_gang(self, gang: str) -> set[str]:
        """Timeout/failure: tear down and report members needing rollback."""
        return self.gangs.reset(gang)
