"""Columnar scheduling data plane: the cluster as parallel numpy arrays.

The paper's core loop — score every node from telemetry each cycle — is
the shape columnar batch evaluation accelerates (Tesserae and Gavel both
formulate placement as matrix operations over the full node set,
PAPERS.md). This module packs the per-cycle filter/score inputs into
parallel arrays with a stable node→row index:

- node columns: telemetry validity, heartbeat, accelerator/generation
  ids (interned strings), cordon flag, node-label class id, free-chip
  count, HBM free/total sums, label-claimed HBM;
- chip columns (2-D, padded to the widest node): free mask (healthy,
  unclaimed, unreserved), per-chip HBM free/total, clock, ICI bandwidth,
  core count, power, duty cycle.

The table is maintained INCREMENTALLY from the same directed change logs
(utils/changelog.py) the class memos consume: a bind updates one row,
never rebuilds the table. Row order mirrors ``snapshot.list()`` so the
engine's rotating-offset early-stop scan (percentageOfNodesToScore) is
reproduced index-for-index — the vectorized path must pick the SAME
candidates the scalar path would, in the same order (the scalar path
stays wired in as the fallback and ground truth; the parity fuzz in
tests/test_columnar.py pins agreement, same pattern as native/
placement.cc ↔ topology/native.py).

Plugins opt in per pod through ``filter_batch``/``score_batch``
(framework.py): anything the columns cannot express — gang slice state,
contiguous-block search, nominated-capacity holds, inter-pod affinity —
returns None and the pod takes the scalar path unchanged.
"""

from __future__ import annotations

import time
import zlib

try:  # numpy ships with the jax toolchain this image bakes in, but the
    import numpy as np  # scheduler must degrade to the scalar path without it

    HAVE_NUMPY = True
except Exception:  # pragma: no cover - exercised only on stripped images
    np = None
    HAVE_NUMPY = False

from ..telemetry.schema import HEALTHY


def pool_of(name: str) -> str:
    """Node -> node-pool key: the name with its trailing replica digits
    (and separator) stripped — ``s12-host-3`` and ``s12-host-0`` share
    pool ``s12-host``; ``t5-1`` -> ``t5``. Real fleets name nodes
    ``<pool>-<ordinal>`` (GKE node pools, TPU slice hosts), so the prefix
    IS the pool. A name with no digit suffix is its own pool."""
    stripped = name.rstrip("0123456789")
    if stripped != name:
        stripped = stripped.rstrip("-")
    return stripped or name


def shard_of_pool(pool: str, shard_count: int) -> int:
    """Stable pool -> shard hash (crc32: identical across processes and
    runs, the same discipline as fleet.shard_of)."""
    return zlib.crc32(pool.encode()) % max(shard_count, 1)


class ColumnarTable:
    """Parallel-array snapshot of the cluster, row-aligned with the
    engine's object snapshot (``snapshot.list()`` order)."""

    def __init__(self, allocator, shards: int = 0) -> None:
        self.allocator = allocator
        # pool sharding (columnarShards knob, 0 = off): rows carry a
        # shard id hashed from their node POOL (pool_of), and three
        # things become O(shard) instead of O(cluster):
        #   - membership rebuilds: rows of untouched pools are block-
        #     copied from the previous arrays (one vectorized gather per
        #     column) instead of re-filled through Python per row;
        #   - qualifying-chip memo invalidation: a row update bumps only
        #     its shard's serial, and qual() re-evaluates only the rows
        #     of moved shards in place of a full-table recompute;
        #   - change-log row repair: unchanged-shard rows are never
        #     revisited.
        # 0 keeps the pre-shard behaviour bit-for-bit (full refill on
        # membership change, whole-cache qual invalidation).
        self.shards = max(int(shards), 0)
        # incremental-commit kernels (nativeplane.IncrementalKernels),
        # attached by the engine when the native plane is live: the
        # post-bind row refresh rewrites the free-chip mask row in one C
        # call instead of a numpy op per column. None = numpy path.
        self.native_refresh = None
        self._idx_scratch = None
        # churn plane (config.churn_plane): attached by the engine —
        # sync() applies multi-row dirt as ONE batched delta-vector pass
        # (_sync_batched) instead of a _fill_row per row. event_kernels
        # (nativeplane.EventKernels) folds the whole batch in one C call;
        # None degrades the batch to a numpy scatter, and batch_events
        # False keeps the per-row scalar path (the ground truth).
        self.batch_events = False
        self.event_kernels = None
        # set by the engine: dirty node names since a version vector,
        # IGNORING membership movement (the ordinary changes_since
        # refuses across membership changes; the sharded rebuild needs
        # exactly that delta to know which surviving rows moved). None /
        # unattributable -> full rebuild, same as before.
        self.membership_dirty_fn = None
        self._vers: tuple | None = None
        self._names: list[str] = []
        self.index: dict[str, int] = {}
        # pool -> shard memo (names repeat across rebuilds; crc32 per
        # node per rebuild would be pure waste)
        self._pool_shard: dict[str, int] = {}
        # string interning for accelerator/generation equality masks; -1
        # never appears in a column, so unknown spec strings match nothing
        self._intern: dict[str, int] = {}
        # node-label classes: distinct labels dicts interned to small ids
        # so nodeSelector matching is one fancy-index over the id column
        self._label_classes: list[dict] = []
        self._label_key: dict[tuple, int] = {}
        self._sel_cache: dict = {}
        # per-(min_free, min_clock) qualifying-chip masks, invalidated by
        # sync serial (any row change)
        self._qual_cache: dict = {}
        self._serial = 0
        self._width = 1
        # per-shard change serials (sharded mode): qual() caches carry a
        # snapshot of this vector and repair only the shards that moved
        self._shard_serials = None
        self._row_shard = None
        # observability (tests + bench)
        # engine metrics sink (set by the engine): when present, sync()
        # stamps its wall time into the cycle_event_apply_ms histogram —
        # the "event application" share of the cycle-phase breakdown
        # (ISSUE 20 satellite; bench run_serve_steady folds it into
        # BENCH_SERVE50K.json). None keeps sync stamp-free.
        self.metrics = None
        self.rebuilds = 0
        self.row_updates = 0
        self.shard_rebuilds = 0   # membership rebuilds served sharded
        self.rows_copied = 0      # rows block-copied instead of refilled
        self.qual_repairs = 0     # qual() cache entries repaired in place

    def __len__(self) -> int:
        return len(self._names)

    # ------------------------------------------------------------- sharding
    def _shard_id(self, name: str) -> int:
        pool = pool_of(name)
        hit = self._pool_shard.get(pool)
        if hit is None:
            hit = shard_of_pool(pool, self.shards)
            if len(self._pool_shard) > 65536:
                self._pool_shard.clear()
            self._pool_shard[pool] = hit
        return hit

    def _row_dirtied(self, i: int) -> None:
        """One row changed in place: invalidate the qualifying-chip memo
        at the finest granularity available — the row's SHARD when
        sharding is on (qual() repairs just that shard's rows), the whole
        cache otherwise (the pre-shard behaviour)."""
        self._serial += 1
        if self._row_shard is not None:
            self._shard_serials[self._row_shard[i]] += 1
        else:
            self._qual_cache.clear()

    def shard_views(self):
        """Contiguous (shard, start, stop) row runs in table order — the
        per-shard array views sharded consumers (per-shard scans, the
        native refresh path) slice the global columns with. Row order is
        still snapshot order, so concatenating the runs IS the table."""
        if self._row_shard is None or not len(self._names):
            return [(0, 0, len(self._names))]
        out = []
        rs = self._row_shard
        start = 0
        cur = int(rs[0])
        for i in range(1, len(rs)):
            s = int(rs[i])
            if s != cur:
                out.append((cur, start, i))
                start, cur = i, s
        out.append((cur, start, len(rs)))
        return out

    # ------------------------------------------------------------- interning
    def _intern_id(self, s: str) -> int:
        hit = self._intern.get(s)
        if hit is None:
            hit = len(self._intern)
            self._intern[s] = hit
        return hit

    def intern_of(self, s: str) -> int:
        """Id of an already-seen string; -1 (matches no row) otherwise."""
        return self._intern.get(s, -1)

    def intern_table(self) -> dict[str, int]:
        """The live string -> id intern map (READ-ONLY; accel and
        generation strings share one id space). Batch scorers that map
        interned ids to per-value weights (HeterogeneityScore) build
        their lookup vectors from it — ids are dense [0, len)."""
        return self._intern

    def _label_id(self, labels: dict) -> int:
        key = tuple(sorted(labels.items()))
        hit = self._label_key.get(key)
        if hit is None:
            hit = len(self._label_classes)
            self._label_key[key] = hit
            self._label_classes.append(dict(labels))
        return hit

    def selector_classes(self, selector: dict):
        """Per-label-CLASS verdict vector for an exact-match nodeSelector
        (index = class id). The native fused kernel consumes this
        directly (one byte per class, broadcast through the class-id
        column inside the kernel); selector_mask broadcasts it here."""
        key = (tuple(sorted(selector.items())), len(self._label_classes))
        by_class = self._sel_cache.get(key)
        if by_class is None:
            by_class = np.fromiter(
                (all(ls.get(k) == v for k, v in selector.items())
                 for ls in self._label_classes),
                dtype=bool, count=len(self._label_classes))
            if len(self._sel_cache) > 64:
                self._sel_cache.clear()
            self._sel_cache[key] = by_class
        return by_class

    def selector_mask(self, selector: dict, rows=None):
        """Rows whose node labels satisfy an exact-match nodeSelector.
        Label classes are few, so the per-class check is done once and the
        verdict broadcast through the class-id column (whole table, or
        the given row subset)."""
        by_class = self.selector_classes(selector)
        lc = self.label_class if rows is None else self.label_class[rows]
        return by_class[lc]

    def new_true(self):
        return np.ones(len(self._names), dtype=bool)

    # ------------------------------------------------------------ allocation
    def _alloc(self, n: int, width: int) -> None:
        self._width = width
        # per-row telemetry identity: (id(metrics), generation). Chip
        # attribute columns move only on telemetry updates; binds and
        # reservations only flip the free mask — so a bind-dirtied row
        # re-fills the dynamic columns and skips the per-chip attribute
        # writes entirely (the hot path at drain time).
        self._row_gen: list = [None] * n
        self._row_chips: list = [()] * n  # (healthy, coords) per chip
        self.valid = np.zeros(n, dtype=bool)
        self.heartbeat = np.zeros(n, dtype=np.float64)
        self.accel = np.full(n, -2, dtype=np.int64)
        self.gen = np.full(n, -2, dtype=np.int64)
        self.unsched = np.zeros(n, dtype=bool)
        self.label_class = np.zeros(n, dtype=np.int64)
        self.free_count = np.zeros(n, dtype=np.int64)
        # per-pool torus geometry: this host's coordinate on its slice's
        # wrapped host grid (scheduler/carve.slice_host_coord), -1 for
        # standalone nodes / slices without coherent torus metadata.
        # Derived from slice_topology + generation + host_index, so it
        # rides the telemetry-identity gate like the chip attributes.
        self.host_cx = np.full(n, -1, dtype=np.int64)
        self.host_cy = np.full(n, -1, dtype=np.int64)
        self.host_cz = np.full(n, -1, dtype=np.int64)
        self.hbm_total_sum = np.zeros(n, dtype=np.int64)
        self.hbm_free_sum = np.zeros(n, dtype=np.int64)
        self.claimed_hbm = np.zeros(n, dtype=np.int64)
        self.chip_free = np.zeros((n, width), dtype=bool)
        self.chip_hbm_free = np.zeros((n, width), dtype=np.int64)
        self.chip_hbm_total = np.zeros((n, width), dtype=np.int64)
        self.chip_clock = np.zeros((n, width), dtype=np.int64)
        self.chip_bw = np.zeros((n, width), dtype=np.int64)
        self.chip_core = np.zeros((n, width), dtype=np.int64)
        self.chip_power = np.zeros((n, width), dtype=np.int64)
        self.chip_duty = np.zeros((n, width), dtype=np.float64)
        # native row-refresh scratch + cached base pointers (recomputed
        # here because every rebuild reallocates the buffers)
        self._idx_scratch = np.empty(max(width, 1), dtype=np.int64)
        self._chip_free_base = self.chip_free.ctypes.data
        self._scratch_ptr = self._idx_scratch.ctypes.data

    def _fill_row(self, i: int, ni) -> bool:
        """Recompute one row from a NodeInfo + the allocator's free set.
        The chip ATTRIBUTE columns are re-written only when the node's
        telemetry identity (object, generation) moved; bind/claim dirt
        touches only the dynamic columns (free mask, counts, claimed
        HBM). False = the row no longer fits the table shape (a node
        grew more chips than the padding width): caller rebuilds."""
        self.unsched[i] = ni.unschedulable
        self.label_class[i] = self._label_id(ni.labels)
        m = ni.metrics
        if m is None:
            if self._row_gen[i] is not None:
                self._row_gen[i] = None
                self._row_chips[i] = ()
                self.valid[i] = False
                self.heartbeat[i] = 0.0
                self.accel[i] = -2
                self.gen[i] = -2
                self.host_cx[i] = -1
                self.host_cy[i] = -1
                self.host_cz[i] = -1
                self.hbm_total_sum[i] = 0
                self.hbm_free_sum[i] = 0
                self.chip_free[i, :] = False
                self.chip_hbm_free[i, :] = 0
                self.chip_hbm_total[i, :] = 0
                self.chip_clock[i, :] = 0
                self.chip_bw[i, :] = 0
                self.chip_core[i, :] = 0
                self.chip_power[i, :] = 0
                self.chip_duty[i, :] = 0.0
            self.free_count[i] = 0
            self.claimed_hbm[i] = 0
            return True
        chips = m.chips
        if len(chips) > self._width:
            return False
        gen_key = (id(m), m.generation, len(chips))
        if self._row_gen[i] != gen_key:
            self._row_gen[i] = gen_key
            self._row_chips[i] = tuple(
                (c.health == HEALTHY, c.coords) for c in chips)
            k = len(chips)
            w = self._width
            self.valid[i] = True
            self.heartbeat[i] = m.heartbeat
            self.accel[i] = self._intern_id(m.accelerator)
            self.gen[i] = self._intern_id(m.tpu_generation)
            cx = cy = cz = -1
            if m.slice_id and m.num_hosts > 1:
                from .carve import slice_grid, slice_host_coord

                gw = slice_grid(m)
                if gw is not None:
                    cx, cy, cz = slice_host_coord(m, gw[0])
            self.host_cx[i] = cx
            self.host_cy[i] = cy
            self.host_cz[i] = cz
            self.hbm_total_sum[i] = m.hbm_total_sum
            self.hbm_free_sum[i] = m.hbm_free_sum
            self.chip_hbm_free[i, :k] = [c.hbm_free_mb for c in chips]
            self.chip_hbm_total[i, :k] = [c.hbm_total_mb for c in chips]
            self.chip_clock[i, :k] = [c.clock_mhz for c in chips]
            self.chip_bw[i, :k] = [c.ici_bandwidth_gbps for c in chips]
            self.chip_core[i, :k] = [c.core_count for c in chips]
            self.chip_power[i, :k] = [c.power_w for c in chips]
            self.chip_duty[i, :k] = [c.duty_cycle_pct for c in chips]
            if k < w:
                self.chip_hbm_free[i, k:] = 0
                self.chip_hbm_total[i, k:] = 0
                self.chip_clock[i, k:] = 0
                self.chip_bw[i, k:] = 0
                self.chip_core[i, k:] = 0
                self.chip_power[i, k:] = 0
                self.chip_duty[i, k:] = 0.0
        free = self.allocator.free_coords(ni)
        self.free_count[i] = len(free)
        self.claimed_hbm[i] = ni.claimed_hbm_mb()
        k = len(chips)
        nk = self.native_refresh
        if nk is not None:
            # one C call rewrites the whole padded free-mask row (zeroing
            # included) from the free chip indices — bit-identical to the
            # numpy writes below, minus their per-op dispatch. Pointers
            # are cached at _alloc time; the scratch round-trips through
            # numpy only for the bulk index assign.
            idx = [j for j, (h, co) in enumerate(self._row_chips[i])
                   if h and co in free]
            self._idx_scratch[:len(idx)] = idx
            nk.refresh_fn(self._chip_free_base + i * self._width,
                          self._width, self._scratch_ptr, len(idx))
        else:
            self.chip_free[i, :k] = [h and (co in free)
                                     for h, co in self._row_chips[i]]
            if k < self._width:
                self.chip_free[i, k:] = False
        return True

    # ----------------------------------------------------------------- sync
    def sync(self, snapshot, vers, changes_since_fn) -> bool:
        """Bring the table to the cycle's version vector. Dirty rows from
        the change logs are re-filled in place; membership changes, a
        trimmed log, or an unattributable allocator change ("*") rebuild
        from scratch. False = the backend exposes no version counters, so
        the table cannot be maintained (callers use the scalar path)."""
        if not HAVE_NUMPY or vers is None:
            return False
        if self._vers == vers:
            return len(self._names) == len(snapshot)
        if self.metrics is None:
            return self._sync_apply(snapshot, vers, changes_since_fn)
        # phase attribution: only real application work is stamped — the
        # version-vector no-op above costs two tuple compares and stays
        # out of the histogram
        t0 = time.perf_counter()
        try:
            return self._sync_apply(snapshot, vers, changes_since_fn)
        finally:
            self.metrics.observe("cycle_event_apply_ms",
                                 (time.perf_counter() - t0) * 1e3)

    def _sync_apply(self, snapshot, vers, changes_since_fn) -> bool:
        if self._vers is None or vers[2] != self._vers[2] \
                or len(snapshot) != len(self._names):
            # membership moved (or first sync): the sharded fast path
            # refills only the pools the delta touched and block-copies
            # the rest; everything else rebuilds from scratch
            if (self.shards and self._vers is not None
                    and self.membership_dirty_fn is not None):
                dirty = self.membership_dirty_fn(self._vers)
                if dirty is not None \
                        and self._rebuild_sharded(snapshot, vers, dirty):
                    return True
            return self._rebuild(snapshot, vers)
        _, dirty = changes_since_fn(self._vers)
        if dirty is None:
            return self._rebuild(snapshot, vers)
        if self.batch_events and len(dirty) > 1:
            return self._sync_batched(snapshot, vers, dirty)
        for name in dirty:
            i = self.index.get(name)
            if i is None:
                # telemetry for a non-member node: no row to update (the
                # object snapshot skips these identically)
                continue
            ni = snapshot.get(name)
            if ni is None or not self._fill_row(i, ni):
                return self._rebuild(snapshot, vers)
            self.row_updates += 1
            self._row_dirtied(i)
        self._vers = vers
        return True

    def _sync_batched(self, snapshot, vers, dirty) -> bool:
        """Churn-plane sync (config.churn_plane): apply a multi-row dirty
        set as one batched delta-vector pass. Rows whose telemetry
        identity is UNCHANGED — the equilibrium common case: binds and
        completions only move the free mask and the dynamic scalars — are
        gathered into flat vectors and applied together
        (_apply_rows_batched); rows whose identity moved (telemetry
        publish, node cleared) still take the scalar _fill_row, which is
        the only writer of the chip-attribute columns. Final table bytes,
        row_updates, and dirty-serial counts are identical to the scalar
        loop in sync() — only the per-row numpy/ctypes dispatch is
        amortized (parity fuzz: tests/test_churn_plane.py)."""
        fast: list = []
        for name in dirty:
            i = self.index.get(name)
            if i is None:
                # telemetry for a non-member node: no row to update (the
                # object snapshot skips these identically)
                continue
            ni = snapshot.get(name)
            if ni is None:
                return self._rebuild(snapshot, vers)
            m = ni.metrics
            if m is not None and len(m.chips) > self._width:
                return self._rebuild(snapshot, vers)
            if m is None \
                    or self._row_gen[i] != (id(m), m.generation,
                                            len(m.chips)):
                if not self._fill_row(i, ni):
                    return self._rebuild(snapshot, vers)
            else:
                fast.append((i, ni))
            self.row_updates += 1
            self._row_dirtied(i)
        if fast:
            self._apply_rows_batched(fast)
        self._vers = vers
        return True

    def _apply_rows_batched(self, fast) -> None:
        """Write a batch of identity-unchanged dirty rows from flat delta
        vectors: per-row scalars (unsched, label class, free count,
        claimed HBM) plus the concatenated free-chip indices with
        offsets. One eventplane C call when the kernel is bound; a numpy
        scatter otherwise. Both are store-for-store twins of
        _fill_row's dynamic-column branch."""
        n = len(fast)
        free_coords = self.allocator.free_coords
        rows = np.empty(n, dtype=np.int64)
        unsched_v = np.empty(n, dtype=np.uint8)
        scalars = np.empty((n, 3), dtype=np.int64)
        idx_all: list[int] = []
        offs = np.empty(n + 1, dtype=np.int64)
        offs[0] = 0
        for r, (i, ni) in enumerate(fast):
            rows[r] = i
            unsched_v[r] = ni.unschedulable
            scalars[r, 0] = self._label_id(ni.labels)
            free = free_coords(ni)
            idx_all.extend(j for j, (h, co)
                           in enumerate(self._row_chips[i])
                           if h and co in free)
            offs[r + 1] = len(idx_all)
            scalars[r, 1] = len(free)
            scalars[r, 2] = ni.claimed_hbm_mb()
        ek = self.event_kernels
        if ek is not None:
            idx = np.asarray(idx_all, dtype=np.int64)
            ek.apply_fn(self._chip_free_base, self._width,
                        rows.ctypes.data, n,
                        idx.ctypes.data, offs.ctypes.data,
                        unsched_v.ctypes.data, scalars.ctypes.data,
                        self.unsched.ctypes.data,
                        self.label_class.ctypes.data,
                        self.free_count.ctypes.data,
                        self.claimed_hbm.ctypes.data)
        else:
            self.unsched[rows] = unsched_v.astype(bool)
            self.label_class[rows] = scalars[:, 0]
            self.free_count[rows] = scalars[:, 1]
            self.claimed_hbm[rows] = scalars[:, 2]
            mask = np.zeros((n, self._width), dtype=bool)
            for r in range(n):
                mask[r, idx_all[offs[r]:offs[r + 1]]] = True
            self.chip_free[rows] = mask

    def refresh_row(self, name: str, ni, old_vers, new_vers) -> bool:
        """In-place single-row refresh for the batch commit loop
        (core._commit_batch): the caller has PROVEN — via change-log
        attribution — that every cluster change between `old_vers` and
        `new_vers` is on `name`, so re-filling that one row from the
        freshly-rebuilt NodeInfo brings the whole table to `new_vers`
        without a changes_since walk. Filling from the NodeInfo (rather
        than applying just the bind's chip delta) keeps the row correct
        even when something ELSE also moved on that node inside the bind
        window — a telemetry publish, a cordon, an async-bind rollback
        all attribute to the same name and are absorbed by the refill.
        The common case (bind only, telemetry identity unchanged) skips
        the chip-attribute columns and rewrites only the free mask and
        counts — the in-place decrement, by way of _fill_row's
        dynamic-column path. No-ops (False) unless the table currently
        sits exactly at `old_vers`; the ordinary sync() then repairs from
        the change logs later, so a refused refresh costs nothing but the
        skipped shortcut."""
        if not HAVE_NUMPY or self._vers is None or self._vers != old_vers \
                or new_vers is None:
            return False
        i = self.index.get(name)
        if i is None:
            return False
        if not self._fill_row(i, ni):
            return False  # shape outgrew the padding: next sync rebuilds
        self.row_updates += 1
        self._row_dirtied(i)
        self._vers = new_vers
        return True

    def _rebuild(self, snapshot, vers) -> bool:
        nodes = snapshot.list()
        width = 1
        for ni in nodes:
            if ni.metrics is not None and len(ni.metrics.chips) > width:
                width = len(ni.metrics.chips)
        self._alloc(len(nodes), width)
        self._names = [ni.name for ni in nodes]
        self.index = {name: i for i, name in enumerate(self._names)}
        self._install_shard_map()
        for i, ni in enumerate(nodes):
            self._fill_row(i, ni)
        self._vers = vers
        self._serial += 1
        self._qual_cache.clear()
        self.rebuilds += 1
        return True

    def _install_shard_map(self) -> None:
        if not self.shards:
            return
        self._row_shard = np.fromiter(
            (self._shard_id(n) for n in self._names),
            dtype=np.int64, count=len(self._names))
        self._shard_serials = np.zeros(self.shards, dtype=np.int64)
        self._qual_cache.clear()

    def _rebuild_sharded(self, snapshot, vers, dirty) -> bool:
        """Membership rebuild at pool granularity: rows whose node
        SURVIVED the membership change untouched (name present before and
        after, no change-log entry) are block-copied from the previous
        arrays with one vectorized gather per column; only new nodes and
        change-log-dirty rows pay the Python per-row fill. The dirty set
        comes from membership_dirty_fn — the change logs WITHOUT the
        membership-version gate — so a copied row is provably
        bit-identical to what _fill_row would recompute. False = the
        table shape moved (padding width changed) or the fast path can't
        serve this delta; the caller runs the full rebuild."""
        nodes = snapshot.list()
        width = 1
        for ni in nodes:
            if ni.metrics is not None and len(ni.metrics.chips) > width:
                width = len(ni.metrics.chips)
        if width != self._width or self._row_shard is None:
            return False
        old_index = self.index
        old_row_gen, old_row_chips = self._row_gen, self._row_chips
        old_cols = [self.valid, self.heartbeat, self.accel, self.gen,
                    self.unsched, self.label_class, self.free_count,
                    self.host_cx, self.host_cy, self.host_cz,
                    self.hbm_total_sum, self.hbm_free_sum,
                    self.claimed_hbm, self.chip_free, self.chip_hbm_free,
                    self.chip_hbm_total, self.chip_clock, self.chip_bw,
                    self.chip_core, self.chip_power, self.chip_duty]
        self._alloc(len(nodes), width)
        self._names = [ni.name for ni in nodes]
        self.index = {name: i for i, name in enumerate(self._names)}
        self._install_shard_map()
        new_cols = [self.valid, self.heartbeat, self.accel, self.gen,
                    self.unsched, self.label_class, self.free_count,
                    self.host_cx, self.host_cy, self.host_cz,
                    self.hbm_total_sum, self.hbm_free_sum,
                    self.claimed_hbm, self.chip_free, self.chip_hbm_free,
                    self.chip_hbm_total, self.chip_clock, self.chip_bw,
                    self.chip_core, self.chip_power, self.chip_duty]
        src: list[int] = []
        dst: list[int] = []
        fill: list[int] = []
        for i, ni in enumerate(nodes):
            j = old_index.get(ni.name)
            if j is None or ni.name in dirty:
                fill.append(i)
            else:
                src.append(j)
                dst.append(i)
        if src:
            src_a = np.asarray(src, dtype=np.int64)
            dst_a = np.asarray(dst, dtype=np.int64)
            for old_c, new_c in zip(old_cols, new_cols):
                new_c[dst_a] = old_c[src_a]
            for j, i in zip(src, dst):
                self._row_gen[i] = old_row_gen[j]
                self._row_chips[i] = old_row_chips[j]
        for i in fill:
            if not self._fill_row(i, nodes[i]):
                return self._rebuild(snapshot, vers)
        self._vers = vers
        self._serial += 1
        self._qual_cache.clear()
        self.shard_rebuilds += 1
        self.rows_copied += len(src)
        self.row_updates += len(fill)
        return True

    # ----------------------------------------------------------------- views
    def qual(self, min_free_mb: int, min_clock_mhz: int):
        """(2-D qualifying-chip mask, per-row qualifying count) for one
        workload class: free chips meeting the class's HBM/clock floors —
        the columnar twin of allocator.class_stats, cached per class until
        any row changes."""
        key = (min_free_mb, min_clock_mhz)
        hit = self._qual_cache.get(key)
        if hit is not None:
            if self._row_shard is None:
                return hit
            # sharded repair: entries survive row updates and re-evaluate
            # ONLY the rows of shards whose serial moved since the entry
            # was cached — O(shard), not O(cluster), per invalidation
            q, qc, serials = hit
            moved = np.flatnonzero(self._shard_serials != serials)
            if moved.size == 0:
                return q, qc
            rows = np.flatnonzero(np.isin(self._row_shard, moved))
            sub = (self.chip_free[rows]
                   & (self.chip_hbm_free[rows] >= min_free_mb)
                   & (self.chip_clock[rows] >= min_clock_mhz))
            q[rows] = sub
            qc[rows] = sub.sum(axis=1)
            serials[moved] = self._shard_serials[moved]
            self.qual_repairs += 1
            return q, qc
        q = (self.chip_free
             & (self.chip_hbm_free >= min_free_mb)
             & (self.chip_clock >= min_clock_mhz))
        qc = q.sum(axis=1)
        if len(self._qual_cache) > 16:
            self._qual_cache.clear()
        entry = ((q, qc) if self._row_shard is None
                 else (q, qc, self._shard_serials.copy()))
        self._qual_cache[key] = entry
        return q, qc

    def name_at(self, row: int) -> str:
        """Node name for a table row (the inverse of `index`) — batch
        scorers that must re-enter object-keyed memos (allocator
        contiguity, slice usage) map their row indices back here."""
        return self._names[row]

    def rows_for(self, infos):
        """Row indices for a list of NodeInfos; None when any name is
        unknown to the table (callers fall back to the scalar path)."""
        idx = self.index
        try:
            return np.fromiter((idx[ni.name] for ni in infos),
                               dtype=np.int64, count=len(infos))
        except KeyError:
            return None
