"""Serving-headroom quota level: reserved capacity for the SLO class.

ISSUE 19's admission half: ``servingHeadroomPct`` carves a slice of
cluster chips that only ``scv/serving`` pods may use — expressed as a
quota LEVEL sitting ABOVE every tenant in the PR 9/13 DRF hierarchy.
The DRFBook already splits serving usage out of its per-node
incremental accounting (fairness.DRFBook, serving_reserve_pct); this
gate is the enforcement tooth: a NON-serving pod (training and harvest
alike — harvest must not squat the reservation either, or a flash
crowd pays an eviction round-trip before its first bind) whose bind
would push the non-serving aggregate past ``(1 - pct) * capacity`` is
unschedulable now and wakes event-driven when capacity frees, exactly
the TenantQuotaGate discipline. Serving pods always pass: the
reservation is a floor for serving, a ceiling for everyone else.

Built only when ``sloServing`` is on AND the reservation is positive —
otherwise the profile carries no trace of it (the bit-identical
knob-off parity leg)."""

from __future__ import annotations

from ..framework import (
    CycleState,
    EnqueueExtensions,
    NODE_ADDED,
    NO_BATCH,
    POD_DELETED,
    PreFilterPlugin,
    QUEUE,
    Snapshot,
    Status,
)
from ...utils.labels import LabelError, spec_for


class ServingHeadroomGate(PreFilterPlugin, EnqueueExtensions):
    """PreFilter: refuse a non-serving pod whose bind would eat into
    the reserved serving headroom. Node-independent (one aggregate
    check per cycle, not per node)."""

    name = "serving-headroom-gate"

    def __init__(self, policy) -> None:
        self.policy = policy  # fairness.PolicyEngine

    def equivalence_key(self, pod):
        """Serving pods are a no-op by construction (always SUCCESS, no
        state) — they batch freely as one class. A NON-serving pod's
        verdict moves with every bind, including our own mid-batch
        commits the batch loop would not re-check, so it never batches
        (the TenantQuotaGate discipline)."""
        try:
            spec = spec_for(pod)
        except LabelError:
            return ("malformed",)  # the filter owns malformed pods
        return ("serving",) if spec.serving else NO_BATCH

    def events_to_register(self):
        # a pod leaving frees aggregate share; new capacity grows the
        # non-serving ceiling — either can cure a headroom rejection
        return (POD_DELETED, NODE_ADDED)

    def queueing_hint(self, event, pod) -> str:
        return QUEUE

    def pre_filter(self, state: CycleState, pod,
                   snapshot: Snapshot) -> Status:
        book = self.policy.book
        if book is None:
            return Status.success()
        spec = state.read_or("workload_spec")
        if spec is None:
            try:
                spec = spec_for(pod)
            except LabelError:
                return Status.success()
        if spec.serving:
            return Status.success()
        book.refresh()
        # a gang member is gated on the gang's UNBOUND remainder, the
        # quota-gate rule with one refinement: siblings parked at Permit
        # hold no cluster-truth usage yet (per-member gating would admit
        # each against the same headroom), but members ALREADY BOUND are
        # in the book's aggregate — whole-gang demand would double-count
        # them and wedge an elastic gang's re-growth toward full size
        mult = 1
        if spec.is_gang:
            from ..elastic.gangs import bound_member_count

            mult = max(spec.gang_size
                       - bound_member_count(book.cluster, spec.gang_name),
                       1)
        if not book.nonserving_over_reserve(spec.chips * mult):
            return Status.success()
        if self.policy.metrics is not None:
            self.policy.metrics.inc("serving_headroom_rejections_total")
        return Status.unschedulable(
            f"serving headroom: non-serving aggregate would exceed "
            f"{1.0 - book._serve_pct:.2f} of cluster chips "
            f"({book._serve_pct:.0%} reserved for scv/serving)")
