"""ISSUE 12 — the 50k-node data plane: pool-sharded ColumnarTable,
sharded reflectors, and the pipelined bind wire.

Contracts under test:

- the pool-sharded table (``columnarShards``) produces BIT-IDENTICAL
  placements vs the unsharded table across the existing columnar fuzz
  shapes, including node-pool membership churn mid-drain (the sharded
  rebuild's block-copy path);
- KubeClient's pipelined wire (``bindPipelineWindow``) lands a window of
  binds in one round with in-order conflict resolution through the same
  409/adopt protocol as the single-POST path;
- sharded reflection: a KubeCluster restricted to its owned pools
  ingests only them (server-side labelSelector + client-side guard) and
  hands watch ownership over with set_owned_pools; the in-memory fleet
  facade (ShardedOwnedView) keeps fleet invariants intact;
- the reservoir histogram keeps memory fixed past the threshold with
  quantiles inside tolerance (the golden test);
- an externally-deleted mid-growth elastic gang retires its _growing
  record on the members' POD_DELETED events.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time

import pytest

np = pytest.importorskip("numpy")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from yoda_scheduler_tpu.scheduler import FakeCluster, Scheduler, SchedulerConfig
from yoda_scheduler_tpu.scheduler.columnar import ColumnarTable, pool_of
from yoda_scheduler_tpu.scheduler.core import FakeClock
from yoda_scheduler_tpu.scheduler.framework import ClusterEvent, POD_DELETED
from yoda_scheduler_tpu.telemetry import TelemetryStore, make_tpu_node
from yoda_scheduler_tpu.utils import Pod, PodPhase
from yoda_scheduler_tpu.utils.obs import Histogram

from test_columnar import T0, build_burst, build_cluster, end_state

SHARDS = 8


def drive(cluster, pods, shards: int, native: bool = False):
    sched = Scheduler(
        cluster,
        SchedulerConfig(max_attempts=3, columnar=True,
                        columnar_shards=shards, native_plane=native,
                        pod_hinted_backoff_s=0.0),
        clock=FakeClock(start=T0))
    for p in pods:
        sched.submit(p)
    sched.run_until_idle(max_cycles=10_000)
    return sched


# ------------------------------------------------------ sharded-table parity
def test_pool_of_shapes():
    assert pool_of("s12-host-3") == "s12-host"
    assert pool_of("t5-1") == "t5"
    assert pool_of("gpu-07") == "gpu"
    assert pool_of("plain") == "plain"


def test_sharded_parity_fuzz():
    """Pool-sharded scans + per-shard repair vs the unsharded table over
    the existing 200-case columnar fuzz shapes: bit-identical pod fates."""
    mismatches = []
    used = 0
    for case in range(210):
        rng_a = random.Random(9000 + case)
        rng_b = random.Random(9000 + case)
        cluster_a = build_cluster(rng_a)
        cluster_b = build_cluster(rng_b)
        pods_a = build_burst(rng_a)
        pods_b = build_burst(rng_b)
        sched_a = drive(cluster_a, pods_a, shards=SHARDS)
        sched_b = drive(cluster_b, pods_b, shards=0)
        used += sched_a.metrics.counters.get(
            "columnar_filter_cycles_total", 0)
        if end_state(pods_a) != end_state(pods_b):
            mismatches.append((case, end_state(pods_a), end_state(pods_b)))
    assert not mismatches, mismatches[:2]
    assert used > 200, used  # the vectorized path actually ran sharded


def _churn_cluster():
    store = TelemetryStore()
    for pool in ("pa", "pb", "pc"):
        for i in range(3):
            m = make_tpu_node(f"{pool}-{i}", chips=4)
            m.heartbeat = T0
            store.put(m)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    return cluster


def _churn_run(shards: int):
    """Drain with a node POOL joining and a node leaving mid-flight —
    the membership-churn case the sharded rebuild serves."""
    cluster = _churn_cluster()
    sched = Scheduler(
        cluster,
        SchedulerConfig(max_attempts=4, columnar=True,
                        columnar_shards=shards, native_plane=False,
                        pod_hinted_backoff_s=0.0),
        clock=FakeClock(start=T0))
    pods = [Pod(f"c{i}", labels={"scv/number": "1",
                                 "tpu/accelerator": "tpu"})
            for i in range(30)]
    for p in pods[:10]:
        sched.submit(p)
    sched.run_until_idle(max_cycles=2000)
    # a whole new pool joins; an existing node leaves
    for i in range(3):
        m = make_tpu_node(f"pd-{i}", chips=4)
        m.heartbeat = T0
        cluster.telemetry.put(m)
        cluster.add_node(f"pd-{i}")
    cluster.remove_node("pa-1")
    for p in pods[10:20]:
        sched.submit(p)
    sched.run_until_idle(max_cycles=2000)
    cluster.remove_node("pb-0")
    for p in pods[20:]:
        sched.submit(p)
    sched.run_until_idle(max_cycles=2000)
    return sched, pods


def test_shard_membership_churn_parity():
    sched_s, pods_s = _churn_run(SHARDS)
    sched_u, pods_u = _churn_run(0)
    assert end_state(pods_s) == end_state(pods_u)
    table = sched_s._columnar
    # the sharded rebuild actually served the churn: rows were block-
    # copied instead of refilled
    assert table.shard_rebuilds > 0
    assert table.rows_copied > 0


def test_sharded_rebuild_rows_bit_identical():
    """After churn, the sharded table's columns equal a from-scratch
    rebuild of the same snapshot (the copy path is provably exact)."""
    sched, _ = _churn_run(SHARDS)
    table = sched._columnar
    snapshot = sched.snapshot()
    vers = sched._cluster_versions()
    assert table.sync(snapshot, vers, sched._changes_since_vers)
    fresh = ColumnarTable(sched.allocator)
    assert fresh.sync(snapshot, vers, sched._changes_since_vers)
    assert table._names == fresh._names
    for col in ("valid", "heartbeat", "accel", "gen", "unsched",
                "label_class", "free_count", "hbm_total_sum",
                "hbm_free_sum", "claimed_hbm", "chip_free",
                "chip_hbm_free", "chip_hbm_total", "chip_clock",
                "chip_bw", "chip_core", "chip_power", "chip_duty"):
        a, b = getattr(table, col), getattr(fresh, col)
        if a.shape != b.shape:  # width padding may differ; compare overlap
            w = min(a.shape[-1], b.shape[-1])
            a = a[..., :w] if a.ndim == 2 else a
            b = b[..., :w] if b.ndim == 2 else b
        assert np.array_equal(a, b), col


def test_qual_cache_shard_repair():
    """A row update invalidates ONLY its shard's slice of the cached
    qualifying-chip mask; the repaired mask equals a fresh compute."""
    store = TelemetryStore()
    for pool in ("qa", "qb"):
        for i in range(4):
            m = make_tpu_node(f"{pool}-{i}", chips=4)
            m.heartbeat = T0
            store.put(m)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    sched = Scheduler(cluster, SchedulerConfig(
        columnar=True, columnar_shards=4, native_plane=False),
        clock=FakeClock(start=T0))
    snapshot = sched.snapshot()
    vers = sched._cluster_versions()
    table = sched._columnar
    assert table.sync(snapshot, vers, sched._changes_since_vers)
    q0, qc0 = table.qual(0, 0)
    assert qc0.sum() == 8 * 4
    # bind a pod onto one node: its row's shard serial moves
    p = Pod("qp", labels={"scv/number": "2", "tpu/accelerator": "tpu"})
    sched.submit(p)
    sched.run_until_idle(max_cycles=100)
    assert p.phase == PodPhase.BOUND
    snapshot = sched.snapshot()
    vers = sched._cluster_versions()
    assert table.sync(snapshot, vers, sched._changes_since_vers)
    q1, qc1 = table.qual(0, 0)
    assert table.qual_repairs >= 1
    fresh = (table.chip_free
             & (table.chip_hbm_free >= 0) & (table.chip_clock >= 0))
    assert np.array_equal(q1, fresh)
    assert np.array_equal(qc1, fresh.sum(axis=1))


# ------------------------------------------------------- pipelined bind wire
@pytest.fixture()
def api_server():
    from fake_apiserver import FakeApiServer

    with FakeApiServer() as server:
        yield server


def _wire_pod(name: str) -> dict:
    return {"metadata": {"name": name, "namespace": "default",
                         "labels": {"scv/number": "1"}},
            "spec": {"schedulerName": "yoda-scheduler"},
            "status": {"phase": "Pending"}}


def test_pipeline_binds_land_in_order(api_server):
    from yoda_scheduler_tpu.k8s.client import KubeClient

    server = api_server
    server.state.add_node("w-0")
    client = KubeClient(server.url)
    pods = []
    for i in range(6):
        server.state.add_pod(_wire_pod(f"bp{i}"))
        pods.append(Pod(f"bp{i}", labels={"scv/number": "1"}))
    items = [(p, "w-0", [(0, 0, i)], None) for i, p in enumerate(pods)]
    outs = client.bind_pipelined(items)
    assert outs == [None] * 6
    # every pod bound on the server, in one pipelined round
    for p in pods:
        live = server.state.pod(p.name)
        assert live["spec"]["nodeName"] == "w-0", p.name


def test_pipeline_conflict_resolves_in_order(api_server):
    from yoda_scheduler_tpu.k8s.client import ApiError, KubeClient

    server = api_server
    server.state.add_node("w-0")
    client = KubeClient(server.url)
    server.state.add_pod(_wire_pod("ok1"))
    server.state.add_pod(_wire_pod("dup"))
    server.state.add_pod(_wire_pod("ok2"))
    # pre-bind "dup" elsewhere: its slot must resolve as a 409 conflict
    # while its window-mates land
    client.bind(Pod("dup", labels={}), "w-0", [(9, 9, 9)])
    items = [
        (Pod("ok1", labels={}), "w-0", [(0, 0, 0)], None),
        (Pod("dup", labels={}), "w-0", [(1, 1, 1)], None),
        (Pod("ok2", labels={}), "w-0", [(2, 2, 2)], None),
    ]
    outs = client.bind_pipelined(items)
    assert outs[0] is None and outs[2] is None
    # the duplicate's read-back found OUR earlier identical target but a
    # different chip assignment -> conflict error, not silent adoption
    assert isinstance(outs[1], ApiError) and outs[1].status == 409


def test_pipelined_cluster_binds(api_server):
    """KubeCluster with bindPipelineWindow drains a burst through the
    pipelined binder; every bind lands and bookkeeping matches."""
    from yoda_scheduler_tpu.k8s.client import KubeClient, KubeCluster

    server = api_server
    server.state.add_node("w-0")
    server.state.add_node("w-1")
    client = KubeClient(server.url)
    cluster = KubeCluster(client, TelemetryStore(), watch=False,
                          bind_pipeline_window=4)
    done = []
    for i in range(8):
        server.state.add_pod(_wire_pod(f"pc{i}"))
        p = Pod(f"pc{i}", labels={"scv/number": "1"})
        cluster.bind_async(p, f"w-{i % 2}", [(0, 0, i)],
                           on_success=lambda pod, node: done.append(pod.name))
    assert cluster.flush_binds(timeout=10.0)
    assert sorted(done) == sorted(f"pc{i}" for i in range(8))
    assert cluster.bind_wire_n == 8
    for i in range(8):
        assert server.state.pod(f"pc{i}")["spec"]["nodeName"] == f"w-{i % 2}"
    cluster.stop()


# ------------------------------------------------------- sharded reflectors
def test_sharded_reflection_ingests_owned_pools_only(api_server):
    from yoda_scheduler_tpu.k8s.client import KubeClient, KubeCluster

    server = api_server
    for pool in ("pa", "pb"):
        for i in range(2):
            name = f"{pool}-{i}"
            server.state.add_node(name, labels={"pool": pool})
            m = make_tpu_node(name, chips=4)
            m.heartbeat = time.time() + 1e8
            server.state.put_metrics(m.to_cr())
    # one pod bound into each pool, plus a pending one
    server.state.add_pod(_wire_pod("pend"))
    for pool in ("pa", "pb"):
        body = _wire_pod(f"bound-{pool}")
        body["spec"]["nodeName"] = f"{pool}-0"
        server.state.add_pod(body)
    client = KubeClient(server.url)
    cluster = KubeCluster(client, TelemetryStore(), watch=True,
                          owned_pools={"pa"}, pool_label="pool")
    cluster.start()
    try:
        assert cluster.wait_synced(10.0)
        assert cluster.node_names() == ["pa-0", "pa-1"]
        assert set(cluster.telemetry.nodes()) == {"pa-0", "pa-1"}
        keys = cluster.known_pod_keys()
        assert "default/pend" in keys          # pending always ingested
        assert "default/bound-pa" in keys      # owned-pool bind
        assert "default/bound-pb" not in keys  # foreign-pool bind dropped
        # watch ownership handover: pb joins the owned set, pa leaves
        v0 = cluster.nodes_version
        cluster.set_owned_pools({"pb"})
        assert cluster.nodes_version > v0
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if cluster.node_names() == ["pb-0", "pb-1"]:
                break
            time.sleep(0.05)
        assert cluster.node_names() == ["pb-0", "pb-1"]
    finally:
        cluster.stop()


def test_fake_apiserver_label_selector(api_server):
    from yoda_scheduler_tpu.k8s.client import KubeClient

    server = api_server
    server.state.add_node("sa-0", labels={"pool": "sa"})
    server.state.add_node("sb-0", labels={"pool": "sb"})
    client = KubeClient(server.url)
    doc = client.list_all("/api/v1/nodes?labelSelector=pool%20in%20(sa)")
    names = [i["metadata"]["name"] for i in doc["items"]]
    assert names == ["sa-0"]
    doc = client.list_all("/api/v1/nodes?labelSelector=pool%3Dsb")
    names = [i["metadata"]["name"] for i in doc["items"]]
    assert names == ["sb-0"]


# ----------------------------------------------------- sharded fleet facade
def test_fleet_sharded_reflection_invariants():
    """Deterministic 4-replica fleet with reflectorSharding: every pod
    binds exactly once, no chip double-booked, and each replica's engine
    sees only its owned pools."""
    from yoda_scheduler_tpu.scheduler.fleet import FleetCoordinator

    store = TelemetryStore()
    for i in range(16):
        m = make_tpu_node(f"fp{i % 8}-{i // 8}", chips=4)
        m.heartbeat = T0 + 1e8
        store.put(m)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    fleet = FleetCoordinator(
        cluster,
        SchedulerConfig(max_attempts=8, telemetry_max_age_s=1e9,
                        reflector_sharding=True),
        replicas=4, mode="sharded", clock=FakeClock(start=T0), seed=3)
    pods = [Pod(f"fs{i}", labels={"scv/number": "1",
                                  "tpu/accelerator": "tpu"})
            for i in range(40)]
    for p in pods:
        fleet.submit(p)
    fleet.run_until_idle(max_cycles=20_000)
    bound = sum(1 for p in pods if p.phase == PodPhase.BOUND)
    assert bound >= 30, bound  # most bind; stragglers may lack shard room
    seen: dict = {}
    chip_owner: dict = {}
    for node in cluster.node_names():
        for p in cluster.pods_on(node):
            assert p.key not in seen
            seen[p.key] = node
            for c in p.assigned_chips():
                assert (node, c) not in chip_owner
                chip_owner[(node, c)] = p.key
    # each replica's membership is a strict subset of the cluster
    total = len(cluster.node_names())
    for rep in fleet.replicas:
        view_nodes = rep.engine.cluster.node_names()
        assert 0 < len(view_nodes) < total
        for n in view_nodes:
            assert rep.engine.fence_provider is not None


def test_fleet_sharded_reflection_no_poolless_starvation():
    """Pods keyed onto a shard whose pools hold NO nodes must still
    bind: routing remaps into populated shards (the wire drive caught
    pools vp0..vp3 all hashing to shard 0 of 2 — the shard-1 replica's
    view was empty and its pods waited forever)."""
    from yoda_scheduler_tpu.scheduler.columnar import pool_of, shard_of_pool
    from yoda_scheduler_tpu.scheduler.fleet import FleetCoordinator

    # all four pools provably land on shard 0 of 2
    assert all(shard_of_pool(pool_of(f"vp{i}-0"), 2) == 0 for i in range(4))
    store = TelemetryStore()
    for i in range(8):
        m = make_tpu_node(f"vp{i % 4}-{i // 4}", chips=4)
        m.heartbeat = T0 + 1e8
        store.put(m)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    fleet = FleetCoordinator(
        cluster,
        SchedulerConfig(max_attempts=8, telemetry_max_age_s=1e9,
                        reflector_sharding=True),
        replicas=2, mode="sharded", shard_count=2,
        clock=FakeClock(start=T0), seed=1)
    pods = [Pod(f"st{i}", labels={"scv/number": "1",
                                  "tpu/accelerator": "tpu"})
            for i in range(24)]
    for p in pods:
        fleet.submit(p)
    fleet.run_until_idle(max_cycles=20_000)
    bound = sum(1 for p in pods if p.phase == PodPhase.BOUND)
    assert bound == 24, bound


# --------------------------------------------------- histogram reservoir
def test_reservoir_histogram_bounded_and_accurate():
    h = Histogram(keep_values=4096)
    rng = random.Random(7)
    n = 200_000
    for _ in range(n):
        h.observe(rng.uniform(0.0, 1000.0))
    assert len(h._values) == 4096       # memory fixed past the threshold
    assert h.n == n
    # golden tolerance: uniform[0,1000] quantiles within ~3% absolute
    for q, expect in ((0.5, 500.0), (0.9, 900.0), (0.99, 990.0)):
        got = h.quantile(q)
        assert abs(got - expect) < 30.0, (q, got)


def test_reservoir_exact_below_threshold():
    h = Histogram(keep_values=1000)
    for i in range(1000):
        h.observe(float(i))
    assert h.quantile(0.5) == 500.0
    assert len(h._values) == 1000


def test_reservoir_deterministic():
    def run():
        h = Histogram(keep_values=128)
        for i in range(10_000):
            h.observe(float(i % 997))
        return h.quantile(0.5), h.quantile(0.99)

    assert run() == run()


# ------------------------------------------------ elastic _growing retire
def test_elastic_growing_retired_on_external_gang_deletion():
    store = TelemetryStore()
    for i in range(2):
        m = make_tpu_node(f"eg-{i}", chips=4)
        m.heartbeat = T0
        store.put(m)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    sched = Scheduler(cluster, SchedulerConfig(
        elastic_gangs=True, telemetry_max_age_s=1e9),
        clock=FakeClock(start=T0))
    assert sched.elastic is not None
    # a mid-growth record with no bound members left (the orphan shape:
    # the gang's pods were deleted externally after admission)
    sched.elastic._growing["ghost"] = 0
    sched.elastic._first_seen["ghost"] = T0
    sched.notify_event(ClusterEvent(POD_DELETED, node="eg-0",
                                    gang="ghost"))
    sched.run_one()
    assert "ghost" not in sched.elastic._growing
    assert "ghost" not in sched.elastic._first_seen
    assert sched.metrics.counters.get("gang_elastic_retired_total") == 1


def test_elastic_growing_survives_shrink_of_live_gang():
    """A POD_DELETED for a gang that still has bound members (a shrink
    eviction) must NOT retire the growing record."""
    store = TelemetryStore()
    m = make_tpu_node("lv-0", chips=4)
    m.heartbeat = T0
    store.put(m)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    sched = Scheduler(cluster, SchedulerConfig(
        elastic_gangs=True, telemetry_max_age_s=1e9),
        clock=FakeClock(start=T0))
    member = Pod("live-w0", labels={"tpu/gang-name": "live",
                                    "scv/number": "1"})
    cluster.bind(member, "lv-0", [(0, 0, 0)])
    sched.elastic._growing["live"] = 0
    sched.notify_event(ClusterEvent(POD_DELETED, node="lv-0",
                                    gang="live"))
    sched.run_one()
    assert "live" in sched.elastic._growing
