"""Queue-sort plugin: strict priority by ``scv/priority`` label.

Reference: pkg/yoda/sort/sort.go:8-18 — higher label value schedules first,
absent/unparseable treated as 0. We add a FIFO tie-break on enqueue time so
equal-priority pods cannot starve each other (the reference's comparator is
not a strict weak order on ties; upstream's queue happened to mask that).
"""

from __future__ import annotations

from ..framework import QueueSortPlugin, QueuedPodInfo
from ...utils.labels import PRIORITY_LABEL


def pod_priority(info: QueuedPodInfo) -> int:
    raw = info.pod.labels.get(PRIORITY_LABEL)
    if raw is None:
        return 0
    try:
        return int(raw)
    except ValueError:
        return 0  # queue sort cannot reject; the filter will surface the error


class PrioritySort(QueueSortPlugin):
    name = "priority-sort"

    def less(self, a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
        pa, pb = pod_priority(a), pod_priority(b)
        if pa != pb:
            return pa > pb
        return a.enqueued < b.enqueued

    def key(self, info: QueuedPodInfo):
        """Sort key consistent with less(): lets the queue use a heap
        (O(log n) pop) instead of a comparator scan (O(n))."""
        return (-pod_priority(info), info.enqueued)
