import pytest

from yoda_scheduler_tpu.scheduler.framework import (
    CycleState,
    Status,
    Code,
    QueuedPodInfo,
    min_max_normalize,
)
from yoda_scheduler_tpu.scheduler.queue import SchedulingQueue
from yoda_scheduler_tpu.scheduler.config import SchedulerConfig, ScoreWeights
from yoda_scheduler_tpu.scheduler.plugins.sort import PrioritySort
from yoda_scheduler_tpu.utils import Pod


def qp(name, prio=None, enqueued=0.0):
    labels = {} if prio is None else {"scv/priority": str(prio)}
    info = QueuedPodInfo(pod=Pod(name, labels=labels))
    info.enqueued = enqueued
    return info


def test_cycle_state_read_write_clone():
    s = CycleState()
    s.write("k", 42)
    assert s.read("k") == 42
    with pytest.raises(KeyError):
        s.read("missing")
    assert s.read_or("missing", "d") == "d"
    c = s.clone()
    c.write("k", 7)
    assert s.read("k") == 42 and c.read("k") == 7


def test_status_truthiness_banned():
    with pytest.raises(TypeError):
        bool(Status.success())
    assert Status.success().ok
    assert Status.unschedulable("x").code == Code.UNSCHEDULABLE


def test_min_max_normalize():
    scores = {"a": 10.0, "b": 20.0, "c": 15.0}
    min_max_normalize(scores)
    assert scores == {"a": 0.0, "b": 100.0, "c": 50.0}
    # all-equal: reference's lowest-- guard maps everything to 100
    same = {"a": 5.0, "b": 5.0}
    min_max_normalize(same)
    assert same == {"a": 100.0, "b": 100.0}


def test_priority_sort_orders_and_fifo_ties():
    less = PrioritySort().less
    assert less(qp("hi", prio=5), qp("lo", prio=1))
    assert not less(qp("lo", prio=1), qp("hi", prio=5))
    # absent/garbage priority behaves as 0 (reference sort.go:12-18)
    assert less(qp("p1", prio=1), qp("none"))
    assert not less(qp("garbage"), qp("p1", prio=1))
    # FIFO tie-break
    assert less(qp("first", prio=2, enqueued=1.0), qp("second", prio=2, enqueued=2.0))


def test_priority_sort_most_constrained_first_within_priority():
    """Equal priority: gang members first, then exact-topology pods, then
    FIFO (chip count deliberately does NOT rank) — and priority still
    dominates all."""
    sort = PrioritySort()
    q = SchedulingQueue(sort.less, key=sort.key)
    q.add(Pod("single", labels={"scv/number": "1"}), now=0.0)
    q.add(Pod("multi", labels={"scv/number": "4"}), now=1.0)
    q.add(Pod("gangm", labels={"scv/number": "4", "tpu/gang-name": "g",
                               "tpu/gang-size": "2"}), now=2.0)
    q.add(Pod("topo", labels={"scv/number": "4", "tpu/topology": "2x2"}),
          now=3.0)
    q.add(Pod("vip", labels={"scv/priority": "1"}), now=4.0)
    order = [q.pop(now=10.0).pod.name for _ in range(5)]
    assert order == ["vip", "gangm", "topo", "single", "multi"]


def test_reference_sort_is_priority_only():
    # the baseline keeps the reference's sort.go semantics: no constraint
    # tie-break, FIFO within a priority band
    from yoda_scheduler_tpu.scheduler.plugins.reference_emulation import RefSort

    sort = RefSort()
    q = SchedulingQueue(sort.less, key=sort.key)
    q.add(Pod("plain", labels={}), now=0.0)
    q.add(Pod("topo", labels={"scv/number": "4", "tpu/topology": "2x2"}),
          now=1.0)
    order = [q.pop(now=10.0).pod.name for _ in range(2)]
    assert order == ["plain", "topo"]


def test_queue_pop_priority_order():
    q = SchedulingQueue(PrioritySort().less)
    for name, prio in [("a", 1), ("b", 9), ("c", 5)]:
        q.add(Pod(name, labels={"scv/priority": str(prio)}), now=0.0)
    assert [q.pop(now=0.0).pod.name for _ in range(3)] == ["b", "c", "a"]
    assert q.pop(now=0.0) is None


def test_queue_backoff_exponential_and_flush():
    q = SchedulingQueue(PrioritySort().less, initial_backoff_s=1.0, max_backoff_s=10.0)
    q.add(Pod("p"), now=0.0)
    info = q.pop(now=0.0)
    q.requeue_backoff(info, now=0.0)           # attempt 1 -> 1s
    assert q.pop(now=0.5) is None
    info = q.pop(now=1.1)
    assert info is not None
    q.requeue_backoff(info, now=1.1)           # attempt 2 -> 2s
    assert q.pop(now=2.0) is None
    info = q.pop(now=3.2)
    for _ in range(6):                         # saturate at max 10s
        q.requeue_backoff(info, now=10.0)
        info = q.pop(now=25.0)
    q.requeue_backoff(info, now=100.0)
    assert q.next_ready_at() == pytest.approx(110.0)


def test_config_from_profile_dict():
    cfg = SchedulerConfig.from_profile(
        {
            "schedulerName": "yoda-scheduler",
            "percentageOfNodesToScore": 30,
            "pluginConfig": [
                {
                    "name": "yoda-tpu",
                    "args": {
                        "scoreWeights": {"free_memory": 4, "allocate": 1},
                        "gangTimeoutSeconds": 5,
                        "topologyWeight": 3,
                    },
                }
            ],
        }
    )
    assert cfg.percentage_of_nodes_to_score == 30
    assert cfg.weights.free_memory == 4 and cfg.weights.allocate == 1
    assert cfg.weights.bandwidth == 1  # untouched default
    assert cfg.gang_timeout_s == 5.0 and cfg.topology_weight == 3


def test_default_weights_match_reference():
    # reference pkg/yoda/score/algorithm.go:16-26
    w = ScoreWeights()
    assert (w.bandwidth, w.clock, w.core, w.power, w.free_memory,
            w.total_memory, w.actual, w.allocate) == (1, 1, 1, 1, 2, 1, 2, 3)


def test_heap_queue_orders_exactly_like_comparator():
    import random

    sort = PrioritySort()
    rng = random.Random(7)
    pods = [Pod(f"p{i}", labels={"scv/priority": str(rng.randint(0, 5))})
            for i in range(50)]
    scan = SchedulingQueue(sort.less)
    heap = SchedulingQueue(sort.less, key=sort.key)
    for i, p in enumerate(pods):
        scan.add(p, now=float(i))
        heap.add(p, now=float(i))
    order_scan = [scan.pop(now=100.0).pod.name for _ in range(len(pods))]
    order_heap = [heap.pop(now=100.0).pod.name for _ in range(len(pods))]
    assert order_scan == order_heap


def test_heap_queue_backoff_and_contains():
    sort = PrioritySort()
    q = SchedulingQueue(sort.less, key=sort.key, initial_backoff_s=1.0)
    q.add(Pod("x"), now=0.0)
    assert q.contains("default/x")
    info = q.pop(now=0.0)
    q.requeue_backoff(info, now=0.0)
    assert q.contains("default/x")
    assert q.pop(now=0.5) is None
    assert q.pop(now=1.5).pod.name == "x"


def test_config_deschedule_interval_from_profile():
    from yoda_scheduler_tpu.scheduler import SchedulerConfig

    cfg = SchedulerConfig.from_profile({
        "schedulerName": "x",
        "pluginConfig": [{"name": "yoda-tpu",
                          "args": {"descheduleIntervalSeconds": 30}}]})
    assert cfg.deschedule_interval_s == 30.0
    assert SchedulerConfig().deschedule_interval_s == 0.0
