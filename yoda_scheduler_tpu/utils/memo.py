"""Single-slot value-keyed memo, shared by every hot-path parse/aggregate
cache (pod specs, chip assignments, telemetry aggregates).

The slot lives on the owning object as ``(key, value)`` under ``attr``; a
changed key recomputes. The slot write is a single attribute assignment
(atomic under the GIL), so readers never see a torn ``(key, value)`` pair —
but the memo cannot protect ``compute`` itself: if another thread mutates the
underlying data without changing the key, the memo pins whatever ``compute``
observed. Publishers must therefore replace-and-rekey (publish a fresh object
or bump the key), never mutate shared state in place.
"""

from __future__ import annotations

from typing import Any, Callable, TypeVar

T = TypeVar("T")


def memo(obj: Any, attr: str, key: Any, compute: Callable[[], T]) -> T:
    cached = getattr(obj, attr, None)
    if cached is not None and cached[0] == key:
        return cached[1]
    value = compute()
    setattr(obj, attr, (key, value))
    return value
