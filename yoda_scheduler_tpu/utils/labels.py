"""Workload label contract and strict parsing.

The reference's user contract is pod labels ``scv/memory``, ``scv/number``,
``scv/clock``, ``scv/priority`` (reference readme.md:27-69); we keep that
exact surface (so a reference user can switch without rewriting manifests)
and extend it with the TPU namespace:

- ``tpu/accelerator``: "tpu" | "gpu" — mixed-cluster partitioning (BASELINE
  config #5); absent = any accelerator that satisfies the resource labels.
- ``tpu/topology``: requested ICI block, e.g. "2x2" — topology-aware packing.
- ``tpu/gang-name`` + ``tpu/gang-size``: multi-host gang (one worker pod per
  host of a pod slice; all-or-nothing admission via the Permit plugin).

Parsing is strict: the reference silently coerced malformed or negative
values to 0 via Atoi-error-swallowing and uint wraparound (reference
pkg/yoda/filter/filter.go:60-86 — SURVEY §3.3 flags this as a hazard). Here a
malformed label raises LabelError, which the filter surfaces as an
Unschedulable status naming the bad label instead of quietly scheduling the
pod as if it had asked for nothing.
"""

from __future__ import annotations

from dataclasses import dataclass

from .memo import memo

MEMORY_LABEL = "scv/memory"       # min free HBM per chip, MB
NUMBER_LABEL = "scv/number"       # chips requested on the node
CLOCK_LABEL = "scv/clock"         # min chip clock, MHz (>= semantics, see below)
PRIORITY_LABEL = "scv/priority"   # queue priority, higher first

ACCELERATOR_LABEL = "tpu/accelerator"
GENERATION_LABEL = "tpu/generation"  # pin a TPU generation ("v4", "v5e", ...)
TOPOLOGY_LABEL = "tpu/topology"
GANG_NAME_LABEL = "tpu/gang-name"
GANG_SIZE_LABEL = "tpu/gang-size"
# elastic gangs (scheduler/elastic/): minimum viable replica count — a
# gang labeled with it may ADMIT at min members when the full size does
# not fit, then grow toward tpu/gang-size as chips free. 0/absent keeps
# the classic all-or-nothing admission. Only meaningful on gang pods and
# only when the elasticGangs knob is on.
GANG_MIN_LABEL = "tpu/gang-min"
# deadline/SLO-aware admission (scheduler/elastic/): seconds after the
# gang's first member arrives by which the job must be RUNNING. Drives
# the start-now-at-min vs wait-for-full decision off the policy engine's
# throughput model (ElasticGangs.deadline_pressed). 0/absent = no
# deadline pressure (start at min only when the full size provably
# cannot fit).
DEADLINE_LABEL = "scv/deadline-seconds"

# Harvest capacity class (scheduler/capacity/): a pod labeled
# ``scv/harvest: "1"`` soaks otherwise-idle chips and is EVICTED FOR
# FREE — outside every disruption protection (preemption budgets, the
# PDB ledger, victim-priority ordering) and first out the door when the
# capacity provisioner drains a node for scale-down. Riding the
# WorkloadSpec keeps every spec-keyed surface (class memos, batch keys)
# sound: a harvest pod and its non-harvest twin never share a class.
HARVEST_LABEL = "scv/harvest"

# SLO serving class (scheduler/elastic/sloguard.py, ISSUE 19). A pod
# labeled ``scv/serving: "1"`` carries latency-sensitive user traffic:
# its e2e scheduling latency is measured against ``scv/slo-ms`` by the
# burn-rate monitor, it is exempt from workload-tier rate limiting and
# queue-depth backpressure, and under SLO pressure the guard shrinks
# elastic training gangs toward tpu/gang-min to make room. Riding the
# WorkloadSpec keeps every spec-keyed surface (class memos, batch keys)
# sound: a serving pod and its batch twin never share a class. A
# scheduling input only when the sloServing knob is on.
SERVING_LABEL = "scv/serving"
# per-request scheduling-latency SLO in milliseconds; requires
# scv/serving (an SLO without the serving class would never be
# monitored — strict parsing rejects the silent no-op).
SLO_MS_LABEL = "scv/slo-ms"

# Policy-engine labels (scheduler/policy/). The workload CLASS names the
# job's throughput profile across accelerator generations (Gavel's
# job-type axis, arXiv:2008.09213) — it rides the WorkloadSpec so every
# spec-keyed surface (class memos, batch keys, per-spec maxima memo)
# distinguishes classes automatically. The TENANT names the quota/DRF
# accounting unit; absent, the pod's namespace is the tenant (it is part
# of the memo/batch keys already).
WORKLOAD_CLASS_LABEL = "scv/class"
TENANT_LABEL = "scv/tenant"


class LabelError(ValueError):
    """A workload label is present but malformed."""

    def __init__(self, label: str, value: str, why: str = "must be a non-negative integer"):
        self.label = label
        super().__init__(f"label {label}={value!r}: {why}")


def _parse_uint(labels: dict[str, str], key: str, default: int) -> int:
    raw = labels.get(key)
    if raw is None:
        return default
    try:
        v = int(raw)
    except ValueError:
        raise LabelError(key, raw) from None
    if v < 0:
        raise LabelError(key, raw)
    return v


def _parse_int(labels: dict[str, str], key: str, default: int) -> int:
    raw = labels.get(key)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        raise LabelError(key, raw, "must be an integer") from None


@dataclass(frozen=True, eq=True)
class WorkloadSpec:
    """The parsed resource request of one pod.

    Semantics notes vs. the reference:
    - ``chips`` defaults to 1 when ``scv/number`` is absent, matching
      PodFitsNumber's default (reference pkg/yoda/filter/filter.go:15).
    - ``min_clock_mhz`` uses >= ("at least this fast"), resolving the
      reference's filter-vs-score inconsistency (== at filter.go:57 but >= at
      collection.go:46 / algorithm.go:48) in favour of the README's stated
      intent ("high-performance GPU", readme.md:55-63).
    """

    chips: int = 1
    min_free_mb: int = 0
    min_clock_mhz: int = 0
    priority: int = 0
    accelerator: str | None = None   # None = any
    tpu_generation: str | None = None  # None = any generation
    topology: str | None = None      # e.g. "2x2"
    gang_name: str | None = None
    gang_size: int = 0
    # elastic-gang minimum (tpu/gang-min): 0 = classic all-or-nothing.
    # A scheduling input only when the elasticGangs knob is on; riding
    # the spec keeps every spec-keyed surface (class memos, batch keys)
    # sound — two gangs differing only in min never share a class.
    gang_min: int = 0
    # start-deadline seconds (scv/deadline-seconds): 0 = none
    deadline_s: int = 0
    # harvest capacity class (scv/harvest): evicted for free — outside
    # preemption budgets and the PDB ledger, first victim of scale-down
    # drains. False/absent = ordinary pod.
    harvest: bool = False
    # SLO serving class (scv/serving): latency-sensitive user traffic —
    # exempt from workload-tier rate limiting/backpressure, measured by
    # the burn-rate monitor, protected by the serving-headroom quota
    # level. False/absent = batch/training.
    serving: bool = False
    # scheduling-latency SLO, ms (scv/slo-ms): 0 = unmonitored. Only
    # valid together with scv/serving.
    slo_ms: int = 0
    # declared throughput-profile class (scv/class); None = classless —
    # the heterogeneity model then falls back to a coarse spec-derived
    # class. A scheduling input ONLY when the policy engine is enabled;
    # carrying it on the spec keeps the class memos and batch keys sound
    # (two pods differing only in class never share a spec).
    workload_class: str | None = None

    # Whether the pod opted into accelerator scheduling at all: a pod with no
    # scv/* labels still defaults to 1 chip (reference behaviour — any pod
    # routed to the yoda scheduler wants an accelerator node).
    @classmethod
    def from_labels(cls, labels: dict[str, str]) -> "WorkloadSpec":
        gang_size = _parse_uint(labels, GANG_SIZE_LABEL, 0)
        gang_name = labels.get(GANG_NAME_LABEL)
        if gang_name is not None and gang_size <= 0:
            raise LabelError(GANG_SIZE_LABEL, labels.get(GANG_SIZE_LABEL, ""),
                             "gang pods must set a positive tpu/gang-size")
        gang_min = _parse_uint(labels, GANG_MIN_LABEL, 0)
        if gang_min:
            if gang_name is None:
                raise LabelError(GANG_MIN_LABEL, labels[GANG_MIN_LABEL],
                                 "tpu/gang-min requires tpu/gang-name")
            if gang_min > gang_size:
                raise LabelError(GANG_MIN_LABEL, labels[GANG_MIN_LABEL],
                                 f"must be <= tpu/gang-size ({gang_size})")
        accel = labels.get(ACCELERATOR_LABEL)
        if accel is not None and accel not in ("tpu", "gpu"):
            raise LabelError(ACCELERATOR_LABEL, accel, 'must be "tpu" or "gpu"')
        gen = labels.get(GENERATION_LABEL)
        if gen is not None:
            from ..topology.generations import GENERATIONS  # validate eagerly

            if gen not in GENERATIONS:
                raise LabelError(GENERATION_LABEL, gen,
                                 f"must be one of {sorted(GENERATIONS)}")
        topo = labels.get(TOPOLOGY_LABEL)
        if topo is not None:
            from ..topology.torus import parse_topology  # validate eagerly

            try:
                parse_topology(topo)
            except ValueError:
                raise LabelError(TOPOLOGY_LABEL, topo, "must look like '2x2x1'") from None
        wclass = labels.get(WORKLOAD_CLASS_LABEL)
        if wclass is not None and not wclass:
            raise LabelError(WORKLOAD_CLASS_LABEL, wclass,
                             "must be a non-empty class name")
        harvest_raw = labels.get(HARVEST_LABEL)
        harvest = False
        if harvest_raw is not None:
            if harvest_raw in ("1", "true", "True"):
                harvest = True
            elif harvest_raw not in ("0", "false", "False"):
                raise LabelError(HARVEST_LABEL, harvest_raw,
                                 'must be "1"/"true" or "0"/"false"')
        serving_raw = labels.get(SERVING_LABEL)
        serving = False
        if serving_raw is not None:
            if serving_raw in ("1", "true", "True"):
                serving = True
            elif serving_raw not in ("0", "false", "False"):
                raise LabelError(SERVING_LABEL, serving_raw,
                                 'must be "1"/"true" or "0"/"false"')
        slo_ms = _parse_uint(labels, SLO_MS_LABEL, 0)
        if slo_ms and not serving:
            raise LabelError(SLO_MS_LABEL, labels[SLO_MS_LABEL],
                             "scv/slo-ms requires scv/serving")
        if serving and harvest:
            raise LabelError(SERVING_LABEL, serving_raw or "",
                             "a pod cannot be both serving and harvest")
        return cls(
            chips=_parse_uint(labels, NUMBER_LABEL, 1),
            min_free_mb=_parse_uint(labels, MEMORY_LABEL, 0),
            min_clock_mhz=_parse_uint(labels, CLOCK_LABEL, 0),
            priority=_parse_int(labels, PRIORITY_LABEL, 0),
            accelerator=accel,
            tpu_generation=gen,
            topology=topo,
            gang_name=gang_name,
            gang_size=gang_size,
            gang_min=gang_min,
            deadline_s=_parse_uint(labels, DEADLINE_LABEL, 0),
            harvest=harvest,
            serving=serving,
            slo_ms=slo_ms,
            workload_class=wclass,
        )

    @property
    def is_gang(self) -> bool:
        return self.gang_name is not None

    def __hash__(self) -> int:
        # cached: specs key the filter-verdict and unschedulable-class
        # caches, so they are hashed once per (pod, node) on the hot path;
        # frozen dataclasses rebuild the field tuple on every hash call
        h = self.__dict__.get("_hash_memo")
        if h is None:
            h = hash((self.chips, self.min_free_mb, self.min_clock_mhz,
                      self.priority, self.accelerator, self.tpu_generation,
                      self.topology, self.gang_name, self.gang_size,
                      self.gang_min, self.deadline_s, self.harvest,
                      self.serving, self.slo_ms, self.workload_class))
            object.__setattr__(self, "_hash_memo", h)
        return h


# NOTE: spec_for() builds its memo key from exactly this label set —
# keep the two in sync
_SPEC_LABELS = (
    NUMBER_LABEL, MEMORY_LABEL, CLOCK_LABEL, PRIORITY_LABEL,
    ACCELERATOR_LABEL, GENERATION_LABEL, TOPOLOGY_LABEL,
    GANG_NAME_LABEL, GANG_SIZE_LABEL, GANG_MIN_LABEL, DEADLINE_LABEL,
    HARVEST_LABEL, SERVING_LABEL, SLO_MS_LABEL, WORKLOAD_CLASS_LABEL,
)
_SPEC_LABEL_SET = frozenset(_SPEC_LABELS)

# the complete public label surface (spec inputs + the bind-time chip
# assignment the scheduler itself publishes) — `cli validate` flags any
# other scv/* or tpu/* label as a probable typo
from .pod import ASSIGNED_CHIPS_LABEL as _ASSIGNED  # no cycle: pod imports only .memo

KNOWN_LABELS = frozenset(_SPEC_LABELS) | {_ASSIGNED, TENANT_LABEL}


def workload_class(pod) -> str:
    """Coarse pod classification for per-class latency metrics (the bench
    decomposes p50 by these; VERDICT r2 weak #1). Not a scheduling input."""
    try:
        spec = spec_for(pod)
    except LabelError:
        return "malformed"
    if spec.serving:
        return "serving"
    if spec.is_gang:
        return "gang"
    if spec.topology is not None:
        return "topology"
    if spec.accelerator == "gpu":
        return "gpu"
    if spec.chips > 1:
        return "tpu-multi"
    if ACCELERATOR_LABEL in pod.labels or NUMBER_LABEL in pod.labels:
        return "tpu-single"
    return "unlabeled"


def is_harvest(pod) -> bool:
    """Whether the pod belongs to the harvest capacity class (evicted
    for free — see HARVEST_LABEL). Malformed labels read as non-harvest:
    a pod that cannot declare its class never gets the weaker
    protections removed by accident."""
    try:
        return spec_for(pod).harvest
    except LabelError:
        return False


def is_serving(pod) -> bool:
    """Whether the pod belongs to the SLO serving class (see
    SERVING_LABEL). Malformed labels read as non-serving: a pod that
    cannot declare its class never acquires the stronger admission
    fastpath by accident."""
    try:
        return spec_for(pod).serving
    except LabelError:
        return False


def tenant_of(pod) -> str:
    """The pod's quota/DRF accounting unit (scheduler/policy/): the
    scv/tenant label when present, else the namespace. Both inputs are
    already inside the engine's memo/batch keys (namespace directly,
    the label via plugin equivalence contributions), so tenancy can
    never alias across a memo class."""
    return pod.labels.get(TENANT_LABEL) or pod.namespace


_SPEC_INTERN: dict[WorkloadSpec, WorkloadSpec] = {}


def _intern_spec(spec: WorkloadSpec) -> WorkloadSpec:
    """One canonical object per spec VALUE: pods sharing a label class then
    share the spec object, so dict lookups in the spec-keyed caches
    short-circuit on identity instead of comparing nine fields."""
    got = _SPEC_INTERN.get(spec)
    if got is not None:
        return got
    if len(_SPEC_INTERN) > 4096:  # churn guard; classes are few in practice
        _SPEC_INTERN.clear()
    _SPEC_INTERN[spec] = spec
    return spec


def spec_for(pod) -> WorkloadSpec:
    """Parse-once spec cache for a pod-like object (anything with ``labels``).

    Keyed by the values of the labels the spec reads, so in-place label
    mutation (bind-time chip assignment, eviction cleanup) can never serve a
    stale spec. The scheduler walks every bound pod's spec on every cycle
    (allocation accounting), so parse cost is hot-path cost. Raises LabelError
    exactly like ``WorkloadSpec.from_labels``; errors are not cached (a
    malformed pod fails its cycle permanently anyway)."""
    labels = pod.labels
    # key = the present spec-label ITEMS, one filtered walk of the (few)
    # labels instead of twelve .get calls. Coverage is exact: any
    # spec-label add/remove/change moves the key, while non-spec labels
    # (the bind-time chip assignment, app labels) never force a reparse.
    key = tuple(kv for kv in labels.items() if kv[0] in _SPEC_LABEL_SET)
    hit = pod.__dict__.get("_spec_cache")
    if hit is not None and hit[0] == key:
        return hit[1]
    spec = _intern_spec(WorkloadSpec.from_labels(labels))
    pod.__dict__["_spec_cache"] = (key, spec)
    return spec
