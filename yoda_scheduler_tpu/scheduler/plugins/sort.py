"""Queue-sort plugin: strict priority by ``scv/priority`` label.

Reference: pkg/yoda/sort/sort.go:8-18 — higher label value schedules first,
absent/unparseable treated as 0. We add two tie-breaks the reference lacks:

- **most-constrained-first** among equal priority: gang members first
  (a gang consumes whole hosts across one slice — the largest structural
  demand), then pods pinned to an exact ICI block shape
  (``tpu/topology``), then FIFO. Classic bin-packing order — gangs and
  block-shaped jobs place while slices are still whole, instead of
  retrying against space the easy pods fragmented; easy pods lose a
  cycle or two, hard pods stop paying the whole queue's length in wait.
  Only STRUCTURAL constraints rank; plain chip count deliberately does
  not (reordering 2-chip jobs ahead of 1-chip jobs spends the same chips
  on fewer pods under capacity pressure, with no contiguity gain to show
  for it).
- FIFO on enqueue time last, so equal-priority/equal-constraint pods cannot
  starve each other (the reference's comparator is not a strict weak order
  on ties; upstream's queue happened to mask that).
"""

from __future__ import annotations

from ..framework import QueueSortPlugin, QueuedPodInfo
from ...utils.labels import GANG_NAME_LABEL, PRIORITY_LABEL, TOPOLOGY_LABEL


def pod_priority(info: QueuedPodInfo) -> int:
    raw = info.pod.labels.get(PRIORITY_LABEL)
    if raw is None:
        return 0
    try:
        return int(raw)
    except ValueError:
        return 0  # queue sort cannot reject; the filter will surface the error


def constraint_rank(info: QueuedPodInfo) -> int:
    """Structural placement difficulty of a pod — higher schedules first
    within a priority band. Gang > exact-topology > unconstrained."""
    labels = info.pod.labels
    rank = 0
    if GANG_NAME_LABEL in labels:
        rank += 2
    if TOPOLOGY_LABEL in labels:
        rank += 1
    return rank


class PrioritySort(QueueSortPlugin):
    name = "priority-sort"

    def equivalence_key(self, pod):
        """Batch-cycle contract: ordering reads only the priority and
        constraint labels, all inside the WorkloadSpec the engine's memo
        key already carries — classmates sort identically."""
        return ()

    def less(self, a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
        pa, pb = pod_priority(a), pod_priority(b)
        if pa != pb:
            return pa > pb
        ca, cb = constraint_rank(a), constraint_rank(b)
        if ca != cb:
            return ca > cb
        return a.enqueued < b.enqueued

    def key(self, info: QueuedPodInfo):
        """Sort key consistent with less(): lets the queue use a heap
        (O(log n) pop) instead of a comparator scan (O(n))."""
        return (-pod_priority(info), -constraint_rank(info), info.enqueued)
