"""The scheduling engine: queue -> cycle -> bind, with gang parking.

This is the native replacement for the upstream kube-scheduler machinery the
reference borrowed wholesale (its scheduleOne loop, queue, binding cycle;
reference pkg/register/register.go:10-12 embeds it as a library). One
scheduler instance owns:

- a SchedulingQueue ordered by the QueueSort plugin, with 1s->10s backoff
  (reference deploy/yoda-scheduler.yaml:19-20)
- the scheduling cycle across extension points (framework.py)
- a waiting-pod parking lot for Permit WAIT verdicts (gang admission)
- structured cycle traces + Prometheus-style metrics (utils/obs.py)

kube-scheduler parity details implemented natively:
- only pods whose spec.schedulerName matches the profile are scheduled
- percentageOfNodesToScore: Filter stops early once enough feasible nodes
  are found, starting from a rotating offset (the adaptive formula for the
  0/default case, reference deploy/yoda-scheduler.yaml:18 inherits it)
- score ties break randomly (seeded)
- PostFilter (preemption) runs only when no node is feasible, mirroring the
  modern framework role the reference misused (SURVEY §3.2).
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque

from .cluster import FakeCluster
from .columnar import ColumnarTable, HAVE_NUMPY, np
from .config import SchedulerConfig
from .framework import (
    BindPlugin,
    CANDIDATE_NODES_KEY,
    ClusterEvent,
    Code,
    CycleState,
    FilterPlugin,
    GANG_MEMBER_ARRIVED,
    NO_BATCH,
    NODE_TELEMETRY_UPDATED,
    NodeInfo,
    POD_DELETED,
    POD_PENDING_ARRIVED,
    PermitPlugin,
    PostFilterPlugin,
    PreFilterPlugin,
    PreScorePlugin,
    QUEUE,
    QueuedPodInfo,
    QueueSortPlugin,
    ReservePlugin,
    ScorePlugin,
    Snapshot,
    Status,
)

# sentinel distinguishing "no cached batch key yet" from a cached None
_BKEY_MISS = object()
from .queue import DRFShardedQueue, SchedulingQueue
from .plugins import (
    ChipAllocator,
    FragmentationScore,
    GangCoordinator,
    GangPermit,
    MaxCollection,
    NodeAdmission,
    PriorityPreemption,
    PrioritySort,
    TelemetryFilter,
    TelemetryScore,
    TopologyScore,
)
from .plugins.prescore import MAX_KEY
from .plugins.topology import SLICE_USE_KEY
from ..utils.labels import (
    GANG_NAME_LABEL, LabelError, is_harvest, spec_for, tenant_of,
    workload_class)
from ..utils.obs import (
    CycleTrace, FlightRecorder, Metrics, SpanRing, TraceLog, span_sampled)
from ..utils.pod import ASSIGNED_CHIPS_LABEL, Pod, PodPhase, format_assigned_chips

# distinguishes "caller supplied no metrics" from "telemetry is None"
_UNSET = object()

# cached single-label tuples for the per-cycle labeled counters (the
# values come from small fixed vocabularies — outcomes, planes, plugin
# names — so the cache stays tiny while saving a dict build + sort per
# scheduling cycle)
_LABEL1_CACHE: dict = {}

_EMPTY_SET: frozenset = frozenset()


def _numpy_fold(smat, kind_flags, weights, n):
    """The ONE numpy definition of the normalize+weighted-sum fold:
    op-for-op `Scheduler._fold_scores` (and the C kernel's
    yoda_batch_fold) — minmax lo/hi, span == 0 -> flat 100.0, else
    0.0 + (v - lo) * 100.0 / span, accumulated totals + w * v per
    scorer in order. The batch-commit loop and the per-cycle fold both
    fall back to THIS when the native kernel is absent, so the three
    implementations cannot drift apart one call site at a time. Returns
    the totals array."""
    totals = np.zeros(n, dtype=np.float64)
    for k in range(smat.shape[0]):
        arr = smat[k, :n]
        if kind_flags[k]:
            lowest = arr.min()
            span = arr.max() - lowest
            if span == 0:
                arr = np.full(n, 100.0)
            else:
                arr = 0.0 + (arr - lowest) * 100.0 / span
        totals = totals + float(weights[k]) * arr
    return totals


def _label1(key: str, value: str) -> tuple:
    hit = _LABEL1_CACHE.get((key, value))
    if hit is None:
        if len(_LABEL1_CACHE) > 4096:
            _LABEL1_CACHE.clear()
        hit = ((key, value),)
        _LABEL1_CACHE[(key, value)] = hit
    return hit


class Clock:
    """Injectable time source so tests/benches control backoff and timeouts."""

    def time(self) -> float:
        return time.time()

    def sleep(self, s: float) -> None:
        time.sleep(s)


class FakeClock(Clock):
    def __init__(self, start: float = 0.0) -> None:
        self._now = start

    def time(self) -> float:
        return self._now

    def sleep(self, s: float) -> None:
        self._now += s

    def advance(self, s: float) -> None:
        self._now += s


class HybridClock(Clock):
    """Real elapsed compute time + virtual sleeps. Benchmarks use this so
    measured latencies include genuine scheduling-cycle cost and queue wait,
    while backoff/permit sleeps advance time instantly instead of stalling
    the harness for real seconds."""

    def __init__(self) -> None:
        self._t0 = time.time()
        self._p0 = time.perf_counter()
        self._virtual = 0.0

    def time(self) -> float:
        return self._t0 + (time.perf_counter() - self._p0) + self._virtual

    def sleep(self, s: float) -> None:
        self._virtual += s


class Profile:
    """A wired plugin set (the KubeSchedulerConfiguration profile analogue)."""

    def __init__(
        self,
        queue_sort: QueueSortPlugin,
        pre_filter: list[PreFilterPlugin] | None = None,
        filter: list[FilterPlugin] | None = None,
        post_filter: list[PostFilterPlugin] | None = None,
        pre_score: list[PreScorePlugin] | None = None,
        score: list[ScorePlugin] | None = None,
        reserve: list[ReservePlugin] | None = None,
        permit: list[PermitPlugin] | None = None,
        bind: BindPlugin | None = None,
    ) -> None:
        self.queue_sort = queue_sort
        self.pre_filter = pre_filter or []
        self.filter = filter or []
        self.post_filter = post_filter or []
        self.pre_score = pre_score or []
        self.score = score or []
        self.reserve = reserve or []
        self.permit = permit or []
        self.bind = bind
        # policy engine (scheduler/policy/): set by default_profile /
        # registry.build_profile when policyObjective / DRF knobs enable
        # it; None = the pre-policy engine, placements bit-identical.
        # The Scheduler attaches its cluster/metrics/flight at init.
        self.policy = None
        # elastic-gang controller (scheduler/elastic/): set when the
        # elasticGangs knob is on; None = classic all-or-nothing gang
        # admission, placements bit-identical.
        self.elastic = None


def _headroom_gate(policy):
    from .policy.headroom import ServingHeadroomGate

    return ServingHeadroomGate(policy)


def default_profile(config: SchedulerConfig,
                    allocator: ChipAllocator | None = None,
                    gangs: GangCoordinator | None = None,
                    ) -> tuple[Profile, ChipAllocator, GangPermit]:
    """The yoda-tpu plugin set: telemetry filter/score (reference capability)
    + topology scorer, chip allocator, gang permit, priority preemption.

    `allocator`/`gangs` may be shared instances: co-hosted profiles
    (multi.py) must see each other's pending reservations or they would
    double-book chips between Reserve and Bind."""
    allocator = allocator or ChipAllocator()
    gangs = gangs or GangCoordinator()
    # elastic gangs (scheduler/elastic/): built only when the knob asks —
    # the off default constructs the EXACT pre-elastic plugin set, so
    # placements stay bit-identical (tests/test_elastic.py)
    elastic = None
    if config.elastic_gangs:
        from .elastic import ElasticGangs

        elastic = ElasticGangs(config)
    # geometric torus placement (scheduler/carve.py): built only when the
    # knob asks — the off default constructs the EXACT pre-torus plugin
    # set, so placements stay bit-identical (tests/test_torus_carve.py)
    carver = None
    if config.torus_placement:
        from .carve import TorusCarver

        carver = TorusCarver(allocator)
    gang_permit = GangPermit(gangs, timeout_s=config.gang_timeout_s,
                             allocator=allocator, elastic=elastic,
                             carver=carver)
    topo = TopologyScore(allocator, weight=config.topology_weight)
    admission = NodeAdmission(allocator)
    # policy engine (scheduler/policy/): built only when a knob asks for
    # it — the unset default constructs the EXACT pre-policy plugin set,
    # so placements stay bit-identical (pinned by tests/test_policy.py)
    policy = None
    # serving headroom (ISSUE 19) rides the policy engine's DRF book:
    # reserving capacity for scv/serving forces the engine on even with
    # no objective/tenants configured
    headroom_on = (config.slo_serving
                   and config.serving_headroom_pct > 0.0)
    policy_enabled = (config.policy_objective or config.drf_fairness
                      or config.tenant_quotas or headroom_on)
    if policy_enabled:
        from .policy import (HeterogeneityScore, PolicyEngine,
                             TenantFairnessSort, TenantQuotaGate)

        policy = PolicyEngine(config)
    hetero = ([HeterogeneityScore(policy.model, config.policy_objective,
                                  weight=config.heterogeneity_weight,
                                  policy=policy)]
              if policy is not None and config.policy_objective
              and config.heterogeneity_weight > 0 else [])
    drf_on = policy is not None and (config.drf_fairness
                                     or config.tenant_quotas)
    profile = Profile(
        queue_sort=(TenantFairnessSort(policy) if drf_on
                    else PrioritySort()),
        # quota gate first (one node-independent check per cycle, before
        # gang planning pays anything); the serving-headroom gate sits
        # beside it — the quota level above every tenant; GangPermit.
        # pre_filter computes multi-slice plans for gangs no single
        # slice can host
        pre_filter=([TenantQuotaGate(policy)] if drf_on else [])
        + ([_headroom_gate(policy)] if headroom_on else [])
        + [gang_permit],
        # admission first: nodeSelector/taint rejections are cheap and spare
        # the telemetry filter's capacity math on excluded nodes
        filter=[admission,
                TelemetryFilter(allocator, gangs, config.telemetry_max_age_s)],
        post_filter=[PriorityPreemption(allocator, gangs)] if config.preemption else [],
        # TopologyScore is both a PreScore (slice-usage map) and a Score plugin
        pre_score=[MaxCollection(allocator)] + ([topo] if config.topology_weight > 0 else []),
        score=[
            TelemetryScore(allocator, config.weights, weight=1),
            *([topo] if config.topology_weight > 0 else []),
            *([FragmentationScore(allocator,
                                  weight=config.fragmentation_weight,
                                  carver=carver)]
              if config.fragmentation_weight > 0 else []),
            *hetero,
            admission,
        ],
        reserve=[allocator, gang_permit],
        permit=[gang_permit],
    )
    profile.policy = policy
    if elastic is not None:
        # the deadline decision reads the policy engine's throughput
        # model when one exists (built just above)
        elastic.policy = policy
    profile.elastic = elastic
    return profile, allocator, gang_permit


class _WaitingPod:
    def __init__(self, info: QueuedPodInfo, node: str, deadline: float) -> None:
        self.info = info
        self.node = node
        self.deadline = deadline


class _BatchCtx:
    """Carry-over from an equivalence-class batch's FIRST (ordinary)
    scheduling cycle into the incremental commit loop (_commit_batch):
    the candidate list in ranking order, every scorer's raw score dict
    (copies — the cycle's own memo entries must not alias them), and the
    prescore outputs the loop maintains per member."""

    __slots__ = ("armed", "state", "spec", "memo_key", "want",
                 "scorers", "candidates", "raws", "names_set", "vers",
                 "usage", "mv_t", "chosen")

    def __init__(self) -> None:
        self.armed = False

    def arm(self, **kw) -> None:
        self.armed = True
        for k, v in kw.items():
            setattr(self, k, v)


class _NativeCycle:
    """One fused native scan's outputs, reshaped for the engine: the
    candidate NodeInfos in scan order, the per-plugin raw score dicts
    (kernel-born for native scorers), the MaxValue fold + per-candidate
    contributions for MaxCollection.native_install, and — when EVERY
    active scorer was native — the fused normalize+weighted totals."""

    __slots__ = ("feasible", "names_set", "checked", "mv6", "contribs",
                 "raws", "totals", "scorers")


# _native_scan verdict: the kernel ran and found ZERO feasible rows —
# final (the numpy mask would agree), so the engine skips the numpy
# attempt and hands the pod to the scalar scan for its diagnostics
_NATIVE_EMPTY = object()

# fence_provider verdict: the replica owned the target node's shard when
# the cycle started, but the lease has since expired or been reassigned —
# the commit is aborted cleanly (reservation unwound, attempt-free
# retry) instead of burning a doomed RPC the authority would fence-reject
FENCE_LOST = object()


class Scheduler:
    def __init__(
        self,
        cluster: FakeCluster,
        config: SchedulerConfig | None = None,
        profile: Profile | None = None,
        clock: Clock | None = None,
        cycle_lock: "threading.RLock | None" = None,
    ) -> None:
        self.cluster = cluster
        self.config = config or SchedulerConfig()
        if profile is None:
            profile, allocator, gang_permit = default_profile(self.config)
            self.allocator: ChipAllocator | None = allocator
            self.gang_permit: GangPermit | None = gang_permit
        else:
            self.allocator = next(
                (p for p in profile.reserve if isinstance(p, ChipAllocator)), None
            )
            self.gang_permit = next(
                (p for p in profile.permit if isinstance(p, GangPermit)), None
            )
        self.profile = profile
        self.clock = clock or Clock()
        self.metrics = Metrics()
        # torus carver observability: the carver is built inside the
        # profile (no Metrics exists yet there) — hand it ours
        _carver = getattr(self.gang_permit, "carver", None)
        if _carver is not None:
            _carver.metrics = self.metrics
        qkw = dict(
            initial_backoff_s=self.config.pod_initial_backoff_s,
            max_backoff_s=self.config.pod_max_backoff_s,
            key=getattr(profile.queue_sort, "key", None),
            metrics=self.metrics,
            hinted_backoff_s=self.config.pod_hinted_backoff_s,
        )
        if getattr(profile.queue_sort, "sharded_drf", False) \
                and getattr(profile, "policy", None) is not None:
            # DRF fairness: per-tenant sharded priority bands with
            # exact-at-pop shares off the live book (queue.py docstring)
            # — the sort plugin supplies the band inputs, the queue does
            # the tenant selection
            from .plugins.sort import pod_priority

            self.queue: SchedulingQueue = DRFShardedQueue(
                profile.queue_sort.less, policy=profile.policy,
                tenant_fn=tenant_of, priority_fn=pod_priority,
                subkey_fn=profile.queue_sort.subkey, **qkw)
        else:
            self.queue = SchedulingQueue(profile.queue_sort.less, **qkw)
        # churn plane half (a): drain the notify inbox in batched slices
        # (queue._drain_batch) instead of one on_event walk per event
        self._churn = bool(config.churn_plane)
        self.queue.batch_drain = self._churn
        # event-driven requeue: register every plugin's EnqueueExtensions
        # (queueing hints) with the queue's event index, plus the engine's
        # own hint for pods waiting on preemption victims to drain
        seen_plugins: set[str] = set()
        for plugins in (profile.pre_filter, profile.filter,
                        profile.post_filter, profile.pre_score,
                        profile.score, profile.reserve, profile.permit):
            for p in plugins:
                if p.name not in seen_plugins:
                    seen_plugins.add(p.name)
                    self.queue.register_plugin(p)
        self.queue.register_hint("victim-drain", (POD_DELETED,),
                                 lambda ev, pod: QUEUE)
        # elastic gangs (scheduler/elastic/): growth members — members of
        # a gang already admitted at >= tpu/gang-min — park under this
        # distinct hint class and wake when capacity frees (a departing
        # pod, a joining node), the same machinery as victim-drain
        self.elastic = getattr(profile, "elastic", None)
        # gang -> (version vector, bound count): growth members re-check
        # their gang's cluster-truth size on EVERY failed cycle, and the
        # O(cluster) pod walk must not be paid per wake — the vector
        # covers binds/unbinds, so a hit is exact
        self._gang_count_memo: dict[str, tuple] = {}
        if self.elastic is not None:
            from .elastic import ELASTIC_GROW_HINT
            from .framework import NODE_ADDED, NODE_TELEMETRY_UPDATED

            # NODE_TELEMETRY_UPDATED rides along because chips also free
            # by RECOVERING (the same event classic gang-permit and the
            # telemetry filter register for): without it a growth member
            # parked behind unhealthy chips waits out its full hinted
            # backoff instead of waking when the slice heals
            self.queue.register_hint(
                ELASTIC_GROW_HINT,
                (POD_DELETED, NODE_ADDED, NODE_TELEMETRY_UPDATED),
                lambda ev, pod: QUEUE)
        # batch scheduling cycles: every distinct plugin (queue sort and
        # binder included) contributes to the scheduling-equivalence key;
        # one NO_BATCH vote makes a pod per-pod-only (framework.Plugin.
        # equivalence_key). The per-key queue index is only built when the
        # knob enables batching at all.
        self._eq_plugins: list = []
        seen_ids: set[int] = set()
        for p in ([profile.queue_sort]
                  + ([profile.bind] if profile.bind is not None else [])):
            if id(p) not in seen_ids:
                seen_ids.add(id(p))
                self._eq_plugins.append(p)
        for plugins in (profile.pre_filter, profile.filter,
                        profile.post_filter, profile.pre_score,
                        profile.score, profile.reserve, profile.permit):
            for p in plugins:
                if id(p) not in seen_ids:
                    seen_ids.add(id(p))
                    self._eq_plugins.append(p)
        # class-level batch-key cache (see _compute_batch_key)
        self._bkey_class_cache: dict = {}
        if self.config.batch_max_pods > 1:
            self.queue.set_batch_key_fn(self._batch_key)
        # cluster events land in the queue's inbox from ANY thread
        # (reflector, binder, test driver); the next pop() routes them
        # through the queueing hints on the engine thread. `wake` lets a
        # serve loop sleep until an event or submission arrives instead
        # of polling.
        self.wake = threading.Event()
        # Intra-replica parallel heads (scheduler/heads.py). head_filter
        # is the queue-segregation predicate: pop/peek skip entries it
        # claims for another head. route_events=False on worker heads
        # keeps N heads from funneling every cluster event into the ONE
        # shared queue N times (memos self-invalidate off the version
        # vector at cycle time, so workers need no event routing — only
        # the wake). Both stay inert (None/True) on a classic engine.
        self.head_filter = None
        self.route_events = True
        # bounded per-head dispatch window: generalizes the one-deep
        # scan prefetch so wire commit overlaps cycle compute beyond
        # depth 1 without letting a slow wire build an unbounded pile of
        # in-flight binds per head. 0 = classic unbounded dispatch.
        self._dispatch_sem = (
            threading.BoundedSemaphore(self.config.head_dispatch_depth)
            if self.config.head_dispatch_depth > 0 else None)
        sub = getattr(cluster, "subscribe", None)
        if sub is not None:
            sub(self.notify_event)
        wc = getattr(cluster.telemetry, "watch_changes", None)
        if wc is not None:
            wc(self._on_telemetry_change)
        self.waiting: dict[str, _WaitingPod] = {}
        self.failed: dict[str, str] = {}  # pod.key -> permanent failure reason
        # pods permanently failed by cycle-level crash containment (a
        # plugin RAISED quarantine_threshold times for them) — a subset
        # of `failed`, kept separately so operators can tell poison pods
        # from ordinary unschedulability. Bounded like doomed_gangs.
        self.quarantined: dict[str, str] = {}
        # apiserver circuit breaker (self-healing): consecutive bind wire
        # failures open it, parking scheduling until the cooldown passes;
        # a post-cooldown probe bind closes it on success. All breaker
        # state is engine-thread-only; binder threads report outcomes in
        # ARRIVAL ORDER through the _bind_results deque, so a success is
        # folded before/after the failures exactly as it happened on the
        # wire (a bare boolean could not order a stale pre-storm success
        # against newer failures, or vice versa).
        self._breaker_failures = 0
        self._breaker_until = 0.0
        self._breaker_cooldown = self.config.breaker_cooldown_s
        # telemetry-blackout degraded mode: previous cycle's regime, so a
        # flip can clear the class memos (staleness verdicts change
        # without any version bump — exactly the invalidation the version
        # vectors cannot see)
        self._degraded = False
        # _commit_batch's current member, for crash attribution when a
        # plugin raises inside the batch commit loop
        self._batch_cursor: QueuedPodInfo | None = None
        # churn-plane fast cycle (config.churn_plane): the resume state a
        # clean fully-bound batch commit leaves behind — (ctx, last bound
        # node, exit version vector). The next same-class batch re-enters
        # _commit_batch directly off it when every guard holds
        # (schedule_batch), skipping the ordinary head cycle.
        self._fast_resume: tuple | None = None
        # poison-vs-systemic discriminator for quarantine: a crash is
        # SYSTEMIC when, since the last crash, no cycle completed
        # cleanly AND the last crash was a DIFFERENT pod — i.e. the
        # engine is crashing across the board (corrupt snapshot input, a
        # bug in shared engine code), in which case permanently failing
        # pods would convert an engine-wide fault into mass data loss.
        # A pod re-crashing its OWN cycles (even back-to-back, with no
        # neighbours left to interleave) stays poison and quarantines.
        self._ok_since_crash = True
        self._last_crash_key: str | None = None
        self.traces = TraceLog()
        # lifecycle span tracing (utils/obs.py): every 1-in-trace_sampling
        # pod gets the full queued/cycle/bind_wire span tree, recorded on
        # THIS engine's clock into a bounded ring and exported as
        # Chrome/Perfetto trace-event JSON (/traces/export, bench.py
        # --trace-out). Appends are GIL-atomic tuple pushes; unsampled
        # pods pay one memoized dict lookup per cycle.
        self.spans = SpanRing()
        # black-box flight recorder: structured engine events (breaker
        # transitions, degraded flips, quarantines, fence aborts, conflict
        # fallbacks) in a bounded ring, auto-dumped to disk when a trip
        # kind fires and a dump dir is configured
        self.flight = FlightRecorder(
            clock=self.clock, dump_dir=self.config.flight_dump_dir or None)
        # policy engine (scheduler/policy/): built plugin-side by
        # default_profile / registry.build_profile; the engine hands it
        # the live surfaces its DRF book and starvation watch read.
        # None (the default) keeps every policy hook out of the cycle.
        self.policy = getattr(profile, "policy", None)
        if self.policy is not None:
            self.policy.attach(self.cluster, self.metrics, self.flight,
                               self.clock)
        # workload-tier admission (scheduler/workload.py): one decision
        # per Workload against the DRF book / quotas / live capacity;
        # pods materialize lazily on admission. None (the default knob)
        # keeps the pod-at-a-time intake bit-identical.
        self.workloads = None
        if self.config.workload_admission:
            from .workload import WorkloadAdmission

            self.workloads = WorkloadAdmission(self)
        if self.elastic is not None:
            self.elastic.attach(self.metrics, self.clock)
        self.rng = random.Random(self.config.rng_seed)
        self._filter_start = 0  # rotating offset for percentageOfNodesToScore
        # node -> ((telemetry generation, pods version), NodeInfo) — see
        # snapshot() for the cross-cycle reuse contract
        self._ni_cache: dict[str, tuple[tuple, NodeInfo]] = {}
        self._known_nodes: set[str] = set()
        # incremental-snapshot state: (Snapshot, cluster gver, telemetry
        # ver, nodes membership ver) from the previous cycle
        self._snap: tuple[Snapshot, int, int, int] | None = None
        # unschedulable-CLASS memo: spec -> (cluster versions, reason). A
        # pod whose identical-spec classmate just failed, with NOTHING
        # changed since (no bind/evict/telemetry/reservation/nomination/
        # membership event), fails in O(1) instead of rescanning every
        # node — the native analogue of upstream kube-scheduler parking
        # unschedulable pods until a relevant cluster event.
        self._unsched_memo: dict = {}
        # feasible-CLASS memo: memo_key -> (cluster versions, feasible
        # node names). The success-path twin of _unsched_memo: a
        # classmate's feasible list is repaired from the change logs
        # (only dirty nodes re-filtered; staleness re-verified per node)
        # instead of rebuilt by a full cluster scan. Gated to per-node-
        # predicate pods only — see the feas_ok gate in
        # _schedule_one_locked and _repair_feasible for the soundness
        # envelope.
        self._feas_memo: dict = {}
        # per-cycle dirty-set memo for _changes_since_vers: in steady
        # state every class memo (feasible, unschedulable, score, slice
        # usage) stores the SAME previous version vector, so one cycle
        # asks for the same (cvers -> now) delta several times — each a
        # walk of three change logs under the cluster lock. Keyed by
        # (cvers, current vector) so a mid-cycle version bump (a Reserve
        # write, a concurrent reflector apply) self-invalidates; cleared
        # each cycle. Returned sets are shared — callers must not mutate.
        self._csv_memo: dict = {}
        # score-CLASS memo: memo_key -> (cluster versions, MaxValue
        # tuple, slice-usage map, scorer names, {plugin: {node: raw}}).
        # Classmate cycles rescore only dirty nodes; see the score
        # section of _schedule_one_locked for the soundness envelope.
        self._score_memo: dict = {}
        # async-bind wire outcomes in ARRIVAL order, appended by binder
        # threads and drained by run_one on the engine thread (the queue
        # is engine-thread-only; deque.append/popleft are GIL-atomic).
        # Entries: None = a wire success (breaker signal only);
        # (info, node, err) = a failure needing recovery.
        self._bind_results: deque = deque()
        # gang -> reason: a member permanently failed during assembly, so
        # the gang can never reach its size with the current incarnations.
        # Parked peers are failed at doom time; peers sitting in backoff
        # fail fast at their next cycle (the park->timeout->requeue loop
        # counts no attempts, so without this they never resolve).
        # ENGINE-THREAD-ONLY dict: submit() (any thread) records revivals
        # in the GIL-atomic deque below and run_one drains it, so a fresh
        # incarnation of the failed member makes the gang viable again
        # without cross-thread mutation of the dict.
        self.doomed_gangs: dict[str, str] = {}
        self._gang_revivals: deque = deque()
        # elastic-gang retirement inbox (PR 10 sliver): POD_DELETED
        # events carrying a gang label land here from any thread; the
        # engine thread drains them in run_one and retires a _growing
        # record whose gang has ZERO bound members left in cluster truth
        # — an externally-deleted mid-growth gang would otherwise
        # survive until backstop eviction and miscount grow/admission
        # metrics when the name is reused.
        self._elastic_retires: deque = deque()
        # columnar data plane (scheduler/columnar.py): parallel-array twin
        # of the object snapshot, maintained from the same change logs.
        # None when numpy is unavailable, the knob is off, or there is no
        # allocator to source free sets from — every consumer then takes
        # the scalar path (its ground truth) unconditionally.
        self._columnar: ColumnarTable | None = (
            ColumnarTable(self.allocator,
                          shards=self.config.columnar_shards)
            if HAVE_NUMPY and self.config.columnar
            and self.allocator is not None else None)
        if self._columnar is not None and self._columnar.shards:
            # sharded membership rebuilds need the change-log delta
            # WITHOUT the membership-version gate _changes_since_vers
            # enforces (that gate exists exactly because per-name logs
            # can't describe joins — the sharded rebuild handles joins
            # itself and only needs the surviving rows' dirt)
            self._columnar.membership_dirty_fn = self._membership_dirty
        if self._columnar is not None:
            # cycle-phase attribution: table sync stamps its wall time
            # into cycle_event_apply_ms (the row-refresh half of event
            # application; the queue-drain half stamps the same series)
            self._columnar.metrics = self.metrics
        # native data plane (scheduler/nativeplane.py): the fused C++
        # kernel running the memo-miss full scan in one GIL-releasing
        # call. Requires the columnar table (it consumes those arrays
        # zero-copy); a missing/stale/unbuildable .so degrades silently
        # to the numpy path — the gauge records which plane is live.
        self._native = None
        # incremental-commit kernels (nativeplane.IncrementalKernels):
        # the batch-commit fold and the post-bind columnar row refresh
        # as single C calls — the "post-bind repair path stops paying
        # numpy per-op overhead" half of the native plane. Gated on the
        # same knob; an older .so degrades just these back to numpy.
        self._incremental = None
        # (tag, FusedResult) from the overlapped scan prefetch, awaiting
        # consume-time validation against the live version vector
        self._prefetched: tuple | None = None
        if self._columnar is not None and self.config.native_plane:
            try:
                from .nativeplane import FusedPlane, IncrementalKernels

                self._native = FusedPlane.load()
                self._incremental = IncrementalKernels.load()
            except Exception:  # pragma: no cover - defensive: a broken
                self._native = None  # ctypes env must not kill the engine
                self._incremental = None
        # native COMMIT plane (nativeplane.CommitKernels, nativeCommit
        # knob): the per-candidate topology packing/blend as one
        # GIL-releasing call, plus the incremental fold/refresh kernels
        # even when the fused scan plane is off — the two knobs compose
        # independently, and each degrades alone on a stale .so.
        self._commitk = None
        if self._columnar is not None and self.config.native_commit:
            try:
                from .nativeplane import CommitKernels, IncrementalKernels

                self._commitk = CommitKernels.load()
                if self._incremental is None:
                    self._incremental = IncrementalKernels.load()
            except Exception:  # pragma: no cover - defensive, as above
                self._commitk = None
        if self._columnar is not None and self._incremental is not None:
            self._columnar.native_refresh = self._incremental
        # churn plane, columnar half: multi-row dirt applied as one
        # batched delta-vector pass, through the eventplane kernel when
        # the .so carries it (a stale .so degrades just this plane to
        # the numpy scatter; knob off keeps the per-row ground truth)
        self._eventk = None
        if self._columnar is not None and self._churn:
            self._columnar.batch_events = True
            try:
                from .nativeplane import EventKernels

                self._eventk = EventKernels.load()
            except Exception:  # pragma: no cover - defensive, as above
                self._eventk = None
            self._columnar.event_kernels = self._eventk
        self.metrics.set_gauge("native_plane_active",
                               1.0 if self._native is not None else 0.0)
        self.metrics.set_gauge("native_commit_active",
                               1.0 if self._commitk is not None else 0.0)
        self.metrics.set_gauge("churn_plane_active",
                               1.0 if self._churn else 0.0)
        self.metrics.set_gauge("event_plane_native",
                               1.0 if self._eventk is not None else 0.0)
        if self.config.native_commit:
            # arm plugins carrying a commit-plane batch form (today:
            # TopologyScore). Armed even when the .so lacks the kernels —
            # the pure-Python half (in-place contribution patch, array
            # usage map) stands on its own.
            for p in list(self.profile.score) + list(self.profile.pre_score):
                hook = getattr(p, "enable_commit_plane", None)
                if hook is not None:
                    hook(self._commitk)
        if self._churn:
            # churn-plane plugin arming (today: TopologyScore's
            # copy-on-write slice-usage views) — pure-Python data-plane
            # amortization, observationally identical outputs
            for p in list(self.profile.score) + list(self.profile.pre_score):
                hook = getattr(p, "enable_churn_plane", None)
                if hook is not None:
                    hook()
        # shared across co-hosted profiles (multi.py) to serialize cycles;
        # private (uncontended) when this engine runs alone
        self.cycle_lock = cycle_lock or threading.RLock()
        # preemption victims re-enter scheduling through this callable.
        # MultiProfileScheduler points it at its schedulerName-routing
        # submit so a victim owned by profile B evicted by profile A's
        # engine lands back in B's queue, not A's; standalone engines
        # default to their own submit (which rejects foreign names).
        self.victim_router = None
        # active defragmentation controller (scheduler/elastic/defrag.py):
        # a closed migration loop on THIS engine's injectable clock,
        # gated per pass on the breaker/degraded interlock and — in a
        # fleet — on shard-0 ownership (FleetCoordinator wires
        # owner_check). None when the knob is off.
        self.defrag = None
        if self.config.defrag_interval_s > 0 and self.allocator is not None:
            from .elastic import DefragController

            self.defrag = DefragController(
                self, self.config.defrag_interval_s,
                max_migrations=self.config.max_migrations_per_pass,
                cooldown_s=self.config.defrag_cooldown_s)
        # closed-loop capacity provisioner (scheduler/capacity/): a
        # control loop on THIS engine's injectable clock scaling node
        # pools up off the pending backlog's recorded shapes and down by
        # drain-and-release — ticked BEFORE the breaker gate (scale-up
        # continues through apiserver storms; its scale-down half gates
        # itself on the breaker/degraded interlocks). None when the
        # knob is off (placements bit-identical). The provider attaches
        # post-construction (attach_provider) — until then every pass
        # no-ops.
        self.provisioner = None
        if self.config.provisioner_interval_s > 0:
            from .capacity import CapacityProvisioner

            self.provisioner = CapacityProvisioner(
                self, self.config.provisioner_interval_s)
        # SLO-guarded colocated serving (ISSUE 19): the burn-rate
        # monitor measures every serving bind against its scv/slo-ms
        # budget; the guard degrades training toward gang-min under
        # pressure and gives the surplus back in the valleys. Both None
        # when the knob is off — the monitor observes nothing, the
        # cycle carries no SLO hook, placements bit-identical.
        self.slo = None
        self.sloguard = None
        if self.config.slo_serving:
            from ..utils.obs import SloMonitor
            from .elastic import SloGuard

            self.slo = SloMonitor(
                self.metrics, flight=self.flight,
                target_pct=self.config.slo_target_pct,
                burn_threshold=self.config.slo_burn_threshold,
                fast_window_s=self.config.slo_fast_window_s,
                slow_window_s=self.config.slo_slow_window_s)
            if self.config.slo_guard_interval_s > 0:
                self.sloguard = SloGuard(
                    self, self.slo, self.config.slo_guard_interval_s,
                    shrink_budget=self.config.slo_shrink_budget,
                    hysteresis_s=self.config.slo_hysteresis_s)
        # shard-lease fencing (scheduler/fleet.py): when set, called as
        # fence_provider(pod, node) right before every bind dispatch.
        # Returns a fencing token to carry on the bind (owned shard), None
        # for an unfenced optimistic bind (node outside the replica's
        # shards), or FENCE_LOST — the lease vanished mid-cycle — which
        # aborts the commit cleanly through the unwind path. None (the
        # default) skips fencing entirely: standalone engines are the
        # fleet of one.
        self.fence_provider = None

    # ----------------------------------------------------------------- intake
    def submit(self, pod: Pod) -> bool:
        """Accept a pod if it targets this scheduler (spec.schedulerName
        routing, as in kube-scheduler)."""
        if pod.scheduler_name != self.config.scheduler_name:
            return False
        gang = pod.labels.get(GANG_NAME_LABEL)
        if gang:
            # a (re)submitted member can complete the gang again; the
            # engine thread applies the revival (run_one drains this) —
            # and parked siblings in backoff wake on the arrival event
            self._gang_revivals.append(gang)
            self.notify_event(ClusterEvent(GANG_MEMBER_ARRIVED, gang=gang))
        self.queue.add(pod, now=self.clock.time())
        self.metrics.inc("pods_submitted_total")
        self.wake.set()
        return True

    def submit_workload(self, w) -> bool:
        """Accept a Workload into the admission tier (workloadAdmission
        knob on; scheduler/workload.py). Parked cost is O(1) — pods
        exist only after the workload admits."""
        if self.workloads is None \
                or w.scheduler_name != self.config.scheduler_name:
            return False
        self.workloads.submit(w)
        self.metrics.inc("workloads_inbox_total")
        self.wake.set()
        return True

    def withdraw_workload(self, key: str,
                          reason: str = "withdrawn") -> bool:
        """Withdraw a workload by key (external CR deletion, operator
        action): parked ones unpark, admitted ones retire their quota
        claim and materialized members in one pass."""
        if self.workloads is None:
            return False
        self.workloads.withdraw(key, reason)
        self.wake.set()
        return True

    def notify_event(self, event: ClusterEvent) -> None:
        """Accept a cluster event from any thread; the queue routes it
        through its queueing hints at the next pop on the engine thread.
        Intake signals (PodPendingArrived) only wake the serve loop — a
        pending pod's arrival cannot cure a parked pod's rejection, so it
        never enters the hint path."""
        if event.kind != POD_PENDING_ARRIVED and self.route_events:
            self.queue.notify(event)
        if (self.elastic is not None and event.kind == POD_DELETED
                and event.gang):
            # a gang member left the cluster: the engine thread checks
            # whether the whole gang is gone and retires its elastic
            # bookkeeping (run_one drains this deque)
            self._elastic_retires.append(event.gang)
        # churn plane: coalesce redundant wake signals — safe because
        # every serve loop clears the flag BEFORE its next run_one, and
        # run_one drains the inbox this event was appended to above
        if self._churn and self.wake.is_set():
            return
        self.wake.set()

    def _on_telemetry_change(self, node: str, old, new) -> None:
        self.notify_event(ClusterEvent(NODE_TELEMETRY_UPDATED, node=node,
                                       old=old, new=new))

    def tracks(self, pod_key: str) -> bool:
        """Is this pod currently in our hands (queued, backing off, or parked
        at Permit)? Used by the serve loop to avoid duplicate submission."""
        return pod_key in self.waiting or self.queue.contains(pod_key)

    def _num_feasible_to_find(self, num_nodes: int) -> int:
        """kube-scheduler's numFeasibleNodesToFind: all nodes below 100; above
        that, percentageOfNodesToScore (adaptive when 0) with a floor of 100.

        The adaptive default additionally caps candidates at 100 (the
        floor): upstream's formula still scores 42% of a 1000-node
        cluster, and past ~100 candidates the min-max-normalised ranking
        is already saturated. Measured on the 1000-node/5000-pod scale
        bench (round 5): cap=150 p50 6585ms vs cap=100 p50 2270ms with
        IDENTICAL placement quality (bound 4046 vs 4060, both runs end
        with zero free chips — capacity-limited, not choice-limited);
        the earlier 150 cap still paid 1.6x the p50 of an explicit
        pct=10 in the round-4 driver run (BENCH_r04 scale). An explicit
        percentage is honoured as given — the cap applies only when the
        operator left the choice to the scheduler."""
        if num_nodes < 100:
            return num_nodes
        pct = self.config.percentage_of_nodes_to_score
        if not pct:
            # min(max(n*adaptive_pct//100, 100), 100) is identically 100
            # whatever the formula yields — the floor and the cap meet.
            # (The formula itself is BELOW 100 for n up to ~204 and above
            # it past that; the constant is the cap + floor, not the
            # formula saturating.)
            return 100
        if pct >= 100:
            return num_nodes
        return max(num_nodes * pct // 100, 100)

    def _sampled(self, pod: Pod) -> bool:
        """Is this pod in the 1-in-trace_sampling span-traced set?
        Memoized on the pod (the decision is a pure function of the key,
        so retries and fleet replicas agree)."""
        s = pod.__dict__.get("_span_sampled")
        if s is None:
            s = span_sampled(pod.key, self.config.trace_sampling)
            pod.__dict__["_span_sampled"] = s
        return s

    @staticmethod
    def _memo_key_of(pod: Pod, spec) -> tuple:
        """Scheduling-CLASS key for the unschedulable/feasible/score memos.
        Every input is fixed at pod creation (labels/selectors are
        immutable while the pod is pending), so retries reuse the key
        built on the first attempt — the tuple/frozenset build was
        measurable across a 5000-pod burst's retry cycles."""
        memo_key = pod.__dict__.get("_memo_key")
        if memo_key is None:
            if (pod.node_selector or pod.tolerations or pod.node_affinity
                    or pod.pod_affinity or pod.pod_anti_affinity
                    or pod.topology_spread or pod.cpu_millis
                    or pod.memory_bytes):
                memo_key = (spec, frozenset(pod.node_selector.items()),
                            tuple((t.get("key", ""),
                                   t.get("operator", "Equal"),
                                   t.get("value", ""), t.get("effect", ""))
                                  for t in pod.tolerations),
                            pod.node_affinity, pod.pod_affinity,
                            pod.pod_anti_affinity, pod.topology_spread,
                            pod.cpu_millis, pod.memory_bytes, pod.namespace)
            else:
                # namespace is part of even the plain class: a bound pod's
                # anti-affinity (symmetry rule) can repel pods of one
                # namespace and not another with identical labels
                memo_key = (spec, pod.namespace)
            pod.__dict__["_memo_key"] = memo_key
        return memo_key

    # ------------------------------------------------------ batch cycles
    def _batch_key(self, pod: Pod):
        """Scheduling-equivalence key for batch cycles (None = this pod
        always takes the per-pod cycle). Two pods with equal keys are
        interchangeable for one scheduling pass: same memo class (resource
        shape, selectors, tolerations, namespace, priority — all inside
        the WorkloadSpec/memo key) and identical per-plugin equivalence
        contributions. Gang members, exact-topology requests, and pods
        with inter-pod terms / spread / hostPorts never batch — their
        cycles carry state outside the key. Memoised per pod: every input
        is fixed at creation, like the memo key."""
        got = pod.__dict__.get("_batch_key", _BKEY_MISS)
        if got is not _BKEY_MISS:
            return got
        key = self._compute_batch_key(pod)
        pod.__dict__["_batch_key"] = key
        return key

    def _compute_batch_key(self, pod: Pod):
        try:
            spec = spec_for(pod)
        except LabelError:
            return None
        if spec.is_gang or spec.topology is not None:
            return None
        if (pod.pod_affinity or pod.pod_anti_affinity
                or pod.topology_spread or pod.host_ports):
            return None
        memo_key = self._memo_key_of(pod, spec)
        # class-level key cache: every equivalence contribution is a
        # function of the pod's scheduling CLASS (the framework audit —
        # "two pods with equal keys are interchangeable" — is exactly
        # what makes them class-determined). The memo key carries the
        # class (spec, selectors, tolerations, affinities, resources,
        # namespace); tenancy rides along explicitly because the DRF
        # sort keys on the scv/tenant label, which the memo key omits.
        # A 25k-pod burst of four classes was paying 25k full plugin
        # walks for four distinct answers.
        cls_key = (memo_key, pod.labels.get("scv/tenant"))
        hit = self._bkey_class_cache.get(cls_key)
        if hit is not None:
            return hit
        parts = []
        for p in self._eq_plugins:
            eq = getattr(p, "equivalence_key", None)
            # duck-typed plugins without the Plugin base (reference
            # emulation) never audited interchangeability: NO_BATCH
            k = eq(pod) if eq is not None else NO_BATCH
            if k is NO_BATCH:
                return None
            if k != ():
                parts.append((getattr(p, "name", type(p).__name__), k))
        out = (memo_key, tuple(parts))
        if len(self._bkey_class_cache) > 4096:
            self._bkey_class_cache.clear()
        self._bkey_class_cache[cls_key] = out
        return out

    def _cluster_versions(self) -> tuple | None:
        """Version vector over everything a filter verdict can depend on:
        bound pods, telemetry, node membership, reservations+nominations.
        None when the backend doesn't expose the counters."""
        pg = getattr(self.cluster, "pods_global_version", None)
        if pg is None:
            return None
        return (pg,
                self.cluster.telemetry.resource_version,
                getattr(self.cluster, "nodes_version", 0),
                self.allocator.version if self.allocator is not None else 0)

    def _changes_since_vers(self, cvers):
        """Node names changed since version vector `cvers` (the
        _cluster_versions tuple): (current vector, dirty set | None).
        None when membership changed, a log was trimmed, or the allocator
        recorded a change whose node set is unknowable ("*") — callers
        must rebuild from scratch. Exposed to plugins through the cycle
        state as ``changes_since_fn`` so per-cycle aggregations (slice
        usage, feasible lists) can repair instead of rescanning."""
        vers, dirty, _ = self._changes_since_directed(cvers)
        return vers, dirty

    def _changes_since_directed(self, cvers):
        """(current vector, dirty | None, grew | None): like
        _changes_since_vers plus the GREW subset — names with at least
        one capacity-releasing (or direction-unknown) change. A name only
        in dirty was touched exclusively by binds/claims, which within
        the memo path's per-node-predicate envelope cannot flip it
        infeasible -> feasible (changelog docstring). Backends without
        direction support contribute their whole dirty set to grew
        (conservative)."""
        vers = self._cluster_versions()
        if vers is None or cvers is None or vers[2] != cvers[2]:
            return vers, None, None
        key = (cvers, vers)
        hit = self._csv_memo.get(key)
        if hit is not None:
            return vers, hit[0], hit[1]
        csince = getattr(self.cluster, "changes_since", None)
        tsince = getattr(self.cluster.telemetry, "changes_since", None)
        if csince is None or tsince is None or self.allocator is None:
            return vers, None, None
        # per-log short-circuit: an unchanged version counter means an
        # empty delta — skip the locked log walk (the commit loop asks
        # after every bind, when typically only two of the three logs
        # moved; the counter reads are GIL-atomic ints)
        if vers[0] == cvers[0]:
            pdirty = pgrew = _EMPTY_SET
        else:
            cdir = getattr(self.cluster, "changes_since_directed", None)
            if cdir is not None:
                _, pdirty, pgrew = cdir(cvers[0])
            else:
                _, pdirty = csince(cvers[0])
                pgrew = pdirty
        if vers[1] == cvers[1]:
            tdirty = _EMPTY_SET
        else:
            _, tdirty = tsince(cvers[1])
        if vers[3] == cvers[3]:
            adirty = agrew = _EMPTY_SET
        else:
            _, adirty, agrew = self.allocator.changes_since_directed(
                cvers[3])
        if (pdirty is None or tdirty is None or adirty is None
                or "*" in adirty):
            dirty = grew = None
        else:
            dirty = pdirty | tdirty | adirty
            # telemetry updates are direction-unknown: all grew
            grew = pgrew | tdirty | agrew
        self._csv_memo[key] = (dirty, grew)
        return vers, dirty, grew

    def _membership_dirty(self, cvers):
        """Dirty node names since `cvers` IGNORING membership movement —
        the sharded columnar rebuild's input (columnar.py): a surviving
        row absent from this set is provably unchanged and block-copies.
        None when any log was trimmed or the allocator recorded an
        unattributable change (the caller then rebuilds in full)."""
        if cvers is None or self.allocator is None:
            return None
        csince = getattr(self.cluster, "changes_since", None)
        tsince = getattr(self.cluster.telemetry, "changes_since", None)
        if csince is None or tsince is None:
            return None
        _, pdirty = csince(cvers[0])
        _, tdirty = tsince(cvers[1])
        _, adirty, _ = self.allocator.changes_since_directed(cvers[3])
        if pdirty is None or tdirty is None or adirty is None \
                or "*" in adirty:
            return None
        return pdirty | tdirty | adirty

    @staticmethod
    def _feas_entry(vers, feasible):
        """Feasible-class memo record: (version vector, NodeInfo tuple,
        name frozenset, name -> position index). The set and index let
        _repair_feasible patch the cached list in O(|dirty|) instead of
        walking every entry."""
        names = tuple(n.name for n in feasible)
        return (vers, tuple(feasible), frozenset(names),
                {n: i for i, n in enumerate(names)})

    def _repair_feasible(self, hit, vers, now, state, pod, snapshot,
                         filters, want):
        """Rebuild a classmate's feasible list by re-filtering ONLY the
        nodes the change logs attribute a change to since the list was
        built. Returns None (caller falls back to the full scan) when:

        - node membership changed (per-name logs can't describe joins),
        - any change log was trimmed past the cached version,
        - the allocator log carries "*" (a gang slice entitlement touched
          an unknowable node set),
        - the repaired list is empty (the preemption path needs real
          per-node verdicts, which only the full scan records).

        Staleness is the one verdict input that moves with TIME rather
        than with any version counter (a node whose sniffer died changes
        no log), so it is re-verified here for every unchanged node — an
        O(1) comparison, unlike the full predicate chain. The re-check
        applies only when an active filter advertises `time_dependent`
        (TelemetryFilter does): a profile with no staleness gate
        (reference emulation) must get repaired lists its own full scan
        would produce, not ours (ADVICE r4).

        Unchanged nodes the original early-exit scan never checked stay
        unchecked — the class keeps scoring the same candidate set until
        one of its nodes changes, which the rotating full-scan start then
        re-diversifies."""
        cvers, cached, cached_names, cached_index = hit
        _, dirty, grew = self._changes_since_directed(cvers)
        if dirty is None:
            return None
        max_age = self.config.telemetry_max_age_s
        # degraded (blackout) mode waives the staleness gate wholesale,
        # so repaired lists must not re-impose it per node
        check_stale = (not state.read_or("degraded")
                       and any(getattr(p, "time_dependent", False)
                               for p in filters))
        if check_stale:
            # O(1) short-circuit: when even the OLDEST stored heartbeat is
            # fresh, no node can be stale — skip the per-name re-checks
            # (the floor is conservative; see TelemetryStore.heartbeat_floor)
            floor_fn = getattr(self.cluster.telemetry, "heartbeat_floor",
                               None)
            if floor_fn is not None:
                floor = floor_fn()
                if floor is not None and (now - floor) <= max_age:
                    check_stale = False
        # the memo holds NodeInfo objects, not names: when `dirty` is
        # attributable every unchanged node's cached info is content-valid
        # (membership changes force dirty=None above), so the common path
        # touches no snapshot lookup at all — only dirty names re-resolve.
        # The hot path (no staleness gate) copies the cached list and
        # deletes the few dirty positions via the stored index — walking
        # all `want` entries per cycle was a measurable slice of
        # bind-cycle cost in the 1000-node drain. Dirty nodes that still
        # pass re-enter at the END (same as the original walk), which the
        # score tie-break order depends on — an in-place variant measured
        # 34 fewer binds at the 1000-node tier.
        if not check_stale:
            repaired = list(cached)
            bad = dirty & cached_names
            if bad:
                for i in sorted((cached_index[n] for n in bad),
                                reverse=True):
                    del repaired[i]  # re-checked below, appended if ok
            # gap-fill candidates: the whole dirty set. Restricting to
            # GREW-dirtied nodes here is tempting but wrong-in-effect:
            # a cached list below `want` means the original early-exit
            # scan never checked some nodes, and a shrink-dirtied
            # UNCHECKED node may be feasible all along — skipping it
            # measurably shrank exploration (53 fewer binds at the
            # 1000-node tier). _repair_unsched CAN restrict to grew:
            # there the failing scan verified every node infeasible.
            fill = dirty
        else:
            repaired = []
            for node in cached:
                if node.name in dirty:
                    continue  # re-checked below so ordering is stable-ish
                if (node.metrics is None
                        or node.metrics.stale(now=now, max_age_s=max_age)):
                    continue
                repaired.append(node)
            fill = dirty
        # dirty-node verdicts: via the columnar table's subset masks when
        # every active filter vectorizes for this pod (the same booleans
        # the scalar chain yields — repair never reads the messages), the
        # per-node plugin chain otherwise. Thresholded: for a couple of
        # bind-dirtied names the scalar chain beats the table's sync +
        # gather overhead; the mask pays off on the big dirty sets event
        # storms and diverse-class drains produce.
        fill_names = sorted(fill)
        verdicts = (self._columnar_subset_ok(state, pod, snapshot, vers,
                                             filters, fill_names)
                    if len(fill_names) >= 6 and self._columnar is not None
                    else None)
        for name in fill_names:
            if len(repaired) >= want:
                # identical to filtering everything then truncating
                # [:want]: any further passer would land past `want` and
                # be cut — so don't pay its predicate chain at all (the
                # dirty set holds every OTHER class's latest bound nodes
                # too, and re-filtering them was most of repair cost)
                break
            node = snapshot.get(name)
            if node is None:
                continue
            if verdicts is not None:
                if verdicts.get(name):
                    repaired.append(node)
                continue
            st = Status.success()
            for p in filters:
                st = p.filter(state, pod, node)
                if not st.ok:
                    break
            if st.ok:
                repaired.append(node)
            elif st.code == Code.ERROR:
                return None  # surface errors via the full scan
        if not repaired:
            return None
        return repaired[:want]

    def _repair_unsched(self, hit, state, pod, snapshot, filters, trace):
        """The failure-path twin of _repair_feasible: bridge a classmate's
        no-feasible-node verdict to the current version vector by
        re-filtering ONLY the dirty nodes. A failing scan checked EVERY
        node, and under the feas_ok gate no predicate flips
        infeasible->feasible without a recorded change (staleness only
        moves the other way), so clean nodes stay infeasible by
        construction. Returns None when the change logs cannot attribute
        the delta (caller runs the full scan), else
        (passing NodeInfos, extra rejector plugin names, dirty names).

        Only GREW-dirtied nodes are re-filtered: a node touched solely by
        binds/claims cannot have become feasible under the envelope above.
        The returned dirty set stays FULL — the caller's restricted
        preemption re-plan needs shrink-dirtied nodes too (a fresh
        lower-priority bind is exactly what creates a victim)."""
        _, dirty, grew = self._changes_since_directed(hit[0])
        if dirty is None:
            return None
        passing = []
        rejectors: set[str] = set()
        for name in sorted(grew):
            node = snapshot.get(name)
            if node is None:
                continue
            st = Status.success()
            rej = None
            for p in filters:
                st = p.filter(state, pod, node)
                if not st.ok:
                    rej = p.name
                    break
            trace.filter_verdicts[name] = "ok" if st.ok else st.message
            if st.code == Code.ERROR:
                return None  # surface errors via the full scan
            if st.ok:
                passing.append(node)
            elif rej is not None:
                rejectors.add(rej)
        return passing, rejectors, dirty

    def _columnar_subset_ok(self, state, pod, snapshot, vers, filters,
                            names):
        """Combined filter verdicts for a SUBSET of nodes via the
        columnar table's row-aligned masks: {name: bool}, or None when
        the table can't serve this pod (unversioned backend, a
        non-vectorizable plugin, names outside the table). Serves the
        class-memo repair paths, whose gap-fill re-filters a handful of
        dirty nodes per cycle — the verdicts are the same booleans the
        scalar chain would produce (parity-fuzzed), minus the message
        strings the repair paths never read."""
        table = self._columnar
        if table is None or vers is None:
            return None
        if not table.sync(snapshot, vers, self._changes_since_vers):
            return None
        idx = table.index
        rows = []
        known = []
        for n in names:
            i = idx.get(n)
            if i is not None:
                rows.append(i)
                known.append(n)
        if not rows:
            return {}
        rows = np.asarray(rows, dtype=np.int64)
        ok = None
        for p in filters:
            fb = getattr(p, "filter_batch", None)
            bm = fb(state, pod, table, rows) if fb is not None else None
            if bm is None:
                return None
            ok = bm if ok is None else (ok & bm)
        if ok is None:
            return dict.fromkeys(known, True)
        return dict(zip(known, ok.tolist()))

    def _columnar_filter(self, state, pod, filters, snapshot, vers, nodes,
                         want, trace):
        """Vectorized full-scan filter: every active plugin contributes a
        boolean row mask (filter_batch), the masks AND together, and the
        rotating-offset early-stop scan is replayed over the combined
        mask by index — the SAME candidates, in the same order, as the
        per-node scalar scan would produce. Returns the feasible list, or
        None when any plugin/pod can't vectorize OR no node passed: the
        zero-pass case falls back to the scalar scan untouched (it owns
        the per-node failure diagnostics the preemption planner and the
        unschedulable-class memo need), with _filter_start deliberately
        left unadvanced so the fallback scan starts where this one did."""
        table = self._columnar
        if not table.sync(snapshot, vers, self._changes_since_vers):
            return None
        if len(table) != len(nodes):
            return None
        allmask = None
        for p in filters:
            fb = getattr(p, "filter_batch", None)
            bm = fb(state, pod, table) if fb is not None else None
            if bm is None:
                return None
            allmask = bm if allmask is None else (allmask & bm)
        if allmask is None:  # no active filters: everything passes
            allmask = table.new_true()
        n = len(nodes)
        start = self._filter_start % n
        order = (np.arange(n) if not start else
                 np.concatenate((np.arange(start, n), np.arange(start))))
        pass_pos = np.flatnonzero(allmask[order])
        if pass_pos.size == 0:
            return None
        if pass_pos.size >= want:
            checked = int(pass_pos[want - 1]) + 1
            sel = order[pass_pos[:want]]
        else:
            checked = n
            sel = order[pass_pos]
        self._filter_start = (start + checked) % n
        feasible = [nodes[i] for i in sel.tolist()]
        for ni in feasible:
            trace.filter_verdicts[ni.name] = "ok"
        self.metrics.inc("columnar_filter_cycles_total")
        return feasible

    # ----------------------------------------------------------- native plane
    def _native_args(self, state, pod, spec, filters, snapshot, vers,
                     nodes, want, degraded):
        """Assemble one fused-kernel request from the plugins' native
        capability hooks. Returns (req, sel_by_class, tel_plugin,
        frag_plugin, scorers, all_native), or None when any active
        filter vetoes — the pod then takes the numpy columnar path.
        Shared by the in-cycle scan and the prefetch dispatcher, so a
        consumed prefetch is built from EXACTLY the args the cycle
        would have built (version-vector equality pins the rest)."""
        table = self._columnar
        if not table.sync(snapshot, vers, self._changes_since_vers):
            return None
        if len(table) != len(nodes):
            return None
        now = state.read_or("now")
        req = {
            "degraded": 1 if degraded else 0,
            "now": float(now if now is not None else time.time()),
            "chips": int(spec.chips),
            "min_free_mb": int(spec.min_free_mb),
            "min_clock_mhz": int(spec.min_clock_mhz),
            "start": self._filter_start % len(nodes),
            "want": int(want),
        }
        sel = None
        for p in filters:
            hook = getattr(p, "native_filter_args", None)
            a = hook(state, pod, table) if hook is not None else None
            if a is None:
                return None
            s = a.pop("sel_by_class", None)
            if s is not None:
                sel = s
            req.update(a)
        scorers = self._gated_scorers(pod, snapshot, degraded)
        # kernel telemetry scoring divides by the kernel's own MaxValue
        # fold, which stands in for MaxCollection's pre_score — a
        # profile without a native_install-capable prescore plugin keeps
        # telemetry scoring on the Python path (whatever writes MAX_KEY
        # there owns the maxima)
        has_installer = any(
            getattr(p, "native_install", None) is not None
            for p in self.profile.pre_score)
        tel_p = frag_p = None
        for p in scorers:
            hook = getattr(p, "native_score_args", None)
            a = hook(state, pod, table) if hook is not None else None
            if a is None:
                continue  # Python-fold scorer (topology, admission)
            kind = a.pop("kind", None)
            if kind == "telemetry" and tel_p is None and has_installer:
                tel_p = p
                req.update(a)
                req["tel_score"] = 1
            elif kind == "fragmentation" and frag_p is None:
                frag_p = p
                req.update(a)
                req["frag_score"] = 1
        # the kernel's fused normalize+weighted sum folds tel then frag;
        # it may stand in for _fold_scores only when the gated scorer
        # set is exactly those plugins IN THAT ORDER (float addition is
        # order-sensitive; mixed cycles fold in Python instead)
        native = [p for p in (tel_p, frag_p) if p is not None]
        req["compute_totals"] = 1 if scorers == native else 0
        return req, sel, tel_p, frag_p, scorers, scorers == native

    def _native_scan(self, state, pod, spec, filters, snapshot, vers,
                     nodes, want, degraded):
        """One fused native cycle: consume a validated prefetch or run
        the kernel inline. Returns a _NativeCycle, _NATIVE_EMPTY (zero
        feasible — final), or None (veto/failure: numpy path next)."""
        args = self._native_args(state, pod, spec, filters, snapshot,
                                 vers, nodes, want, degraded)
        if args is None:
            self.metrics.inc("native_fallbacks_total")
            return None
        req, sel, tel_p, frag_p, scorers, all_native = args
        res = None
        pf = self._prefetched
        if pf is not None:
            tag, pres = pf
            self._prefetched = None
            # consume-time validation: same pod object, same version
            # vector (⇒ same snapshot, table, spec-derived args), same
            # scan window and regime, and no heartbeat aged past the
            # staleness gate since dispatch. ANYTHING else → discard and
            # count, exactly like the batch-conflict fallback.
            if (pres is not None and tag[0] is pod and tag[1] == vers
                    and tag[2] == req["start"] and tag[3] == want
                    and tag[4] == bool(degraded) and tag[5] == len(nodes)
                    and self._prefetch_fresh(req)):
                res = pres
                self.metrics.inc("prefetch_hits_total")
            else:
                self.metrics.inc("prefetch_stale_total")
        if res is None:
            res = self._native.run(self._columnar, req, sel_by_class=sel)
        if res is None:
            self.metrics.inc("native_fallbacks_total")
            return None
        self.metrics.inc("native_scans_total")
        if res.found == 0:
            return _NATIVE_EMPTY
        feasible = [nodes[i] for i in res.rows]
        names = [ni.name for ni in feasible]
        nc = _NativeCycle()
        nc.feasible = feasible
        nc.names_set = frozenset(names)
        nc.checked = res.checked
        nc.mv6 = res.mv6
        nc.contribs = {
            name: (tuple(res.contrib[k]) if res.qcount[k] else None)
            for k, name in enumerate(names)}
        raws = {}
        if tel_p is not None:
            raws[tel_p.name] = dict(zip(names, res.tel))
        if frag_p is not None:
            raws[frag_p.name] = dict(zip(names, res.frag))
        nc.raws = raws
        nc.totals = dict(zip(names, res.totals)) if all_native else None
        nc.scorers = scorers
        return nc

    def _prefetch_fresh(self, req: dict) -> bool:
        """May a prefetched mask stand in for a fresh scan, staleness-
        wise? Age only grows, so a node stale at DISPATCH is still stale
        now — the one divergence is a node whose heartbeat aged past the
        gate BETWEEN dispatch and consume. When even the oldest stored
        heartbeat is fresh at consume time, no such node exists."""
        if not req.get("tel_filter") or req.get("degraded"):
            return True
        floor_fn = getattr(self.cluster.telemetry, "heartbeat_floor", None)
        if floor_fn is None:
            return False
        floor = floor_fn()
        return floor is None or (req["now"] - floor) <= req["max_age"]

    def _dispatch_prefetch(self) -> None:
        """Overlapped scan prefetch (run_one, after each cycle): while
        the finished cycle's bind is still on the wire — and reflector
        threads ingest — the worker runs the NEXT queue head's memo-miss
        fused scan against the snapshot version just produced. The scan
        releases the GIL, so this costs the engine thread nothing but
        the dispatch bookkeeping; _native_scan validates the result by
        version vector at consume time. Only memo-MISS heads are worth
        prefetching: a class with a live feasible/unschedulable memo
        entry repairs in O(dirty) anyway."""
        plane = self._native
        if plane.inflight:
            return
        if self._prefetched is not None:
            # the cycle that just finished never reached the consume
            # point (memo hit, veto, gang/nominated pod, different head):
            # the banked result's tag can no longer match a future cycle
            # once this cycle moved the version vector — discard it now
            # so its buffers unpin and prefetching resumes, and count it
            # like any other stale result
            self._prefetched = None
            self.metrics.inc("prefetch_stale_total")
        now = self.clock.time()
        if now < self._breaker_until:
            return
        info = self.queue.peek(now, exclude=self.head_filter)
        if info is None:
            return
        pod = info.pod
        try:
            spec = spec_for(pod)
        except LabelError:
            return
        if spec.is_gang:
            return
        if self.allocator is not None \
                and self.allocator.nomination_of(pod.key) is not None:
            return
        memo_key = self._memo_key_of(pod, spec)
        if memo_key in self._feas_memo or memo_key in self._unsched_memo:
            return
        vers = self._cluster_versions()
        if vers is None:
            return
        snapshot = self.snapshot()
        nodes = snapshot.list()
        if not nodes:
            return
        degraded = self._detect_degraded(now)
        state = CycleState()
        state.write("now", now)
        state.write("workload_spec", spec)
        state.write("snapshot", snapshot)
        state.write("cycle_versions", vers)
        if degraded:
            state.write("degraded", True)
        filters = [p for p in self.profile.filter
                   if getattr(p, "relevant", None) is None
                   or p.relevant(pod, snapshot)]
        want = self._num_feasible_to_find(len(nodes))
        args = self._native_args(state, pod, spec, filters, snapshot,
                                 vers, nodes, want, degraded)
        if args is None:
            return
        req, sel = args[0], args[1]
        tag = (pod, vers, req["start"], want, bool(degraded), len(nodes))
        plane.prefetch_submit(tag, self._columnar, req, sel_by_class=sel)
        self.metrics.inc("prefetch_dispatched_total")

    # --------------------------------------------------------------- snapshot
    def snapshot(self) -> Snapshot:
        """Per-cycle view. Incremental: backends exposing change logs
        (changes_since on the cluster and telemetry store, plus a node
        membership version) let a cycle rebuild ONLY the nodes that
        changed since the previous cycle — a bind touches one node, so at
        1000 nodes the per-cycle cost is O(dirty), not O(cluster). Node
        membership changes or an out-of-range log fall back to the full
        walk, which itself reuses per-node NodeInfos via _ni_cache."""
        cluster = self.cluster
        csince = getattr(cluster, "changes_since", None)
        tsince = getattr(cluster.telemetry, "changes_since", None)
        nver = getattr(cluster, "nodes_version", None)
        if csince is not None and tsince is not None and self._snap is not None:
            snap, pv0, tv0, nv0 = self._snap
            if nver == nv0:  # membership unchanged
                pv, pdirty = csince(pv0)
                tv, tdirty = tsince(tv0)
                if pdirty is not None and tdirty is not None:
                    dirty = pdirty | tdirty
                    if not dirty:
                        self._snap = (snap, pv, tv, nv0)
                        return snap
                    # SHARED dict, mutated in place: the superseded
                    # snapshot is never read for pre-mutation content
                    # after this cycle starts (memo_ok's `prev` checks run
                    # before snapshot()), and copying 1000 entries per
                    # cycle was a measurable slice of the drain at scale.
                    # The fresh Snapshot wrapper still gets its own
                    # identity + lazily-recomputed list()/flags.
                    infos = snap._node_infos
                    pods_version = getattr(cluster, "pods_version", None)
                    # pre-mutation fact needed below: did any dirty node
                    # flip unschedulable True -> False? Must be captured
                    # BEFORE infos[name] is overwritten (the dict is
                    # shared, so reading it after would see the NEW info)
                    uncordoned = False
                    if snap._any_unsched:
                        uncordoned = any(
                            n in infos and infos[n].unschedulable
                            for n in dirty)
                    for name in dirty:
                        if name not in infos:
                            continue  # telemetry for a non-member node
                        ni = self._make_node_info(name)
                        infos[name] = ni
                        if pods_version is not None:
                            key = (getattr(ni.metrics, "generation", None),
                                   pods_version(name))
                            self._ni_cache[name] = (key, ni)
                    # membership version unchanged here, so budgets and
                    # namespace labels are too
                    fresh = Snapshot(infos, budgets=snap.budgets,
                                     namespaces=snap._namespaces)
                    # carry the any-taints / any-anti-affinity facts: only
                    # dirty nodes can have introduced one (a removal leaves
                    # the conservative True, costing nothing but the
                    # skipped optimization)
                    if snap._any_taints is not None:
                        fresh._any_taints = snap._any_taints or any(
                            infos[n].taints for n in dirty if n in infos)
                    if snap._any_pod_anti is not None:
                        fresh._any_pod_anti = snap._any_pod_anti or any(
                            p.pod_anti_affinity
                            for n in dirty if n in infos
                            for p in infos[n].pods)
                    if snap._any_alloc is not None:
                        fresh._any_alloc = snap._any_alloc or any(
                            infos[n].allocatable is not None
                            for n in dirty if n in infos)
                    if snap._any_pref_pod is not None:
                        fresh._any_pref_pod = snap._any_pref_pod or any(
                            p.preferred_pod_affinity
                            for n in dirty if n in infos
                            for p in infos[n].pods)
                    if snap._any_unsched is not None:
                        if uncordoned:
                            # a dirty node WAS unschedulable before this
                            # rebuild (captured pre-mutation above): it
                            # may just have been uncordoned — recompute
                            # exactly (an uncordon of the LAST cordoned
                            # node must drop the admission filter out of
                            # the hot path, not pin it there until the
                            # next full snapshot). O(nodes), but only on
                            # cycles that touched a cordoned node. This
                            # is what makes NodeSpecChanged requeue hints
                            # worth taking: the woken pod's retry runs
                            # against the cheap path again.
                            fresh._any_unsched = any(
                                ni.unschedulable for ni in infos.values())
                        else:
                            fresh._any_unsched = snap._any_unsched or any(
                                infos[n].unschedulable
                                for n in dirty if n in infos)
                    self._snap = (fresh, pv, tv, nv0)
                    return fresh
        return self._full_snapshot()

    def _snapshot_one_dirty(self, name: str, prev_vers, vers
                            ) -> "Snapshot | None":
        """Commit-loop snapshot: the caller has PROVEN — change-log
        attribution against `prev_vers` — that `name` is the only node
        changed since the cached snapshot and membership is unchanged.
        Rebuild that one NodeInfo and re-wrap: exactly what snapshot()
        would produce with dirty == {name}, minus its re-walk of the
        change logs the caller already performed. None = the cached
        snapshot isn't at `prev_vers` (caller uses the generic path)."""
        if self._snap is None or prev_vers is None:
            return None
        snap, pv0, tv0, nv0 = self._snap
        if (pv0 != prev_vers[0] or tv0 != prev_vers[1]
                or nv0 != prev_vers[2] or nv0 != vers[2]):
            return None
        infos = snap._node_infos
        old = infos.get(name)
        if old is None:
            return None
        uncordoned = bool(snap._any_unsched) and old.unschedulable
        ni = self._make_node_info(name)
        infos[name] = ni
        pods_version = getattr(self.cluster, "pods_version", None)
        if pods_version is not None:
            self._ni_cache[name] = (
                (getattr(ni.metrics, "generation", None),
                 pods_version(name)), ni)
        fresh = Snapshot(infos, budgets=snap.budgets,
                         namespaces=snap._namespaces)
        # flag carries: the generic path's any(...) over dirty, unrolled
        # for the single node (it reads the post-rebuild info, as here)
        if snap._any_taints is not None:
            fresh._any_taints = snap._any_taints or bool(ni.taints)
        if snap._any_pod_anti is not None:
            fresh._any_pod_anti = snap._any_pod_anti or any(
                p.pod_anti_affinity for p in ni.pods)
        if snap._any_alloc is not None:
            fresh._any_alloc = (snap._any_alloc
                                or ni.allocatable is not None)
        if snap._any_pref_pod is not None:
            fresh._any_pref_pod = snap._any_pref_pod or any(
                p.preferred_pod_affinity for p in ni.pods)
        if snap._any_unsched is not None:
            if uncordoned:
                fresh._any_unsched = any(
                    x.unschedulable for x in infos.values())
            else:
                fresh._any_unsched = snap._any_unsched or ni.unschedulable
        self._snap = (fresh, vers[0], vers[1], nv0)
        return fresh

    def _make_node_info(self, name: str, metrics=_UNSET) -> NodeInfo:
        """One coherent NodeInfo: telemetry + bound pods + node-object meta
        (labels/taints for the admission plugin; backends without node
        metadata — plain fakes — yield empty meta). Callers that already
        fetched the node's telemetry (cache-key computation) pass it in to
        avoid a second store lookup per node."""
        cluster = self.cluster
        meta_fn = getattr(cluster, "node_meta", None)
        labels, taints = meta_fn(name) if meta_fn is not None else ({}, ())
        alloc_fn = getattr(cluster, "node_allocatable", None)
        unsched_fn = getattr(cluster, "node_unschedulable", None)
        if metrics is _UNSET:
            metrics = cluster.telemetry.get(name)
        return NodeInfo(name=name, metrics=metrics,
                        pods=cluster.pods_on(name), labels=labels,
                        taints=taints,
                        allocatable=alloc_fn(name)
                        if alloc_fn is not None else None,
                        unschedulable=bool(unsched_fn(name))
                        if unsched_fn is not None else False)

    def _full_snapshot(self) -> Snapshot:
        cluster = self.cluster
        # sample the version vector BEFORE reading any node data: a
        # concurrent mutation during the walk then just re-flags its node
        # dirty next cycle. Sampling after would absorb the event — the
        # stored version covers a change the snapshot never saw, and
        # changes_since would never report it again.
        csince = getattr(cluster, "changes_since", None)
        tsince = getattr(cluster.telemetry, "changes_since", None)
        pre = None
        if csince is not None and tsince is not None:
            pre = (csince(1 << 62)[0], tsince(1 << 62)[0],
                   getattr(cluster, "nodes_version", 0))
        pods_version = getattr(cluster, "pods_version", None)
        infos: dict[str, NodeInfo] = {}
        names = cluster.node_names()
        for name in names:
            metrics = cluster.telemetry.get(name)
            if pods_version is not None:
                key = (getattr(metrics, "generation", None), pods_version(name))
                cached = self._ni_cache.get(name)
                if cached is not None and cached[0] == key:
                    infos[name] = cached[1]
                    continue
                ni = self._make_node_info(name, metrics)
                self._ni_cache[name] = (key, ni)
            else:
                ni = self._make_node_info(name, metrics)
            infos[name] = ni
        # prune per-node caches for departed nodes on EVERY backend — the
        # allocator's free-set cache fills from free_coords() regardless of
        # whether this backend supports NodeInfo reuse
        gone = self._known_nodes - set(infos)
        if gone:
            for n in gone:
                self._ni_cache.pop(n, None)
            if self.allocator is not None:
                self.allocator.forget_nodes(gone)
            # plugin-local per-node caches (filter verdicts, score terms)
            # prune through the same hook
            for plugins in (self.profile.filter, self.profile.pre_score,
                            self.profile.score):
                for p in plugins:
                    forget = getattr(p, "forget_nodes", None)
                    if forget is not None:
                        forget(gone)
        self._known_nodes = set(infos)
        budgets_fn = getattr(cluster, "disruption_budgets", None)
        ns_fn = getattr(cluster, "namespace_labels_map", None)
        snap = Snapshot(infos,
                        budgets=budgets_fn() if budgets_fn is not None else (),
                        namespaces=ns_fn() if ns_fn is not None else None)
        if pre is not None:
            self._snap = (snap, pre[0], pre[1], pre[2])
        return snap

    # ------------------------------------------------------------- the cycle
    def schedule_one(self, info: QueuedPodInfo) -> str:
        """One pod's cycle. Serialized via cycle_lock: a cycle snapshots the
        cluster, then reserves/binds against that snapshot — a concurrent
        bind from a co-hosted profile's engine between the two would
        double-book chips (upstream kube-scheduler likewise runs ONE
        scheduleOne loop across all profiles)."""
        with self.cycle_lock:
            try:
                return self._schedule_one_locked(info)
            except Exception as e:
                # cycle-level exception containment: a raising plugin
                # fails the POD, never the engine thread
                return self._contain_crash(info, e)

    def schedule_batch(self, infos: list[QueuedPodInfo]) -> str:
        """One shared scheduling cycle over an equivalence-class batch
        (queue.pop_batch). The FIRST pod runs the ordinary per-pod cycle —
        full semantics, and it arms the commit context only when that
        cycle stayed inside the class-memo soundness envelope. On a bound
        outcome the remaining classmates commit greedily against its
        candidate ranking with incremental claim/score/maxima updates
        (_commit_batch); every member the incremental path cannot place
        EXACTLY as a per-pod cycle would — a concurrent event moved the
        version vector, candidates exhausted, the cluster maxima shifted —
        falls back to the ordinary per-pod cycle inline, right here, so
        no pod is ever lost or reordered behind the rest of the queue.

        Churn-plane fast cycle (config.churn_plane): when the PREVIOUS
        same-class batch's commit ran clean end to end, its context is
        still armed (_fast_resume) — this batch re-enters the commit
        loop directly (_fast_cycle), skipping the ordinary head cycle,
        and only falls back here on a guard miss or mid-batch conflict.
        With the knob on, single-pod batches also run through this body
        (not schedule_one) so their cycles arm and consume the context
        too — at equilibrium the queue often drains one pod at a time."""
        if len(infos) == 1 and not self._churn:
            return self.schedule_one(infos[0])
        with self.cycle_lock:
            if self._fast_resume is not None:
                done = self._fast_cycle(infos)
                if done:
                    if done == len(infos):
                        return "bound"
                    infos = infos[done:]
            ctx = _BatchCtx()
            try:
                first = self._schedule_one_locked(infos[0], batch_ctx=ctx)
            except Exception as e:
                first = self._contain_crash(infos[0], e)
            rest = infos[1:]
            done = 0
            if first == "bound" and ctx.armed:
                if rest:
                    self.metrics.inc("batch_cycles_total")
                self._batch_cursor = None
                try:
                    done = self._commit_batch(ctx, rest)
                except Exception as e:
                    # attribute the crash to the member the commit loop
                    # was processing (every earlier member completed);
                    # the rest fall back to per-pod cycles below
                    cur = self._batch_cursor
                    if cur is not None and cur in rest:
                        done = rest.index(cur) + 1
                        self._contain_crash(cur, e)
                    else:
                        done = 0
                        self.metrics.inc("cycle_crashes_total")
                finally:
                    self._batch_cursor = None
            leftover = rest[done:]
            # unschedulable-class batch fast path: when the head's cycle
            # just recorded (or reconfirmed) the class's no-feasible-node
            # verdict, its batchmates — same equivalence class, so same
            # memo key — would each pay a full per-pod cycle only to hit
            # that same memo at the same version vector. Fail them off
            # the memo directly (attempts, backoff, traces, and metrics
            # exactly as the per-pod memo-hit path would), under the same
            # soundness envelope the memo itself requires; any member the
            # fast path can't prove eligible falls through to the
            # ordinary per-pod cycle below.
            fast_ok = (first in ("unschedulable", "failed")
                       and not ctx.armed and leftover
                       and self.defrag is None
                       and (self.allocator is None
                            or not self.allocator.has_holds()))
            if fast_ok:
                prev = self._snap[0] if self._snap is not None else None
                fast_ok = prev is None or not prev.any_pod_anti_affinity()
            for i, info in enumerate(leftover):
                # breaker gate FIRST: a storm that opened the circuit
                # mid-batch parks the rest attempt-free — the memo fast
                # path must not burn their attempts while the server is
                # down (run_one's gate would have held them)
                if self.clock.time() < self._breaker_until:
                    # the circuit breaker opened mid-batch (a storm is
                    # failing every bind): park the remaining members
                    # back on the active queue with no attempt burned —
                    # run_one's gate holds them until the cooldown
                    now_park = self.clock.time()
                    for parked in leftover[i:]:
                        # now= closes the batch-cycle stint and opens a
                        # fresh queue-wait one, so the breaker-cooldown
                        # wait lands in e2e_queue_wait_ms like any park
                        self.queue.requeue_immediate(parked, now=now_park)
                    break
                if fast_ok and self._batch_fast_fail(info):
                    continue
                try:
                    self._schedule_one_locked(info)
                except Exception as e:
                    self._contain_crash(info, e)
            return first

    def _fast_cycle(self, infos: list[QueuedPodInfo]) -> int:
        """Churn-plane fast cycle: consume the resume state a clean,
        fully-bound batch commit left behind (_fast_resume) and run this
        batch straight through the incremental commit loop, skipping the
        ordinary head cycle. The entry guards re-establish everything a
        head cycle re-derives OUTSIDE the version vector — regime, holds,
        nominations, policy gates, the pod's equivalence class; the
        attribution check inside _commit_batch proves everything inside
        it (foreign dirt of any kind falls back). Returns how many
        members were handled; 0 = nothing consumed (guard miss or
        first-member conflict), the caller runs the ordinary cycle."""
        ctx, r_node, r_vers = self._fast_resume
        self._fast_resume = None
        now = self.clock.time()
        reason = None
        p0 = infos[0].pod
        if p0.phase == PodPhase.BOUND and p0.node:
            reason = "foreign_bound"  # the full cycle owns the drop
        elif self._degraded or self._detect_degraded(now):
            reason = "degraded"  # regime (or a pending flip): full
            # cycles own the memo clears and the staleness waivers
        elif self.defrag is not None:
            reason = "defrag"  # pins land between cycles, outside vers
        elif self.allocator is not None and (
                self.allocator.has_holds()
                or self.allocator.nomination_of(p0.key) is not None):
            reason = "holds"  # per-pod holds break class equivalence
        elif getattr(self.profile, "policy", None) is not None:
            reason = "policy"  # fairness gates re-read live shares
        else:
            try:
                spec = spec_for(p0)
            except LabelError:
                reason = "spec"
            else:
                if spec.is_gang:
                    reason = "gang"
                elif self._memo_key_of(p0, spec) != ctx.memo_key:
                    reason = "class_moved"
        if reason is None:
            # attribution pre-check, the same test _commit_batch applies
            # per member — run it BEFORE paying the commit loop's matrix
            # setup, because at equilibrium a completion between batches
            # is the COMMON miss (foreign dirt) and the ordinary cycle
            # is about to take a fresh snapshot anyway
            vers, dirty, _grew = self._changes_since_directed(r_vers)
            if vers is None or dirty is None or not dirty <= {r_node}:
                conflicted = True
                if dirty is not None and vers is not None:
                    snap_infos = (self._snap[0]._node_infos
                                  if self._snap is not None else None)
                    if snap_infos is not None:
                        conflicted = any(n != r_node and n in snap_infos
                                         for n in dirty)
                if conflicted:
                    reason = "foreign_dirt"
        if reason is not None:
            self.metrics.inc("fast_cycle_guard_misses_total")
            self.flight.record("fast_cycle_guard_miss", pod=p0.key,
                               reason=reason)
            return 0
        self.metrics.inc("fast_cycles_total")
        self._batch_cursor = None
        try:
            done = self._commit_batch(ctx, infos, prev_node=r_node,
                                      prev_vers=r_vers)
        except Exception as e:
            # same crash attribution as schedule_batch's commit call
            cur = self._batch_cursor
            if cur is not None and cur in infos:
                done = infos.index(cur) + 1
                self._contain_crash(cur, e)
            else:
                done = 0
                self.metrics.inc("cycle_crashes_total")
        finally:
            self._batch_cursor = None
        if done < len(infos):
            self.metrics.inc("fast_cycle_fallbacks_total")
        return done

    def _batch_fast_fail(self, info: QueuedPodInfo) -> bool:
        """Fail one batchmate off the unschedulable-class memo without a
        per-pod cycle — bit-identical to the memo-hit path in
        _schedule_one_locked (same attempts bookkeeping, trace shape,
        metrics, and requeue/backoff), legal only when THIS pod's memo
        entry sits exactly at the live version vector. False = not
        provably eligible; the caller runs the ordinary cycle."""
        pod = info.pod
        if pod.phase == PodPhase.BOUND and pod.node:
            return False  # foreign-bound: the full cycle owns the drop
        now = self.clock.time()
        degraded = self._detect_degraded(now)
        if degraded != self._degraded:
            return False  # regime flip: the full cycle owns the clears
        if degraded:
            # a fast-failed batchmate is a real scheduling cycle run
            # under the blackout regime: the counter must see it, or a
            # batched degraded drain undercounts by (batch-1)/batch
            self.metrics.inc("degraded_cycles_total")
        try:
            spec = spec_for(pod)
        except LabelError:
            return False
        if spec.is_gang:
            return False
        vers = self._cluster_versions()
        if vers is None:
            return False
        hit = self._unsched_memo.get(self._memo_key_of(pod, spec))
        if hit is None or hit[0] != vers:
            return False
        if info.cycle_started >= 0.0:
            info.t_cycle += max(now - info.cycle_started, 0.0)
        info.cycle_started = now
        trace = CycleTrace(pod=pod.key, started=now)
        trace.plane = "memo"
        self.metrics.inc("unsched_memo_hits_total")
        self._unschedulable(info, trace, hit[1], rejected_by=hit[2])
        return True

    def _commit_batch(self, ctx: _BatchCtx, infos: list[QueuedPodInfo],
                      prev_node: str | None = None,
                      prev_vers=None) -> int:
        """Greedy batch commit: place each classmate against the shared
        candidate ranking, updating ONLY what the previous bind touched —
        the bound node's row (NodeInfo rebuild + re-filter + re-score),
        its slice's usage entry, and the maxima fold — then re-rank with
        one vectorized normalize+sum over the raw vectors. Every update
        replicates the corresponding per-pod repair path op-for-op (the
        parity fuzz in tests/test_batch.py pins placements identical), so
        a batched drain and a per-pod drain of the same trace bind the
        same pods to the same chips. Returns how many infos were fully
        handled; the caller runs per-pod cycles for the rest.

        Churn-plane fast cycle: `prev_node`/`prev_vers` resume a PRIOR
        batch's fully-consumed commit context across the cycle boundary
        (schedule_batch guards the entry), so an equilibrium drain of one
        equivalence class pays ONE ordinary head cycle and then commits
        every later batch through this loop. The attribution check below
        is the safety net either way: any dirt not on the previously
        bound node sends the caller back to the ordinary cycle."""
        state = ctx.state
        spec = ctx.spec
        candidates = ctx.candidates
        raws = ctx.raws
        scorers = ctx.scorers
        want = ctx.want
        max_age = self.config.telemetry_max_age_s
        floor_fn = getattr(self.cluster.telemetry, "heartbeat_floor", None)
        table = self._columnar
        # exit-time memo state: the class memos must end up EXACTLY where
        # the equivalent per-pod chain would leave them, or the next
        # classmate's repair produces a differently-ordered candidate
        # list and the tie-break diverges. The feasible entry tracks the
        # latest COMPLETED repair (per-pod stores it at repair time); the
        # score entry tracks the latest completed rescore (per-pod stores
        # it after scoring) — a bail between the two stores the mixed
        # state the per-pod chain would also be in at that point.
        if prev_node is None:
            prev_node = ctx.chosen
            prev_cycle_vers = ctx.vers
            mem_feas = (ctx.vers, list(candidates))
            mem_score = (ctx.vers, ctx.mv_t, ctx.usage)
        else:
            # resumed continuation: the memos already sit at the previous
            # commit's exit vector — re-seed the exit state from THERE,
            # not from the head cycle's long-stale vector
            prev_cycle_vers = prev_vers
            mem_feas = (prev_vers, list(candidates))
            mem_score = (prev_vers, ctx.mv_t,
                         state.read_or(SLICE_USE_KEY) or {})
        raws_ok = True  # False only when a rescore ERROR left raws torn
        handled = 0
        kinds = [(p, raws[p.name],
                  getattr(p, "score_inputs", None) == "node+slice_usage",
                  self._normalize_kind(p), getattr(p, "weight", 1))
                 for p in scorers]
        # per-scorer raw scores as one preallocated row-major matrix in
        # candidate order, maintained in LOCKSTEP with `candidates` (the
        # dicts in `raws` stay the score-memo's exit format): the
        # per-member normalize+weighted-sum fold then runs over array
        # views — one fused native call (IncrementalKernels.batch_fold)
        # or a handful of numpy ops — instead of rebuilding an array
        # from dict lookups per scorer per member. Capacity +1: a member
        # removes the bound node and re-appends at most one row.
        n_sc = len(kinds)
        cap = len(candidates) + 1
        smat = np.empty((n_sc, cap), dtype=np.float64)
        for k, (_p, raw, _c, _k, _w) in enumerate(kinds):
            smat[k, :len(candidates)] = [raw[ni.name] for ni in candidates]
        fold_kinds = np.asarray(
            [1 if t[3] == "minmax" else 0 for t in kinds], dtype=np.int64)
        fold_w = np.asarray([float(t[4]) for t in kinds],
                            dtype=np.float64)
        totals_buf = np.empty(cap, dtype=np.float64)
        ties_buf = np.empty(cap, dtype=np.int64)
        nk = self._incremental
        fold_fn = nk.fold_fn if nk is not None else None
        # buffer pointers captured once — the per-member call passes
        # plain ints (a ctypes cast per call would cost more than the
        # numpy ops the fused fold removes)
        p_smat, p_kinds = smat.ctypes.data, fold_kinds.ctypes.data
        p_w, p_tot, p_ties = (fold_w.ctypes.data, totals_buf.ctypes.data,
                              ties_buf.ctypes.data)
        stride = smat.shape[1]
        # candidate NAME set maintained in lockstep with the list: the
        # per-member frozenset then builds off this set instead of
        # re-walking 100 NodeInfo.name attributes
        cand_names = {ni.name for ni in candidates}
        completed = True  # False once any member falls off the loop
        # churn plane: per-member counter bumps are batched into one inc
        # per call ("metrics sampled, not stamped") — totals identical,
        # minus two locked dict updates per member
        defer = self._churn
        n_hits = n_binds = 0
        for info in infos:
            self._batch_cursor = info  # crash attribution (schedule_batch)
            completed = False  # back True only when this member BINDS
            pod = info.pod
            now = self.clock.time()
            # conflict detection by ATTRIBUTION, not by version equality:
            # the previous bind legitimately moved the vector, so the
            # batch may continue only when every change since the previous
            # member's cycle is on the node that bind touched. Anything
            # else — a reflector apply, a telemetry publish, a cordon, an
            # async-bind rollback, even one landing DURING our own bind
            # call — sends the rest of the batch to per-pod cycles and
            # their fresh snapshots.
            vers, dirty, _grew = self._changes_since_directed(
                prev_cycle_vers)
            conflicted = (vers is None or dirty is None
                          or not dirty <= {prev_node})
            if conflicted and dirty is not None and vers is not None:
                # NON-MEMBER dirt cannot conflict: a dirty name outside
                # this engine's snapshot membership (a sharded-reflection
                # replica's foreign pools, telemetry for unknown nodes)
                # is exactly what snapshot()/sync() skip — no candidate,
                # score, or prescore input can depend on it. Membership
                # CHANGES can't hide here: they move vers[2], which the
                # attribution above already turned into dirty=None.
                snap_infos = (self._snap[0]._node_infos
                              if self._snap is not None else None)
                if snap_infos is not None:
                    conflicted = any(n != prev_node and n in snap_infos
                                     for n in dirty)
            if conflicted:
                self.metrics.inc("batch_conflict_fallbacks_total")
                self.flight.record("batch_conflict_fallback",
                                   pod=pod.key, prev_node=prev_node)
                break
            self._csv_memo.clear()
            state.write("now", now)
            # incremental: attribution above proved dirty == {prev_node},
            # so patch the cached snapshot directly (generic fallback
            # when the cache isn't exactly at the previous vector)
            snapshot = self._snapshot_one_dirty(prev_node,
                                                prev_cycle_vers, vers)
            if snapshot is None:
                snapshot = self.snapshot()
            state.write("snapshot", snapshot)
            state.write("cycle_versions", vers)
            if snapshot.any_pod_anti_affinity():
                break  # memo envelope broke: full per-pod cycles own it
            new_prev = snapshot.get(prev_node)
            if new_prev is None:
                break
            # per-member relevance re-gate, exactly the per-pod cycle's:
            # an absorbed change on the bound node itself (a cordon
            # landing inside our bind window attributes to prev_node) can
            # flip a snapshot fact and pull a filter back into play
            filters = [p for p in self.profile.filter
                       if getattr(p, "relevant", None) is None
                       or p.relevant(pod, snapshot)]
            if any(getattr(p, "time_dependent", False) for p in filters) \
                    and not state.read_or("degraded"):
                # (degraded mode waives staleness entirely, so the
                # aged-out-heartbeat bail below would only thrash)
                floor = floor_fn() if floor_fn is not None else None
                if floor is None or (now - floor) > max_age:
                    # some heartbeat may have aged out mid-batch: only the
                    # per-pod repair path re-verifies staleness per node
                    self.metrics.inc("batch_conflict_fallbacks_total")
                    break
            if table is not None and not self._churn:
                # keep the columnar twin hot: one in-place row refresh
                # from the rebuilt NodeInfo instead of a changes_since
                # walk at the next sync. Sound because the attribution
                # check above proved every change since the previous
                # cycle's vector is on prev_node — and new_prev reflects
                # ALL of them, not just our bind (a telemetry publish or
                # cordon absorbed into the bind window refills correctly).
                # The free_coords/claimed_hbm work is memoized on
                # new_prev, so the re-filter below reuses it.
                # Churn plane: SKIP the per-member refresh — the loop
                # never reads the table, and letting the dirt accumulate
                # means the next ordinary cycle's sync applies the whole
                # batch in one eventplane call (_sync_batched) instead of
                # a _fill_row here per member. Same final table bytes:
                # refresh_row is a declared shortcut, never a source of
                # truth (its own docstring).
                table.refresh_row(prev_node, new_prev, prev_cycle_vers,
                                  vers)
            # ---- candidate list: exactly _repair_feasible for a single
            # dirty node — drop the bound node, re-filter it against its
            # rebuilt info, passing nodes re-enter at the END (score
            # tie-break order depends on this)
            for i, ni in enumerate(candidates):
                if ni.name == prev_node:
                    del candidates[i]
                    cand_names.discard(prev_node)
                    lc = len(candidates)
                    if i < lc:  # keep the score matrix in lockstep
                        smat[:, i:lc] = smat[:, i + 1:lc + 1]
                    break
            appended = False
            if len(candidates) < want:
                st = Status.success()
                for p in filters:
                    st = p.filter(state, pod, new_prev)
                    if not st.ok:
                        break
                if st.code == Code.ERROR:
                    break
                if st.ok:
                    candidates.append(new_prev)
                    cand_names.add(prev_node)
                    appended = True
            if not candidates:
                # the class ran out of known candidates: the per-pod full
                # scan (and its unschedulable/preemption bookkeeping) owns
                # this — identical to repair returning an empty list (the
                # feasible memo stays at the last COMPLETED repair, just
                # as a failed per-pod repair leaves it)
                break
            # repair completed: per-pod refreshes the feasible entry at
            # exactly this point, so the exit state does too
            mem_feas = (vers, list(candidates))
            names = frozenset(cand_names)
            # ---- prescore outputs: each plugin updates its own memo +
            # cycle-state contribution exactly (MaxCollection maxima,
            # TopologyScore slice usage)
            prev_usage = state.read_or(SLICE_USE_KEY) or {}
            ok = True
            for p in self.profile.pre_score:
                if not p.pre_score_update(state, pod, new_prev, names):
                    ok = False
                    break
            if not ok:
                break
            usage = state.read_or(SLICE_USE_KEY) or {}
            mvv = state.read_or(MAX_KEY)
            mv_t = (mvv.bandwidth, mvv.clock, mvv.core, mvv.free_memory,
                    mvv.power, mvv.total_memory) if mvv is not None else None
            if mv_t != ctx.mv_t:
                # the cluster maxima moved (the bound node held the unique
                # max-attribute chip): every maxima-normalised raw score is
                # stale, which is exactly the score-memo miss the per-pod
                # cycle full-rescoring handles
                break
            sid = (new_prev.metrics.slice_id
                   if new_prev.metrics is not None else None)
            slice_moved = bool(sid) and usage.get(sid) != prev_usage.get(sid)
            # ---- re-score only what changed: the bound node (if it
            # re-entered) for every scorer, plus its slice-mates for
            # slice-coupled scorers — the score-memo replay rule
            # candidates needing a fresh score are KNOWN: the re-appended
            # bound node (its raw entry was just popped) and — for
            # slice-coupled scorers when its slice's usage moved — that
            # slice's other members. Scoring exactly those (instead of
            # scanning every candidate per scorer for a membership check)
            # computes the same values the scan would.
            appended_idx = len(candidates) - 1 if appended else None
            mates = None
            if slice_moved and any(t[2] for t in kinds):
                mates = [(idx, node) for idx, node in enumerate(candidates)
                         if idx != appended_idx
                         and node.metrics is not None
                         and node.metrics.slice_id == sid]
            for k, (p, raw, coupled, _kind, _w) in enumerate(kinds):
                raw.pop(prev_node, None)
                row = smat[k]
                if appended_idx is not None:
                    node = candidates[appended_idx]
                    s, st = p.score(state, pod, node)
                    if st.code == Code.ERROR:
                        ok = False
                        break
                    raw[node.name] = s
                    row[appended_idx] = s
                if coupled and mates:
                    for idx, node in mates:
                        s, st = p.score(state, pod, node)
                        if st.code == Code.ERROR:
                            ok = False
                            break
                        raw[node.name] = s
                        row[idx] = s
                    if not ok:
                        break
            if not ok:
                raws_ok = False  # mid-rescore ERROR: raws are torn
                break
            # ---- normalize + weighted sum + tie set, op-for-op the
            # scalar fold: one fused native call when the incremental
            # kernel is loaded, the elementwise numpy twin otherwise
            # (both perform the same IEEE double ops in the same order,
            # so the tie set — and the seeded tie-break — are identical)
            n = len(candidates)
            n_ties = (fold_fn(p_smat, n_sc, stride, p_kinds, p_w, n,
                              p_tot, p_ties)
                      if fold_fn is not None else -1)
            if n_ties > 0:
                best_nodes = [candidates[int(ties_buf[j])].name
                              for j in range(n_ties)]
            else:
                totals = _numpy_fold(smat, fold_kinds, fold_w, n)
                best = totals.max()
                best_nodes = [candidates[i].name
                              for i in np.flatnonzero(totals == best)]
            chosen = self.rng.choice(best_nodes)
            # selection complete: candidates/raws/usage are the exact
            # per-pod repair state for THIS member's version vector. The
            # batch commit IS the feasible-class repair path, fused — the
            # counter keeps meaning "classmate placed off the class memo
            # instead of a fresh scan" for dashboards and tests alike.
            if defer:
                n_hits += 1
            else:
                self.metrics.inc("feas_memo_hits_total")
            mem_score = (vers, mv_t, usage)
            prev_cycle_vers = vers
            # ---- Reserve -> (Permit) -> Bind, the ordinary sub-steps
            trace = CycleTrace(pod=pod.key, started=now)
            # batch commit IS the class-memo repair path; plane-attribute
            # member cycles the same way (cycle_plane_total{plane="memo"})
            trace.plane = "memo"
            info.commit_started = self.clock.time()  # e2e: commit opens
            reserved: list[ReservePlugin] = []
            st = Status.success()
            for p in self.profile.reserve:
                try:
                    st = p.reserve(state, pod, chosen)
                except Exception:
                    # crash surfaces through schedule_batch's containment
                    self._unwind_reserved(reserved, state, pod, chosen)
                    raise
                if not st.ok:
                    for r in reversed(reserved):
                        r.unreserve(state, pod, chosen)
                    break
                reserved.append(p)
            if not st.ok:
                # a racing claim emptied the chosen node between score and
                # reserve: per-pod handling for this member, fresh cycles
                # for the rest
                self._unschedulable(info, trace, f"reserve: {st.message}",
                                    rejected_by=(p.name,))
                self.metrics.inc("batch_conflict_fallbacks_total")
                handled += 1
                break
            # Permit: the equivalence contract (framework.equivalence_key)
            # guarantees permit plugins are no-ops for batchable pods, but
            # call them anyway — a WAIT/deny here is a contract breach we
            # surface through the ordinary rollback, not silently
            permit_ok = True
            for p in self.profile.permit:
                try:
                    pst, _timeout = p.permit(state, pod, chosen)
                except Exception:
                    self._unwind_reserved(reserved, state, pod, chosen)
                    raise
                if not pst.ok:
                    for r in reversed(reserved):
                        r.unreserve(state, pod, chosen)
                    self._unschedulable(info, trace,
                                        f"permit: {pst.message}",
                                        rejected_by=(p.name,))
                    handled += 1
                    permit_ok = False
                    break
            if not permit_ok:
                break
            if not self._bind(info, chosen, trace):
                # _bind rolled back and requeued; remaining members need
                # the fresh snapshot a per-pod cycle takes
                self.metrics.inc("batch_conflict_fallbacks_total")
                handled += 1
                break
            if defer:
                n_binds += 1
            else:
                self.metrics.inc("batched_binds_total")
            handled += 1
            prev_node = chosen
            completed = True
        if n_hits:
            self.metrics.inc("feas_memo_hits_total", n_hits)
        if n_binds:
            self.metrics.inc("batched_binds_total", n_binds)
        # churn-plane fast cycle: a commit loop that ran CLEAN end to end
        # (every member bound — or no members at all, the single-pod
        # head) leaves its context armed for the next same-class batch;
        # schedule_batch re-guards at consume time and the attribution
        # check above re-proves soundness against whatever happened in
        # between. Any fall-off means the ordinary cycle owns the class
        # again until a fresh commit re-arms.
        self._fast_resume = ((ctx, prev_node, prev_cycle_vers)
                             if self._churn and completed and raws_ok
                             else None)
        # exit-time memo refresh (see mem_feas/mem_score above): the next
        # classmate — batched, or the per-pod fallback the caller runs for
        # the rest of this batch — must see the memos the equivalent
        # per-pod chain would have produced, with the same list ORDER
        # (tie-breaks ride on it). A torn raw dict (mid-rescore ERROR)
        # drops the score entry instead; values-exactness makes a fresh
        # rescore produce identical floats anyway.
        if len(self._feas_memo) > 256:
            self._feas_memo.clear()
        self._feas_memo[ctx.memo_key] = self._feas_entry(*mem_feas)
        if raws_ok:
            if len(self._score_memo) > 256:
                self._score_memo.clear()
            self._score_memo[ctx.memo_key] = (mem_score[0], mem_score[1],
                                              mem_score[2], ctx.names_set,
                                              raws)
        else:
            self._score_memo.pop(ctx.memo_key, None)
        return handled

    def _detect_degraded(self, now: float) -> bool:
        """Telemetry-blackout verdict for one cycle (side-effect-free;
        the regime-flip bookkeeping stays in _schedule_one_locked). Also
        used by the prefetch dispatcher, whose scan must run under the
        same regime the consuming cycle will detect."""
        if not self.config.degraded_mode:
            return False
        ceil_fn = getattr(self.cluster.telemetry, "heartbeat_ceiling", None)
        if ceil_fn is None:
            return False
        ceil = ceil_fn()
        return (ceil is not None
                and (now - ceil) > self.config.telemetry_max_age_s)

    def _gated_scorers(self, pod, snapshot, degraded: bool) -> list:
        """The cycle's effective scorer set: degraded mode drops
        telemetry-dependent scorers, relevance gates drop plugins that
        cannot move this pod's ranking. One definition — the scoring
        section and the native scan must agree or their folds diverge."""
        scorers = []
        for p in self.profile.score:
            if degraded and getattr(p, "telemetry_dependent", False):
                # blackout degraded mode: stale quality numbers would
                # rank nodes on noise — capacity/topology scorers carry
                # the placement until the feed recovers
                continue
            gate = getattr(p, "score_relevant", None)
            if gate is None:
                gate = getattr(p, "relevant", None)
            if gate is None or gate(pod, snapshot):
                scorers.append(p)
        return scorers

    def _schedule_one_locked(self, info: QueuedPodInfo,
                             batch_ctx: "_BatchCtx | None" = None) -> str:
        # churn-plane fast cycle: any ordinary cycle invalidates the
        # carried commit context — it may bind, repair memos, or mutate
        # the score dicts the context aliases. A batch that re-arms does
        # so at _commit_batch exit, AFTER this clear.
        self._fast_resume = None
        if self._native is not None and self._native.inflight:
            # thread-safety contract (nativeplane.py): the table must be
            # quiescent before this cycle's first sync — wait for the
            # in-flight prefetch scan (sub-ms) and bank its result for
            # the consume-time validation in _native_scan
            got = self._native.prefetch_wait()
            if got is not None:
                self._prefetched = got
        pod = info.pod
        now = self.clock.time()
        # e2e decomposition: open the compute phase. A batch member
        # falling back to this per-pod cycle arrives with the stint
        # run_one opened at the shared pop still live — fold that
        # pop-to-fallback wait into t_cycle first (it IS batch cycle
        # time), or the interval would vanish from the breakdown
        if info.cycle_started >= 0.0:
            info.t_cycle += max(now - info.cycle_started, 0.0)
        info.cycle_started = now
        trace = CycleTrace(pod=pod.key, started=now)
        # lifecycle spans: sampled pods stamp each extension point (one
        # clock read per phase); everyone else pays one memoized lookup
        rec = self.spans if self._sampled(pod) else None
        if pod.phase == PodPhase.BOUND and pod.node:
            # a foreign fleet replica bound this pod after it entered our
            # queue (shared-state optimistic scheduling — free-for-all
            # poaching or a split-brain window queued it twice): drop the
            # entry off cluster truth instead of burning a full cycle
            # that would end in the authority's 409
            if self.allocator is not None:
                self.allocator.unnominate(pod.key)
            self.metrics.inc("foreign_bind_skips_total")
            self._finish(trace, "foreign-bound", node=pod.node,
                         reason="already bound by a foreign replica")
            return "foreign-bound"
        state = CycleState()
        state.write("now", now)
        self._csv_memo.clear()  # per-cycle dirty-set cache

        try:
            spec = spec_for(pod)
        except LabelError as e:
            # malformed request: permanent failure, not silent 0-coercion
            pod.phase = PodPhase.FAILED
            self.failed[pod.key] = str(e)
            self.metrics.inc("pods_failed_total")
            self._finish(trace, "failed", reason=str(e))
            return "failed"
        doom = self.doomed_gangs.get(spec.gang_name) if spec.is_gang else None
        if doom is not None:
            # a peer permanently failed while this member sat in backoff:
            # assembly can never finish, fail fast instead of re-parking
            self._fail_permanently(info, doom, trace=trace)
            return "failed"
        state.write("workload_spec", spec)

        # serving-pressure growth hold (SloGuard): while the guard is
        # pressed OR shrunk capacity is still owed back, elastic GROWTH
        # members (gang already running at >= tpu/gang-min) park instead
        # of re-absorbing the chips the shrink pass just freed for
        # serving. The give-back publishes a POD_DELETED wake through
        # the elastic-grow hint class, releasing them in the valley.
        if (self.sloguard is not None and self.elastic is not None
                and spec.is_gang and spec.gang_min > 0
                and self.sloguard.holding(now)
                and spec.gang_name not in self.doomed_gangs
                and self._bound_members_of(spec.gang_name)
                >= spec.gang_min):
            from .elastic import ELASTIC_GROW_HINT

            self.metrics.inc("serving_growth_holds_total")
            return self._unschedulable(
                info, trace,
                f"gang {spec.gang_name}: growth held while serving "
                "pressure holds the freed chips",
                rejected_by=(ELASTIC_GROW_HINT,), gang_doom=False)

        # telemetry-blackout degraded mode: when even the NEWEST stored
        # heartbeat is past the staleness gate, the whole feed is dark —
        # one node's dead sniffer never trips this — and the engine keeps
        # scheduling off last-known capacity (TelemetryFilter waives its
        # staleness gate, telemetry-dependent scorers drop out) instead
        # of marking every node stale-infeasible. Detected per cycle; a
        # regime flip clears the class memos, because staleness verdicts
        # change with TIME and no version vector records the transition.
        degraded = self._detect_degraded(now)
        if degraded != self._degraded:
            self._degraded = degraded
            self._unsched_memo.clear()
            self._feas_memo.clear()
            self._score_memo.clear()
            self.metrics.set_gauge("degraded", 1.0 if degraded else 0.0)
            self.metrics.inc("degraded_transitions_total")
            self.flight.record("degraded_mode", active=degraded)
        if degraded:
            state.write("degraded", True)
            self.metrics.inc("degraded_cycles_total")

        # unschedulable-class fast path (see _unsched_memo). Gang pods and
        # nominated preemptors carry state outside the version vector.
        # Admission inputs (nodeSelector/tolerations) are part of the class:
        # two pods with identical labels but different tolerations must not
        # share a verdict. The common no-admission case keys on the interned
        # spec alone (a tuple never equals a WorkloadSpec, so no collision).
        # the symmetry rule makes verdicts depend on ARBITRARY pod labels
        # (a bound pod's anti-affinity selector can distinguish pods with
        # identical WorkloadSpecs), so the class memo is unsound while any
        # bound pod carries anti-affinity. The previous cycle's snapshot
        # answers that cheaply; if an anti-affinity pod binds later, the
        # version vector already invalidates every memo entry.
        prev = self._snap[0] if self._snap is not None else None
        memo_ok = (not spec.is_gang
                   and (prev is None or not prev.any_pod_anti_affinity())
                   and (self.allocator is None
                        or self.allocator.nomination_of(pod.key) is None))
        memo_key = self._memo_key_of(pod, spec)
        vers = self._cluster_versions()
        if memo_ok and vers is not None:
            hit = self._unsched_memo.get(memo_key)
            if hit is not None and hit[0] == vers:
                self.metrics.inc("unsched_memo_hits_total")
                trace.plane = "memo"
                return self._unschedulable(info, trace, hit[1],
                                           rejected_by=hit[2])

        snapshot = self.snapshot()
        state.write("snapshot", snapshot)
        state.write("changes_since_fn", self._changes_since_vers)
        # the version vector sampled BEFORE the snapshot was built:
        # plugin memos must store THIS vector, never a live re-sample —
        # an event landing between snapshot build and a later sample
        # would be absorbed (the memo's version covers it while its data
        # predates it), and changes_since would never report it again
        state.write("cycle_versions", vers)

        # PreFilter
        for p in self.profile.pre_filter:
            st = p.pre_filter(state, pod, snapshot)
            if st.code == Code.UNSCHEDULABLE:
                return self._unschedulable(info, trace, st.message,
                                           rejected_by=(p.name,))
            if st.code == Code.ERROR:
                return self._cycle_error(info, trace, st.message)

        # migration-plan pin (scheduler/elastic/defrag.py): a defrag
        # victim's FIRST re-placement cycle considers ONLY its planned
        # destination — the dry-run proved it fits there, and unpinned
        # re-scoring would bounce it straight back into the hole the
        # migration just opened. One-shot: a failed pinned cycle (the
        # destination was taken meanwhile) leaves later retries
        # unrestricted. Never overrides gang narrowing (victims are
        # never gang members).
        if (self.defrag is not None
                and state.read_or(CANDIDATE_NODES_KEY) is None):
            pin = self.defrag.take_pin(pod.key)
            if pin is not None:
                state.write(CANDIDATE_NODES_KEY, frozenset((pin,)))
                # class memos are unsound under candidate narrowing: the
                # pinned one-node scan must neither be STORED class-wide
                # (a classmate would inherit a single-node feasible list
                # or an O(1) "no feasible node" verdict while the cluster
                # has capacity) nor SKIPPED via a feasible-memo hit (the
                # class list ignores the pin). Gang narrowing never hits
                # this because is_gang already cleared memo_ok; the pin
                # is the only narrowing a memo-eligible pod can carry.
                # (On FakeCluster the victim is also allocator-nominated,
                # which clears memo_ok anyway — this gate is what keeps
                # the real-apiserver path sound, where eviction destroys
                # the incarnation and no nomination is placed.)
                memo_ok = False

        # Filter with early-stop (percentageOfNodesToScore)
        nodes = snapshot.list()
        want = self._num_feasible_to_find(len(nodes))
        # a nominated preemptor evaluates its nominated node FIRST (upstream
        # behavior): its verdict is then always known, so _unschedulable can
        # release the hold the moment the node stops being feasible
        nom = (self.allocator.nomination_of(pod.key)
               if self.allocator is not None else None)
        # per-cycle relevance gating: plugins exposing `relevant(pod,
        # snapshot)` drop out of the per-node loops when they cannot affect
        # this pod (e.g. admission on an untainted cluster) — the gate runs
        # once per cycle, not once per node
        filters = [p for p in self.profile.filter
                   if getattr(p, "relevant", None) is None
                   or p.relevant(pod, snapshot)]

        # per-class incremental feasible list: classmates dominate bursts,
        # and a bind dirties ONE node — repair the previous classmate's
        # feasible list from the change logs instead of re-filtering the
        # whole cluster. STRICTER gate than _unsched_memo: the memo there
        # requires exact version equality, while repair bridges versions
        # re-filtering only dirty nodes — sound ONLY for per-node
        # predicates. Domain-scoped constraints (topologySpread skew,
        # required pod (anti-)affinity incl. the symmetry rule) flip
        # verdicts of UNCHANGED same-domain nodes on a bind, so any such
        # pod — or any bound anti-affinity pod, checked on the CURRENT
        # snapshot, not memo_ok's previous one — takes the full scan.
        feas_ok = (memo_ok and nom is None and vers is not None
                   and not pod.topology_spread
                   and not pod.pod_affinity and not pod.pod_anti_affinity
                   and not snapshot.any_pod_anti_affinity())
        feasible: list[NodeInfo] | None = None
        rejectors: set[str] = set()
        t_filter0 = now  # span stamp: filter phase effectively starts here
        if feas_ok:
            hit = self._feas_memo.get(memo_key)
            if hit is not None:
                feasible = self._repair_feasible(
                    hit, vers, now, state, pod, snapshot, filters, want)
                if feasible is not None:
                    trace.plane = "memo"
                    self.metrics.inc("feas_memo_hits_total")
                    # refresh versions + infos so the next classmate's
                    # dirty set stays small
                    self._feas_memo[memo_key] = self._feas_entry(
                        vers, feasible)

        if feasible is None and feas_ok:
            # unschedulable-class REPAIR: the classmate's "no feasible
            # node" verdict was recorded under an older version vector. A
            # failing scan checked EVERY node, and no predicate under the
            # feas_ok gate flips infeasible->feasible without a recorded
            # change, so only the DIRTY nodes can have become feasible —
            # re-filter just those instead of rescanning the cluster (the
            # retry storms this replaces were the round-5 backoff wall's
            # main compute cost: each one a full scan plus a preemption
            # re-plan).
            hit = self._unsched_memo.get(memo_key)
            if hit is not None and hit[0] != vers:
                rep = self._repair_unsched(hit, state, pod, snapshot,
                                           filters, trace)
                if rep is not None:
                    trace.plane = "memo"
                    passing, extra_rej, dirty = rep
                    if passing:
                        self.metrics.inc("unsched_memo_repairs_total")
                        feasible = passing[:want]
                        # the class is schedulable again: retire the
                        # unschedulable entry and seed the feasible memo
                        # so the next classmate repairs from here
                        del self._unsched_memo[memo_key]
                        self._feas_memo[memo_key] = self._feas_entry(
                            vers, feasible)
                    else:
                        combined = hit[2] | extra_rej
                        self._unsched_memo[memo_key] = (vers, hit[1],
                                                        combined)
                        self.metrics.inc("unsched_memo_repairs_total")
                        # preemption could have become viable only on a
                        # dirty node (e.g. a fresh lower-priority bind):
                        # run the planner restricted to those. Falls back
                        # to the full scan when a post-filter plugin can't
                        # restrict or PDB accounting couples the verdicts
                        # cluster-wide.
                        if self.profile.post_filter:
                            if snapshot.budgets or not all(
                                    getattr(p, "supports_restricted", False)
                                    for p in self.profile.post_filter):
                                feasible = None  # full scan decides
                            else:
                                out = self._run_post_filter(
                                    info, trace, state, pod, spec,
                                    snapshot, now, only_nodes=dirty)
                                if out is not None:
                                    return out
                                return self._unschedulable(
                                    info, trace, hit[1],
                                    rejected_by=tuple(combined))
                        else:
                            return self._unschedulable(
                                info, trace, hit[1],
                                rejected_by=tuple(combined))

        # native fused scan: when every active filter AND prescore can be
        # expressed in the fused kernel, the memo-miss full scan — filter
        # mask, rotating early-stop top-k, MaxValue fold, native scorers'
        # raw terms — collapses into ONE GIL-releasing C call over the
        # columnar arrays (scheduler/nativeplane.py). Same gates as the
        # numpy path below; any veto falls through to numpy columnar,
        # then scalar — the fallback chain is scalar <- numpy <- native,
        # each layer the ground truth of the one above.
        nat: "_NativeCycle | None" = None
        native_empty = False
        if (feasible is None and self._native is not None
                and vers is not None and nom is None and not spec.is_gang
                and nodes and state.read_or(CANDIDATE_NODES_KEY) is None):
            out = self._native_scan(state, pod, spec, filters, snapshot,
                                    vers, nodes, want, degraded)
            if out is _NATIVE_EMPTY:
                # zero feasible rows, verdict-final (the numpy mask would
                # agree bit-for-bit): skip the redundant numpy scan; the
                # scalar loop below owns the per-node failure diagnostics
                # and _filter_start deliberately stays unadvanced
                native_empty = True
                trace.plane = "native"
            elif out is not None:
                nat = out
                trace.plane = "native"
                feasible = nat.feasible
                for ni in feasible:
                    trace.filter_verdicts[ni.name] = "ok"
                self._filter_start = (self._filter_start % len(nodes)
                                      + nat.checked) % len(nodes)
                if feas_ok:
                    if len(self._feas_memo) > 256:
                        self._feas_memo.clear()
                    self._feas_memo[memo_key] = self._feas_entry(
                        vers, feasible)

        # columnar full scan: when every active filter can express this
        # pod's predicates over the node table, the whole cluster is
        # evaluated in a handful of numpy calls instead of a per-(pod,
        # node) Python loop. Gated to pods whose cycle carries no state
        # the columns can't see (nomination ordering, PreFilter candidate
        # narrowing, gang membership); zero-pass and every bail-out fall
        # through to the scalar scan below, which remains ground truth.
        if (feasible is None and not native_empty
                and self._columnar is not None
                and vers is not None and nom is None and not spec.is_gang
                and nodes and state.read_or(CANDIDATE_NODES_KEY) is None):
            feasible = self._columnar_filter(state, pod, filters, snapshot,
                                            vers, nodes, want, trace)
            if feasible is not None:
                trace.plane = "numpy"
            if feas_ok and feasible:
                if len(self._feas_memo) > 256:
                    self._feas_memo.clear()
                self._feas_memo[memo_key] = self._feas_entry(vers, feasible)

        if feasible is None:
            trace.plane = "native" if native_empty else "scalar"
            # bounded diagnostics after a kernel-final empty verdict: the
            # scalar scan's only remaining outputs are the failure REASON
            # (sorted per-node messages, truncated at ~500 chars) and the
            # REJECTORS set. The rejectors come exactly from the columnar
            # masks (first-failing plugin per row, one vectorized pass);
            # the reason needs only the alphabetically-first nodes up to
            # the truncation budget — nodes are already in sorted order,
            # so scanning a prefix builds the identical string a full
            # walk would. A full FAILING scan advances the rotation by
            # len(nodes) ≡ 0 (mod n), so the bounded scan advances by 0
            # too. At 50k nodes this turns an O(cluster) Python walk per
            # no-fit class into O(truncation).
            diag_budget = None
            if native_empty:
                rej = self._columnar_rejectors(state, pod, filters)
                if rej is not None:
                    rejectors |= rej
                    diag_budget = 1000
            if diag_budget is not None:
                order = range(len(nodes))
            else:
                order = [(self._filter_start + i) % len(nodes)
                         for i in range(len(nodes))]
                if nom is not None:
                    ni = next((i for i in order
                               if nodes[i].name == nom[0]), None)
                    if ni is not None:
                        order.remove(ni)
                        order.insert(0, ni)
            # sound candidate narrowing from PreFilter (gang slice
            # membership / chosen slice / plan quotas): nodes outside the
            # set are provably infeasible under predicates preemption
            # cannot relax, so the filter chain is skipped for them
            cand = state.read_or(CANDIDATE_NODES_KEY)
            feasible = []
            checked = 0
            diag_size = 0
            for i in order:
                node = nodes[i]
                if cand is not None and node.name not in cand:
                    continue
                checked += 1
                st = Status.success()
                rej = None
                for p in filters:
                    st = p.filter(state, pod, node)
                    if not st.ok:
                        rej = p.name
                        break
                trace.filter_verdicts[node.name] = ("ok" if st.ok
                                                    else st.message)
                if st.code == Code.ERROR:
                    return self._cycle_error(info, trace, st.message)
                if st.ok:
                    feasible.append(node)
                    if len(feasible) >= want:
                        break
                elif rej is not None:
                    rejectors.add(rej)
                if diag_budget is not None and not st.ok:
                    diag_size += len(node.name) + len(st.message) + 2
                    if diag_size > diag_budget:
                        break
            if diag_budget is None:
                self._filter_start = ((self._filter_start + checked)
                                      % max(len(nodes), 1))
            if feas_ok and feasible:
                if len(self._feas_memo) > 256:
                    self._feas_memo.clear()
                self._feas_memo[memo_key] = self._feas_entry(vers, feasible)

        if rec is not None:
            rec.record("cycle.filter", pod.key, t_filter0, self.clock.time(),
                       {"plane": trace.plane or "scalar",
                        "feasible": len(feasible) if feasible else 0,
                        "want": want})
        if not feasible:
            # a nominated preemptor whose victims are still in graceful
            # termination is just waiting for capacity it is already
            # entitled to — don't plan a second preemption round (`nom`
            # from the filter-ordering step above; the cycle lock means it
            # cannot have changed since)
            if nom is not None and any(
                    p.terminating for p in self.cluster.pods_on(nom[0])):
                return self._unschedulable(
                    info, trace,
                    f"waiting for victims on {nom[0]} to terminate",
                    rejected_by=("victim-drain",))
            # same for a gang holding a slice-level entitlement: while its
            # victims drain anywhere on the nominated slice, wait
            if spec.is_gang and self.allocator is not None:
                gnom = self.allocator.gang_nomination_of(spec.gang_name)
                if gnom is not None and any(
                        p.terminating
                        for ni in snapshot.list()
                        if ni.metrics is not None
                        and ni.metrics.slice_id == gnom[0]
                        for p in ni.pods):
                    return self._unschedulable(
                        info, trace,
                        f"waiting for victims on slice {gnom[0]} to "
                        "terminate", rejected_by=("victim-drain",))
            # elastic GROWTH members (gang already admitted at >= min in
            # cluster truth) park event-driven instead of preempting:
            # growth rides capacity as it frees — the defrag controller
            # and ordinary departures publish the POD_DELETED wakes —
            # and never evicts anyone to grow an already-running job
            out = self._elastic_growth_park(info, spec, trace)
            if out is not None:
                return out
            # PostFilter: preemption — the plugin plans, the engine evicts
            out = self._run_post_filter(info, trace, state, pod, spec,
                                        snapshot, now)
            if out is not None:
                return out
            # elastic admit-at-min: preemption could not cure this gang
            # member either — if enough members are already placed
            # (parked at Permit + bound), start the gang NOW at reduced
            # size instead of letting the whole assembly time out
            out = self._elastic_admit_at_min(info, spec, trace)
            if out is not None:
                return out
            # build the diagnostic bounded: at 1000 nodes a full join of
            # every failure verdict costs more than the whole cycle
            parts: list[str] = []
            size = 0
            for n, v in sorted(trace.filter_verdicts.items()):
                if v == "ok":
                    continue
                parts.append(f"{n}: {v}")
                size += len(parts[-1])
                if size > 500:
                    parts.append("...")
                    break
            reason = "no feasible node: " + "; ".join(parts)[:500]
            if memo_ok and vers is not None:
                # classmates fail in O(1) until any cluster event; the
                # rejecting-plugin set rides along so their queueing hints
                # apply to O(1) failures too
                if len(self._unsched_memo) > 256:
                    self._unsched_memo.clear()
                self._unsched_memo[memo_key] = (vers, reason,
                                                frozenset(rejectors))
            return self._unschedulable(info, trace, reason,
                                       rejected_by=tuple(rejectors))

        t_score0 = self.clock.time() if rec is not None else 0.0
        # PreScore. When the candidate set came off the feasible-class
        # memo, hand prescore plugins its name frozenset so they can key
        # their own incremental folds on set identity (MaxCollection
        # re-folds only touched components when the set is unchanged).
        if feas_ok:
            fent = self._feas_memo.get(memo_key)
            if (fent is not None and fent[0] == vers
                    and len(fent[1]) == len(feasible)):
                state.write("feasible_names", fent[2])
        for p in self.profile.pre_score:
            if nat is not None:
                inst = getattr(p, "native_install", None)
                if inst is not None:
                    # the fused kernel already folded this plugin's
                    # output (MaxValue + per-candidate contributions):
                    # install it exactly where pre_score would leave it
                    inst(state, spec, vers, nat.names_set, nat.contribs,
                         nat.mv6)
                    continue
            st = p.pre_score(state, pod, feasible)
            if st.code == Code.ERROR:
                return self._cycle_error(info, trace, st.message)

        # Score + per-plugin normalize + weighted sum (relevance-gated
        # like the filter loop; a plugin may declare a separate
        # score_relevant when its scoring inputs are narrower than its
        # filtering inputs)
        totals: dict[str, float] = {n.name: 0.0 for n in feasible}
        scorers = (nat.scorers if nat is not None
                   else self._gated_scorers(pod, snapshot, degraded))

        # SCORE-class memo: a classmate's raw per-plugin scores are
        # verbatim repeats for every node the change logs call clean —
        # rescore ONLY dirty nodes (the score twin of _repair_feasible).
        # Sound only for plugins that DECLARE their per-node inputs via
        # `score_inputs`: "node" (node serial + allocator pending version
        # + the pod's label class + the cycle maxima) or
        # "node+slice_usage" (additionally the node's slice-usage entry).
        # Any other scorer, a maxima change, or a scorer-set change falls
        # back to full scoring. Normalize/weighted-sum always re-run on
        # the full raw vector (min-max is a whole-set operation).
        mvv = state.read_or(MAX_KEY)
        mv_t = (mvv.bandwidth, mvv.clock, mvv.core, mvv.free_memory,
                mvv.power, mvv.total_memory) if mvv is not None else None
        usage = state.read_or(SLICE_USE_KEY) or {}
        names_set = tuple(p.name for p in scorers)
        repairable = feas_ok and all(
            getattr(p, "score_inputs", None) in ("node", "node+slice_usage")
            for p in scorers)
        dirty_s = None
        hit = self._score_memo.get(memo_key) if repairable else None
        if hit is not None and hit[1] == mv_t and hit[3] == names_set:
            _, dirty_s = self._changes_since_vers(hit[0])
        cached_usage = hit[2] if hit is not None else {}
        if scorers:
            # equilibrium memo-churn gauge: how often steady-state
            # arrivals land on a replayable score memo vs forcing a full
            # rescore (bench.run_serve_steady folds these into a rate)
            self.metrics.inc("score_memo_hits_total" if dirty_s is not None
                             else "score_memo_misses_total")
        # columnar batch scoring: on memo-MISS cycles (first of a class,
        # maxima moved, candidate set changed) plugins exposing
        # score_batch evaluate ALL candidates in one array expression
        # (normalize then becomes one broadcast over the raw vector).
        # When the score-class memo replay is available it stays
        # preferred — replaying ~want cached floats beats recomputing
        # them, vectorized or not; plugins without a batch form
        # (topology sub-block search, admission preferences) keep the
        # scalar loop either way.
        # candidate row-index array, resolved lazily on the first
        # memo-miss cycle that can use batch scoring (sync is idempotent
        # per version vector — the repair path usually already paid it)
        col_rows = None
        # under the commit plane, batch scoring also arms on fused-native
        # cycles: the fused kernel carries no topology term, so on a nat
        # memo-miss TopologyScore would otherwise scalar-loop over every
        # candidate — exactly the per-pod Python the plane removes (the
        # sync below is a version-vector no-op there; the nat scan paid it)
        if ((nat is None or self._commitk is not None) and dirty_s is None
                and self._columnar is not None
                and vers is not None and scorers):
            if self._columnar.sync(snapshot, vers, self._changes_since_vers):
                col_rows = self._columnar.rows_for(feasible)
        raws: dict[str, dict[str, float]] = {}
        # per-plugin folds are DEFERRED: when every plugin declares its
        # normalize shape, the whole stack folds in one fused pass over
        # the candidate matrix (_fold_all_scores) — op-for-op the same
        # floats as folding each plugin in turn, minus a Python loop
        # over ~want candidates per scorer per cycle
        fold_jobs: list = []
        for p in scorers:
            if nat is not None:
                nraw = nat.raws.get(p.name)
                if nraw is not None:
                    # raw terms straight from the fused kernel; the fold
                    # stays in profile order so mixed native/Python
                    # cycles accumulate bit-identically. When EVERY
                    # scorer was native the kernel also fused
                    # normalize+sum (nat.totals, applied below).
                    raws[p.name] = nraw
                    if nat.totals is None:
                        fold_jobs.append((p, nraw))
                    continue
            raw: dict[str, float] = {}
            if col_rows is not None:
                sb = getattr(p, "score_batch", None)
                arr = sb(state, pod, self._columnar, col_rows) \
                    if sb is not None else None
                if arr is not None:
                    for i, node in enumerate(feasible):
                        raw[node.name] = float(arr[i])
                    self.metrics.inc("columnar_score_batches_total")
                    raws[p.name] = raw
                    fold_jobs.append((p, raw))
                    continue
            cached = hit[4].get(p.name, {}) if dirty_s is not None else {}
            slice_coupled = (getattr(p, "score_inputs", None)
                             == "node+slice_usage")
            for node in feasible:
                name = node.name
                if dirty_s is not None and name not in dirty_s:
                    m = node.metrics
                    sid = m.slice_id if m is not None else None
                    if (not (slice_coupled and sid
                             and usage.get(sid) != cached_usage.get(sid))
                            and name in cached):
                        raw[name] = cached[name]
                        continue
                s, st = p.score(state, pod, node)
                if st.code == Code.ERROR:
                    return self._cycle_error(info, trace, st.message)
                raw[name] = s
            raws[p.name] = raw
            fold_jobs.append((p, raw))
        if nat is not None and nat.totals is not None:
            totals = nat.totals
        else:
            self._fold_all_scores(state, pod, fold_jobs, feasible, totals)
        if repairable and vers is not None:
            if len(self._score_memo) > 256:
                self._score_memo.clear()
            self._score_memo[memo_key] = (vers, mv_t, usage, names_set,
                                          raws)
        trace.scores = totals

        best_score = max(totals.values())
        best_nodes = [n for n, s in totals.items() if s == best_score]
        chosen = self.rng.choice(best_nodes)
        if rec is not None:
            rec.record("cycle.score", pod.key, t_score0, self.clock.time(),
                       {"scorers": [p.name for p in scorers],
                        "chosen": chosen})

        # arm the batch commit loop (schedule_batch): classmates popped
        # with this pod may commit against this cycle's candidate ranking
        # via incremental updates — but ONLY when the whole cycle ran
        # inside the class-memo soundness envelope (feas_ok + declared
        # score inputs, i.e. `repairable`) and every normalize/prescore
        # step has an exact incremental form. Anything else leaves the
        # context un-armed and the classmates take per-pod cycles.
        if (batch_ctx is not None and repairable and vers is not None
                and HAVE_NUMPY
                and all(getattr(p, "pre_score_update", None) is not None
                        for p in self.profile.pre_score)
                and all(self._normalize_kind(p) in ("identity", "minmax")
                        for p in scorers)):
            batch_ctx.arm(
                state=state, spec=spec, memo_key=memo_key, want=want,
                scorers=scorers,
                candidates=list(feasible),
                raws={pn: dict(r) for pn, r in raws.items()},
                names_set=names_set, vers=vers, usage=usage, mv_t=mv_t,
                chosen=chosen)

        # Reserve
        info.commit_started = self.clock.time()  # e2e: commit phase opens
        reserved: list[ReservePlugin] = []
        for p in self.profile.reserve:
            try:
                st = p.reserve(state, pod, chosen)
            except Exception:
                # a RAISING reserve plugin must not leak the partial
                # reservation chain; the engine's containment then
                # quarantine-tracks the crash
                self._unwind_reserved(reserved, state, pod, chosen)
                raise
            if not st.ok:
                for r in reversed(reserved):
                    r.unreserve(state, pod, chosen)
                return self._unschedulable(info, trace,
                                           f"reserve: {st.message}",
                                           rejected_by=(p.name,))
            reserved.append(p)
        if rec is not None:
            rec.record("cycle.reserve", pod.key, info.commit_started,
                       self.clock.time(), {"node": chosen})

        # Permit
        t_permit0 = self.clock.time() if rec is not None else 0.0
        for p in self.profile.permit:
            try:
                st, timeout = p.permit(state, pod, chosen)
            except Exception:
                self._unwind_reserved(reserved, state, pod, chosen)
                raise
            if st.code == Code.WAIT:
                self.waiting[pod.key] = _WaitingPod(info, chosen, now + timeout)
                self.metrics.inc("pods_waiting_total")
                self._finish(trace, "waiting", node=chosen, reason=st.message)
                return "waiting"
            if not st.ok:
                for r in reversed(reserved):
                    r.unreserve(state, pod, chosen)
                return self._unschedulable(info, trace,
                                           f"permit: {st.message}",
                                           rejected_by=(p.name,))
        if rec is not None and self.profile.permit:
            rec.record("cycle.permit", pod.key, t_permit0,
                       self.clock.time(), {"node": chosen})

        # Bind this pod, then any gang peers its admission released
        if not self._bind(info, chosen, trace):
            # anchor bind failed: the gang (if any) must not half-bind —
            # reject the waiting peers now instead of letting them park
            # until the Permit deadline with reservations held
            if self.gang_permit is not None:
                gang = self.gang_permit.gang_of(pod)
                if gang:
                    self._fail_gang(gang)
            return "bind-error"
        if self.gang_permit is not None:
            peers_ok = True
            for peer_key in self.gang_permit.peers_to_approve(pod):
                if not self._bind_waiting_peer(peer_key):
                    peers_ok = False
            if spec.is_gang and self.allocator is not None and peers_ok:
                # gang FULLY bound: its slice entitlement (if it preempted
                # its way in) is consumed. A failed peer bind keeps the
                # hold — the straggler needs its refill window protected
                # until it re-binds (the entitlement expiry bounds the
                # worst case).
                self.allocator.unnominate_gang(spec.gang_name)
        return "bound"

    # ------------------------------------------------------------ sub-steps
    @staticmethod
    def _unwind_reserved(reserved, state, pod, node) -> None:
        """Best-effort rollback of a partial reservation chain after a
        RAISING reserve/permit plugin, with REAL cycle state (gang plan
        decrements need the snapshot + chosen node — _contain_crash's
        bare backstop sweep cannot reconstruct them). Swallows unreserve
        errors: the original crash must reach the engine's containment,
        not be masked by a secondary failure. Shared by the per-pod and
        batch-commit reserve/permit loops so the unwind contract has one
        definition."""
        for r in reversed(reserved):
            try:
                r.unreserve(state, pod, node)
            except Exception:
                pass

    @staticmethod
    def _normalize_kind(p) -> str | None:
        """Resolve a score plugin's declared normalize shape
        (framework.ScorePlugin.normalize_kind); a plugin that never
        overrode `normalize` is identity without declaring it."""
        kind = getattr(p, "normalize_kind", None)
        if kind is not None:
            return kind
        if type(p).normalize is ScorePlugin.normalize:
            return "identity"
        return None

    def _fold_scores(self, state, pod, p, raw, totals) -> None:
        """Normalize + weighted-sum one plugin's raw scores into totals.
        Plugins with a declared normalize shape get the normalization
        FUSED into the accumulation — op-for-op the same floats as
        normalize-then-sum, minus the per-cycle dict copy and second dict
        walk (the score-replay allocations were a measured slice of the
        1000-node drain's ~170 us/bind floor). Undeclared shapes keep the
        generic copy-then-normalize path unchanged."""
        w = getattr(p, "weight", 1)
        kind = self._normalize_kind(p)
        if kind == "identity":
            for name, s in raw.items():
                totals[name] += w * s
            return
        if kind == "minmax" and raw:
            # exactly framework.min_max_normalize(lo=0, hi=100) followed
            # by `totals[name] += w * s`, with the temporary dict elided
            vals = raw.values()
            lowest = min(vals)
            highest = max(vals)
            span = highest - lowest
            if span == 0:
                for name in raw:
                    totals[name] += w * 100.0
            else:
                for name, s in raw.items():
                    totals[name] += w * (0.0 + (s - lowest) * 100.0 / span)
            return
        nraw = dict(raw)  # normalize mutates: keep the memo's copy raw
        p.normalize(state, pod, nraw)
        for name, s in nraw.items():
            totals[name] += w * s

    def _columnar_rejectors(self, state, pod, filters) -> "set[str] | None":
        """First-failing-plugin attribution for a kernel-final EMPTY
        verdict, from the columnar masks: plugin p rejects a row iff the
        row survived every earlier filter and fails p's mask — exactly
        the scalar chain's early-exit attribution, one vectorized pass
        per plugin. None when any filter can't vectorize (the caller
        walks the scalar chain instead). The table already sits at the
        cycle's version (the native scan synced it)."""
        table = self._columnar
        if table is None:
            return None
        alive = None
        out: set[str] = set()
        for p in filters:
            fb = getattr(p, "filter_batch", None)
            bm = fb(state, pod, table) if fb is not None else None
            if bm is None:
                return None
            rejected = ~bm if alive is None else (alive & ~bm)
            if rejected.any():
                out.add(p.name)
            alive = bm if alive is None else (alive & bm)
        return out

    def _fold_all_scores(self, state, pod, jobs, feasible, totals) -> None:
        """Fold every deferred (plugin, raw) pair into `totals`. When
        every plugin declares a vectorizable normalize shape and the
        candidate set is big enough to matter, ONE pass over the raw
        matrix — the fused native fold (IncrementalKernels.batch_fold)
        or its numpy twin — replaces the per-plugin per-candidate Python
        loops; both perform exactly _fold_scores' IEEE ops in the same
        order, so every total is bit-identical (the batch parity fuzz
        pins the same fold in _commit_batch). Undeclared shapes and
        small sets keep the per-plugin dict fold."""
        n = len(feasible)
        if (HAVE_NUMPY and n >= 16 and jobs
                and all(self._normalize_kind(p) in ("identity", "minmax")
                        for p, _ in jobs)):
            names = [ni.name for ni in feasible]
            smat = np.empty((len(jobs), n), dtype=np.float64)
            for k, (_p, raw) in enumerate(jobs):
                smat[k] = [raw[nm] for nm in names]
            kinds = np.asarray(
                [1 if self._normalize_kind(p) == "minmax" else 0
                 for p, _ in jobs], dtype=np.int64)
            ws = np.asarray([float(getattr(p, "weight", 1))
                             for p, _ in jobs], dtype=np.float64)
            nk = self._incremental
            tot = np.empty(n, dtype=np.float64)
            if nk is not None:
                ties = np.empty(n, dtype=np.int64)
                got = nk.batch_fold(smat, kinds, ws, n, tot, ties)
            else:
                got = -1
            if got <= 0:  # no kernel (or malformed): the numpy twin
                tot = _numpy_fold(smat, kinds, ws, n)
            for nm, v in zip(names, tot.tolist()):
                totals[nm] = v
            return
        for p, raw in jobs:
            self._fold_scores(state, pod, p, raw, totals)

    def _run_post_filter(self, info: QueuedPodInfo, trace: CycleTrace,
                         state: CycleState, pod: Pod, spec, snapshot,
                         now: float, only_nodes: set | None = None
                         ) -> str | None:
        """PostFilter (preemption): the plugin plans, the engine evicts.
        Returns "preempting" when a plan was executed, None when no plugin
        produced one. `only_nodes` restricts planning to the named nodes
        (the unschedulable-class repair path: only dirty nodes can have
        become curable) — callers pass it only when every post-filter
        plugin advertises `supports_restricted`."""
        if self.policy is not None and self.policy.quotas:
            # per-tenant preemption budgets: hand the planner a victim
            # predicate so budget-exhausted tenants' pods drop out of
            # the candidate pools and plans route AROUND them (the
            # whole-plan admits() below stays the exact backstop)
            budgets = self.policy.budgets
            state.write("victim_budget_ok",
                        lambda v: budgets.has_budget(tenant_of(v), now))
        if self.elastic is not None:
            # elastic shrink-to-min: surplus members of bound elastic
            # gangs join the victim pools (preempt._make_shrink_ok) —
            # the cheaper alternative to untouchable gangs, still under
            # the PDB ledger and the tenant budgets above
            state.write("elastic_shrinkable", True)
        for p in self.profile.post_filter:
            if only_nodes is not None:
                nominated, victims, st = p.post_filter(
                    state, pod, snapshot, trace.filter_verdicts,
                    only_nodes=only_nodes)
            else:
                nominated, victims, st = p.post_filter(
                    state, pod, snapshot, trace.filter_verdicts)
            if st.ok and nominated is not None:
                # harvest-class victims (scv/harvest) are evicted for
                # FREE: they never pass through the budget gate below,
                # never charge a tenant's rolling budget, and count
                # harvest_evictions_total{reason} instead of the
                # per-tenant victim attribution — the planner already
                # kept them out of the PDB ledger
                budgeted = [v for v in victims if not is_harvest(v)]
                # per-tenant preemption budgets (scheduler/policy/): a
                # plan that would overdraw ANY victim tenant's rolling
                # budget is refused whole — the preemptor stays
                # unschedulable until budgets refill or capacity frees.
                # Gated BEFORE any eviction, so a budget can never be
                # half charged; the PDB ledger already ranked plans
                # below the budget layer, so both protections hold.
                if (self.policy is not None
                        and not self.policy.budgets.admits(budgeted, now)):
                    # admits() counted the denial per budget level
                    # (preemptions_budget_denied_total{tenant})
                    self.flight.record(
                        "preemption_budget_denied", pod=pod.key,
                        victims=len(victims))
                    continue
                # on a real API server evict() is a DELETE: the victim's
                # controller recreates it as a new incarnation which the
                # serve loop submits — requeueing the dead object locally
                # would race it (same contract as Descheduler.run_once)
                local = getattr(self.cluster, "supports_local_requeue", False)
                if self.policy is not None:
                    self.policy.budgets.charge(budgeted, now)
                for victim in victims:
                    victim_harvest = is_harvest(victim)
                    self.cluster.evict(victim)
                    self.metrics.inc("pods_evicted_total")
                    if victim_harvest:
                        self.metrics.inc("harvest_evictions_total",
                                         labels={"reason": "preemption"})
                    if self.elastic is not None:
                        try:
                            vspec = spec_for(victim)
                        except LabelError:
                            vspec = None
                        if (vspec is not None and vspec.is_gang
                                and vspec.gang_min > 0):
                            # shrink-to-min: the donor gang drops one
                            # member (never below min — the planner's
                            # surplus accounting guarantees it) and its
                            # re-placed member will re-grow it
                            self.elastic.on_member_evicted(
                                vspec, reason="preemption")
                    if self.policy is not None and not victim_harvest:
                        # per-tenant disruption attribution: who LOST a
                        # pod to preemption. A DISTINCT family from the
                        # flat plan counter below — mixing victim-count
                        # labels into preemptions_total would make
                        # sum() over that family read plans + victims.
                        # Harvest victims counted above instead: the
                        # harvested tenant did not "lose" protected work
                        self.metrics.inc("preemption_victims_total",
                                         labels={"tenant": tenant_of(victim)})
                    if local:
                        router = self.victim_router or self.submit
                        if not router(victim):
                            self.metrics.inc("preempt_victims_unrouted_total")
                if self.allocator is not None:
                    # hold the freed capacity until the preemptor binds
                    # or fails — otherwise requeued victims (or co-hosted
                    # profiles) refill the hole and the preemptor
                    # livelocks. A gang holds its whole SLICE (per-host
                    # chips, bounded by an expiry so an abandoned gang
                    # can't block the slice forever).
                    if spec.is_gang:
                        ni = snapshot.get(nominated)
                        slice_id = (ni.metrics.slice_id
                                    if ni is not None and ni.metrics
                                    else "")
                        self.allocator.nominate_gang(
                            spec.gang_name, slice_id, spec.chips,
                            spec.priority,
                            expires_at=now + 2 * self.config.gang_timeout_s,
                            cpu_millis=pod.cpu_millis,
                            memory_bytes=pod.memory_bytes)
                    else:
                        self.allocator.nominate(
                            pod.key, nominated, spec.chips, spec.priority,
                            cpu_millis=pod.cpu_millis,
                            memory_bytes=pod.memory_bytes,
                            host_ports=pod.host_ports)
                self.metrics.inc("preemptions_total")
                # budget-violating preemptions are legal (best-effort,
                # upstream semantics) but operators need to SEE them
                viol = state.read_or("preempt_pdb_violations", 0)
                if viol:
                    self.metrics.inc("preempt_pdb_violations_total", viol)
                info.last_failure = f"preempting on {nominated}"
                self.queue.requeue_immediate(info, now=self.clock.time())
                self._finish(trace, "preempting", reason=info.last_failure)
                return "preempting"
        return None

    def _bind(self, info: QueuedPodInfo, node: str, trace: CycleTrace) -> bool:
        """Bind through the configured binder. On failure (API outage
        outlasting the client's retry budget, pod deleted, bound elsewhere)
        the reservation is rolled back and the pod requeued with backoff —
        an escaped exception here used to strand the pod Pending forever.

        Backends exposing bind_async (the real-API KubeCluster) get
        upstream kube-scheduler's binding-cycle model: the cache is
        updated optimistically and the POST runs on a binder worker while
        this engine moves to the next pod; a terminal wire failure rolls
        the cache back (freeing the chips — allocation accounting follows
        the cache) and re-enters the pod through _async_bind_failed."""
        pod = info.pod
        rec = self.spans if self._sampled(pod) else None
        entry = self.allocator.assignment_of(pod) if self.allocator is not None else None
        coords = entry[1] if entry is not None else None
        dispatched_async = False
        fence = None
        if self.fence_provider is not None:
            fence = self.fence_provider(pod, node)
            if fence is FENCE_LOST:
                # shard lease lost between cycle start and commit: abort
                # cleanly through the unwind path — reservation released,
                # capacity event for parked pods, attempt-free retry (the
                # pod did nothing wrong; the next cycle re-places it,
                # possibly unfenced on a shard we no longer prefer)
                if self.allocator is not None:
                    self.allocator.unreserve(CycleState(), pod, node)
                    self.notify_event(ClusterEvent(POD_DELETED, node=node,
                                                   origin=pod.key))
                self.metrics.inc("lease_lost_aborts_total")
                self.flight.record("fence_abort", pod=pod.key, node=node)
                self.queue.requeue_immediate(info, now=self.clock.time())
                self._finish(trace, "lease-lost", node=node,
                             reason="shard lease lost mid-cycle")
                return False
        fence_kw = {} if fence is None else {"fence": fence}
        t_wire0 = self.clock.time()
        try:
            if self.profile.bind is not None:
                self.profile.bind.bind(CycleState(), pod, node)
            else:
                bind_async = getattr(self.cluster, "bind_async", None)
                # GANG members always bind synchronously: the anchor-fail
                # _fail_gang rollback, the peers_ok gate, and the slice
                # entitlement release below all read _bind's return value
                # — dispatch-time success would neuter the all-or-nothing
                # invariants (a half-bound gang with its entitlement
                # released). Singles get the async binding cycle.
                is_gang_member = (self.gang_permit is not None
                                  and self.gang_permit.gang_of(pod))
                if (bind_async is not None and self.config.async_binding
                        and not is_gang_member):
                    # pass coords through: real-API backends publish them
                    # as the chip-assignment annotation so the claim
                    # survives a scheduler restart. The preemptor's
                    # NOMINATION is consumed only on wire success (the
                    # entitlement must survive a transient bind failure,
                    # same as the sync failure path below); the callbacks
                    # touch only thread-safe state — queue recovery is
                    # marshalled back onto the engine thread via
                    # _bind_results (the queue itself is engine-thread
                    # only).
                    dispatched_async = True
                    on_fail = (lambda p, n, e, _info=info:
                               self._bind_results.append((_info, n, e)))
                    on_success = self._async_bind_succeeded
                    sem = self._dispatch_sem
                    if sem is not None:
                        # bounded dispatch window: block until a slot
                        # frees (wire completion releases it). Release
                        # exactly once per dispatch — through whichever
                        # callback fires, or on a SYNCHRONOUS dispatch
                        # exception (pipelined backends raise 409s
                        # through dispatch itself, before any callback).
                        sem.acquire()
                        released = [False]

                        def _rel():
                            if not released[0]:
                                released[0] = True
                                sem.release()

                        on_fail = (lambda p, n, e, _info=info,
                                   _inner=on_fail:
                                   (_rel(), _inner(p, n, e)) and None)
                        on_success = (lambda p, n, _inner=on_success:
                                      (_rel(), _inner(p, n)) and None)
                        try:
                            bind_async(pod, node, coords, on_fail=on_fail,
                                       on_success=on_success, **fence_kw)
                        except Exception:
                            _rel()
                            raise
                    else:
                        bind_async(pod, node, coords, on_fail=on_fail,
                                   on_success=on_success, **fence_kw)
                else:
                    self.cluster.bind(pod, node, coords, **fence_kw)
        except Exception as e:
            # lost-response recovery (satellite of the chaos work): before
            # rolling back, ask the cluster whether the bind actually
            # LANDED — a failure after the server applied the mutation
            # (fake fault -1, KubeClient AmbiguousRequestError whose
            # confirm GET also failed) must be ADOPTED, not replayed:
            # requeueing a bound pod is the duplicate-bind window.
            bound_to = None
            bn = getattr(self.cluster, "bound_node_of", None)
            if bn is not None:
                try:
                    bound_to = bn(pod.key)
                except Exception:
                    bound_to = None
            if self._is_authority_conflict(e):
                # server-returned conflict — the apiserver's 409 or a
                # bind-authority webhook denial: a FOREIGN replica's
                # commit beat ours (optimistic shared-state scheduling),
                # never a wire failure, never the breaker. Checked BEFORE the
                # adoption branch: a 409 means our POST was REJECTED, so
                # even bound_to == node is someone else's same-key win on
                # the same node (our own landed-but-409-on-replay case is
                # resolved inside KubeClient.bind and never raises) —
                # adopting it through the ambiguous tail would overwrite
                # the winner's chip assignment with our coords.
                return self._bind_conflict(info, node, trace, e, bound_to)
            if bound_to != node:
                self._breaker_failure(e)
                if self.allocator is not None:
                    # release the pending reservation; keep any nomination (a
                    # preemptor's entitlement survives a transient bind failure)
                    self.allocator.unreserve(CycleState(), pod, node)
                    # freed reservation = capacity event for OTHER parked pods
                    self.notify_event(ClusterEvent(POD_DELETED, node=node,
                                                   origin=pod.key))
                self.metrics.inc("bind_errors_total")
                self._unschedulable(info, trace, f"bind failed: {e}",
                                    outcome="bind-error")
                return False
            # the cluster shows OUR bind: the wire failed, the mutation
            # did not — fall through to the ordinary success tail
            self.metrics.inc("ambiguous_bind_recoveries_total")
            self._breaker_success()
        else:
            if not dispatched_async:
                # a synchronous wire success is the breaker's probe signal
                # (async successes report in order via _bind_results)
                self._breaker_success()
        # wire phase closes here: for sync backends this is the real bind
        # RTT (retries and confirm GETs included); for async dispatch it
        # is the dispatch cost — the binder-measured RTT lands in the
        # cluster's bind_wire_ms histogram instead
        wire_end = self.clock.time()
        wire_s = max(wire_end - t_wire0, 0.0)
        if rec is not None:
            rec.record("bind_wire", pod.key, t_wire0, wire_end,
                       {"node": node, "dispatched_async": dispatched_async})
        if self.allocator is not None:
            if not dispatched_async:
                # reservation + entitlement consumed in one lock round
                # (async dispatch defers unnominate to wire success)
                self.allocator.finish_bind(pod)
            else:
                self.allocator.complete(pod)  # reservation consumed
        if coords is not None and not dispatched_async:
            # publish the chip assignment on the pod regardless of binder,
            # so allocation accounting sees it next cycle (bind_async set
            # it itself at dispatch — re-setting here would race the
            # binder rollback's label pop on a fast failure)
            pod.labels[ASSIGNED_CHIPS_LABEL] = format_assigned_chips(coords)
        now_b = self.clock.time()
        e2e_ms = (now_b - info.enqueued) * 1e3
        self.metrics.observe("schedule_latency_ms", e2e_ms)
        # per-class decomposition (gang / multi-chip / gpu / unlabeled ...):
        # aggregate p50 hides class-level regressions behind class mix
        cls = workload_class(pod)
        cname = _LABEL1_CACHE.get(("_lat_cls", cls))
        if cname is None:
            cname = "schedule_latency_ms_class_" + cls
            _LABEL1_CACHE[("_lat_cls", cls)] = cname
        self.metrics.observe(cname, e2e_ms)
        if self.slo is not None:
            # serving-SLO feed: every scv/serving bind's enqueue->bind
            # latency scores against its scv/slo-ms target — the burn-
            # rate monitor's only input signal (starvation is caught
            # separately by the guard's parked-serving check)
            try:
                sspec = spec_for(pod)
            except LabelError:
                sspec = None
            if sspec is not None and sspec.serving and sspec.slo_ms > 0:
                self.slo.observe(e2e_ms, sspec.slo_ms, now_b)
        # e2e latency decomposition: the queue/engine stamps partition this
        # pod's enqueue->bind interval into queue-wait (active + backoff),
        # cycle compute (every attempt's pre-commit work), commit
        # (reserve/permit/bookkeeping) and wire — bench.e2e_breakdown
        # reads these histograms, and their p50s must cover >=95% of the
        # measured e2e p50 (the CI fence)
        if info.commit_started >= 0.0 and info.cycle_started >= 0.0:
            compute_s = info.t_cycle + max(
                info.commit_started - info.cycle_started, 0.0)
            commit_s = max(now_b - info.commit_started - wire_s, 0.0)
            self.metrics.observe("e2e_queue_wait_ms", info.t_queue * 1e3)
            self.metrics.observe("e2e_cycle_compute_ms", compute_s * 1e3)
            self.metrics.observe("e2e_commit_ms", commit_s * 1e3)
            self.metrics.observe("e2e_wire_ms", wire_s * 1e3)
        self.metrics.inc("pods_scheduled_total")
        if self.policy is not None:
            # fold the bind into the DRF book (one dirty node off the
            # change log), retire any gang in-flight claim, and
            # republish per-tenant shares/breaches
            self.policy.on_bind(pod)
            self.policy.resolved(pod.key)
        if self.elastic is not None:
            # elastic-gang bookkeeping: a bind into a gang admitted below
            # desired size is a GROW (gang_grow_total); reaching desired
            # retires the growing record. Gang members always bind
            # synchronously, so this is wire-proven, never dispatch-time.
            try:
                bspec = spec_for(pod)
            except LabelError:
                bspec = None
            if bspec is not None and bspec.is_gang and bspec.gang_min > 0:
                self.elastic.on_member_bound(
                    self.cluster, bspec,
                    n_bound=self._bound_members_of(bspec.gang_name))
        if not dispatched_async:
            # Scheduled is posted on WIRE success only (upstream posts it
            # after the binding subresource lands): sync binds and adopted
            # ambiguous binds are proven here; async dispatches post from
            # _async_bind_succeeded, so a terminal wire failure never
            # leaves a false Scheduled trail behind a Pending pod
            self._post_scheduled_event(pod, node)
        self._finish(trace, "bound", node=node)
        return True

    def _post_scheduled_event(self, pod, node: str) -> None:
        post = getattr(self.cluster, "post_event", None)
        if post is not None:
            try:
                post(pod, "Scheduled",
                     f"Successfully assigned {pod.key} to {node}")
            except Exception:
                pass  # observability must never fail a bind

    def _bind_conflict(self, info: QueuedPodInfo, node: str,
                       trace: CycleTrace, err: Exception,
                       bound_to: str | None,
                       release_reservation: bool = True) -> bool:
        """A server-returned 409 rejected our optimistic commit — the
        scheduler-fleet conflict path. Two shapes:

        - FOREIGN-BIND conflict (`bound_to` names another node, or the
          pod reads BOUND): another replica won the pod. Drop our queue
          entry off cluster truth — requeueing would loop 409 forever.
        - NODE-CLAIM conflict (pod still unbound): a foreign bind landed
          on our chosen node between snapshot and commit, so our rows
          were stale. The foreign bind already bumped the change log —
          the next cycle's snapshot repair re-filters exactly the dirty
          rows — so retry locally, attempt-free (the pod did nothing
          wrong). A pathological conflict streak falls back to the
          ordinary backoff path so a livelock can't hot-spin the engine.

        Either way the server ANSWERED: a 409 is proof of a live
        apiserver, so it feeds the breaker's success side, never its
        failure count. Shared by the sync bind path and the async drain
        (`release_reservation=False` there: the reservation was already
        consumed at dispatch and the binder rolled its cache back)."""
        pod = info.pod
        self.metrics.inc("bind_conflicts_total")
        self.flight.record(
            "bind_conflict", pod=pod.key, node=node,
            resolution=("foreign-bind"
                        if bound_to is not None
                        or pod.phase == PodPhase.BOUND else "node-claim"))
        self._breaker_success()
        if release_reservation and self.allocator is not None:
            self.allocator.unreserve(CycleState(), pod, node)
            # the freed reservation is a capacity event for parked pods
            self.notify_event(ClusterEvent(POD_DELETED, node=node,
                                           origin=pod.key))
        if bound_to is not None or pod.phase == PodPhase.BOUND:
            if self.allocator is not None:
                self.allocator.unnominate(pod.key)
            if bound_to is not None and pod.node != bound_to:
                # our copy disagrees with cluster truth (stale Pending on
                # a wire backend, or a hypothetical optimistic write to
                # the losing node): adopt the winner's node. The chip
                # annotation is the winner's to publish — ours was never
                # set on the sync path, and the async binder rolled its
                # optimistic label back before reporting.
                pod.node = bound_to
                pod.phase = PodPhase.BOUND
            self.metrics.inc("foreign_bind_conflicts_total")
            self._finish(trace, "foreign-bound", node=pod.node,
                         reason=str(err))
            return False
        info.conflicts += 1
        if info.conflicts >= 8:
            # losing 8 straight optimistic races means the cluster is
            # pathologically contended (or our view persistently stale):
            # back off like an ordinary unschedulable pod instead of
            # spinning attempt-free retries
            info.conflicts = 0
            self._unschedulable(info, trace, f"bind conflict: {err}",
                                outcome="bind-conflict")
            return False
        self.metrics.inc("bind_conflict_retries_total")
        self.queue.requeue_immediate(info, now=self.clock.time())
        self._finish(trace, "bind-conflict", node=node, reason=str(err))
        return False

    def _async_bind_succeeded(self, pod, node) -> None:
        """on_success callback for dispatched binds, run on a BINDER
        thread: consume the preemptor entitlement (wire success is when
        the nomination is provably spent) and record the wire-healthy
        signal IN ORDER with any failures — the engine folds the deque
        sequentially, so a success only resets the breaker streak for
        failures that actually preceded it (the breaker counters
        themselves stay engine-thread-only)."""
        if self.allocator is not None:
            self.allocator.unnominate(pod.key)
        self._post_scheduled_event(pod, node)  # wire-proven, like the sync path
        self._bind_results.append(None)

    @staticmethod
    def _is_authority_conflict(e: Exception) -> bool:
        """A server-side REJECTION of our commit: the apiserver's own
        409, or a pods/binding admission-webhook denial — which a real
        apiserver surfaces with the WEBHOOK's status code (ours sets
        409; third-party authorities commonly 400/403). Either way the
        authority ANSWERED and refused, so the verdict takes the
        conflict path (foreign-bind adopt / attempt-free local retry),
        never the breaker and never the bind-error backoff. The denial
        shape itself has ONE definition (k8s.client.is_webhook_denial —
        imported lazily: this is the error path, and core must not
        import the k8s package at module load)."""
        if getattr(e, "status", None) == 409:
            return True
        from ..k8s.client import is_webhook_denial

        return is_webhook_denial(e)

    @staticmethod
    def _is_wire_failure(e: Exception) -> bool:
        """Only WIRE-class bind failures feed the breaker: connection
        drops, timeouts, and transport errors surfaced with status 0
        (k8s ApiError wrapping an ambiguous/connection failure). A
        server-RETURNED status (409 conflict, 404 pod gone) is proof the
        apiserver is alive — counting those would park scheduling on a
        healthy-but-contended cluster."""
        status = getattr(e, "status", None)
        if status is not None:
            return status == 0
        return isinstance(e, (ConnectionError, TimeoutError, OSError))

    def _breaker_failure(self, e: Exception) -> None:
        """One more consecutive bind WIRE failure (engine thread only;
        non-wire errors are ignored — see _is_wire_failure). At the
        threshold the breaker OPENS: run_one parks scheduling until the
        cooldown passes, so an apiserver error storm stops burning every
        queued pod's attempts/backoff against a dead server. Re-opening
        after a failed post-cooldown probe doubles the cooldown
        (capped), the classic half-open escalation."""
        if self.config.breaker_threshold <= 0:
            return
        if not self._is_wire_failure(e):
            return
        self._breaker_failures += 1
        if self._breaker_failures < self.config.breaker_threshold:
            return
        now = self.clock.time()
        if now >= self._breaker_until:
            self._breaker_until = now + self._breaker_cooldown
            self._breaker_cooldown = min(
                self._breaker_cooldown * 2,
                8 * self.config.breaker_cooldown_s)
            self.metrics.inc("breaker_opens_total")
            self.metrics.set_gauge("breaker_open", 1.0)
            # trip kind: auto-dumps the flight ring when a dump dir is
            # configured — the black box lands on disk WHILE the storm is
            # live, not after someone asks
            self.flight.record("breaker_open",
                               failures=self._breaker_failures,
                               cooldown_s=self._breaker_until - now,
                               error=f"{type(e).__name__}: {e}")

    def _breaker_success(self) -> None:
        """A bind reached the server: reset the failure streak and close
        an open breaker (engine thread only)."""
        if not self._breaker_failures and not self._breaker_until:
            return
        was_open = self._breaker_until > 0.0
        self._breaker_failures = 0
        self._breaker_until = 0.0
        self._breaker_cooldown = self.config.breaker_cooldown_s
        self.metrics.set_gauge("breaker_open", 0.0)
        if was_open:
            self.metrics.inc("breaker_closes_total")
            self.flight.record("breaker_close")

    def _drain_bind_failures(self) -> None:
        """Fold async wire outcomes and recover pods whose dispatched
        binds never reached the server. Binder workers only APPEND to
        the thread-safe _bind_results deque; the requeue itself runs
        HERE, on the engine thread (the SchedulingQueue has no internal
        lock — a binder-thread mutation would race pop()'s backoff flush
        and could drop the entry). Success markers are interleaved in
        arrival order, so the breaker streak resets exactly when the
        wire actually recovered — a stale pre-storm success cannot wipe
        newer failures, and a post-storm success closes an open breaker."""
        while True:
            try:
                item = self._bind_results.popleft()
            except IndexError:
                return
            if item is None:
                self._breaker_success()  # wire success, in sequence
                continue
            info, node, err = item
            pod = info.pod
            if self.tracks(pod.key):
                # the serve loop's intake raced the rollback and already
                # resubmitted the reverted pod: a second queue entry
                # would double-bind
                continue
            # lost-response adoption, the async twin of _bind's: when the
            # cluster ALREADY shows this pod bound to the reported node,
            # the POST landed and only the response died — the
            # dispatch-time optimistic accounting is correct as it
            # stands, so consume the nomination and move on instead of
            # requeueing a bound pod into a duplicate-bind loop
            bound_to = None
            bn = getattr(self.cluster, "bound_node_of", None)
            if bn is not None:
                try:
                    bound_to = bn(pod.key)
                except Exception:
                    bound_to = None
                if bound_to == node \
                        and not self._is_authority_conflict(err):
                    # ambiguous wire failure whose POST actually landed
                    # (a 409 is NOT this: the server REJECTED our POST,
                    # so a same-node bound_to is a foreign same-key win
                    # — conflict-resolved below, winner's chips intact)
                    if self.allocator is not None:
                        self.allocator.unnominate(pod.key)
                    self.metrics.inc("ambiguous_bind_recoveries_total")
                    self._post_scheduled_event(pod, node)  # landed after all
                    self._breaker_success()
                    continue
            if self._is_authority_conflict(err):
                # conflict, the async flavour: the binder already rolled
                # its cache (and our optimistic chip label) back before
                # reporting, and the dispatch-time reservation was
                # consumed — so only the pod's fields (shared-object
                # backends that never applied) and the queue need
                # attention before sharing the sync resolution logic.
                # The label is deliberately NOT popped in the foreign
                # case: on shared-object backends it is the WINNER's.
                # The dispatch-time success tail already counted this pod
                # in pods_scheduled_total/latency; record the correction
                # so per-replica bind shares can be computed exactly
                # (counters are monotonic — never decremented)
                self.metrics.inc("async_bind_conflict_corrections_total")
                trace = CycleTrace(pod=pod.key, started=self.clock.time())
                if bound_to is None:
                    if pod.node == node:
                        pod.phase = PodPhase.PENDING
                        pod.node = None
                        pod.labels.pop(ASSIGNED_CHIPS_LABEL, None)
                    self.notify_event(ClusterEvent(POD_DELETED, node=node,
                                                   origin=pod.key))
                self._bind_conflict(info, node, trace, err, bound_to,
                                    release_reservation=False)
                continue
            self._breaker_failure(err)
            pod.phase = PodPhase.PENDING
            pod.node = None
            pod.labels.pop(ASSIGNED_CHIPS_LABEL, None)
            self.metrics.inc("bind_errors_total")
            # the cache rollback freed the optimistically-claimed chips:
            # a capacity event for OTHER parked pods (the backend's own
            # DELETED event never fires — the bind never landed)
            self.notify_event(ClusterEvent(POD_DELETED, node=node,
                                           origin=pod.key))
            trace = CycleTrace(pod=pod.key, started=self.clock.time())
            # the dispatch-time success was already counted in
            # pods_scheduled_total/latency; the error counter plus the
            # bind-error trace record the correction
            self._unschedulable(info, trace, f"async bind failed: {err}",
                                outcome="bind-error")

    def _unschedulable(self, info: QueuedPodInfo, trace: CycleTrace, reason: str,
                       outcome: str = "unschedulable",
                       rejected_by: tuple = (),
                       gang_doom: bool = True) -> str:
        info.last_failure = reason
        # any orderly non-conflict outcome breaks a 409 streak: the
        # conflict counter means CONSECUTIVE optimistic-race losses, not
        # lifetime losses (see _bind_conflict's fallback)
        info.conflicts = 0
        # operator-facing trail (kubectl describe pod): backends with a
        # wire (KubeCluster) POST a FailedScheduling Event carrying the
        # same reason the cycle trace records — deduplicated and queued
        # off-thread there, a no-op on in-memory fakes
        post = getattr(self.cluster, "post_event", None)
        if post is not None:
            try:
                post(info.pod, "FailedScheduling", reason, type_="Warning")
            except Exception:
                pass  # observability must never fail the cycle
        if self.allocator is not None and self.allocator.has_pod_nominations():
            nom = self.allocator.nomination_of(info.pod.key)
            if (nom is not None and trace.filter_verdicts.get(nom[0]) != "ok"
                    and not any(p.terminating
                                for p in self.cluster.pods_on(nom[0]))):
                # the nominated node no longer fits this pod (chips went
                # unhealthy, telemetry stale, node gone): release the hold so
                # it doesn't block the node's capacity forever — upstream
                # clears nominatedNodeName the same way. While victims are
                # still draining (terminating pods present) the node is
                # EXPECTED to fail the filter, so the hold survives — this
                # is the whole point of nominatedNodeName semantics.
                self.allocator.unnominate(info.pod.key)
        if self.config.max_attempts and info.attempts + 1 >= self.config.max_attempts:
            if gang_doom:
                self._doom_gang_of(info, reason)
            # elastic growth members (gang_doom=False) fail ALONE: the
            # gang keeps running at its reduced size — permanently
            # failing a grow attempt must not tear the whole job down
            self._fail_permanently(info, reason, trace=trace)
            return "failed"
        for pname in rejected_by:
            # per-plugin rejection attribution (labeled metric): which
            # plugin is gating the pending backlog, by name
            self.metrics.inc("filter_rejections_total",
                             labels=_label1("plugin", pname))
        now = self.clock.time()
        if self.policy is not None:
            # starvation watch: a pod still unbound past the configured
            # threshold trips the flight recorder + per-tenant counter
            self.policy.note_wait(info.pod, now - info.enqueued)
        self.queue.requeue_backoff(info, now=now,
                                   rejected_by=tuple(rejected_by))
        self.metrics.inc("pods_unschedulable_total")
        self._finish(trace, outcome, reason=reason)
        return outcome

    def _doom_gang_of(self, info: QueuedPodInfo, reason: str) -> None:
        """A permanently-failed member dooms its gang: the remaining
        members can never reach gang-size with the current incarnations,
        so give the slice entitlement back and fail the peers too —
        parked ones NOW, backoff ones at their next cycle (their
        park->timeout->requeue loop counts no attempts, so they would
        otherwise never resolve). Shared by the max-attempts branch and
        crash quarantine; no-op for non-gang pods."""
        try:
            spec = spec_for(info.pod)
        except LabelError:
            return
        if not spec.is_gang:
            return
        if self.allocator is not None:
            self.allocator.unnominate_gang(spec.gang_name)
        doom = (f"gang {spec.gang_name}: member {info.pod.key} "
                f"permanently failed: {reason}")
        self.doomed_gangs[spec.gang_name] = doom
        while len(self.doomed_gangs) > 1024:
            # never-resubmitted doomed gangs would otherwise accumulate
            # forever; oldest doom evicts first (a revived-then-stale
            # entry only costs the evicted gang's members one extra
            # park/timeout round)
            self.doomed_gangs.pop(next(iter(self.doomed_gangs)))
        self._doom_parked_members(spec.gang_name, doom)

    def _contain_crash(self, info: QueuedPodInfo, e: Exception) -> str:
        """Cycle-level exception containment: a plugin RAISED somewhere in
        this pod's cycle. The engine thread survives unconditionally —
        the pod pays: its (possibly partial) reservation is defensively
        rolled back, the crash is counted, and the pod requeues with
        backoff until quarantine_threshold crashes mark it poison and
        fail it permanently (one malformed pod must not monopolise the
        engine with crash-requeue loops forever)."""
        pod = info.pod
        state = CycleState()
        try:
            state.write("workload_spec", spec_for(pod))
        except LabelError:
            pass
        # did the crashed cycle leave a pending reservation? (the in-loop
        # unwinds in the reserve/permit paths normally clear it with real
        # state; this backstop covers raise sites between them)
        entry = (self.allocator.assignment_of(pod)
                 if self.allocator is not None else None)
        for p in reversed(self.profile.reserve):
            # idempotent sweep: unreserve keys on the pod and tolerates
            # never-reserved pods, so crashes before Reserve cost nothing
            try:
                p.unreserve(state, pod, "")
            except Exception:
                pass
        if entry is not None:
            # the sweep freed reserved chips: a capacity event for OTHER
            # hint-parked pods, exactly like the bind-failure rollback
            # (origin keeps the crashed pod off its own event)
            self.notify_event(ClusterEvent(POD_DELETED, node=entry[0],
                                           origin=pod.key))
        info.crashes += 1
        self.metrics.inc("cycle_crashes_total")
        self.flight.record("cycle_crash", pod=pod.key,
                           error=f"{type(e).__name__}: {e}",
                           crashes=info.crashes)
        trace = CycleTrace(pod=pod.key, started=self.clock.time())
        reason = f"cycle crash: {type(e).__name__}: {e}"
        thresh = self.config.quarantine_threshold
        # quarantine only POISON pods; see __init__'s discriminator — an
        # engine-wide fault crashing pod after pod must not permanently
        # fail the whole pending workload
        systemic = (not self._ok_since_crash
                    and self._last_crash_key is not None
                    and self._last_crash_key != pod.key)
        self._ok_since_crash = False
        self._last_crash_key = pod.key
        if thresh and info.crashes >= thresh and not systemic:
            reason = (f"quarantined after {info.crashes} crashing cycles "
                      f"({type(e).__name__}: {e})")
            self.quarantined[pod.key] = reason
            while len(self.quarantined) > 1024:
                self.quarantined.pop(next(iter(self.quarantined)))
            self.metrics.inc("pods_quarantined_total")
            self.flight.record("quarantine", pod=pod.key, reason=reason)
            self._doom_gang_of(info, reason)
            self._fail_permanently(info, reason, trace=trace)
            return "quarantined"
        self.queue.requeue_backoff(info, now=self.clock.time())
        self._finish(trace, "crash", reason=reason)
        return "crash"

    def _cycle_error(self, info: QueuedPodInfo, trace: CycleTrace, reason: str) -> str:
        self.queue.requeue_backoff(info, now=self.clock.time())
        self.metrics.inc("cycle_errors_total")
        self._finish(trace, "error", reason=reason)
        return "error"

    def _finish(self, trace: CycleTrace, outcome: str, node: str | None = None,
                reason: str = "") -> None:
        now = self.clock.time()
        trace.finish(outcome, node=node, reason=reason, now=now)
        self.traces.add(trace)
        self.metrics.inc("scheduling_outcomes_total",
                         labels=_label1("outcome", outcome))
        if trace.plane:
            self.metrics.inc("cycle_plane_total",
                             labels=_label1("plane", trace.plane))
        if self._sampled_key(trace.pod):
            attrs = {"outcome": outcome}
            if trace.plane:
                attrs["plane"] = trace.plane
            if node:
                attrs["node"] = node
            if reason:
                attrs["reason"] = reason[:200]
            self.spans.record("cycle", trace.pod, trace.started, now, attrs)

    def _sampled_key(self, key: str) -> bool:
        """Span-sampling verdict from a bare pod key (call sites without
        the pod object — crash/failure traces)."""
        return span_sampled(key, self.config.trace_sampling)

    # -------------------------------------------------------- waiting / gangs
    def check_waiting(self) -> None:
        """Reject gangs whose Permit deadline passed; roll everything back."""
        now = self.clock.time()
        expired_gangs: set[str] = set()
        for key, w in list(self.waiting.items()):
            if w.deadline <= now:
                gang = self.gang_permit.gang_of(w.info.pod) if self.gang_permit else None
                if gang:
                    expired_gangs.add(gang)
                else:
                    self._rollback_waiting(key)
        for gang in expired_gangs:
            self.metrics.inc("gang_timeouts_total")
            self._fail_gang(gang)

    def _fail_gang(self, gang: str) -> None:
        """Tear a gang down: reject its parked members (reservations roll
        back, pods requeue with backoff) and release any slice entitlement
        it won by preemption. The policy engine's in-flight tenant quota
        claim — recorded when the quota gate ADMITTED the gang — is
        retired here too: a failed assembly holds no capacity, so leaving
        the claim to its TTL would gate same-tenant work against
        headroom nobody is using."""
        for key in self.gang_permit.fail_gang(gang):
            self._rollback_waiting(key)
        if self.allocator is not None:
            self.allocator.unnominate_gang(gang)
        if self.policy is not None:
            self.policy.gang_failed(gang)
        if self.elastic is not None:
            self.elastic.reset(gang)

    # ------------------------------------------------------- elastic gangs
    def _drain_elastic_retires(self) -> None:
        """Retire elastic bookkeeping for gangs whose members were
        deleted externally (the PR 10 sliver): a POD_DELETED carrying a
        gang label queued the gang here; if cluster truth now shows ZERO
        bound members, the gang is gone (or restarting from scratch) and
        its _growing/_first_seen/_pending_admission records must not
        survive to miscount grows and admissions when the name is
        reused. A SHRINK eviction never trips this — the gang keeps
        >= min members bound, so the count stays positive."""
        elastic = self.elastic
        seen: set[str] = set()
        while self._elastic_retires:
            try:
                gang = self._elastic_retires.popleft()
            except IndexError:
                break
            if gang in seen or elastic is None:
                continue
            seen.add(gang)
            if (gang in elastic._growing
                    or gang in elastic._first_seen
                    or gang in elastic._pending_admission) \
                    and self._bound_members_of(gang) == 0:
                elastic.reset(gang)
                self.metrics.inc("gang_elastic_retired_total")
                self.flight.record("elastic_gang_retired", gang=gang)

    def _bound_members_of(self, gang: str) -> int:
        """Cluster-truth bound member count, memoised on the version
        vector: growth members ask on every failed cycle, and between
        cluster changes the answer cannot move. Miss (or no versioned
        backend) falls through to the full pod walk."""
        from .elastic import bound_member_count

        vers = self._cluster_versions()
        if vers is None:
            return bound_member_count(self.cluster, gang)
        hit = self._gang_count_memo.get(gang)
        if hit is not None and hit[0] == vers:
            return hit[1]
        n = bound_member_count(self.cluster, gang)
        if len(self._gang_count_memo) > 4096:
            self._gang_count_memo.clear()  # churn backstop
        self._gang_count_memo[gang] = (vers, n)
        return n

    def _elastic_growth_park(self, info: QueuedPodInfo, spec,
                             trace: CycleTrace) -> str | None:
        """A gang member found no capacity, but its gang ALREADY runs at
        >= tpu/gang-min in cluster truth: it is a GROWTH member. Park it
        under the elastic-grow hint class (woken by POD_DELETED /
        NODE_ADDED) with the gang-doom path disarmed — a growth member
        exhausting max_attempts fails alone; the reduced-size gang keeps
        running. Returns the outcome, or None when not applicable."""
        if (self.elastic is None or not spec.is_gang
                or spec.gang_min <= 0
                or spec.gang_name in self.doomed_gangs):
            return None
        if self._bound_members_of(spec.gang_name) < spec.gang_min:
            return None
        from .elastic import ELASTIC_GROW_HINT

        return self._unschedulable(
            info, trace,
            f"gang {spec.gang_name}: running at reduced size, waiting "
            "for chips to grow", rejected_by=(ELASTIC_GROW_HINT,),
            gang_doom=False)

    def _bind_waiting_peer(self, peer_key: str) -> bool:
        """Bind a gang member parked at Permit off its held reservation —
        the peer-approve contract, shared by gang completion and elastic
        admit-at-min so the two paths cannot diverge. True unless the
        peer existed and its bind failed (a failed bind requeues the
        member through _bind's ordinary failure path)."""
        w = self.waiting.pop(peer_key, None)
        if w is None:
            return True
        return self._bind(w.info, w.node,
                          CycleTrace(pod=peer_key, started=w.info.enqueued))

    def _elastic_admit_at_min(self, info: QueuedPodInfo, spec,
                              trace: CycleTrace) -> str | None:
        """A gang member found no capacity and preemption produced no
        plan. If the gang has >= tpu/gang-min members placed (parked at
        Permit + bound), admit it AT THE CURRENT SIZE: bind the parked
        members now — their reservations are consumed, exactly the
        peer-approve path — and park THIS member (and, next cycles, any
        other unplaced member) for growth. Returns the outcome, or None
        when not applicable (the caller then takes the ordinary
        unschedulable path, assembly keeps waiting for full size)."""
        if (self.elastic is None or not spec.is_gang
                or spec.gang_min <= 0 or self.gang_permit is None
                or spec.gang_name in self.doomed_gangs):
            return None
        gang = spec.gang_name
        waiting = [k for k in self.gang_permit.gangs.waiting_members(gang)
                   if k in self.waiting]
        n_bound = self._bound_members_of(gang)
        if not waiting or n_bound + len(waiting) < spec.gang_min:
            return None
        # record the admission FIRST: the members binding right now are
        # the floor, not growth (on_member_bound decrements the initial
        # allowance before counting grows)
        self.elastic.note_admitted_at_min(gang, initial=len(waiting),
                                          reason="no-fit")
        for peer_key in self.gang_permit.fail_gang(gang):
            self._bind_waiting_peer(peer_key)
        # a peer bind can fail at the wire (outage): _bind requeued it
        # with backoff, but the gang must not stand "admitted" below min.
        # Withdraw the elastic record — the requeued members re-enter
        # CLASSIC assembly (permit counts cluster-truth bound members
        # toward completeness), so nothing is lost, and no below-min gang
        # is ever left running under an admitted-at-min banner.
        if self._bound_members_of(gang) < spec.gang_min:
            self.elastic.reset(gang)
            self.flight.record("elastic_admit_aborted", gang=gang,
                               reason="peer bind failed below min")
            return self._unschedulable(
                info, trace,
                f"gang {gang}: admit-at-min aborted (peer bind failed)",
                gang_doom=False)
        from .elastic import ELASTIC_GROW_HINT

        return self._unschedulable(
            info, trace,
            f"gang {gang}: admitted at min ({spec.gang_min}/"
            f"{spec.gang_size}), waiting for chips to grow",
            rejected_by=(ELASTIC_GROW_HINT,), gang_doom=False)

    def _doom_parked_members(self, gang: str, reason: str) -> None:
        """Permanently fail the gang's parked members (doomed-gang path:
        a peer exhausted its attempts, so assembly can never finish).
        Bound members are untouched — members only bind after the gang
        completed, at which point no assembly failure can occur."""
        if self.gang_permit is None:
            return
        for key in self.gang_permit.fail_gang(gang):
            w = self.waiting.pop(key, None)
            if w is None:
                continue
            self._unreserve_waiting(w)
            self._fail_permanently(w.info, reason)
        if self.policy is not None:
            # the doomed gang's in-flight tenant quota claim dies with it
            self.policy.gang_failed(gang)
        if self.elastic is not None:
            self.elastic.reset(gang)

    def _fail_permanently(self, info: QueuedPodInfo, reason: str,
                          trace: CycleTrace | None = None) -> None:
        """Terminal failure bookkeeping, shared by the max-attempts branch,
        the doomed-gang fail-fast, and parked-member dooming."""
        info.pod.phase = PodPhase.FAILED
        self.failed[info.pod.key] = reason
        if self.allocator is not None:
            self.allocator.unnominate(info.pod.key)
        if self.policy is not None:
            # drop the pod from the starvation-watch dedup set: a pod
            # that tripped and then failed terminally must not pin the
            # set toward its clear-all backstop (which would re-trip
            # still-starving pods)
            self.policy.resolved(info.pod.key)
        self.metrics.inc("pods_failed_total")
        if trace is None:
            trace = CycleTrace(pod=info.pod.key, started=self.clock.time())
        self._finish(trace, "failed", reason=reason)

    def _unreserve_waiting(self, w: _WaitingPod) -> None:
        state = CycleState()
        try:
            state.write("workload_spec", spec_for(w.info.pod))
        except LabelError:
            pass
        for p in reversed(self.profile.reserve):
            p.unreserve(state, w.info.pod, w.node)
        # the rollback returned reserved chips to the free pool — to a
        # parked capacity-starved pod that is indistinguishable from a
        # pod leaving the node, so publish it as one (no cluster backend
        # sees allocator-only changes, hence no event would fire). origin
        # keeps the rolled-back pod itself from riding its own event out
        # of backoff (park -> timeout -> self-wake livelock)
        self.notify_event(ClusterEvent(POD_DELETED, node=w.node,
                                       origin=w.info.pod.key))

    def _rollback_waiting(self, key: str) -> None:
        w = self.waiting.pop(key, None)
        if w is None:
            return
        self._unreserve_waiting(w)
        # a gang member rolled back at Permit (assembly timeout) is parked
        # on the gang plugin: a sibling's (re)arrival — or freed capacity —
        # is what can complete assembly next time, so route those events
        # to its queueing hints instead of leaving only the blind timer
        rejected_by = ()
        if self.gang_permit is not None \
                and self.gang_permit.gang_of(w.info.pod):
            rejected_by = (self.gang_permit.name,)
        self.queue.requeue_backoff(w.info, now=self.clock.time(),
                                   rejected_by=rejected_by)

    def forget(self, pod_key: str) -> None:
        """The pod vanished from the cluster (external DELETE while queued
        or parked at Permit): drop every trace so its reservation and
        nomination hold don't leak. A parked gang member takes its whole
        gang down — the gang can never complete without it, and its key
        left in the coordinator's waiting set would otherwise let a
        re-formed gang 'complete' with a phantom member."""
        w = self.waiting.pop(pod_key, None)
        if w is not None:
            self._unreserve_waiting(w)
            gang = self.gang_permit.gang_of(w.info.pod) if self.gang_permit else None
            if gang:
                self._fail_gang(gang)  # surviving peers requeue
                self.doomed_gangs.pop(gang, None)  # gone = not doomed
        for q in self.queue.remove(pod_key):
            # a QUEUED gang member (e.g. mid-preemption, before parking)
            # also takes its gang's state and slice entitlement with it
            gang = self.gang_permit.gang_of(q.pod) if self.gang_permit else None
            if gang:
                self._fail_gang(gang)
                self.doomed_gangs.pop(gang, None)
        if self.allocator is not None:
            self.allocator.unnominate(pod_key)
        if self.policy is not None:
            self.policy.resolved(pod_key)  # starvation-watch dedup set
        self.failed.pop(pod_key, None)
        self.quarantined.pop(pod_key, None)

    def reconcile(self, pods) -> tuple[int, int]:
        """Restart reconciliation: rebuild assumed/in-flight bind state
        from CLUSTER truth after a scheduler crash. For each candidate
        pod (the previous incarnation's workload, as recovered from the
        apiserver or the test driver): binding present in the cluster =>
        ADOPT it — the chip-assignment annotation rode the Binding, so
        allocation accounting follows from cluster state alone; absent =>
        the pod never made it past the wire (a crash between Reserve and
        Bind left only engine-local state, which died with the engine) —
        scrub any stale assignment annotation and REQUEUE it. No pod is
        lost, none is double-bound. Returns (adopted, requeued)."""
        adopted = requeued = 0
        bn = getattr(self.cluster, "bound_node_of", None)
        for pod in pods:
            if self.tracks(pod.key) or pod.key in self.failed:
                continue
            node = bn(pod.key) if bn is not None else None
            if node is not None:
                pod.node = node
                pod.phase = PodPhase.BOUND
                adopted += 1
                self.metrics.inc("reconcile_adopted_total")
                continue
            pod.node = None
            pod.phase = PodPhase.PENDING
            pod.labels.pop(ASSIGNED_CHIPS_LABEL, None)
            if self.submit(pod):
                requeued += 1
                self.metrics.inc("reconcile_requeued_total")
        if adopted or requeued:
            # no-op passes stay out of the black box: multi-profile and
            # paginated reconciles route per-pod through here
            self.flight.record("reconcile", adopted=adopted,
                               requeued=requeued)
        return adopted, requeued

    # -------------------------------------------------------------- main loop
    def run_one(self) -> str | None:
        """One scheduling cycle: expire parked gangs, pop the next ready
        pod, schedule it. Returns the cycle outcome, or None when nothing
        is ready (queue empty, everyone backing off, or parked at Permit) —
        callers decide how to wait (next_wake_at)."""
        if self.waiting:
            self.check_waiting()
        if self._bind_results:
            self._drain_bind_failures()
        while self._gang_revivals:  # recorded by submit() on any thread
            try:
                self.doomed_gangs.pop(self._gang_revivals.popleft(), None)
            except IndexError:
                break
        if self._elastic_retires:
            self._drain_elastic_retires()
        if self.provisioner is not None:
            # capacity tick BEFORE the breaker gate: an apiserver storm
            # must not stop scale-up (pending work still needs homes
            # when the wire heals); the pass's scale-down half checks
            # the breaker/degraded interlocks itself. Contained like
            # the defrag tick — a controller crash never takes the
            # scheduling loop down.
            try:
                self.provisioner.maybe_run(self.clock.time())
            except Exception:
                self.metrics.inc("provisioner_errors_total")
        if self.clock.time() < self._breaker_until:
            # circuit open (apiserver error storm): park scheduling — the
            # queue keeps its order and nobody's attempts burn; resumes
            # cleanly when the cooldown passes (next_wake_at floors the
            # queue wake at the breaker deadline)
            self.metrics.inc("breaker_parked_cycles_total")
            return None
        if self.defrag is not None:
            # active defragmentation tick (engine thread, injectable
            # clock): run a migration pass when due — behind the breaker
            # gate above, so an open circuit never migrates, and guarded
            # inside against degraded mode / fleet ownership / no demand
            try:
                self.defrag.maybe_run(self.clock.time())
            except Exception:
                # the controller is best-effort: a planning crash must
                # not take the scheduling loop down with it
                self.metrics.inc("defrag_errors_total")
        if self.workloads is not None:
            # workload-tier admission pass (engine thread): at most
            # admissionBurst O(1) decisions, contained like the defrag
            # tick — an admission crash must not take the loop down
            try:
                self.workloads.tick(self.clock.time())
            except Exception:
                self.metrics.inc("workload_admission_errors_total")
        if self.sloguard is not None:
            # SLO guard tick (engine thread): evaluate burn-rate
            # pressure and shrink/give-back when due — behind the
            # breaker gate (its evictions ride the bind wire's health)
            # and contained like every controller tick
            try:
                self.sloguard.maybe_run(self.clock.time())
            except Exception:
                self.metrics.inc("slo_guard_errors_total")
        maxp = self.config.batch_max_pods
        if maxp > 1:
            if self.allocator is None or self.allocator.has_holds():
                # nominated preemptors / gang-slice entitlements make
                # filter verdicts depend on per-pod holds the equivalence
                # key cannot see: per-pod cycles until the holds drain
                maxp = 1
        if maxp > 1:
            infos = self.queue.pop_batch(now=self.clock.time(),
                                         max_pods=maxp,
                                         exclude=self.head_filter)
            if not infos:
                return None
            self.metrics.observe("batch_size", len(infos))
            started = self.clock.time()
            for i in infos:
                self._record_queued_span(i, started)
                # batch members' compute phase opens at the shared pop:
                # time spent waiting for earlier members IS batch cycle
                # time (the head's _schedule_one_locked restamps itself)
                i.cycle_started = started
            outcome = self.schedule_batch(infos)
        else:
            info = self.queue.pop(now=self.clock.time(),
                                  exclude=self.head_filter)
            if info is None:
                return None
            started = self.clock.time()
            self._record_queued_span(info, started)
            outcome = self.schedule_one(info)
        if outcome not in ("crash", "quarantined"):
            # a cycle completed without crashing: the next crash is a
            # per-pod (poison) signal again, not a systemic one
            self._ok_since_crash = True
        self.metrics.observe("cycle_latency_ms",
                             (self.clock.time() - started) * 1e3)
        if self._native is not None and self.config.native_prefetch:
            # best-effort, and CONTAINED like the cycle itself: a raising
            # capability hook here runs outside the per-pod crash
            # containment, and the completed cycle's real outcome must
            # not be replaced by an escaping dispatch error
            try:
                self._dispatch_prefetch()
            except Exception:
                self.metrics.inc("prefetch_dispatch_errors_total")
        return outcome

    def _record_queued_span(self, info: QueuedPodInfo, now: float) -> None:
        """One `queued` lifecycle span per queue stint (sampled pods):
        intake wait for the first pop, a backoff segment (with the parking
        plugins) for every retry stint."""
        if info.stint_started < 0.0 or not self._sampled(info.pod):
            return
        attrs: dict = {"segment": "backoff" if info.attempts else "intake",
                       "attempts": info.attempts}
        if info.rejected_by:
            attrs["parked_by"] = list(info.rejected_by)
        self.spans.record("queued", info.pod.key, info.stint_started, now,
                          attrs)

    def next_wake_at(self) -> float | None:
        """Earliest future instant at which run_one could make progress:
        the nearest gang-permit deadline or backoff expiry — or now, when
        undrained cluster events could activate a parked pod (the queue's
        next_ready_at reads 0.0 while its inbox is non-empty). None =
        idle."""
        wakes = []
        if self.waiting:
            wakes.append(min(w.deadline for w in self.waiting.values()))
        nxt = self.queue.next_ready_at()
        if nxt is not None:
            # an open circuit breaker defers queue work (but never permit
            # deadlines — check_waiting still runs while parked)
            wakes.append(max(nxt, self._breaker_until))
        if self.defrag is not None:
            # the defrag pass is a wake source only while pods are
            # PENDING (its demand gate): a due pass may free exactly the
            # chips a parked pod needs, and without this wake a
            # run_until_idle drain would sleep past it. With nothing
            # pending the controller would no-op, so idle stays idle.
            # The gate is maybe_run's own (DefragController.demanded —
            # fleet-wide when wired, so a shard-0 owner with an empty
            # local queue still wakes for passes other replicas need).
            # Floored at the breaker deadline like the queue wake above:
            # run_one returns at the breaker gate BEFORE the defrag tick,
            # so a due next_at would otherwise spin the wait loop.
            if self.defrag.demanded():
                wakes.append(max(self.defrag.next_at, self._breaker_until))
        if self.workloads is not None:
            nx = self.workloads.next_ready_at(self.clock.time())
            if nx is not None:
                # a due admission pass runs inside run_one, which parks
                # at the breaker gate first — floor like the queue wake
                wakes.append(max(nx, self._breaker_until))
        if self.provisioner is not None and self.provisioner.busy():
            # NOT floored at the breaker: the capacity tick runs before
            # the breaker gate in run_one (scale-up continues degraded)
            wakes.append(self.provisioner.next_at)
        if self.sloguard is not None and self.sloguard.demanded():
            # the guard is a wake source while pressure is live, shrunk
            # capacity awaits give-back, or burn windows must close —
            # floored at the breaker like the defrag wake (the tick
            # runs behind the gate)
            wakes.append(max(self.sloguard.next_at, self._breaker_until))
        return min(wakes) if wakes else None

    def run_until_idle(self, max_cycles: int = 100_000) -> int:
        """Drive cycles until no pending work remains (tests/bench harness).
        Returns the number of cycles executed."""
        cycles = 0
        while cycles < max_cycles:
            if self.run_one() is not None:
                cycles += 1
                continue
            wake = self.next_wake_at()
            if wake is None:
                break  # fully idle
            self.clock.sleep(max(wake - self.clock.time(), 0.01))
            cycles += 1
        return cycles

    # ------------------------------------------------------------- reporting
    def bin_pack_utilization(self) -> float:
        """% of healthy TPU chips claimed by bound pods, over TPU nodes that
        could host work — the BASELINE bin-pack metric."""
        total = 0
        used = 0
        for name in self.cluster.node_names():
            m = self.cluster.telemetry.get(name)
            if m is None or m.accelerator != "tpu":
                continue
            healthy = m.healthy_coords()
            total += len(healthy)
            ni = NodeInfo(name=name, metrics=m, pods=self.cluster.pods_on(name))
            used += len(ni.assigned_coords() & healthy)
        return 100.0 * used / total if total else 0.0
