"""Conflict-aware publication of TpuNodeMetrics CRs to the API server.

The reference's running system had a live SCV publisher feeding the
scheduler (the SCV dependency, reference go.mod:6; RBAC for `scvs` at
reference deploy/yoda-scheduler.yaml:205-216) but kept its code out of
repo. This is the first-party equivalent the sniffer DaemonSet invokes
(`cli sniff --publish`): create-or-update with optimistic concurrency done
right — a PUT without the current resourceVersion is rejected by a real
API server, which is exactly the defect the round-2 review found in the
previous inline-YAML publisher (it created once and then went permanently
stale).

Protocol per publish:
- GET the CR; 404 -> POST (a lost create race, 409, restarts the loop)
- carry the GET's resourceVersion into the PUT; 409 (someone else wrote
  between our GET and PUT) -> re-GET and retry, bounded.
"""

from __future__ import annotations

import logging
import threading
import time

from .schema import TpuNodeMetrics
from ..k8s.client import ApiError, KubeClient, METRICS_PATH

log = logging.getLogger("yoda-tpu.publisher")


class CrPublisher:
    def __init__(self, client: KubeClient, max_conflict_retries: int = 4) -> None:
        self.client = client
        self.max_conflict_retries = max_conflict_retries

    def publish(self, metrics: TpuNodeMetrics) -> None:
        """Create-or-update the node's CR; raises ApiError when conflicts
        persist past the retry budget (the next interval tick re-publishes
        fresher data anyway — per-node CRs have a single writer in steady
        state, so persistent conflicts mean a misconfigured second sniffer)."""
        path = f"{METRICS_PATH}/{metrics.node}"
        body = metrics.to_cr()
        last: ApiError | None = None
        for _ in range(self.max_conflict_retries + 1):
            try:
                current = self.client.request("GET", path)
            except ApiError as e:
                if e.status != 404:
                    raise
                # creates must NOT carry a resourceVersion (a previous PUT
                # attempt in this loop may have stamped one; the API server
                # rejects such creates)
                body.get("metadata", {}).pop("resourceVersion", None)
                try:
                    self.client.request("POST", METRICS_PATH, body)
                    return
                except ApiError as e2:
                    if e2.status != 409:  # 409: lost the create race; re-GET
                        raise
                    last = e2
                    continue
            rv = current.get("metadata", {}).get("resourceVersion")
            body.setdefault("metadata", {})["resourceVersion"] = rv
            try:
                self.client.request("PUT", path, body)
                return
            except ApiError as e:
                if e.status != 409:
                    raise
                last = e  # concurrent writer bumped the rv; re-GET
        raise ApiError("PUT", path, 409,
                       f"persistent conflicts: {last}".encode())


def run_publisher(client: KubeClient, node_name: str | None = None,
                  interval_s: float = 5.0,
                  stop_event: threading.Event | None = None,
                  once: bool = False) -> int:
    """The sniffer daemon's main loop: snapshot local telemetry, publish,
    sleep. Publish errors are logged and retried next tick — a transient
    API outage must not kill the DaemonSet pod (the staleness gate already
    protects the scheduler from frozen data)."""
    from .duty import DutySamplerPool
    from .sniffer import local_node_metrics

    pub = CrPublisher(client)
    stop = stop_event or threading.Event()
    # long-running publisher: measure duty cycles with the probe sampler
    # pool so the scheduler's utilisation term sees real busyness (a
    # --once snapshot has no sampling window; its duty reads 0 = neutral)
    pool = None if once else DutySamplerPool()
    duty_of = pool.duty_of if pool is not None else None
    published = 0
    try:
        while True:
            try:
                pub.publish(local_node_metrics(node_name, duty_of=duty_of))
                published += 1
            except Exception as e:
                log.warning("publish failed (next tick retries): %s", e)
            if once:
                return 0 if published else 1
            if stop.wait(interval_s):
                return 0
    finally:
        if pool is not None:
            pool.stop()
