"""TPU generation catalog + generation-aware telemetry and scheduling.

The reference models interchangeable GPU cards only; generations.py adds the
TPU fleet reality (3-D vs 2-D tori, per-generation host packaging and HBM).
"""

import pytest

from yoda_scheduler_tpu.scheduler import FakeCluster, Scheduler, SchedulerConfig
from yoda_scheduler_tpu.scheduler.core import FakeClock
from yoda_scheduler_tpu.telemetry import TelemetryStore, make_slice, make_tpu_node
from yoda_scheduler_tpu.topology import GENERATIONS, generation
from yoda_scheduler_tpu.utils import Pod, PodPhase
from yoda_scheduler_tpu.utils.labels import LabelError, WorkloadSpec


def test_catalog_structure():
    assert set(GENERATIONS) == {"v2", "v3", "v4", "v5e", "v5p", "v6e"}
    for g in GENERATIONS.values():
        assert g.hbm_mb > 0 and g.chips_per_host in (4, 8)
        assert g.torus_rank in (2, 3)
    # 2-D generations pack 8-chip hosts; 3-D pack 4-chip boards
    assert generation("v5e").host_block == (2, 4, 1)
    assert generation("v4").host_block == (2, 2, 1)
    with pytest.raises(ValueError, match="unknown TPU generation"):
        generation("v99")


def test_validate_slice_topology():
    # v4-32: 2x2x4 over 4 hosts — fine
    assert generation("v4").validate_slice_topology("2x2x4") == (2, 2, 4)
    # 2-D generation rejects a cube
    with pytest.raises(ValueError, match="2-D"):
        generation("v5e").validate_slice_topology("4x4x4")
    # v5e-64: 8x8 over 8 hosts — fine
    assert generation("v5e").validate_slice_topology("8x8") == (8, 8, 1)
    # not divisible into host blocks
    with pytest.raises(ValueError, match="not divisible"):
        generation("v5e").validate_slice_topology("6x6")
    # over pod size
    with pytest.raises(ValueError, match="max out"):
        generation("v6e").validate_slice_topology("32x16")


def test_make_slice_v5e_2d():
    nodes = make_slice("s0", "8x8", generation="v5e")
    assert len(nodes) == 8  # 64 chips / 8 per host
    coords = {c.coords for n in nodes for c in n.chips}
    assert len(coords) == 64
    assert all(z == 0 for _, _, z in coords)  # flat torus
    n0 = nodes[0]
    assert n0.tpu_generation == "v5e"
    assert n0.topology == "2x4x1"
    assert n0.chips[0].hbm_total_mb == generation("v5e").hbm_mb


def test_make_tpu_node_generation_defaults():
    n = make_tpu_node("a", chips=4, generation="v5p")
    assert n.tpu_generation == "v5p"
    assert n.chips[0].hbm_total_mb == generation("v5p").hbm_mb
    # explicit override still wins
    n2 = make_tpu_node("b", chips=4, generation="v5p", hbm_total_mb=1234)
    assert n2.chips[0].hbm_total_mb == 1234


def test_generation_label_parsing():
    spec = WorkloadSpec.from_labels({"tpu/generation": "v6e"})
    assert spec.tpu_generation == "v6e"
    with pytest.raises(LabelError, match="tpu/generation"):
        WorkloadSpec.from_labels({"tpu/generation": "volta"})


def _sched(nodes):
    store = TelemetryStore()
    for n in nodes:
        n.heartbeat = 0.0
        store.put(n)
    cluster = FakeCluster(store)
    cluster.add_nodes_from_telemetry()
    return Scheduler(cluster, SchedulerConfig(max_attempts=3, telemetry_max_age_s=1e9),
                     clock=FakeClock())


def test_generation_routing_heterogeneous_fleet():
    """A pod pinning v5e must never land on the v4 node, and vice versa."""
    sched = _sched([make_tpu_node("v4-node", chips=4, generation="v4"),
                    make_tpu_node("v5e-node", chips=8, generation="v5e")])
    p_v5e = Pod("want5e", labels={"tpu/generation": "v5e", "scv/number": "2"})
    p_v4 = Pod("want4", labels={"tpu/generation": "v4", "scv/number": "2"})
    any_gen = Pod("any", labels={"scv/number": "1"})
    for p in (p_v5e, p_v4, any_gen):
        sched.submit(p)
    sched.run_until_idle()
    assert p_v5e.phase == PodPhase.BOUND and p_v5e.node == "v5e-node"
    assert p_v4.phase == PodPhase.BOUND and p_v4.node == "v4-node"
    assert any_gen.phase == PodPhase.BOUND


def test_generation_unsatisfiable_fails():
    sched = _sched([make_tpu_node("v4-node", chips=4, generation="v4")])
    p = Pod("want6e", labels={"tpu/generation": "v6e"})
    sched.submit(p)
    sched.run_until_idle()
    assert p.phase == PodPhase.FAILED


def test_device_kind_mapping():
    """The real-cluster sniffer must label nodes with a catalog generation."""
    from yoda_scheduler_tpu.telemetry.sniffer import generation_of

    assert generation_of("TPU v4") == "v4"
    assert generation_of("TPU v2") == "v2"
    assert generation_of("TPU v5 lite") == "v5e"
    assert generation_of("TPU v5e") == "v5e"
    assert generation_of("TPU v5") == "v5p"
    assert generation_of("TPU v5p") == "v5p"
    assert generation_of("TPU v6 lite") == "v6e"
    assert generation_of("TPU v6e") == "v6e"
    assert generation_of("Tesla V100") == ""
    assert generation_of("") == ""


def test_crd_enum_matches_catalog():
    """deploy/crd-tpunodemetrics.yaml's tpu_generation enum must track the
    GENERATIONS catalog, or new-generation CRs get rejected by the apiserver."""
    import os
    import re

    crd_path = os.path.join(os.path.dirname(__file__), "..", "deploy",
                            "crd-tpunodemetrics.yaml")
    with open(crd_path) as f:
        src = f.read()
    m = re.search(r"tpu_generation:\s*\n\s*type: string\s*\n\s*enum: \[(.*?)\]", src)
    assert m, "tpu_generation enum missing from CRD"
    enum = {v.strip().strip('"') for v in m.group(1).split(",")}
    assert enum == set(GENERATIONS) | {""}


def test_topology_request_on_2d_slice():
    """tpu/topology packing works on a flat (v5e) torus."""
    sched = _sched(make_slice("s0", "4x4", generation="v5e"))
    p = Pod("flat", labels={"scv/number": "4", "tpu/topology": "2x2",
                            "tpu/generation": "v5e"})
    sched.submit(p)
    sched.run_until_idle()
    assert p.phase == PodPhase.BOUND
    chips = p.assigned_chips()
    xs = sorted(c[0] for c in chips)
    ys = sorted(c[1] for c in chips)
    assert len(chips) == 4
    assert xs[-1] - xs[0] == 1 and ys[-1] - ys[0] == 1  # contiguous 2x2
    assert all(c[2] == 0 for c in chips)
