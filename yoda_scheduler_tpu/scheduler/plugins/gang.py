"""Gang scheduling: all-or-nothing admission of multi-host pod-slice jobs.

New TPU-native capability (SURVEY §7 "hard part (1)"): a v4-32 Llama job is
one worker pod per host of a 4-host slice; binding 3 of 4 workers deadlocks
the job while holding 12 chips. The k8s framework scores nodes one pod at a
time, so cross-pod state lives in a shared GangCoordinator and admission
goes through Permit:

- first member to Reserve picks the slice (members' Filter then sticks to it)
- every member's Permit returns WAIT until the gang is complete
- the last member's arrival approves all waiting members (bind together)
- timeout or any member's failure rejects the whole gang: all reservations
  roll back, the chosen slice resets, everything requeues with backoff.
"""

from __future__ import annotations

import threading

from ..framework import CycleState, PermitPlugin, ReservePlugin, Status
from ...utils.labels import GANG_NAME_LABEL, WorkloadSpec, spec_for
from ...utils.pod import Pod


def bound_gang_members(state: CycleState, gang: str) -> tuple[set[str], str | None]:
    """(pod keys, slice id) of gang members ALREADY BOUND in the cluster,
    from this cycle's snapshot — cluster truth, not coordinator state.

    This is what lets a gang survive partial binds: if a peer's bind fails
    after the anchor bound (API outage mid-gang), or the scheduler restarts
    mid-assembly, the coordinator's waiting set is gone but the bound
    members are still visible on their nodes. A retrying member counts them
    toward gang completeness and sticks to their slice. Cached per cycle in
    CycleState (one snapshot scan per gang per cycle).

    Caveat: gang names must be unique per job — reusing a name while an
    older gang's pods are still bound would let the new gang 'complete'
    against them."""
    key = "gang_bound:" + gang
    cached = state.read_or(key)
    if cached is not None:
        return cached
    keys: set[str] = set()
    slice_id: str | None = None
    snapshot = state.read_or("snapshot")
    if snapshot is not None:
        for ni in snapshot.list():
            for p in ni.pods:
                if (p.labels.get(GANG_NAME_LABEL) == gang
                        and not p.terminating):
                    keys.add(p.key)
                    if ni.metrics is not None and ni.metrics.slice_id:
                        slice_id = ni.metrics.slice_id
    state.write(key, (keys, slice_id))
    return keys, slice_id


class GangCoordinator:
    """Shared cross-cycle gang state (gang name -> members/slice)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._slice: dict[str, str] = {}          # gang -> chosen slice id
        self._waiting: dict[str, set[str]] = {}   # gang -> waiting pod keys

    def chosen_slice(self, gang: str) -> str | None:
        with self._lock:
            return self._slice.get(gang)

    def choose_slice(self, gang: str, slice_id: str) -> None:
        with self._lock:
            self._slice.setdefault(gang, slice_id)

    def add_waiting(self, gang: str, pod_key: str) -> int:
        with self._lock:
            s = self._waiting.setdefault(gang, set())
            s.add(pod_key)
            return len(s)

    def waiting_members(self, gang: str) -> set[str]:
        with self._lock:
            return set(self._waiting.get(gang, set()))

    def reset(self, gang: str) -> set[str]:
        """Tear down gang state; returns the members that were waiting."""
        with self._lock:
            members = self._waiting.pop(gang, set())
            self._slice.pop(gang, None)
            return members


class GangPermit(PermitPlugin, ReservePlugin):
    name = "gang-permit"

    def __init__(self, gangs: GangCoordinator, timeout_s: float = 30.0) -> None:
        self.gangs = gangs
        self.timeout_s = timeout_s

    # Reserve: the first member fixes the slice choice for the whole gang.
    def reserve(self, state: CycleState, pod: Pod, node: str) -> Status:
        spec: WorkloadSpec = state.read("workload_spec")
        if spec.is_gang:
            snapshot = state.read_or("snapshot")
            node_info = snapshot.get(node) if snapshot is not None else None
            if node_info is not None and node_info.metrics is not None:
                self.gangs.choose_slice(spec.gang_name, node_info.metrics.slice_id)
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node: str) -> None:
        return None

    def permit(self, state: CycleState, pod: Pod, node: str) -> tuple[Status, float]:
        spec: WorkloadSpec = state.read("workload_spec")
        if not spec.is_gang:
            return Status.success(), 0.0
        n_waiting = self.gangs.add_waiting(spec.gang_name, pod.key)
        # members already bound in the cluster count toward completeness:
        # this re-admits stragglers of a partially-bound gang (peer bind
        # failure, scheduler restart mid-assembly) instead of parking them
        # at 1/N forever
        bound, _ = bound_gang_members(state, spec.gang_name)
        n = n_waiting + len(bound - {pod.key})
        if n >= spec.gang_size:
            # gang complete: this pod proceeds; the engine approves the rest
            return Status.success(), 0.0
        return Status.wait(
            f"gang {spec.gang_name}: {n}/{spec.gang_size} members placed"
        ), self.timeout_s

    # ------------------------------------------------------------ engine hooks
    def peers_to_approve(self, pod: Pod) -> set[str]:
        """After `pod`'s Permit succeeded, which waiting pods bind with it."""
        try:
            spec = spec_for(pod)
        except Exception:
            return set()
        if not spec.is_gang:
            return set()
        members = self.gangs.reset(spec.gang_name)
        members.discard(pod.key)
        return members

    def gang_of(self, pod: Pod) -> str | None:
        try:
            spec = spec_for(pod)
        except Exception:
            return None
        return spec.gang_name

    def fail_gang(self, gang: str) -> set[str]:
        """Timeout/failure: tear down and report members needing rollback."""
        return self.gangs.reset(gang)
