"""Deterministic chaos injection: seeded fault plans + a chaos cluster.

The scheduler's two single points of failure are the apiserver connection
and the telemetry feed (the paper's placement quality is worthless if the
control loop wedges or double-places when either goes away). This module
scripts those outages DETERMINISTICALLY — a seed fully determines which
faults fire and when, on the engine's injectable clock — so the invariant
fuzz in tests/test_chaos.py can replay hundreds of distinct outage
scenarios and every failure reproduces from its seed alone.

Fault kinds:

- APISERVER_STORM    bind requests fail with wire errors (5xx storm /
                     connection refused); nothing is applied.
- BIND_LOST          the bind IS applied server-side, then the response is
                     lost (fake_apiserver fault ``-1`` / KubeClient
                     AmbiguousRequestError analogue) — the ambiguous
                     failure the adoption path must resolve without a
                     duplicate bind.
- TELEMETRY_BLACKOUT every sniffer heartbeat stops: the whole feed ages
                     out and the engine must degrade to capacity-only
                     scheduling instead of rejecting every node as stale.
- PLUGIN_ERROR       a plugin RAISES mid-cycle (filter/score/reserve);
                     the engine must contain the crash to the pod.
- ENGINE_CRASH       the scheduler process dies mid-drain; the test driver
                     builds a fresh engine against the same cluster and
                     reconciles in-flight state from cluster truth.

The plan is pure data: the driver (test/bench) owns applying the
clock-keyed transitions that cannot be expressed as call-site injection
(telemetry blackout, engine crash); ChaosCluster injects the bind-surface
faults at the exact call the real apiserver would fail.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from .scheduler.cluster import FakeCluster
from .scheduler.framework import (
    FilterPlugin,
    ReservePlugin,
    ScorePlugin,
    Status,
)

APISERVER_STORM = "ApiServerStorm"
BIND_LOST = "BindLost"
TELEMETRY_BLACKOUT = "TelemetryBlackout"
PLUGIN_ERROR = "PluginError"
ENGINE_CRASH = "EngineCrash"
# fleet-only kinds (multi-replica shared-state scheduling): one REPLICA
# dies mid-drain (the fleet rebuilds it and reconciles from cluster
# truth), a replica's shard LEASES are revoked mid-bind-window (its
# fenced commits must abort cleanly), and a SPLIT-BRAIN window injects a
# duplicate replica — the same pods queued on two replicas at once, with
# the original holder's lease epoch gone stale — so the authority's
# conflict + fencing checks are the only thing standing between the
# fleet and a double bind.
REPLICA_CRASH = "ReplicaCrash"
LEASE_EXPIRY = "LeaseExpiry"
SPLIT_BRAIN = "SplitBrain"
# the webhook-era kinds, attacking exactly the vanilla-apiserver path:
# a replica that can BIND but not WATCH (its view freezes while commits
# still flow — the webhook is then the only thing standing between its
# stale placements and a double-booking), lease clocks drifting between
# replicas (renewals silently missed; stale fencing epochs travel to the
# authority), a SLOW apiserver (latency is not failure: the breaker must
# not trip and no invariant may bend), and the webhook itself going DOWN
# under both failure policies (Fail = binds 500 until it returns;
# Ignore = pod-level checks only, the documented unsafe-under-partition
# trade).
NETWORK_PARTITION = "NetworkPartition"
CLOCK_SKEW = "ClockSkew"
SLOW_APISERVER = "SlowApiServer"
WEBHOOK_DOWN = "WebhookDown"
# elastic/defrag kind (ISSUE 10): force the defrag controller's
# migration pass at a seeded instant — the descheduler EVICTING a pod
# while another fleet replica concurrently BINDS onto the same node (and
# while elastic gangs are mid-growth), so the authority's conflict
# battery and the controller's safety rails are the only thing standing
# between active defragmentation and a lost pod / double-booked chip.
DEFRAG_RACE = "DefragRace"
# capacity-provisioner kinds (ISSUE 15), attacking the closed capacity
# loop through its provider: a STOCKOUT window denies every capacity
# request after full provisioning latency (the cloud said no), a QUOTA
# window denies them as policy (retrying harder is wrong — backoff and
# breaker must absorb it), a LOST_RESPONSE window creates the node but
# never answers (the request is written off; the arriving node must be
# ADOPTED through membership reconciliation, never leaked), and a FLAP
# window delivers the node then yanks it shortly after (orphaned pods
# requeue; the loop must re-provision without fleet-size oscillation).
PROVIDER_STOCKOUT = "ProviderStockout"
PROVIDER_QUOTA_DENIED = "ProviderQuotaDenied"
PROVISION_LOST_RESPONSE = "ProvisionLostResponse"
PROVISION_FLAP = "ProvisionFlap"
# workload-admission kind (ISSUE 13): at a seeded instant, race the
# admission tier — withdraw a random workload (possibly mid-admission,
# its members half-materialized across replicas) and/or revoke the
# admission owner's leases so the shard-0 handover lands while the
# parked backlog is being decided. The fleet-wide claim-once registry
# and the one-pass withdraw retirement are the only things standing
# between that and a double-materialized workload / leaked quota claim.
ADMISSION_RACE = "AdmissionRace"
# SLO-serving kind (ISSUE 19): a FLASH_CROWD window multiplies the
# serving class's open-loop arrival rate (the driver reads the plan's
# windows and scales its own generator — the window IS the crowd).
# Crossed with provider stockouts (capacity cannot arrive), lease
# expiry (the guard's shard-0 ownership moves mid-shrink), and replica
# crashes, the burn-rate trip, the shrink-to-min pass, and the
# hysteresis'd give-back are the only things standing between a traffic
# spike and a starved serving class / an oscillating training fleet.
FLASH_CROWD = "FlashCrowd"

ALL_KINDS = (APISERVER_STORM, BIND_LOST, TELEMETRY_BLACKOUT, PLUGIN_ERROR,
             ENGINE_CRASH)
# the fleet fuzz's kind mix: the single-engine kinds that stress the
# commit path, plus the three fleet-only kinds above (blackout/plugin
# crashes are engine-local and already covered by the single-engine fuzz)
FLEET_KINDS = (APISERVER_STORM, BIND_LOST, REPLICA_CRASH, LEASE_EXPIRY,
               SPLIT_BRAIN)
# the webhook/partition fuzz's mix (tests/test_chaos.py, run against a
# VANILLA-authority cluster + webhook gate): storms and lost binds keep
# the wire honest while the four new kinds attack the watch/lease/
# webhook legs, and replica crashes exercise shard rebalancing under it
WEBHOOK_KINDS = (APISERVER_STORM, BIND_LOST, REPLICA_CRASH,
                 NETWORK_PARTITION, CLOCK_SKEW, SLOW_APISERVER,
                 WEBHOOK_DOWN)
# the elastic/defrag fuzz's mix (tests/test_chaos.py): DEFRAG_RACE
# migrations interleaved with the commit-path stressors, replica
# crashes, and partitions — elastic gangs grow through all of it, and
# "no gang ever drops below tpu/gang-min from our own migrations" joins
# the four global invariants
ELASTIC_KINDS = (APISERVER_STORM, BIND_LOST, REPLICA_CRASH,
                 NETWORK_PARTITION, DEFRAG_RACE)
# the workload-admission fuzz's mix (tests/test_workload.py): admission
# races + lease churn + the commit-path stressors, over a fleet whose
# ENTIRE intake is workloads — no pod lost / no double-materialize /
# no leaked claim join the four global invariants
ADMISSION_KINDS = (APISERVER_STORM, BIND_LOST, LEASE_EXPIRY,
                   ADMISSION_RACE)
# the capacity fuzz's mix (tests/test_capacity.py): all four provider
# kinds plus the fleet stressors — partitions freeze a replica's view of
# arriving nodes, lease expiry / replica crashes move provisioner
# ownership mid-wave (the takeover's membership reconciliation is what
# stands between a crashed owner's in-flight requests and a leaked node)
PROVISIONER_KINDS = (APISERVER_STORM, BIND_LOST, REPLICA_CRASH,
                     LEASE_EXPIRY, NETWORK_PARTITION, PROVIDER_STOCKOUT,
                     PROVIDER_QUOTA_DENIED, PROVISION_LOST_RESPONSE,
                     PROVISION_FLAP)
# the SLO-serving fuzz's mix (tests/test_slo.py): flash crowds landing
# inside provider stockouts (shrink is the ONLY source of chips), lease
# expiry moving the guard's ownership mid-pass, and replica crashes —
# "no gang below min, serving never starves once pressure registers,
# zero shrink/give-back oscillation pairs inside one hysteresis window"
# join the four global invariants
SLO_KINDS = (FLASH_CROWD, PROVIDER_STOCKOUT, LEASE_EXPIRY,
             REPLICA_CRASH)


class LostResponseError(ConnectionError):
    """The mutation was applied; the response never arrived (the
    fake-apiserver ``-1`` fault / k8s AmbiguousRequestError analogue)."""


class WebhookUnavailableError(RuntimeError):
    """failurePolicy=Fail with the webhook unreachable: the apiserver
    refuses the bind with a server-returned 500 ('failed calling
    webhook'). status=500 so the engine treats it as an ORDERLY refusal
    — backoff retry, never the breaker (the apiserver itself answered)
    and never the conflict path (nothing was judged)."""

    status = 500


@dataclass(frozen=True)
class FaultWindow:
    kind: str
    start: float
    end: float

    def active(self, now: float) -> bool:
        return self.start <= now < self.end


class FaultPlan:
    """A seeded schedule of fault windows on a virtual clock. The same
    (seed, horizon, kinds) always yields the same windows — the whole
    point: a failing chaos scenario replays from its seed."""

    def __init__(self, seed: int, horizon_s: float = 20.0,
                 kinds: tuple = ALL_KINDS, max_windows: int = 3) -> None:
        rng = random.Random(seed)
        self.seed = seed
        self.horizon_s = horizon_s
        self.windows: list[FaultWindow] = []
        for _ in range(rng.randint(1, max_windows)):
            kind = rng.choice(kinds)
            start = rng.uniform(0.5, horizon_s * 0.6)
            if kind in (ENGINE_CRASH, REPLICA_CRASH, LEASE_EXPIRY,
                        DEFRAG_RACE):
                # a crash / lease revocation / forced defrag pass is an
                # instant, not an interval; the driver fires it once when
                # the clock first passes `start`
                self.windows.append(FaultWindow(kind, start, start))
                continue
            dur = rng.uniform(1.0, horizon_s * 0.4)
            self.windows.append(
                FaultWindow(kind, start, min(start + dur, horizon_s)))
        self.windows.sort(key=lambda w: (w.start, w.kind))

    def active(self, kind: str, now: float) -> bool:
        return any(w.kind == kind and w.active(now) for w in self.windows)

    def kinds(self) -> set:
        return {w.kind for w in self.windows}

    def windows_of(self, kind: str) -> list[FaultWindow]:
        return [w for w in self.windows if w.kind == kind]

    def fault_end(self) -> float:
        """Instant after which no fault is active (convergence must be
        reached some time after this)."""
        return max((w.end for w in self.windows), default=0.0)


class ChaosCluster(FakeCluster):
    """FakeCluster whose binding surface fails on the plan's schedule —
    the in-memory analogue of fault-injecting the apiserver.

    `bind_script` additionally maps absolute bind-call indices (0-based,
    counted across the cluster's lifetime) to fault kinds, for tests that
    need "exactly the Nth bind fails" rather than a time window."""

    def __init__(self, telemetry=None, plan: FaultPlan | None = None,
                 clock=None, bind_script: dict[int, str] | None = None,
                 flight=None) -> None:
        super().__init__(telemetry)
        self.plan = plan
        self.clock = clock
        self.bind_script = dict(bind_script or {})
        self.bind_calls = 0
        self.injected: dict[str, int] = {}
        # optional utils.obs.FlightRecorder: injected faults land in the
        # same black-box ring as the engine's reactions, so a dump reads
        # as one interleaved timeline (fault fired -> breaker opened ->
        # recovery path taken)
        self.flight = flight

    def _now(self) -> float:
        return self.clock.time() if self.clock is not None else 0.0

    def _bind_fault(self) -> str | None:
        idx = self.bind_calls
        self.bind_calls += 1
        scripted = self.bind_script.get(idx)
        if scripted is not None:
            return scripted
        if self.plan is None:
            return None
        now = self._now()
        for kind in (APISERVER_STORM, BIND_LOST):
            if self.plan.active(kind, now):
                return kind
        return None

    def _count(self, kind: str) -> None:
        self.injected[kind] = self.injected.get(kind, 0) + 1
        if self.flight is not None:
            self.flight.record("fault_injected", fault=kind,
                               bind_call=self.bind_calls - 1)

    # one bind's injected latency during a SLOW_APISERVER window: long
    # enough to push lease renew deadlines and queue deadlines around
    # (the virtual clock advances), short enough that a window's worth of
    # binds stays inside the convergence budget
    slow_bind_latency_s = 0.25

    def _maybe_slow(self) -> None:
        """SLOW_APISERVER: latency, not failure — the bind completes
        after a delay (virtual clock advances). The breaker must never
        count it and no invariant may bend under it."""
        if (self.plan is not None and self.clock is not None
                and self.plan.active(SLOW_APISERVER, self._now())):
            self._count(SLOW_APISERVER)
            self.clock.sleep(self.slow_bind_latency_s)

    def bind(self, pod, node, assigned_chips=None, fence=None) -> None:
        self._maybe_slow()
        fault = self._bind_fault()
        if fault == APISERVER_STORM:
            self._count(fault)
            raise ConnectionError("chaos: apiserver unavailable (storm)")
        if fault == BIND_LOST:
            # the mutation lands, the response does not — the caller sees
            # an error for a bind the cluster already holds. (A conflict
            # rejection raises INSTEAD of the lost response: the server
            # never applied anything, so there is nothing to lose.)
            super().bind(pod, node, assigned_chips, fence=fence)
            self._count(fault)
            raise LostResponseError("chaos: bind applied, response lost")
        super().bind(pod, node, assigned_chips, fence=fence)


class AsyncChaosCluster(ChaosCluster):
    """ChaosCluster that also speaks the bind_async contract, executing
    the "wire" synchronously inline so the engine's async recovery path
    (_drain_bind_failures) is exercised deterministically: a storm fault
    reports on_fail without applying (the KubeCluster binder's rollback
    already ran by the time on_fail fires there — here nothing was
    applied, which is the same post-rollback state); a lost-response
    fault APPLIES the bind and then reports on_fail."""

    def bind_async(self, pod, node, assigned_chips=None,
                   on_fail=None, on_success=None, fence=None) -> None:
        self._maybe_slow()
        fault = self._bind_fault()
        if fault == APISERVER_STORM:
            self._count(fault)
            if on_fail is not None:
                on_fail(pod, node,
                        ConnectionError("chaos: apiserver storm (async)"))
            return
        if fault == BIND_LOST:
            try:
                super(ChaosCluster, self).bind(pod, node, assigned_chips,
                                               fence=fence)
            except Exception as e:  # conflict rejection: report it, the
                if on_fail is not None:  # response-loss never happened
                    on_fail(pod, node, e)
                return
            self._count(fault)
            if on_fail is not None:
                on_fail(pod, node,
                        LostResponseError("chaos: async bind applied, "
                                          "response lost"))
            return
        try:
            super(ChaosCluster, self).bind(pod, node, assigned_chips,
                                           fence=fence)
        except Exception as e:
            if on_fail is not None:
                on_fail(pod, node, e)
            return
        if on_success is not None:
            on_success(pod, node)


class VanillaAuthorityCluster(ChaosCluster):
    """ChaosCluster in the VANILLA-apiserver posture: the server itself
    enforces only the pod-level 409 (a conformant kube-apiserver's whole
    battery); the chip/HBM/fence half runs in an attached WEBHOOK GATE
    that a WEBHOOK_DOWN window takes away — under both failure policies:

    - ``fail_open=False`` (failurePolicy=Fail): a bind during the window
      is refused with a server-returned 500 (WebhookUnavailableError) —
      safety over availability; the engine backs the pod off and it
      binds when the webhook returns.
    - ``fail_open=True`` (failurePolicy=Ignore): binds flow with only
      the pod-level check. Availability over safety — combined with a
      concurrently PARTITIONED replica this is exactly the double-
      booking window (demonstrated by a targeted test; the fuzz keeps
      the two windows disjoint for fail-open seeds, which is the
      deployment guidance in ARCHITECTURE.md)."""

    def __init__(self, telemetry=None, plan: FaultPlan | None = None,
                 clock=None, bind_script: dict[int, str] | None = None,
                 flight=None, fail_open: bool = False) -> None:
        super().__init__(telemetry, plan=plan, clock=clock,
                         bind_script=bind_script, flight=flight)
        self.fail_open = fail_open
        self.webhook_checked = 0   # full-battery verdicts served
        self.webhook_skipped = 0   # fail-open binds admitted unchecked

    def _webhook_down(self) -> bool:
        return (self.plan is not None
                and self.plan.active(WEBHOOK_DOWN, self._now()))

    def _check_bind(self, pod, node, assigned_chips, fence) -> None:
        # the vanilla half: the binding subresource 409s an already-
        # assigned pod no matter what
        cur = self._bound_keys.get(pod.key)
        if cur is not None:
            self._reject("pod_bound",
                         f"pod {pod.key} is already bound to {cur}")
        if self._webhook_down():
            self._count(WEBHOOK_DOWN)
            if self.fail_open:
                self.webhook_skipped += 1
                if self.flight is not None:
                    self.flight.record("webhook_fail_open", pod=pod.key,
                                       node=node, state="down")
                return  # failurePolicy=Ignore: pod-level check only
            raise WebhookUnavailableError(
                'failed calling webhook "yoda-bind-authority.yoda.tpu": '
                "connection refused (failurePolicy=Fail)")
        self.webhook_checked += 1
        # webhook up: the full battery — the pod-level check re-runs
        # inside, which is harmless (it just passed)
        super()._check_bind(pod, node, assigned_chips, fence)


class PartitionableView:
    """Per-replica cluster facade for NETWORK_PARTITION: while frozen,
    the replica's WATCH-side reads (membership, per-node pod lists, the
    change-log versions) serve a snapshot taken at partition start — the
    replica schedules off an ever-staler view — while its BINDS (and the
    bind path's recovery reads: ``bound_node_of`` models the confirm
    GET) still reach the live cluster. The replica's own binds are
    write-through into the frozen view, as a real client's optimistic
    cache update would be; everything it cannot see is what the
    authority's conflict battery exists for.

    Everything not explicitly frozen delegates to the inner cluster."""

    def __init__(self, inner) -> None:
        self._inner = inner
        self._frozen: dict | None = None
        # post-thaw rebuild floor: change-log versions handed out while
        # frozen count only OUR writes, so a memo holding one cannot be
        # diffed against the real log (foreign changes interleaved with
        # ours would be skipped). Any version below the floor rebuilds.
        self._rebuild_below: int | None = None

    # ------------------------------------------------------------- chaos
    def freeze(self) -> None:
        inner = self._inner
        nodes = inner.node_names()
        self._frozen = {
            "nodes": nodes,
            "pods_on": {n: inner.pods_on(n) for n in nodes},
            "pods_ver": {n: inner.pods_version(n) for n in nodes},
            "nodes_ver": inner.nodes_version,
            "gver": inner.pods_global_version,
        }

    def thaw(self) -> None:
        self._frozen = None
        self._rebuild_below = self._inner.pods_global_version

    @property
    def partitioned(self) -> bool:
        return self._frozen is not None

    # ----------------------------------------------------- frozen reads
    def node_names(self):
        f = self._frozen
        return list(f["nodes"]) if f is not None else \
            self._inner.node_names()

    def pods_on(self, node):
        f = self._frozen
        return list(f["pods_on"].get(node, ())) if f is not None else \
            self._inner.pods_on(node)

    def all_pods(self):
        f = self._frozen
        if f is None:
            return self._inner.all_pods()
        return [p for pods in f["pods_on"].values() for p in pods]

    def pods_version(self, node):
        f = self._frozen
        return f["pods_ver"].get(node, 0) if f is not None else \
            self._inner.pods_version(node)

    @property
    def nodes_version(self):
        f = self._frozen
        return f["nodes_ver"] if f is not None else \
            self._inner.nodes_version

    @property
    def pods_global_version(self):
        f = self._frozen
        return f["gver"] if f is not None else \
            self._inner.pods_global_version

    def changes_since(self, version):
        f = self._frozen
        if f is None:
            if self._rebuild_below is not None \
                    and version < self._rebuild_below:
                # a frozen-era version: not diffable — full rebuild
                return self._inner.pods_global_version, None
            return self._inner.changes_since(version)
        # no watch = no change information: anything not already applied
        # reads as "rebuild from (frozen) state" — deliberately the
        # conservative full-rebuild signal, never a bogus empty diff for
        # a version we cannot actually diff against
        if version == f["gver"]:
            return f["gver"], set()
        return f["gver"], None

    def changes_since_directed(self, version):
        if self._frozen is None:
            if self._rebuild_below is not None \
                    and version < self._rebuild_below:
                return self._inner.pods_global_version, None, None
            return self._inner.changes_since_directed(version)
        ver, dirty = self.changes_since(version)
        # dirty is () or None (rebuild); grew mirrors it per the
        # changelog contract (both None on rebuild, grew ⊆ dirty)
        return ver, dirty, (set() if dirty is not None else None)

    # -------------------------------------------------- live bind path
    def bind(self, pod, node, assigned_chips=None, fence=None) -> None:
        self._inner.bind(pod, node, assigned_chips, fence=fence)
        f = self._frozen
        if f is not None:
            # the client SAW its 2xx: write through into the frozen view
            # (a real scheduler's optimistic cache update), bumping the
            # frozen versions so the replica's memos notice its own write
            f["pods_on"].setdefault(node, []).append(pod)
            f["pods_ver"][node] = f["pods_ver"].get(node, 0) + 1
            f["gver"] += 1

    def evict(self, pod) -> None:
        node = pod.node
        self._inner.evict(pod)
        f = self._frozen
        if f is not None and node in f["pods_on"]:
            f["pods_on"][node] = [p for p in f["pods_on"][node]
                                  if p.uid != pod.uid]
            f["pods_ver"][node] = f["pods_ver"].get(node, 0) + 1
            f["gver"] += 1

    def __getattr__(self, name):
        # telemetry, node_meta, bound_node_of, lease_authority, subscribe,
        # bind_conflicts, ... — everything else is live
        return getattr(self._inner, name)


def blackout(store, now: float, max_age_s: float) -> None:
    """Start a telemetry blackout: every stored heartbeat ages past the
    staleness gate at once (the whole sniffer fleet went dark long
    enough ago that nothing is fresh). Publishes COPIES so the store can
    see the old heartbeat and keep its ceiling (the engine's blackout
    detector) exact."""
    import dataclasses

    for m in store.list():
        store.put(dataclasses.replace(
            m, heartbeat=now - (max_age_s + 1.0)))


def revive(store, now: float) -> None:
    """End a blackout: the sniffer fleet republishes fresh heartbeats."""
    import dataclasses

    for m in store.list():
        store.put(dataclasses.replace(m, heartbeat=now))


class SimulatedProvider:
    """Fault-injected capacity provider (scheduler/capacity/ provider
    contract) for the chaos harness and benches: seeded provisioning-
    latency draws on the engine's injectable clock, with each request's
    FATE decided deterministically from the fault plan at request time:

    - healthy: the node is created (through the given backend adapter —
      FakeBackend or WireBackend, i.e. the ordinary intake) after the
      drawn latency and a ``ready`` result is delivered at the next
      poll.
    - PROVIDER_STOCKOUT / PROVIDER_QUOTA_DENIED: full latency, then a
      denial result — the provisioner's backoff/breaker must absorb it.
    - PROVISION_LOST_RESPONSE: the node IS created on schedule but no
      result ever arrives — the write-off + adoption path's analogue of
      the lost bind response.
    - PROVISION_FLAP: a ready result, then the provider yanks the node
      ``flap_after_s`` later (orphaned pods routed back through the
      backend's orphan router).

    The provider assigns request ids, so fleet replicas sharing one
    provider can never collide; a result whose request the (possibly
    freshly taken-over) provisioner does not recognise exercises the
    adoption path by construction."""

    def __init__(self, backend, clock=None, plan: FaultPlan | None = None,
                 seed: int = 0, latency_s: tuple = (0.2, 1.5),
                 flap_after_s: float = 2.0, flight=None) -> None:
        from .scheduler.capacity import ProvisionRequest, ProvisionResult

        self._Request = ProvisionRequest
        self._Result = ProvisionResult
        self.backend = backend
        self.clock = clock
        self.plan = plan
        self.rng = random.Random(seed)
        self.latency_s = latency_s
        self.flap_after_s = flap_after_s
        self.flight = flight
        self._seq = 0
        self._pending: list = []   # (ready_at, req, fate)
        self._flaps: list = []     # (due_at, node)
        self.injected: dict[str, int] = {}
        self.created: list[str] = []
        self.released: list[str] = []
        self.flapped: list[str] = []
        self.lost_nodes: list[str] = []  # created, response never sent

    def _now(self) -> float:
        return self.clock.time() if self.clock is not None else 0.0

    def _count(self, kind: str, **detail) -> None:
        self.injected[kind] = self.injected.get(kind, 0) + 1
        if self.flight is not None:
            self.flight.record("fault_injected", fault=kind, **detail)

    def _fate(self, now: float) -> str:
        if self.plan is None:
            return "ready"
        for kind in (PROVIDER_STOCKOUT, PROVIDER_QUOTA_DENIED,
                     PROVISION_LOST_RESPONSE, PROVISION_FLAP):
            if self.plan.active(kind, now):
                return kind
        return "ready"

    # ------------------------------------------------------------ contract
    def request(self, pool: str, template, now: float | None = None):
        now = self._now() if now is None else now
        self._seq += 1
        req = self._Request(id=self._seq, pool=pool, template=template,
                            requested_at=now)
        fate = self._fate(now)
        if fate not in ("ready", PROVISION_FLAP):
            # a flap's observable fault is the YANK — counting it here
            # too would double-book one fault (and a node released
            # before its flap deadline never flaps at all)
            self._count(fate, pool=pool, request=req.id)
        ready_at = now + self.rng.uniform(*self.latency_s)
        self._pending.append((ready_at, req, fate))
        return req

    def next_event_at(self, now: float) -> float | None:
        """Earliest pending completion or flap — the provisioner's
        next_wake_at contribution on a virtual clock."""
        times = ([t for t, _, _ in self._pending]
                 + [t for t, _ in self._flaps])
        return min(times) if times else None

    def poll(self, now: float | None = None) -> list:
        now = self._now() if now is None else now
        results: list = []
        keep: list = []
        for ready_at, req, fate in self._pending:
            if ready_at > now:
                keep.append((ready_at, req, fate))
                continue
            if fate == PROVIDER_STOCKOUT:
                results.append(self._Result(
                    req.id, req.pool, "stockout",
                    detail="chaos: no capacity for shape"))
                continue
            if fate == PROVIDER_QUOTA_DENIED:
                results.append(self._Result(
                    req.id, req.pool, "quota-denied",
                    detail="chaos: project quota exceeded"))
                continue
            name = f"{req.pool}-{req.id}"
            names = self.backend.create(name, req.template, now)
            self.created.extend(names)
            if fate == PROVISION_LOST_RESPONSE:
                # node real, answer gone: the caller writes the request
                # off and must adopt the node when it shows up
                self.lost_nodes.extend(names)
                continue
            results.append(self._Result(req.id, req.pool, "ready",
                                        node=names[0],
                                        nodes=tuple(names)))
            if fate == PROVISION_FLAP:
                self._flaps.append((now + self.flap_after_s, names))
        self._pending = keep
        due = [f for f in self._flaps if f[0] <= now]
        if due:
            self._flaps = [f for f in self._flaps if f[0] > now]
            for _, names in due:
                for name in names:
                    self._count(PROVISION_FLAP, node=name)
                    self.backend.destroy(name)
                    self.flapped.append(name)
        return results

    def release(self, node: str, pool: str) -> bool:
        # a released node's pending flap is cancelled: the caller gave
        # the node back, so yanking it later would destroy a node that
        # no longer exists and double-book it as released AND flapped
        self._flaps = [(t, [n for n in names if n != node])
                       for t, names in self._flaps]
        self._flaps = [(t, names) for t, names in self._flaps if names]
        self.backend.destroy(node)
        self.released.append(node)
        return True


class _CrashWindow:
    """Shared crash condition for the chaos plugins below: raise during
    the plan's PLUGIN_ERROR windows (all pods, or the seeded subset
    `match` selects), or always when armed without a plan."""

    def __init__(self, plan: FaultPlan | None = None, clock=None,
                 match=None) -> None:
        self.plan = plan
        self.clock = clock
        self.match = match  # pod -> bool; None = every pod
        self.crashes = 0

    def should_crash(self, pod) -> bool:
        if self.plan is not None:
            now = self.clock.time() if self.clock is not None else 0.0
            if not self.plan.active(PLUGIN_ERROR, now):
                return False
        if self.match is not None and not self.match(pod):
            return False
        self.crashes += 1
        return True


class CrashingFilter(FilterPlugin, _CrashWindow):
    """A filter plugin that raises (not: returns ERROR) on schedule — the
    exact misbehaviour cycle-level containment exists for."""

    name = "chaos-crash-filter"

    def __init__(self, plan=None, clock=None, match=None) -> None:
        _CrashWindow.__init__(self, plan, clock, match)

    def filter(self, state, pod, node) -> Status:
        if self.should_crash(pod):
            raise RuntimeError(f"chaos: filter crash for {pod.key}")
        return Status.success()


class CrashingScore(ScorePlugin, _CrashWindow):
    name = "chaos-crash-score"
    weight = 0  # never influences placement when it does not crash

    def __init__(self, plan=None, clock=None, match=None) -> None:
        _CrashWindow.__init__(self, plan, clock, match)

    def score(self, state, pod, node):
        if self.should_crash(pod):
            raise RuntimeError(f"chaos: score crash for {pod.key}")
        return 0.0, Status.success()


class CrashingReserve(ReservePlugin, _CrashWindow):
    name = "chaos-crash-reserve"

    def __init__(self, plan=None, clock=None, match=None) -> None:
        _CrashWindow.__init__(self, plan, clock, match)

    def reserve(self, state, pod, node) -> Status:
        if self.should_crash(pod):
            raise RuntimeError(f"chaos: reserve crash for {pod.key}")
        return Status.success()

    def unreserve(self, state, pod, node) -> None:
        return None
