"""Telemetry schema: the TPU-native analogue of the reference's SCV CRD.

The reference consumes one ``Scv`` custom resource per node (named after the
node) with per-card fields ``FreeMemory/TotalMemory/Clock/Bandwidth/Core/
Power/Health`` and node-level ``CardNumber/FreeMemorySum/TotalMemorySum``
(use sites: reference pkg/yoda/filter/filter.go:13-57,
pkg/yoda/collection/collection.go:59-78, pkg/yoda/score/algorithm.go:57-87).

Here the unit of accounting is a TPU *chip*:

- ``FreeMemory``/``TotalMemory`` (MB)  -> HBM free/total (MB)
- ``Clock`` (MHz, graphics clock)      -> TensorCore/MXU clock (MHz)
- ``Bandwidth`` (PCIe GB/s)            -> ICI link bandwidth (GB/s)
- ``Core`` (CUDA core count)           -> MXU count (systolic arrays per chip)
- ``Power`` (W)                        -> TDP/board power (W)
- ``Health``                           -> chip health from libtpu runtime

plus TPU-only fields the GPU reference has no equivalent for and which the
topology-aware scorer and gang scheduler need: ICI coordinates of each chip in
its pod slice, the slice id/topology, and the node's host index within a
multi-host slice.

Everything is a plain frozen-ish dataclass (no k8s API machinery): the store
(`store.py`) is the watch-cache analogue, and `to_cr()`/`from_cr()` give the
CRD wire form for the real-cluster path (deploy/crd-tpunodemetrics.yaml).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, asdict
from typing import Iterable

from ..utils.memo import memo

HEALTHY = "Healthy"
TPU = "tpu"
GPU = "gpu"

# CRD identity for the real-cluster path (group renamed from the reference's
# core.run-linux.com, deploy/yoda-scheduler.yaml:206-208).
CRD_GROUP = "metrics.yoda.tpu"
CRD_VERSION = "v1"
CRD_PLURAL = "tpunodemetrics"


@dataclass
class Chip:
    """Telemetry for one accelerator chip (TPU chip or, in mixed clusters,
    one GPU card — the schema is accelerator-agnostic per the north star)."""

    index: int
    hbm_free_mb: int
    hbm_total_mb: int
    health: str = HEALTHY
    clock_mhz: int = 940          # TensorCore clock (v4: 940 MHz)
    ici_bandwidth_gbps: int = 100  # per-link ICI bandwidth
    core_count: int = 4            # MXUs per chip (v4 TensorCore: 4 MXUs)
    power_w: int = 170
    coords: tuple[int, int, int] = (0, 0, 0)  # position in the slice's ICI torus
    duty_cycle_pct: float = 0.0    # measured MXU duty cycle, 0..100

    @property
    def healthy(self) -> bool:
        return self.health == HEALTHY


@dataclass
class TpuNodeMetrics:
    """Per-node accelerator telemetry; one object per node, keyed by node name
    (the reference looks its Scv up by node name, pkg/yoda/scheduler.go:80)."""

    node: str
    chips: list[Chip] = field(default_factory=list)
    accelerator: str = TPU         # "tpu" | "gpu" — mixed-cluster partitioning
    tpu_generation: str = ""       # "v4", "v5e", ... ("" = unspecified)
    slice_id: str = ""             # "" = standalone node (no multi-host slice)
    topology: str = ""             # e.g. "2x2x1" (chips this host contributes)
    slice_topology: str = ""       # e.g. "2x2x4" (whole pod slice)
    host_index: int = 0            # this host's rank within the slice
    num_hosts: int = 1             # hosts in the slice
    generation: int = 0            # bumped by the publisher on every update
    heartbeat: float = field(default_factory=time.time)

    # -- node-level aggregates (the reference stores these materialized as
    # FreeMemorySum/TotalMemorySum; we derive them so they can never skew) --
    @property
    def chip_count(self) -> int:
        return len(self.chips)

    def _aggregates(self) -> tuple[int, int, list[Chip]]:
        """Aggregate memo keyed by `generation`: every publisher path bumps it
        via TelemetryStore.put, and the scheduler reads these on every
        (pod, node) hot-path visit. Do not mutate `chips` without re-putting."""
        def compute() -> tuple[int, int, list[Chip]]:
            free = total = 0
            healthy: list[Chip] = []
            for c in self.chips:
                free += c.hbm_free_mb
                total += c.hbm_total_mb
                if c.health == HEALTHY:
                    healthy.append(c)
            return free, total, healthy

        return memo(self, "_agg_memo", self.generation, compute)

    @property
    def hbm_free_sum(self) -> int:
        return self._aggregates()[0]

    @property
    def hbm_total_sum(self) -> int:
        return self._aggregates()[1]

    def healthy_chips(self) -> list[Chip]:
        """Healthy chips (shared memoised list — treat as read-only)."""
        return self._aggregates()[2]

    def healthy_coords(self) -> frozenset[tuple[int, int, int]]:
        """ICI coords of healthy chips (memoised like the other aggregates)."""
        return memo(
            self, "_coords_memo", self.generation,
            lambda: frozenset(c.coords for c in self._aggregates()[2]),
        )

    def stale(self, now: float | None = None, max_age_s: float = 60.0) -> bool:
        """Staleness gate — the reference has no heartbeat concept; a dead
        sniffer kept serving frozen numbers. Filter treats stale telemetry as
        unschedulable rather than trusting it."""
        return ((now if now is not None else time.time()) - self.heartbeat) > max_age_s

    # ------------------------------------------------------------------ wire
    def to_cr(self) -> dict:
        """Render as a Kubernetes custom-resource dict (status subresource)."""
        body = asdict(self)
        chips = body.pop("chips")
        name = body.pop("node")
        return {
            "apiVersion": f"{CRD_GROUP}/{CRD_VERSION}",
            "kind": "TpuNodeMetrics",
            "metadata": {"name": name},
            "status": {**body, "chips": chips},
        }

    @classmethod
    def from_cr(cls, cr: dict) -> "TpuNodeMetrics":
        status = dict(cr.get("status", {}))
        chips = [
            Chip(**{**c, "coords": tuple(c.get("coords", (0, 0, 0)))})
            for c in status.pop("chips", [])
        ]
        return cls(node=cr["metadata"]["name"], chips=chips, **status)


def aggregate_slice(nodes: Iterable[TpuNodeMetrics]) -> dict[str, list[TpuNodeMetrics]]:
    """Group nodes by slice id (standalone nodes land under their own name)."""
    out: dict[str, list[TpuNodeMetrics]] = {}
    for n in nodes:
        out.setdefault(n.slice_id or n.node, []).append(n)
    for members in out.values():
        members.sort(key=lambda m: m.host_index)
    return out
