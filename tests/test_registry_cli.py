"""Registry, config-driven profile assembly, CLI simulate, metrics server."""

import json
import urllib.request

import pytest

from yoda_scheduler_tpu.cli import load_config, main
from yoda_scheduler_tpu.scheduler import SchedulerConfig
from yoda_scheduler_tpu.scheduler.framework import PreScorePlugin
from yoda_scheduler_tpu.scheduler.plugins import (
    GangPermit,
    TelemetryFilter,
    TopologyScore,
)
from yoda_scheduler_tpu.scheduler.registry import build_profile, registered
from yoda_scheduler_tpu.utils.obs import Metrics, TraceLog


def test_registry_lists_builtins():
    names = registered()
    for expected in ("priority-sort", "telemetry-filter", "telemetry-score",
                     "topology-score", "gang-permit", "priority-preemption"):
        assert expected in names


def test_build_profile_from_enablement():
    enabled = {
        "queueSort": ["priority-sort"],
        "filter": ["telemetry-filter"],
        "preScore": ["max-collection"],
        "score": ["telemetry-score", "topology-score"],
        "permit": ["gang-permit"],
    }
    profile = build_profile(SchedulerConfig(), enabled)
    assert isinstance(profile.filter[0], TelemetryFilter)
    # topology-score auto-registers its PreScore half
    assert any(isinstance(p, TopologyScore) for p in profile.pre_score)
    # gang-permit's Reserve hook (slice choice) auto-registers
    assert any(isinstance(p, GangPermit) for p in profile.reserve)


def test_build_profile_unknown_plugin():
    with pytest.raises(KeyError):
        build_profile(SchedulerConfig(), {"filter": ["no-such-plugin"]})


def test_load_config_yaml(tmp_path):
    cfg_file = tmp_path / "config.yaml"
    cfg_file.write_text(
        """
apiVersion: kubescheduler.config.k8s.io/v1
kind: KubeSchedulerConfiguration
profiles:
  - schedulerName: custom-sched
    percentageOfNodesToScore: 25
    plugins:
      filter:
        enabled: [{name: telemetry-filter}]
      score:
        enabled: [{name: telemetry-score}]
    pluginConfig:
      - name: yoda-tpu
        args:
          topologyWeight: 9
          scoreWeights: {free_memory: 7}
"""
    )
    cfg, enabled = load_config(str(cfg_file))
    assert cfg.scheduler_name == "custom-sched"
    assert cfg.percentage_of_nodes_to_score == 25
    assert cfg.topology_weight == 9
    assert cfg.weights.free_memory == 7
    assert enabled["filter"] == ["node-admission", "telemetry-filter"]


def test_cli_simulate_end_to_end(capsys):
    rc = main([
        "simulate",
        "example/test-pod.yaml",
        "example/test-deployment.yaml",
        "example/resnet-v4-8.yaml",
        "example/llama-v4-32-gang.yaml",
        "--tpu-slices", "2", "--tpu-nodes", "2", "--gpu-nodes", "1",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["bound"] == 9
    # every BASELINE workload bound
    assert out["pods"]["default/test-pod"]["phase"] == "Bound"
    assert out["pods"]["default/resnet50-train"]["chips"].count(";") == 3
    gang_nodes = {
        v["node"] for k, v in out["pods"].items() if "llama2-7b" in k}
    assert len(gang_nodes) == 4
    slices = {n.rsplit("-host-", 1)[0] for n in gang_nodes}
    assert len(slices) == 1  # whole gang on one slice


def test_cli_simulate_unplaceable_terminates_promptly(capsys):
    """A manifest that can NEVER place (v5e gang, zero v5e slices) must
    report Pending pods with exit 1 in bounded time — the virtual clock
    turns retry backoffs into simulated time instead of wall sleeps
    (previously this hung for max_cycles x backoff real seconds)."""
    import time as _time

    t0 = _time.monotonic()
    rc = main(["simulate", "example/mixtral-v5e-64.yaml",
               "example/test-pod.yaml", "--max-cycles", "2000"])
    assert rc == 1
    assert _time.monotonic() - t0 < 60.0
    out = json.loads(capsys.readouterr().out)
    # the v5e pods stay Pending (no v5e slice exists)...
    assert out["bound"] == 1
    # ...but the placeable pod binds even though the unplaceable gang's
    # virtual backoff races simulated time far past the 60s staleness
    # gate — heartbeats are pinned so the fleet never ages out mid-run
    assert out["pods"]["default/test-pod"]["phase"] == "Bound"


def test_cli_simulate_v5e_manifest_places(capsys):
    rc = main(["simulate", "example/mixtral-v5e-64.yaml",
               "--v5e-slices", "2"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["bound"] == 9


def test_cli_sniff(capsys):
    rc = main(["sniff", "--node-name", "test-host"])
    assert rc == 0
    cr = json.loads(capsys.readouterr().out)
    assert cr["metadata"]["name"] == "test-host"
    assert cr["kind"] == "TpuNodeMetrics"
    # CPU-only test host: zero chips, never fabricated
    assert cr["status"]["chips"] == []


def test_metrics_http_server():
    from yoda_scheduler_tpu.utils.httpserv import serve

    metrics = Metrics()
    metrics.inc("pods_scheduled_total", 3)
    metrics.observe("schedule_latency_ms", 1.5)
    traces = TraceLog()
    server, _ = serve(metrics, traces, port=0)
    host, port = server.server_address
    try:
        body = urllib.request.urlopen(
            f"http://{host}:{port}/metrics").read().decode()
        assert "yoda_tpu_pods_scheduled_total 3" in body
        assert "schedule_latency_ms_bucket" in body
        assert urllib.request.urlopen(
            f"http://{host}:{port}/healthz").read() == b"ok"
        traces_doc = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/traces").read())
        assert traces_doc == []
    finally:
        server.shutdown()


def test_merge_enablement_keeps_defaults():
    from yoda_scheduler_tpu.scheduler.registry import merge_enablement

    # listing only `score:` must not disable filtering/permit (k8s semantics)
    merged = merge_enablement({"score": {"enabled": [{"name": "telemetry-score"}]}})
    assert merged["filter"] == ["node-admission", "telemetry-filter"]
    assert merged["permit"] == ["gang-permit"]
    assert "telemetry-score" in merged["score"]
    # explicit disable-all clears a point
    merged = merge_enablement({"permit": {"disabled": [{"name": "*"}]}})
    assert merged["permit"] == []
    # targeted disable
    merged = merge_enablement({"score": {"disabled": [{"name": "topology-score"}]}})
    assert merged["score"] == ["telemetry-score", "node-admission"]


def test_config_defaults_single_source_of_truth():
    # from_profile with empty args must equal the dataclass defaults
    cfg = SchedulerConfig.from_profile({"pluginConfig": [{"name": "yoda-tpu", "args": {}}]})
    assert cfg.topology_weight == SchedulerConfig().topology_weight
    assert cfg.telemetry_max_age_s == SchedulerConfig().telemetry_max_age_s


class TestValidate:
    def _run(self, tmp_path, content):
        from yoda_scheduler_tpu.cli import main

        p = tmp_path / "m.yaml"
        p.write_text(content)
        return main(["validate", str(p)])

    def test_good_manifests_pass(self, capsys):
        from yoda_scheduler_tpu.cli import main

        rc = main(["validate", "example/test-pod.yaml",
                   "example/llama-v4-32-gang.yaml",
                   "example/mixtral-v5e-64.yaml",
                   "example/llama-multislice-gang.yaml",
                   "example/serving-with-admission.yaml"])
        out = capsys.readouterr().out
        assert rc == 0 and "OK" in out

    def test_malformed_label_reported(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: bad
  labels: {scv/number: "-3"}
spec: {schedulerName: yoda-scheduler}
""")
        out = capsys.readouterr().out
        assert rc == 1 and "scv/number" in out

    def test_unknown_label_flagged_as_typo(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: typo
  labels: {tpu/topologyy: 2x2}
spec: {schedulerName: yoda-scheduler}
""")
        out = capsys.readouterr().out
        assert rc == 1 and "tpu/topologyy" in out and "typo" in out

    def test_gang_member_count_mismatch(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: w0
  labels: {tpu/gang-name: g, tpu/gang-size: "4", scv/number: "4"}
spec: {schedulerName: yoda-scheduler}
---
apiVersion: v1
kind: Pod
metadata:
  name: w1
  labels: {tpu/gang-name: g, tpu/gang-size: "4", scv/number: "4"}
spec: {schedulerName: yoda-scheduler}
""")
        out = capsys.readouterr().out
        assert rc == 1 and "2 member pods" in out and "park at Permit" in out

    def test_null_labels_and_non_mapping_docs_reported_not_crashed(
            self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: nolabels
  labels:
spec: {schedulerName: yoda-scheduler}
---
- not
- a
- k8s-object
""")
        out = capsys.readouterr().out
        assert rc == 1 and "not a mapping" in out
        assert "Traceback" not in out

    def test_topology_rank_vs_generation(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: impossible
  labels: {tpu/topology: 2x2x2, tpu/generation: v5e, scv/number: "8"}
spec: {schedulerName: yoda-scheduler}
""")
        out = capsys.readouterr().out
        assert rc == 1 and "2-D tori" in out

    def test_toleration_lint(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: badtol
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  tolerations:
    - {key: dedicated, operator: Equals, value: ml, effect: NoSchedule}
    - {key: dedicated, operator: Equal, value: ml, effect: NoSched}
    - {operator: Equal, value: x}
    - {key: dedicated, operator: Exists, value: ml}
""")
        out = capsys.readouterr().out
        assert rc == 1
        assert "operator 'Equals'" in out
        assert "effect 'NoSched'" in out
        assert "empty key requires" in out
        assert "must not set a value" in out

    def test_nodeselector_non_string_value(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Deployment
metadata: {name: d}
spec:
  replicas: 1
  template:
    metadata:
      labels: {scv/number: "1"}
    spec:
      schedulerName: yoda-scheduler
      nodeSelector: {pool: 3}
""")
        out = capsys.readouterr().out
        assert rc == 1 and "not a string" in out

    def test_valid_admission_spec_passes(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: ok
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  nodeSelector: {pool: gold}
  tolerations:
    - {key: dedicated, operator: Exists, effect: NoSchedule}
    - {operator: Exists}
""")
        out = capsys.readouterr().out
        assert rc == 0 and "OK" in out

    def test_malformed_spec_shapes_reported_not_crashed(self, tmp_path,
                                                        capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: badshape
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  tolerations: notalist
  nodeSelector: [a, b]
---
apiVersion: v1
kind: Pod
metadata:
  name: badtolentry
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  tolerations:
    - just-a-string
""")
        out = capsys.readouterr().out
        assert rc == 1
        assert "tolerations is str, not a list" in out
        assert "nodeSelector is list, not a mapping" in out
        assert "tolerations[0] is str, not a mapping" in out
        assert "Traceback" not in out

    def test_node_affinity_lint(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: badaff
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  affinity:
    nodeAffinity:
      requiredDuringSchedulingIgnoredDuringExecution:
        nodeSelectorTerms:
          - matchExpressions:
              - {key: pool, operator: Inn, values: [gold]}
              - {key: pool, operator: In}
              - {key: gen, operator: Exists, values: [x]}
              - {key: gen, operator: Gt, values: [a]}
""")
        out = capsys.readouterr().out
        assert rc == 1
        assert "operator 'Inn'" in out
        assert "In requires non-empty values" in out
        assert "Exists must not set values" in out
        assert "Gt needs exactly one integer" in out

    def test_valid_node_affinity_passes(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: okaff
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  affinity:
    nodeAffinity:
      requiredDuringSchedulingIgnoredDuringExecution:
        nodeSelectorTerms:
          - matchExpressions:
              - {key: pool, operator: In, values: [gold]}
              - {key: gen, operator: Gt, values: ["5"]}
""")
        out = capsys.readouterr().out
        assert rc == 0 and "OK" in out

    def test_node_affinity_malformed_shapes_and_matchfields(self, tmp_path,
                                                            capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: shapes
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  affinity: {nodeAffinity: [notadict]}
---
apiVersion: v1
kind: Pod
metadata:
  name: fields
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  affinity:
    nodeAffinity:
      requiredDuringSchedulingIgnoredDuringExecution:
        nodeSelectorTerms:
          - matchFields:
              - {key: spec.unschedulable, operator: In, values: ["false"]}
---
apiVersion: v1
kind: Pod
metadata:
  name: intval
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  affinity:
    nodeAffinity:
      requiredDuringSchedulingIgnoredDuringExecution:
        nodeSelectorTerms:
          - matchExpressions:
              - {key: pool, operator: In, values: [5]}
""")
        out = capsys.readouterr().out
        assert rc == 1
        assert "nodeAffinity is list, not a mapping" in out
        assert "only metadata.name" in out
        assert "not a string" in out
        assert "Traceback" not in out

    def test_pdb_lint(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: policy/v1
kind: PodDisruptionBudget
metadata: {name: pct}
spec:
  selector: {matchLabels: {app: serve}}
  minAvailable: 50%
---
apiVersion: policy/v1
kind: PodDisruptionBudget
metadata: {name: noselector}
spec:
  minAvailable: 1
---
apiVersion: policy/v1
kind: PodDisruptionBudget
metadata: {name: badexpr}
spec:
  selector:
    matchExpressions:
      - {key: tier, operator: Inn, values: [canary]}
  minAvailable: 1
""")
        out = capsys.readouterr().out
        assert rc == 1
        # percentage budgets are now evaluated (observed-count resolution,
        # utils/pdb.py) — no lint for 50%
        assert "pct" not in out
        assert "selects no pods" in out
        assert "operator 'Inn'" in out

    def test_valid_pdb_passes(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: policy/v1
kind: PodDisruptionBudget
metadata: {name: ok}
spec:
  selector: {matchLabels: {app: serve}}
  minAvailable: 2
""")
        out = capsys.readouterr().out
        assert rc == 0 and "OK" in out

    def test_preferred_affinity_lint(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: badpref
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  affinity:
    nodeAffinity:
      preferredDuringSchedulingIgnoredDuringExecution:
        - weight: 500
          preference:
            matchExpressions:
              - {key: pool, operator: In, values: [gold]}
        - weight: 10
          preference:
            matchExpressions:
              - {key: pool, operator: Inn, values: [gold]}
""")
        out = capsys.readouterr().out
        assert rc == 1
        assert "weight 500" in out
        assert "operator 'Inn'" in out

    def test_preferred_affinity_missing_preference(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: nopref
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  affinity:
    nodeAffinity:
      preferredDuringSchedulingIgnoredDuringExecution:
        - weight: 50
""")
        out = capsys.readouterr().out
        assert rc == 1 and "no preference.matchExpressions" in out

    def test_pod_affinity_lint(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: badpodaff
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  affinity:
    podAntiAffinity:
      requiredDuringSchedulingIgnoredDuringExecution:
        - labelSelector: {matchLabels: {app: web}}
        - topologyKey: zone
        - labelSelector:
            matchExpressions:
              - {key: tier, operator: Inn, values: [a]}
          topologyKey: zone
    podAffinity:
      preferredDuringSchedulingIgnoredDuringExecution:
        - weight: 10
        - weight: 500
          podAffinityTerm:
            labelSelector: {matchLabels: {a: b}}
            topologyKey: zone
""")
        out = capsys.readouterr().out
        assert rc == 1
        assert "no topologyKey" in out
        assert "no labelSelector" in out.replace("\n", " ")
        assert "operator 'Inn'" in out
        assert "podAffinityTerm" in out
        assert "weight 500" in out

    def test_valid_pod_affinity_passes(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: okpodaff
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  affinity:
    podAntiAffinity:
      requiredDuringSchedulingIgnoredDuringExecution:
        - labelSelector: {matchLabels: {app: web}}
          topologyKey: kubernetes.io/hostname
""")
        out = capsys.readouterr().out
        assert rc == 0 and "OK" in out

    def test_topology_spread_lint(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: badspread
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  topologySpreadConstraints:
    - {maxSkew: 0, topologyKey: zone, labelSelector: {matchLabels: {a: b}}}
    - {maxSkew: 1, labelSelector: {matchLabels: {a: b}}}
    - {maxSkew: 1, topologyKey: zone, whenUnsatisfiable: Maybe,
       labelSelector: {matchLabels: {a: b}}}
    - {maxSkew: 1, topologyKey: zone}
""")
        out = capsys.readouterr().out
        assert rc == 1
        assert "maxSkew=0" in out
        assert "no topologyKey" in out.replace("\n", " ")
        assert "whenUnsatisfiable='Maybe'" in out
        assert "counts no pods" in out

    def test_resource_request_lint(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: badreq
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  containers:
    - name: c
      resources:
        requests: {cpu: lots, memory: 1Qx}
""")
        out = capsys.readouterr().out
        assert rc == 1
        assert "cpu request 'lots'" in out
        assert "memory request '1Qx'" in out

    def test_matchfields_lint_details(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: mf
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  affinity:
    nodeAffinity:
      requiredDuringSchedulingIgnoredDuringExecution:
        nodeSelectorTerms:
          - matchFields: {key: metadata.name, operator: In, values: [n]}
          - matchFields:
              - {key: metadata.name, operator: Inn, values: [n]}
          - matchFields:
              - {key: metadata.name, operator: In}
""")
        out = capsys.readouterr().out
        assert rc == 1
        assert "not a list" in out
        assert "operator 'Inn'" in out
        assert "non-empty values" in out

    def test_valid_matchfields_passes(self, tmp_path, capsys):
        rc = self._run(tmp_path, """
apiVersion: v1
kind: Pod
metadata:
  name: okmf
  labels: {scv/number: "1"}
spec:
  schedulerName: yoda-scheduler
  affinity:
    nodeAffinity:
      requiredDuringSchedulingIgnoredDuringExecution:
        nodeSelectorTerms:
          - matchFields:
              - {key: metadata.name, operator: In, values: [node-5]}
""")
        out = capsys.readouterr().out
        assert rc == 0 and "OK" in out
